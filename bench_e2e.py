"""End-to-end multi-raft benchmark (VERDICT r1 #2): real store
PROCESSES — C++ epoll transport between them, shared C++ multi-group
journal log engine (one fsync per flush round across groups), the
device-plane MultiRaftEngine driving elections/commits — with
client-measured committed entries/s and commit-ack latency.

Topology: 3 store processes, each hosting one replica of every group;
leadership is spread by election priority (group k prefers endpoint
k % 3).  Appliers run in the leader's process (the reference's
benchmark drivers live in-JVM too); every op is one raft entry carried
through log fsync -> pipelined AppendEntries -> follower fsync ->
quorum reduce on the engine plane -> FSM apply -> ack.

Prints ONE JSON line and writes BENCH_E2E.json (picked up into
bench.py's "extra.e2e" so the driver's device-plane record carries the
end-to-end number).

vs_baseline is against 1e5 ops/s — the (unverifiable, recollection-only)
upstream small-payload figure in BASELINE.md; the reference repo
publishes no benchmark numbers (mount empty).

Environment note: the protocol plane is host Python either way; the
engine plane runs on CPU jax here because the only TPU on this box sits
behind a ~100ms tunnel that would dominate an END-TO-END latency
measurement (bench.py measures the real device plane separately).
"""

import argparse
import asyncio
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


# ===========================================================================
# store process
# ===========================================================================

async def run_store(args) -> None:
    # the engine plane must run on HOST cpu-jax here: this box's only
    # TPU sits behind a ~100ms tunnel (env JAX_PLATFORMS=cpu alone is
    # overridden by the axon plugin, so force it via jax.config — the
    # same dance tests/conftest.py does)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpuraft.conf import Configuration
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.core.node import Node
    from tpuraft.core.node_manager import NodeManager
    from tpuraft.core.state_machine import StateMachine
    from tpuraft.entity import PeerId, Task
    from tpuraft.options import NodeOptions, TickOptions
    from tpuraft.rpc.native_tcp import NativeTcpRpcServer, NativeTcpTransport

    me = args.index
    endpoints = args.peers.split(",")
    G = args.groups
    base = os.path.join(args.dir, f"store{me}")

    class CountFSM(StateMachine):
        applied = 0

        async def on_apply(self, it):
            while it.valid():
                CountFSM.applied += 1
                it.next()

    server = NativeTcpRpcServer(endpoints[me])
    await server.start()
    manager = NodeManager(server)
    transport = NativeTcpTransport(endpoint=endpoints[me])
    cap = 1 << max(4, (G + 3).bit_length())
    engine = MultiRaftEngine(TickOptions(
        max_groups=cap, max_peers=4, tick_interval_ms=10))
    await engine.start()
    factory = engine.ballot_box_factory()

    # store-wide SAFE read-confirmation amortizer: the batcher is
    # engine-agnostic (it only needs nodes + replicators), so the raw
    # protocol-plane bench exercises the same coalesced read fences the
    # RheaKV stack serves through
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    read_batcher = ReadConfirmBatcher()

    nodes = []
    for k in range(G):
        gid = f"g{k}"
        # leader placement: endpoint (k % n) gets the high priority
        peers = [
            PeerId(ep.split(":")[0], int(ep.split(":")[1]), 0,
                   100 if k % len(endpoints) == i else 10)
            for i, ep in enumerate(endpoints)]
        conf = Configuration(peers)
        opts = NodeOptions(
            election_timeout_ms=args.election_timeout_ms,
            initial_conf=conf,
            fsm=CountFSM(),
            log_uri=f"multilog://{base}/mlog#{gid}",
            raft_meta_uri=(f"file://{base}/meta/{gid}"
                           if args.meta == "file" else "memory://"),
            enable_metrics=False)
        # one multi_heartbeat RPC per endpoint pair per beat interval
        opts.raft_options.coalesce_heartbeats = True
        node = Node(gid, peers[me], opts, transport,
                    ballot_box_factory=factory)
        node.node_manager = manager
        manager.add(node)
        ok = await node.init()
        assert ok
        node.read_only_service.attach_confirm_batcher(read_batcher)
        nodes.append(node)

    print("BOOTED", flush=True)

    # wait for local leadership of this process's share; converging
    # G elections across 3 time-sliced processes is O(G) work, so the
    # deadline scales with G (and 98% placement is good enough to
    # measure — the driver reports the real count)
    want = [n for i, n in enumerate(nodes) if i % len(endpoints) == me]
    deadline = time.monotonic() + 120 + G * 0.06
    while time.monotonic() < deadline:
        n_led = sum(1 for n in want if n.is_leader())
        if n_led >= max(1, int(len(want) * 0.98)):
            break
        await asyncio.sleep(0.5)
    led = [n for n in want if n.is_leader()]
    print(f"LEADING {len(led)}/{len(want)}", flush=True)

    loop = asyncio.get_running_loop()
    reader = asyncio.StreamReader()
    await loop.connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin)

    async def measured_run(duration: float, window: int):
        """Windowed pipelined appliers on every locally-led group."""
        stop_at = time.monotonic() + duration
        ok = [0]
        errs = [0]
        lats: list[float] = []

        async def drive(node):
            # `window` batches of `batch` entries in flight per group —
            # apply_batch amortizes the lock/flush per batch, like the
            # reference's applyBatch=32 Disruptor drain
            batch = args.batch
            sem = asyncio.Semaphore(window)
            payload = b"x" * args.payload

            def batch_cb(t0, sample):
                left = [batch]

                def cb(st):
                    if st.is_ok():
                        ok[0] += 1
                    else:
                        errs[0] += 1
                    left[0] -= 1
                    if left[0] == 0:
                        sem.release()
                        if sample:
                            lats.append(time.perf_counter() - t0)
                return cb

            pending = set()
            i = 0
            if args.pace_ms:
                # paced mode (scale runs): spread each group's batch
                # cadence uniformly so offered load is shaped, not a
                # thundering herd on the shared core
                import random
                await asyncio.sleep(random.random() * args.pace_ms / 1e3)
            while time.monotonic() < stop_at:
                if not node.is_leader():
                    # leadership moved (possibly to another store
                    # process, whose own driver for this group takes
                    # over): idle instead of spraying not-leader
                    # rejections at the stale node — the RouteTable-
                    # client analog, ladder edition
                    await asyncio.sleep(
                        max(args.pace_ms / 1e3, 0.05) if args.pace_ms
                        else 0.05)
                    continue
                await sem.acquire()
                if args.pace_ms:
                    await asyncio.sleep(args.pace_ms / 1e3)
                if errs[0] > ok[0] + 1000:
                    # cluster unhealthy (election churn): back off
                    # instead of spinning failed applies at CPU speed
                    await asyncio.sleep(0.1)
                i += 1
                t0 = time.perf_counter()
                cb = batch_cb(t0, i % 8 == 0)
                tasks = [Task(data=payload, done=cb) for _ in range(batch)]
                fut = asyncio.ensure_future(node.apply_batch(tasks))
                pending.add(fut)
                fut.add_done_callback(pending.discard)
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            # drain outstanding acks
            for _ in range(window):
                try:
                    await asyncio.wait_for(sem.acquire(), 5.0)
                except asyncio.TimeoutError:
                    break

        t_start = time.monotonic()
        # drive EVERY local node, gated on live leadership (not the
        # boot-time led list): a group whose leadership migrates to
        # this store mid-window gets driven here, and the stale node
        # stops being sprayed with not-leader applies
        await asyncio.gather(*(drive(n) for n in nodes))
        elapsed = time.monotonic() - t_start
        lats.sort()
        import resource

        return {
            "ok": ok[0], "errs": errs[0], "elapsed": elapsed,
            "applied": CountFSM.applied,
            "lat_p50_ms": round(lats[len(lats) // 2] * 1e3, 3) if lats else None,
            "lat_p99_ms": round(lats[int(len(lats) * 0.99)] * 1e3, 3)
            if lats else None,
            # scale accounting (VERDICT r2 #1): memory + event-loop task
            # population at this G, per store process
            "rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
            "asyncio_tasks": len(asyncio.all_tasks()),
        }

    async def measured_read_mix(duration: float, frac: float):
        """Read/write-mix run: each in-flight slot is a read_index()
        fence (probability ``frac``) or an apply batch.  Reads count as
        ONE op each; the store-wide ReadConfirmBatcher coalesces every
        led group's fences into shared beat-plane rounds."""
        import random as _rnd

        stop_at = time.monotonic() + duration
        ok = [0]
        errs = [0]
        rlats: list[float] = []

        async def drive(node):
            batch = args.batch
            sem = asyncio.Semaphore(args.window)
            payload = b"x" * args.payload
            rng = _rnd.Random(id(node) & 0xffff)

            def batch_cb():
                left = [batch]

                def cb(st):
                    if st.is_ok():
                        ok[0] += 1
                    else:
                        errs[0] += 1
                    left[0] -= 1
                    if left[0] == 0:
                        sem.release()
                return cb

            async def one_read(sample: bool):
                t0 = time.perf_counter()
                try:
                    # bounded: a read wedged by churn must cost one slot
                    # for a few seconds, not hang the whole phase
                    await asyncio.wait_for(node.read_index(), 10.0)
                    ok[0] += 1
                    if sample:
                        rlats.append(time.perf_counter() - t0)
                except Exception:  # noqa: BLE001 — election churn etc.
                    errs[0] += 1
                finally:
                    sem.release()

            pending = set()
            i = 0
            while time.monotonic() < stop_at:
                if not node.is_leader():
                    await asyncio.sleep(0.05)
                    continue
                await sem.acquire()
                i += 1
                if rng.random() < frac:
                    fut = asyncio.ensure_future(one_read(i % 4 == 0))
                else:
                    cb = batch_cb()   # ONE shared countdown per batch
                    tasks = [Task(data=payload, done=cb)
                             for _ in range(batch)]
                    fut = asyncio.ensure_future(node.apply_batch(tasks))
                pending.add(fut)
                fut.add_done_callback(pending.discard)
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            for _ in range(args.window):
                try:
                    await asyncio.wait_for(sem.acquire(), 5.0)
                except asyncio.TimeoutError:
                    break

        t_start = time.monotonic()
        await asyncio.gather(*(drive(n) for n in nodes))
        elapsed = time.monotonic() - t_start
        rlats.sort()
        svc_totals: dict[str, int] = {}
        for n in nodes:
            for k, v in n.read_only_service.counters().items():
                svc_totals[k] = svc_totals.get(k, 0) + v
        return {
            "ok": ok[0], "errs": errs[0], "elapsed": elapsed,
            "read_frac": frac,
            "read_p50_ms": round(rlats[len(rlats) // 2] * 1e3, 3)
            if rlats else None,
            "read_p99_ms": round(rlats[int(len(rlats) * 0.99)] * 1e3, 3)
            if rlats else None,
            "read_plane": dict(read_batcher.counters(), **svc_totals),
        }

    async def latency_probe(n_ops: int):
        """Low-load sequential acks on ONE group: the adaptive-tick
        commit-ack latency end-to-end."""
        if not led:
            return {"n": 0}
        node = led[0]
        lats = []
        for i in range(n_ops):
            fut = loop.create_future()
            t0 = time.perf_counter()
            await node.apply(Task(data=b"lat", done=fut.set_result))
            st = await fut
            if st.is_ok():
                lats.append(time.perf_counter() - t0)
            await asyncio.sleep(0.002)
        lats.sort()
        if not lats:
            return {"n": 0}
        return {
            "n": len(lats),
            "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
            "p99_ms": round(lats[int(len(lats) * 0.99)] * 1e3, 3),
            "min_ms": round(lats[0] * 1e3, 3),
        }

    async def latency_breakdown(n_ops: int):
        """Per-stage timestamps along ONE group's low-load commit-ack
        path (VERDICT r2 #3): apply -> stage -> leader fsync -> RPC
        (follower fsync inside) -> quorum tick -> commit advance -> FSM
        ack, via transient wrappers — production code stays clean."""
        if not led:
            return {"n": 0}
        node = led[0]
        lm = node.log_manager
        box = node.ballot_box
        marks: dict = {}

        orig_flush = lm.flush_staged

        async def flush_wrap(upto=None):
            marks.setdefault("flush_s", time.perf_counter())
            r = await orig_flush(upto)
            marks.setdefault("flush_e", time.perf_counter())
            return r

        orig_call = transport.call

        async def call_wrap(dst, method, req, timeout_ms=None):
            # r4: entry appends ride the send plane's multi_append
            # batches; heartbeats (multi_heartbeat) and probes are not
            # the measured path
            entrylike = method == "multi_append" or (
                method == "append_entries"
                and getattr(req, "entries", None))
            if entrylike:
                marks.setdefault("rpc_s", time.perf_counter())
            r = await orig_call(dst, method, req, timeout_ms=timeout_ms)
            if entrylike:
                marks.setdefault("rpc_e", time.perf_counter())
            return r

        orig_tick = engine.tick_once

        def tick_wrap():
            t = time.perf_counter()
            r = orig_tick()
            if "adv" in marks:
                marks.setdefault("tick_s", t)
                marks.setdefault("tick_e", time.perf_counter())
            return r

        orig_adv = box._advance

        def adv_wrap(idx):
            marks.setdefault("adv", time.perf_counter())
            return orig_adv(idx)

        lm.flush_staged = flush_wrap
        transport.call = call_wrap
        engine.tick_once = tick_wrap
        box._advance = adv_wrap
        stages: dict[str, list] = {}
        total = []
        try:
            for _ in range(n_ops):
                marks.clear()
                fut = loop.create_future()
                t0 = time.perf_counter()
                await node.apply(Task(data=b"brk", done=fut.set_result))
                st = await fut
                t_ack = time.perf_counter()
                if not st.is_ok():
                    continue
                rel = {k: (v - t0) * 1e3 for k, v in marks.items()}
                rel["ack"] = (t_ack - t0) * 1e3
                for k, v in rel.items():
                    stages.setdefault(k, []).append(v)
                total.append(rel["ack"])
                await asyncio.sleep(0.002)
        finally:
            lm.flush_staged = orig_flush
            transport.call = orig_call
            engine.tick_once = orig_tick
            box._advance = orig_adv

        def pct(xs, q):
            if not xs:
                return None
            s = sorted(xs)
            return round(s[min(len(s) - 1, int(len(s) * q))], 3)

        p99 = {k: pct(v, 0.99) for k, v in sorted(stages.items())}
        # name the tail's dominant *start-latency* stage from the data:
        # tick_s = commit-advancing tick scheduled late (loop
        # contention), rpc_s = batch RPC dispatch, flush_s = fsync start
        starts = {k: p99[k] for k in ("tick_s", "rpc_s", "flush_s")
                  if p99.get(k) is not None}
        dom = max(starts, key=starts.get) if starts else None
        return {
            "n": len(total),
            "note": "relative ms marks across ops; rpc includes "
                    "follower fsync (multi_append batch RPC); adv = "
                    "quorum commit advanced on the engine; tick = the "
                    "advancing tick's span",
            "stage_p50_ms": {k: pct(v, 0.5) for k, v in sorted(stages.items())},
            "stage_p99_ms": p99,
            "tail_attribution": (
                f"ack p99 {p99.get('ack')}ms: dominant start-latency "
                f"stage at p99 is {dom} ({starts.get(dom)}ms) of "
                + ", ".join(f"{k}={v}ms" for k, v in starts.items())),
        }

    while True:
        line = (await reader.readline()).decode().strip()
        if not line or line == "QUIT":
            break
        cmd = line.split()
        if cmd[0] == "GO":
            res = await measured_run(float(cmd[1]), args.window)
            print("RESULT " + json.dumps(res), flush=True)
        elif cmd[0] == "PROF":
            import cProfile
            import pstats

            prof = cProfile.Profile()
            prof.enable()
            res = await measured_run(float(cmd[1]), args.window)
            prof.disable()
            path = os.path.join(args.dir, f"prof_{me}.txt")
            with open(path, "w") as f:
                pstats.Stats(prof, stream=f).sort_stats("cumulative"
                                                        ).print_stats(50)
            res["prof"] = path
            print("RESULT " + json.dumps(res), flush=True)
        elif cmd[0] == "RMIX":
            res = await measured_read_mix(float(cmd[1]), float(cmd[2]))
            print("RESULT " + json.dumps(res), flush=True)
        elif cmd[0] == "LAT":
            res = await latency_probe(int(cmd[1]))
            print("RESULT " + json.dumps(res), flush=True)
        elif cmd[0] == "BRK":
            res = await latency_breakdown(int(cmd[1]))
            print("RESULT " + json.dumps(res), flush=True)

    for n in nodes:
        await n.shutdown()
    await engine.shutdown()
    await server.stop()
    await transport.close()


# ===========================================================================
# parent / driver
# ===========================================================================

def free_ports(n: int) -> list[int]:
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=256)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--warmup", type=float, default=3.0)
    ap.add_argument("--window", type=int, default=8,
                    help="outstanding apply BATCHES per led group")
    ap.add_argument("--batch", type=int, default=32,
                    help="entries per apply_batch (reference applyBatch)")
    ap.add_argument("--payload", type=int, default=16)
    ap.add_argument("--pace-ms", type=float, default=0.0,
                    help="per-group pause between batches (shapes offered "
                         "load for high-G scale runs; 0 = saturate)")
    ap.add_argument("--election-timeout-ms", type=int, default=1500)
    ap.add_argument("--json-out", default="BENCH_E2E.json",
                    help="result file (relative to the repo root)")
    ap.add_argument("--meta", default="file", choices=["file", "memory"],
                    help="raft meta storage; 'memory' speeds up boot at "
                         "high G (meta is not in the commit-ack path)")
    ap.add_argument("--read-mix", default="",
                    help="comma-separated read fractions (e.g. "
                         "'0.95,0.5'): after the write phase, run one "
                         "read/write-mix phase per fraction — reads are "
                         "read_index() fences amortized by the "
                         "store-wide ReadConfirmBatcher; rows land in "
                         "extra.read_mix of the JSON")
    ap.add_argument("--skip-brk", action="store_true",
                    help="skip the per-stage breakdown round")
    ap.add_argument("--dir", default="")
    ap.add_argument("--store", action="store_true",
                    help="internal: run as a store process")
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--peers", default="")
    args = ap.parse_args()

    if args.store:
        asyncio.run(run_store(args))
        return

    import tempfile

    # build the native libs ONCE before spawning (stores would race it)
    from tpuraft.storage.multilog import ensure_built as build_multilog
    from tpuraft.rpc.native_tcp import ensure_built as build_transport

    build_multilog()
    build_transport()

    workdir = args.dir or tempfile.mkdtemp(prefix="tpuraft_e2e_")
    ports = free_ports(args.stores)
    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []
    try:
        for i in range(args.stores):
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "bench_e2e.py"),
                 "--store", "--index", str(i), "--peers", peers,
                 "--groups", str(args.groups), "--dir", workdir,
                 "--window", str(args.window), "--batch", str(args.batch),
                 "--payload", str(args.payload),
                 "--pace-ms", str(args.pace_ms),
                 "--meta", args.meta,
                 "--election-timeout-ms", str(args.election_timeout_ms)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env))

        def expect(p, prefix, timeout_s=180.0):
            import select

            t0 = time.monotonic()
            while True:
                left = timeout_s - (time.monotonic() - t0)
                if left <= 0:
                    raise TimeoutError(f"no {prefix!r} from store")
                # readline() alone would block past the deadline on a
                # silent-but-alive store; gate it on pipe readability
                ready, _, _ = select.select([p.stdout], [], [],
                                            min(left, 1.0))
                if not ready:
                    if p.poll() is not None:
                        raise RuntimeError("store process died")
                    continue
                line = p.stdout.readline().decode().strip()
                if line.startswith(prefix):
                    return line
                if not line and p.poll() is not None:
                    raise RuntimeError("store process died")

        for p in procs:
            # boot is O(G) node inits time-sliced on this host
            expect(p, "BOOTED", timeout_s=max(180.0, args.groups * 0.15))
        leading = [expect(p, "LEADING",
                          timeout_s=max(180.0, 150 + args.groups * 0.08))
                   for p in procs]
        n_led = sum(int(s.split()[1].split("/")[0]) for s in leading)

        def round_all(cmd):
            for p in procs:
                p.stdin.write((cmd + "\n").encode())
                p.stdin.flush()
            return [json.loads(expect(p, "RESULT")[len("RESULT "):])
                    for p in procs]

        def round_one(p, cmd):
            # low-load probes run on ONE store while the others idle —
            # probing all three concurrently triples the CPU in every
            # "low-load" sample on a 1-core host
            p.stdin.write((cmd + "\n").encode())
            p.stdin.flush()
            return json.loads(expect(p, "RESULT")[len("RESULT "):])

        round_all(f"GO {args.warmup}")          # warmup
        results = round_all(f"GO {args.duration}")
        read_rows = []
        for frac_s in [f for f in args.read_mix.split(",") if f]:
            frac = float(frac_s)
            rr = round_all(f"RMIX {args.duration} {frac}")
            r_ok = sum(r["ok"] for r in rr)
            r_el = max(r["elapsed"] for r in rr)
            plane: dict = {}
            for r in rr:
                for k, v in r.get("read_plane", {}).items():
                    plane[k] = plane.get(k, 0) + v
            read_rows.append({
                "read_frac": frac,
                "ops_per_sec": round(r_ok / r_el, 1),
                "errors": sum(r["errs"] for r in rr),
                "read_p50_ms": [r["read_p50_ms"] for r in rr],
                "read_p99_ms": [r["read_p99_ms"] for r in rr],
                "read_plane": plane,
            })
        lat = round_one(procs[0], "LAT 200")    # low-load single-group acks
        brk = (None if args.skip_brk
               else round_one(procs[0], "BRK 150"))  # per-stage breakdown
        for p in procs:
            p.stdin.write(b"QUIT\n")
            p.stdin.flush()

        total_ok = sum(r["ok"] for r in results)
        elapsed = max(r["elapsed"] for r in results)
        cps = total_ok / elapsed
        out = {
            "metric": "e2e_multiraft_commits_per_sec",
            "value": round(cps, 1),
            "unit": "commits/s",
            "topology": "single-process",
            "vs_baseline": round(cps / 1e5, 3),
            "extra": {
                "groups": args.groups, "stores": args.stores,
                "leaders_placed": n_led,
                "payload_bytes": args.payload,
                "window_per_group": args.window,
                "duration_s": args.duration,
                "errors": sum(r["errs"] for r in results),
                "per_store_cps": [round(r["ok"] / r["elapsed"], 1)
                                  for r in results],
                "underload_ack_p50_ms": [r["lat_p50_ms"] for r in results],
                "underload_ack_p99_ms": [r["lat_p99_ms"] for r in results],
                "lowload_single_group_ack": lat,
                "ack_breakdown": brk,
                "read_mix": read_rows,
                "rss_mb_per_store": [r.get("rss_mb") for r in results],
                "asyncio_tasks_per_store": [r.get("asyncio_tasks")
                                            for r in results],
                "host_cores": os.cpu_count(),
                "per_core_commits_per_sec": round(
                    cps / max(1, os.cpu_count()), 1),
                "stack": "native-tcp + multilog(shared fsync) + "
                         "engine plane + priority placement",
                "baseline": "1e5 ops/s (upstream recollection, "
                            "unverifiable — BASELINE.md; measured on a "
                            "multi-core Xeon ~ 3-6K ops/s/core — this "
                            "host is 1 vCPU, so compare per-core)",
            },
        }
        print(json.dumps(out))
        path = os.path.join(REPO, args.json_out)
        if os.path.exists(path):
            # a fresh full run must not drop the bench-gate calibration
            # keys (re-recorded separately via `bench_gate.py --record`)
            try:
                with open(path) as f:
                    prev = json.load(f).get("extra", {})
                for k, v in prev.items():
                    if k.startswith("gate_"):
                        out["extra"].setdefault(k, v)
            except Exception:  # noqa: BLE001 — corrupt old file
                pass
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)


if __name__ == "__main__":
    main()
