"""bench_multiproc: the cross-process re-run of the region-density A/B.

Every committed BENCH_REGIONS row before this one measured client + S
stores multiplexed onto ONE event loop in ONE process — the PR 15
write-plane rows carried an explicit "single-process asterisk": at
w256 the client and all three stores contend for one interpreter, so
the recorded ceiling conflates protocol cost with loop contention.

This bench retires the asterisk: each store is a REAL OS process
(examples.proc_supervisor spawning examples.rheakv_server mains — own
CPython, own GIL, own loop), the client its own process (this one),
wired over real sockets.  Rows land in BENCH_REGIONS.json as
``row_mp[_<regions>]_w<N>_r0`` with ``topology: "multi-process"`` and
per-process CPU attribution (``/proc/<pid>/stat`` utime+stime deltas
over the measured window), so throughput can be read against cores
actually burned per store.

    python bench_multiproc.py                      # w24 + w256 at 1024x3
    python bench_multiproc.py --regions 128 --workers 256 --duration 6
"""

import argparse
import asyncio
import json
import os
import resource
import shutil
import struct
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _self_cpu_s() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_utime + ru.ru_stime


async def run(args) -> list[dict]:
    from examples.proc_supervisor import (
        ProcSupervisor,
        StoreProcess,
        free_endpoints,
        server_argv,
    )
    from examples.rheakv_server import client_for
    from tpuraft.core.lanes import WorkerLane
    from tpuraft.rheakv.client import BatchingOptions

    R, S = args.regions, args.stores
    endpoints = free_endpoints(S)
    t0 = time.monotonic()
    sup = ProcSupervisor([
        StoreProcess(ep, server_argv(
            ep, endpoints, R, os.path.join(args.dir, f"store{i}"),
            transport=args.transport, store=args.store,
            log_scheme=args.log_scheme, eto_ms=args.election_timeout_ms,
            apply_lane=not args.no_apply_lane, metrics_port=0))
        for i, ep in enumerate(endpoints)])
    await sup.start(ready_timeout_s=120 + R * 0.1)
    boot_s = time.monotonic() - t0

    if args.transport == "native":
        from tpuraft.rpc.native_tcp import NativeTcpTransport
        transport = NativeTcpTransport()
    else:
        from tpuraft.rpc.tcp import TcpTransport
        transport = TcpTransport()
    encode_lane = None if args.no_encode_lane else WorkerLane("cli-encode")
    client = client_for(
        endpoints, R, transport=transport,
        batching=BatchingOptions(enabled=True, encode_lane=encode_lane),
        timeout_ms=20000, max_retries=10)
    await client.start()

    # leadership convergence, observed from OUTSIDE (no in-proc store
    # handles here): sampled writes across the keyspace must land
    t1 = time.monotonic()
    probes = min(R, 64)
    deadline = time.monotonic() + 120 + R * 0.1
    while time.monotonic() < deadline:
        oks = 0
        for i in range(probes):
            key = struct.pack(">I", int((i + 0.5) * (1 << 32) / probes))
            try:
                await asyncio.wait_for(client.put(key + b"/warm", b"w"),
                                       20.0)
                oks += 1
            except Exception:  # noqa: BLE001 — still electing
                pass
        if oks >= int(probes * 0.98):
            break
        await asyncio.sleep(1.0)
    elect_s = time.monotonic() - t1

    payload = b"v" * 32
    rows = []
    for workers in args.worker_phases:
        import random
        ok = [0]
        errs = [0]
        lats: list[float] = []
        stop_at = time.monotonic() + args.duration

        async def worker(wid: int) -> None:
            r = random.Random(wid)
            while time.monotonic() < stop_at:
                key = struct.pack(">I", r.getrandbits(32)) \
                    + b"/%04d" % r.randrange(100)
                t = time.perf_counter()
                try:
                    await client.put(key, payload)
                    ok[0] += 1
                    lats.append(time.perf_counter() - t)
                except Exception:  # noqa: BLE001 — counted
                    errs[0] += 1
                await asyncio.sleep(args.pace_ms / 1e3)

        cpu0 = {p.name: p.cpu_seconds() or 0.0 for p in sup.procs}
        self0 = _self_cpu_s()
        t2 = time.monotonic()
        await asyncio.gather(*(worker(i) for i in range(workers)))
        elapsed = time.monotonic() - t2
        cpu1 = {p.name: p.cpu_seconds() or 0.0 for p in sup.procs}
        self1 = _self_cpu_s()
        lats.sort()
        cpu_stores = {name: round(cpu1[name] - cpu0[name], 2)
                      for name in cpu0}
        scraped = await sup.scrape_all()
        lane_keys = ("lane", "widen", "loop_lag", "draining")
        store_metrics = {
            name: {k: v for k, v in m.items()
                   if any(s in k for s in lane_keys)}
            for name, m in scraped.items()}
        row = {
            "regions": R,
            "stores": S,
            "topology": "multi-process",
            # the fabric only expresses parallelism the host HAS: with
            # cpu_cores_used pinned at ~host_cpus the row is core-bound,
            # not loop-bound — compare rows only at equal host_cpus
            "host_cpus": len(os.sched_getaffinity(0)),
            "boot_s": round(boot_s, 1),
            "elect_s": round(elect_s, 1),
            "ops_per_sec": round(ok[0] / elapsed, 1),
            "ok": ok[0],
            "errors": errs[0],
            "ack_p50_ms": round(lats[len(lats) // 2] * 1e3, 2)
            if lats else None,
            "ack_p99_ms": round(lats[int(len(lats) * 0.99)] * 1e3, 2)
            if lats else None,
            "workers": workers,
            "pace_ms": args.pace_ms,
            "read_frac": 0.0,
            "transport": args.transport,
            "store": args.store,
            "apply_lane": not args.no_apply_lane,
            "encode_lane": not args.no_encode_lane,
            # per-process CPU attribution over the measured window:
            # with real processes a store's burn is ITS OWN number, not
            # a share of one loop's wall clock
            "cpu_s_per_store": cpu_stores,
            "cpu_s_client": round(self1 - self0, 2),
            "cpu_cores_used": round(
                (sum(cpu_stores.values()) + self1 - self0) / elapsed, 2),
            "kv_batch_rpcs_per_s": round(
                client.batch_rpcs / elapsed, 1),
            "kv_batch_items_per_rpc": round(
                client.batch_items / max(1, client.batch_rpcs), 2),
            "store_metrics": store_metrics,
        }
        print("RESULT " + json.dumps(row), flush=True)
        rows.append(row)
        # reset client batch counters between phases
        client.batch_rpcs = client.batch_items = 0

    await client.shutdown()
    await transport.close()
    if encode_lane is not None:
        await encode_lane.aclose()
    await sup.stop()
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--regions", type=int, default=1024)
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--workers", default="24,256",
                    help="comma-separated worker phases (each gets its "
                         "own committed row)")
    ap.add_argument("--pace-ms", type=float, default=2.0)
    ap.add_argument("--election-timeout-ms", type=int, default=10000)
    ap.add_argument("--transport", choices=["tcp", "native"],
                    default="native")
    ap.add_argument("--store", choices=["memory", "native"],
                    default="native")
    ap.add_argument("--log-scheme", choices=["file", "multilog"],
                    default="multilog")
    ap.add_argument("--no-apply-lane", action="store_true",
                    help="disable the per-store FSM apply lane (A/B)")
    ap.add_argument("--no-encode-lane", action="store_true",
                    help="disable the client batch-encode lane (A/B)")
    ap.add_argument("--json-out", default="BENCH_REGIONS.json")
    ap.add_argument("--dir", default="")
    args = ap.parse_args()
    args.worker_phases = [int(w) for w in args.workers.split(",") if w]

    if args.transport == "native":
        from tpuraft.rpc.native_tcp import ensure_built
        ensure_built()
    if args.store == "native":
        from tpuraft.rheakv.native_store import ensure_built as kv_built
        kv_built()
    if args.log_scheme == "multilog":
        from tpuraft.storage.multilog import ensure_built as ml_built
        ml_built()
    tmp = not args.dir
    if tmp:
        args.dir = tempfile.mkdtemp(prefix=f"tpuraft_mp_{args.regions}_")
    t0 = time.monotonic()
    try:
        rows = asyncio.run(run(args))
    finally:
        if tmp:
            shutil.rmtree(args.dir, ignore_errors=True)
    wall = round(time.monotonic() - t0, 1)

    path = os.path.join(REPO, args.json_out)
    out = {}
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
    for row in rows:
        row["wall_s"] = wall
        key = "row_mp" if args.regions == 1024 \
            else f"row_mp_{args.regions}"
        key += f"_w{row['workers']}_r0"
        if args.no_apply_lane:
            key += "_nolane"
        out[key] = row
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    for row in rows:
        print(json.dumps({"workers": row["workers"],
                          "ops_per_sec": row["ops_per_sec"],
                          "cpu_cores_used": row["cpu_cores_used"]}),
              flush=True)


if __name__ == "__main__":
    main()
