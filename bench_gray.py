"""Gray-failure A/B: a sustained slow-disk leader at region density,
with health detection + evacuation ON vs OFF.

The fail-slow scenario the chaos harness never priced: one store's
disk turns slow (every fsync pays tens of ms) while the store stays
"alive" — at 128 regions it leads ~a third of the keyspace, and every
write it leads limps.  With the gray-failure plane ON
(StoreEngineOptions.health_scoring + evacuate_on_sick), the
HealthTracker scores the store SICK off the LogManager's own flush
timing and evacuates its leases at a bounded rate; KV put p99 must
recover toward the healthy baseline WHILE THE FAULT STILL HOLDS.
With detection OFF, p99 stays detonated for the duration.

    python bench_gray.py [--regions 128] [--workers 32] [--json]

Writes BENCH_GRAY.json: healthy/faulted/recovered p99 per arm + the
ratios the acceptance criteria key on (recovered_x <= 3 with detection
ON, faulted_x > 10 with it OFF on a quiet host).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import time

from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer
from tpuraft.storage.fault import ChaosDir


def _p(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


class _Cluster:
    def __init__(self, n_stores: int, n_regions: int, data_path: str,
                 detection: bool):
        self.net = InProcNetwork()
        self.endpoints = [f"127.0.0.1:{6400 + i}" for i in range(n_stores)]

        def bkey(k):
            return b"g%06d" % k

        self.regions = [
            Region(id=k + 1, start_key=bkey(k) if k else b"",
                   end_key=bkey(k + 1) if k + 1 < n_regions else b"",
                   peers=list(self.endpoints))
            for k in range(n_regions)]
        self.data_path = data_path
        self.detection = detection
        self.stores: dict[str, StoreEngine] = {}

    async def start(self) -> None:
        for ep in self.endpoints:
            server = RpcServer(ep)
            self.net.bind(server)
            self.net.start_endpoint(ep)
            opts = StoreEngineOptions(
                server_id=ep,
                initial_regions=[r.copy() for r in self.regions],
                data_path=self.data_path,
                election_timeout_ms=1000,
                health_scoring=self.detection,
                # detect fast relative to the measurement windows
                health_eval_interval_ms=250,
                evacuation_rate=8,
            )
            store = StoreEngine(opts, server,
                                InProcTransport(self.net, ep))
            await store.start()
            self.stores[ep] = store

    async def stop(self) -> None:
        for ep, store in list(self.stores.items()):
            self.net.stop_endpoint(ep)
            self.net.unbind(ep)
            await store.shutdown()
        self.stores.clear()

    def busiest_leader(self) -> str:
        return max(self.stores,
                   key=lambda ep: len(self.stores[ep].leader_region_ids()))


async def _run_arm(detection: bool, n_regions: int, n_workers: int,
                   data_path: str, healthy_s: float, fault_s: float,
                   seed: int) -> dict:
    # co-hosting artifact guard: all three "stores" share this
    # process's default executor, so the victim's 60ms fsyncs would
    # queue-starve the HEALTHY stores' flushes (and read as false
    # stalls) — separate processes don't have this coupling, so give
    # the bench enough threads that they don't here either
    from concurrent.futures import ThreadPoolExecutor

    asyncio.get_running_loop().set_default_executor(
        ThreadPoolExecutor(max_workers=192, thread_name_prefix="gray-io"))
    os.makedirs(data_path, exist_ok=True)
    chaos = {}
    for ep_i in range(3):
        ep = f"127.0.0.1:{6400 + ep_i}"
        ip, port = ep.rsplit(":", 1)
        chaos[ep] = ChaosDir(os.path.join(data_path,
                                          f"{ip}_{port}")).install()
    c = _Cluster(3, n_regions, data_path, detection)
    rng = random.Random(seed)
    try:
        await c.start()
        pd = FakePlacementDriverClient([r.copy() for r in c.regions])
        kv = RheaKVStore(pd, InProcTransport(c.net, "bench-client:0"),
                         timeout_ms=8000, max_retries=8,
                         batching=BatchingOptions(enabled=True),
                         jitter_seed=seed)
        await kv.start()
        keys = [b"g%06d/x" % rng.randrange(n_regions)
                for _ in range(4 * n_workers)]

        lat: list[tuple[float, float]] = []   # (t_done, latency_s)
        stop = asyncio.Event()

        async def worker(i: int):
            wrng = random.Random(seed * 977 + i)
            n = 0
            while not stop.is_set():
                n += 1
                key = wrng.choice(keys)
                t0 = time.monotonic()
                try:
                    await kv.put(key, b"v%08d" % n)
                    lat.append((time.monotonic(),
                                time.monotonic() - t0))
                except Exception:
                    # bounced past retries: count as a max-latency op so
                    # shedding can't fake a good p99 by erroring fast
                    lat.append((time.monotonic(), 8.0))
            return n

        workers = [asyncio.ensure_future(worker(i))
                   for i in range(n_workers)]

        def window_p99(t_from: float, t_to: float) -> tuple[float, int]:
            w = [d for t, d in lat if t_from <= t < t_to]
            return _p(w, 0.99) * 1000.0, len(w)

        # phase 1: healthy baseline
        t0 = time.monotonic()
        await asyncio.sleep(healthy_s)
        t_fault = time.monotonic()
        healthy_p99, healthy_n = window_p99(t0 + healthy_s * 0.3, t_fault)

        # phase 2: sustained slow disk on the busiest leader store —
        # the fault HOLDS until the end of the run
        victim = c.busiest_leader()
        led_before = len(c.stores[victim].leader_region_ids())
        chaos[victim].set_slow(fsync_ms=300, write_ms=5, jitter_ms=200,
                               seed=seed)
        await asyncio.sleep(fault_s)
        t_end = time.monotonic()
        # "faulted" = the detection/limp window right after injection;
        # "recovered" = the last 40% of the fault phase (evacuation has
        # run by then when detection is ON)
        faulted_p99, faulted_n = window_p99(t_fault,
                                            t_fault + fault_s * 0.4)
        recovered_p99, recovered_n = window_p99(t_end - fault_s * 0.4,
                                                t_end)
        stop.set()
        ops = sum(await asyncio.gather(*workers))
        victim_store = c.stores[victim]
        out = {
            "detection": detection,
            "ops": ops,
            "healthy_p99_ms": round(healthy_p99, 1),
            "faulted_p99_ms": round(faulted_p99, 1),
            "recovered_p99_ms": round(recovered_p99, 1),
            "window_ops": [healthy_n, faulted_n, recovered_n],
            "victim": victim,
            "victim_led_regions_before": led_before,
            "victim_led_regions_after":
                len(victim_store.leader_region_ids()),
            "evacuations": sum(s.evacuations for s in c.stores.values()),
            "shed_items": sum(s.kv_processor.shed_items
                              for s in c.stores.values()),
        }
        if victim_store.health is not None:
            out["victim_health"] = victim_store.health.score()
        chaos[victim].heal_slow()   # shutdown at disk speed
        await kv.shutdown()
        return out
    finally:
        await c.stop()
        for cd in chaos.values():
            cd.uninstall()


async def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--regions", type=int, default=128)
    ap.add_argument("--workers", type=int, default=32)
    ap.add_argument("--healthy-s", type=float, default=10.0)
    ap.add_argument("--fault-s", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    import tempfile

    results = {}
    for detection in (True, False):
        with tempfile.TemporaryDirectory(prefix="tpuraft-gray-") as d:
            arm = await _run_arm(detection, args.regions, args.workers, d,
                                 args.healthy_s, args.fault_s, args.seed)
        arm["faulted_x"] = round(
            arm["faulted_p99_ms"] / max(arm["healthy_p99_ms"], 0.1), 1)
        arm["recovered_x"] = round(
            arm["recovered_p99_ms"] / max(arm["healthy_p99_ms"], 0.1), 1)
        results["on" if detection else "off"] = arm
        print(json.dumps(arm), flush=True)

    record = {
        "bench": "bench_gray",
        "regions": args.regions,
        "workers": args.workers,
        "fault": "sustained slow disk on the busiest leader store "
                 "(fsync +300ms±200, write +5ms) held for the whole "
                 "fault phase",
        "arms": results,
        "claim": "with detection ON, recovered p99 is within ~3x of "
                 "healthy while the fault still holds (evacuation moved "
                 "the leases); with detection OFF it stays >10x",
    }
    with open("BENCH_GRAY.json", "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    if args.json:
        print(json.dumps(record))


if __name__ == "__main__":
    asyncio.run(main())
