"""bench_geo: commit/ack latency vs geo topology — the degradation
envelope as a committed artifact (BENCH_GEO.json).

Each row boots the soak's in-proc KV cluster under a seeded
NetworkTopology shape and measures, through a warmed leader:

- **commit** latency: a direct raft ``apply`` on the region leader,
  clocked to its commit closure — one quorum round over the shaped
  WAN, no client stack;
- **ack** latency: a full KV client ``put`` — routing + RPC + quorum +
  FSM apply + response, the end-to-end number a user sees.

Rows (the ISSUE's matrix): 3-zone (3 full replicas), 5-zone (5 full
replicas), 3-zone under degraded WAN (latency x6, +1% loss), and the
witness-vs-full comparison at 3 zones (2 data + 1 witness vs 3 full
data replicas over the SAME link shape).

    python bench_geo.py                 # all rows -> BENCH_GEO.json
    python bench_geo.py --ops 100 --out /tmp/geo.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import tempfile
import time

from examples.soak import SoakCluster
from tpuraft.entity import Task
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.pd_client import FakePlacementDriverClient


def _pct(xs: list[float], q: float) -> float:
    # SAME definition as bench_scale.py/bench_e2e.py's pct, so p99 rows
    # are comparable across the committed bench artifacts
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


def _stats(xs: list[float]) -> dict:
    return {
        "p50_ms": round(_pct(xs, 0.50), 2),
        "p99_ms": round(_pct(xs, 0.99), 2),
        "mean_ms": round(statistics.fmean(xs), 2) if xs else 0.0,
        "n": len(xs),
    }


async def run_shape(name: str, n_stores: int, zones: int, witness: bool,
                    degrade: bool, ops: int, seed: int) -> dict:
    with tempfile.TemporaryDirectory(prefix="tpuraft-geo-") as tmp:
        c = SoakCluster(n_stores, tmp, geo_zones=zones, witness=witness,
                        geo_seed=seed, election_timeout_ms=1000)
        kv = None
        try:
            for ep in c.endpoints:
                await c.start_store(ep)
            if degrade:
                c.topology.degrade_wan(latency_x=6.0, extra_loss=0.01,
                                       bandwidth_x=1.0)
            pd = FakePlacementDriverClient([r.copy() for r in c.regions])
            kv = RheaKVStore(pd, c.client_transport(), max_retries=3)
            await kv.start()
            # warm: leader elected, routes cached
            deadline = time.monotonic() + 20.0
            while c.leader_endpoint(1) is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"{name}: no leader")
                await asyncio.sleep(0.05)
            await kv.put(b"warm", b"1")
            leader_node = \
                c.stores[c.leader_endpoint(1)].get_region_engine(1).node

            commit_ms: list[float] = []
            for i in range(ops):
                fut = asyncio.get_running_loop().create_future()
                # a REAL encoded KV PUT: the region FSM applies it (raw
                # bytes would poison a KV state machine)
                blob = KVOperation(KVOp.PUT, b"geo", b"%d" % i).encode()
                t0 = time.perf_counter()
                await leader_node.apply(Task(
                    data=blob,
                    done=lambda st, f=fut: f.done() or f.set_result(st)))
                st = await asyncio.wait_for(fut, 30.0)
                if st.is_ok():
                    commit_ms.append((time.perf_counter() - t0) * 1e3)

            ack_ms: list[float] = []
            for i in range(ops):
                t0 = time.perf_counter()
                await asyncio.wait_for(
                    kv.put(b"k%03d" % (i % 16), b"v%d" % i), 30.0)
                ack_ms.append((time.perf_counter() - t0) * 1e3)

            return {
                "topology": name,
                "stores": n_stores,
                "zones": zones,
                "witness": witness,
                "degraded_wan": degrade,
                "commit": _stats(commit_ms),
                "ack": _stats(ack_ms),
                "topology_counters": dict(c.topology.counters),
            }
        finally:
            if kv is not None:
                await kv.shutdown()
            for ep in list(c.stores):
                await c.stop_store(ep)
            ct = getattr(c, "_client_t", None)
            if ct is not None and hasattr(ct, "close"):
                await ct.close()


SHAPES = [
    # (name, stores, zones, witness, degrade)
    ("3-zone", 3, 3, False, False),
    ("5-zone", 5, 5, False, False),
    ("3-zone-degraded-wan", 3, 3, False, True),
    ("3-zone-witness-2+1", 3, 3, True, False),
]


async def main_async(args) -> dict:
    rows = []
    for name, stores, zones, witness, degrade in SHAPES:
        ops = max(10, args.ops // (6 if degrade else 1))
        row = await run_shape(name, stores, zones, witness, degrade,
                              ops, args.seed)
        rows.append(row)
        print(json.dumps(row), flush=True)
    return {
        "bench": "geo",
        "seed": args.seed,
        "ops_per_row": args.ops,
        "link_shape": {"intra_ms": 0.2, "base_wan_ms": 3.0,
                       "jitter_ms": 1.0, "loss": 0.001,
                       "degrade": "latency x6, +1% loss"},
        "rows": rows,
        "note": ("commit = raft apply->commit closure at the leader "
                 "(one shaped-WAN quorum round); ack = full KV client "
                 "put.  witness row: 2 data + 1 witness — the quorum "
                 "ack may come from the witness's metadata append, so "
                 "commit cost matches the 3-full-replica row without a "
                 "third data copy."),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ops", type=int, default=150,
                    help="ops per row (degraded rows run 1/6th)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default="BENCH_GEO.json")
    args = ap.parse_args()
    result = asyncio.run(main_async(args))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
