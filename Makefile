# tpuraft CI recipe (SURVEY.md §6 "race detection / sanitizers" +
# VERDICT r1 weak #7: reproducible in-repo automation).
#
#   make            -> build the native engines (release .so's)
#   make check      -> sanitizer-instrumented native torture drivers
#                      (TSAN + ASAN/UBSAN x 3 engines), the full Python
#                      test suite, and a short linearizability soak
#   make test       -> Python suite only
#   make san        -> sanitizer drivers only
#   make bench      -> the device-plane headline benchmark (one JSON line)

PY ?= python

all: native

native:
	$(MAKE) -C native

san:
	$(MAKE) -C native check-native

test:
	$(PY) -m pytest tests/ -q

soak:
	$(PY) -m examples.soak --duration 30 --seed 1

check: san test soak
	@echo "make check: native sanitizers + suite + soak all green"

bench:
	$(PY) bench.py

clean:
	$(MAKE) -C native clean

.PHONY: all native san test soak check bench clean
