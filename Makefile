# tpuraft CI recipe (SURVEY.md §6 "race detection / sanitizers" +
# VERDICT r1 weak #7: reproducible in-repo automation).
#
#   make            -> build the native engines (release .so's)
#   make check      -> graftcheck lint, sanitizer-instrumented native
#                      torture drivers (TSAN + ASAN/UBSAN x 3 engines),
#                      the full Python test suite, and a short
#                      linearizability soak
#   make test       -> Python suite only
#   make lint       -> graftcheck static analysis over tpuraft/ (lock
#                      discipline, lock-order cycles, wire-schema drift,
#                      blocking-call + future-leak lints, interprocedural
#                      transitive-blocking + loop-affinity, [G] lane-site
#                      coverage, host-sync + donated-read); <10s
#   make san        -> sanitizer drivers only
#   make chaos-smoke-> storage-plane crash-consistency harness + short
#                      power-loss soak + multi-process chaos soak
#                      (leader SIGKILL -> supervised restart ->
#                      linearizable history)
#   make bench      -> the device-plane headline benchmark (one JSON line)
#   make bench-gate -> short e2e + KV serving benches; fails on >20%
#                      regression vs the committed BENCH_E2E.json /
#                      BENCH_REGIONS.json calibrations, plus the
#                      tracing-overhead row: untraced rows enforce the
#                      trace plane's zero-cost-when-disabled claim, and
#                      a 5%-sampled tracing run must stay within 5% of
#                      the same-session untraced measurement

PY ?= python

all: native

native:
	$(MAKE) -C native

san:
	$(MAKE) -C native check-native

test:
	$(PY) -m pytest tests/ -q

# graftcheck: the Python plane's analog of `make san` (PAPER.md §6 race
# detection) — eight AST checkers for the defect classes the chaos
# harness kept catching dynamically (PR 2 storage lock races + wedged
# waiters, PR 3 wire drift, PR 10's hand-wired lane lifecycle sites).
# v2 adds a whole-program pass: call-graph summary propagation makes
# the blocking/loop-confined/holds rules transitive, infers executor
# contexts, and the device-plane lint covers [G] lane lifecycle sites,
# host syncs in jitted bodies, and donated-buffer reads.  raw-clock
# keeps consensus-path timing on the injectable store clock (raw
# time.monotonic()/time.time() in tpuraft/core + tpuraft/rheakv needs
# a reasoned waiver; docs/operations.md "Clock discipline runbook").
# Intentional
# wire/lock-order changes: review, then `python -m tpuraft.analysis
# --record` and commit the lockfiles (docs/operations.md "Static
# analysis & wire-format changes").  `--json` for CI annotation.
lint:
	$(PY) -m tpuraft.analysis

soak:
	$(PY) -m examples.soak --duration 30 --seed 1

# Crash-consistency smoke (<2min, tier-1-safe): the storage-plane fault
# harness (~260 seeded power-loss crashes over FileLogStorage, the meta
# journal and the native multilog), the membership-chaos harness
# (joint-consensus invariants under seeded crashes), plus short soaks
# with power-loss faults and membership churn in the nemesis menu
# (docs/operations.md "Crash-consistency testing" + "Elastic
# membership runbook"), a short disk-pressure soak (quota shrink +
# ENOSPC bursts -> reclaim/shed/resume; "Disk-pressure runbook"), a
# short time-chaos soak (per-store clock drift/jump/freeze + leader
# kills under a lease-read mix; "Clock discipline runbook"), and a
# region-lifecycle soak (PD-driven heat splits, cold merges, cross-
# store moves under a shifting zipfian hotspot, with a keyspace-
# coverage oracle between every actuation; "Region lifecycle
# runbook").
chaos-smoke:
	$(PY) -m pytest tests/test_storage_fault.py tests/test_membership_chaos.py tests/test_quiescence.py tests/test_witness.py tests/test_read_only.py tests/test_gray_failure.py tests/test_append_batch.py tests/test_region_lifecycle.py -q
	$(PY) -m examples.soak --duration 20 --seed 1 --power-loss
	$(PY) -m examples.soak --duration 20 --seed 8 --write-burst --power-loss
	$(PY) -m examples.soak --duration 20 --seed 3 --churn --power-loss
	$(PY) -m examples.soak --duration 20 --seed 5 --regions 48 --engine --quiesce --kv-batching
	$(PY) -m examples.soak --duration 20 --seed 2 --geo 3 --witness
	$(PY) -m examples.proc_supervisor --soak --seconds 6 --apply-lane
	$(PY) -m examples.soak --duration 20 --seed 4 --read-mix 0.95 --kv-batching
	$(PY) -m examples.soak --duration 20 --seed 6 --gray
	$(PY) -m examples.soak --duration 16 --seed 7 --regions 24 --hotspot
	$(PY) -m examples.soak --duration 20 --seed 5 --disk-pressure
	$(PY) -m examples.soak --duration 20 --seed 9 --clock-chaos --lease-reads --read-mix 0.7
	$(PY) -m examples.soak --duration 20 --seed 11 --regions 12 --lifecycle

# The PRE-MERGE bar for consensus-path changes (VERDICT r2 weak #6):
# the multi-minute chaos soaks are what actually catch protocol bugs
# (the r1 stale-read bug fell to one) — the 30s `make check` soak
# exercises ~1/10th of that.  Runs three seeds x 2 minutes.
soak-long:
	$(PY) -m examples.soak --duration 120 --seed 1
	$(PY) -m examples.soak --duration 120 --seed 7
	$(PY) -m examples.soak --duration 120 --seed 42

# Perf regression gate, two rows: (1) a short bench_e2e.py run at the
# committed BENCH_E2E.json configuration fails if e2e commits/s
# regresses >20% vs the committed same-shape calibration
# (extra.gate_commits_per_sec); (2) a short bench_region_density.py run
# fails if KV ops/s through the full serving stack regresses >20% vs
# BENCH_REGIONS.json extra.gate_kv_ops_per_sec — the KV-vs-protocol gap
# (ROADMAP #1) can't silently reopen.  Re-record both with
# `python bench_gate.py --record`.  A below-floor run retries best-of-3
# before failing so shared-host noise doesn't flap CI.  Threshold/
# duration/retries via BENCH_GATE_THRESHOLD / BENCH_GATE_DURATION /
# BENCH_GATE_RETRIES env.
bench-gate:
	$(PY) bench_gate.py

# Mesh-mode lane-parity dryrun: 8 virtual CPU devices, one sharded
# engine plane, and an assertion per [G] lane (witness commit clamp,
# stepdown/priority ticks, device read fences, election delivery) —
# the group-axis sharding can't silently drop a protocol lane.
multichip-smoke:
	JAX_PLATFORMS=cpu $(PY) bench_multichip.py --smoke

check: lint san test soak multichip-smoke bench-gate
	@echo "make check: lint + native sanitizers + suite + soak + perf gate all green"
	@echo "(consensus-path changes: also run make soak-long before merge;"
	@echo " storage-path changes: also run make chaos-smoke)"

bench:
	$(PY) bench.py

clean:
	$(MAKE) -C native clean

.PHONY: all native san test lint soak chaos-smoke check bench bench-gate multichip-smoke clean
