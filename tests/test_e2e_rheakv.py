"""Multi-process RheaKV end-to-end: 3 store OS processes over real TCP,
a client in this process, and a kill -9 of a leader store.

The KV-tier analog of test_e2e_counter (reference: running the rheakv
server example on three machines — SURVEY.md §3.3).
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.asyncio
@pytest.mark.parametrize("stack", [
    [],                                           # tcp + memory KV + file log
    ["--transport", "native", "--store", "native",
     "--log-scheme", "multilog"],                 # FULL native + shared journal
], ids=["default", "native-multilog"])
async def test_three_process_kv_cluster_kill9_leader(tmp_path, stack):
    if stack:
        from tpuraft.rpc.native_tcp import ensure_built as build_t
        from tpuraft.rheakv.native_store import ensure_built as build_kv
        from tpuraft.storage.multilog import ensure_built as build_ml

        build_t(); build_kv(); build_ml()
    ports = _free_ports(3)
    stores = [f"127.0.0.1:{p}" for p in ports]
    procs: dict[int, subprocess.Popen] = {}
    env = dict(os.environ, PYTHONPATH=REPO)
    try:
        for p, ep in zip(ports, stores):
            procs[p] = subprocess.Popen(
                [sys.executable, "-m", "examples.rheakv_server",
                 "--serve", ep, "--stores", ",".join(stores),
                 "--regions", "2", "--data", str(tmp_path / str(p))]
                + stack,
                cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        from examples.rheakv_server import client_for

        kv = client_for(stores, 2, timeout_ms=3000)
        await kv.start()
        try:
            # ride out interpreter boot (~2s each) + first elections
            deadline = time.monotonic() + 60
            ok = False
            while time.monotonic() < deadline:
                try:
                    ok = await kv.put(b"\x10boot", b"up")
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert ok, "cluster never became writable"

            import struct
            keys = [struct.pack(">I", i * 0x30000000) for i in range(5)]
            for i, k in enumerate(keys):       # both regions
                assert await kv.put(k, b"v%d" % i)
            for i, k in enumerate(keys):
                assert await kv.get(k) == b"v%d" % i

            # SIGKILL whichever store currently leads region 1
            leader_ep = kv._leaders.get(1) or stores[0]
            port = int(leader_ep.split(":")[1].split("/")[0])
            procs[port].send_signal(signal.SIGKILL)
            procs[port].wait()

            # survivors re-elect; acked data survives the hard crash
            deadline = time.monotonic() + 30
            v = None
            while time.monotonic() < deadline:
                try:
                    v = await kv.get(keys[0])
                    if v is not None:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.5)
            assert v == b"v0", v
            for i, k in enumerate(keys):
                got = None
                for _ in range(20):
                    try:
                        got = await kv.get(k)
                        break
                    except Exception:
                        await asyncio.sleep(0.5)
                assert got == b"v%d" % i, (i, got)
            # and it still accepts writes
            wrote = False
            for _ in range(20):
                try:
                    wrote = await kv.put(b"\x20after", b"crash")
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert wrote
            assert await kv.get(b"\x20after") == b"crash"
        finally:
            await kv.shutdown()
            await kv.transport.close()
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs.values():
            proc.wait()
