"""CLI service + RouteTable integration tests.

Reference parity: ``test:core/CliServiceTest`` and ``test:core/RouteTableTest``
run against a TestCluster (SURVEY.md §5 "CLI/route" row).
"""

import asyncio
import contextlib

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliService
from tpuraft.route_table import RouteTable


@contextlib.asynccontextmanager
async def cluster3(tmp_path=None, **kw):
    c = TestCluster(3, tmp_path=tmp_path, **kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


async def test_get_leader_and_peers(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        got = await cli.get_leader(c.group_id, c.conf)
        assert got == leader.server_id
        peers = await cli.get_peers(c.group_id, c.conf)
        assert sorted(map(str, peers)) == sorted(map(str, c.peers))


async def test_transfer_leader_via_cli(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        target = next(p for p in c.peers if p != leader.server_id)
        st = await cli.transfer_leader(c.group_id, c.conf, target)
        assert st.is_ok(), st
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if (await cli.get_leader(c.group_id, c.conf)) == target:
                break
            await asyncio.sleep(0.05)
        assert (await cli.get_leader(c.group_id, c.conf)) == target


async def test_remove_and_add_peer_via_cli(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        victim = next(p for p in c.peers if p != leader.server_id)
        st = await cli.remove_peer(c.group_id, c.conf, victim)
        assert st.is_ok(), st
        peers = await cli.get_peers(c.group_id, c.conf)
        assert victim not in peers and len(peers) == 2
        st = await cli.add_peer(c.group_id, Configuration(peers), victim)
        assert st.is_ok(), st
        peers = await cli.get_peers(c.group_id, c.conf)
        assert victim in peers and len(peers) == 3


async def test_snapshot_via_cli(tmp_path):
    async with cluster3(tmp_path, snapshot=True) as c:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"x")
        cli = CliService(c.client_transport())
        st = await cli.snapshot(c.group_id, leader.server_id)
        assert st.is_ok(), st


async def test_cli_follows_leader_redirect(tmp_path):
    """Ops issued while the cached leader is stale must refresh + retry."""
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        await cli.get_leader(c.group_id, c.conf)  # warm the cache
        target = next(p for p in c.peers if p != leader.server_id)
        assert (await leader.transfer_leadership_to(target)).is_ok()
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if c.nodes[target].is_leader():
                break
            await asyncio.sleep(0.05)
        third = next(p for p in c.peers
                     if p not in (leader.server_id, target))
        st = await cli.transfer_leader(c.group_id, c.conf, third)
        assert st.is_ok(), st


async def test_route_table_refresh(tmp_path):
    async with cluster3(tmp_path) as c:
        await c.wait_leader()
        rt = RouteTable()
        assert rt.update_configuration(
            c.group_id, ",".join(str(p) for p in c.peers))
        cli = CliService(c.client_transport())
        st = await rt.refresh_leader(cli, c.group_id)
        assert st.is_ok(), st
        leader = rt.select_leader(c.group_id)
        assert leader is not None and c.nodes[leader].is_leader()
        st = await rt.refresh_configuration(cli, c.group_id)
        assert st.is_ok(), st
        conf = rt.get_configuration(c.group_id)
        assert sorted(map(str, conf.list_all())) == sorted(map(str, c.peers))


async def test_route_table_unknown_group():
    rt = RouteTable()
    assert rt.select_leader("nope") is None
    st = await rt.refresh_leader(CliService(None), "nope")
    assert not st.is_ok()


async def test_cli_message_codec_roundtrip():
    from tpuraft.rpc.cli_messages import ChangePeersRequest, CliResponse
    from tpuraft.rpc.messages import decode_message, encode_message

    req = ChangePeersRequest(group_id="g", peer_id="1.2.3.4:80",
                             new_peers=["a:1", "b:2"])
    assert decode_message(encode_message(req)) == req
    resp = CliResponse(code=0, msg="", old_peers=["a:1"], new_peers=["b:2"])
    assert decode_message(encode_message(resp)) == resp


async def test_rebalance(tmp_path):
    async with cluster3(tmp_path) as c:
        await c.wait_leader()
        cli = CliService(c.client_transport())
        st = await cli.rebalance([c.group_id], c.conf)
        assert st.is_ok(), st


async def test_reset_learners_via_cli(tmp_path):
    """`[1.3+]` CliService#resetLearners: replace the whole learner set
    in one joint-consensus change."""
    from tpuraft.entity import PeerId

    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        l1 = PeerId.parse("127.0.0.1:5103")
        l2 = PeerId.parse("127.0.0.1:5104")
        c.peers.append(l1)
        await c.start(l1)
        c.peers.append(l2)
        await c.start(l2)
        st = await cli.add_learners(c.group_id, c.conf, [l1])
        assert st.is_ok(), st
        assert await cli.get_learners(c.group_id, c.conf) == [l1]
        # reset: l1 out, l2 in — one atomic change
        st = await cli.reset_learners(c.group_id, c.conf, [l2])
        assert st.is_ok(), st
        assert await cli.get_learners(c.group_id, c.conf) == [l2]
        # the new learner replicates; the removed one stops receiving
        st = await c.apply_ok(leader, b"post-reset")
        assert st.is_ok(), st
        for _ in range(100):
            if b"post-reset" in c.fsms[l2].logs:
                break
            await asyncio.sleep(0.02)
        assert b"post-reset" in c.fsms[l2].logs
        assert l2 in c.nodes[leader.server_id].list_learners()
        assert l1 not in c.nodes[leader.server_id].list_learners()
