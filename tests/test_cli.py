"""CLI service + RouteTable integration tests.

Reference parity: ``test:core/CliServiceTest`` and ``test:core/RouteTableTest``
run against a TestCluster (SURVEY.md §5 "CLI/route" row).
"""

import asyncio
import contextlib

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliService
from tpuraft.route_table import RouteTable


@contextlib.asynccontextmanager
async def cluster3(tmp_path=None, **kw):
    c = TestCluster(3, tmp_path=tmp_path, **kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


async def test_get_leader_and_peers(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        got = await cli.get_leader(c.group_id, c.conf)
        assert got == leader.server_id
        peers = await cli.get_peers(c.group_id, c.conf)
        assert sorted(map(str, peers)) == sorted(map(str, c.peers))


async def test_transfer_leader_via_cli(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        target = next(p for p in c.peers if p != leader.server_id)
        st = await cli.transfer_leader(c.group_id, c.conf, target)
        assert st.is_ok(), st
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if (await cli.get_leader(c.group_id, c.conf)) == target:
                break
            await asyncio.sleep(0.05)
        assert (await cli.get_leader(c.group_id, c.conf)) == target


async def test_remove_and_add_peer_via_cli(tmp_path):
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        victim = next(p for p in c.peers if p != leader.server_id)
        st = await cli.remove_peer(c.group_id, c.conf, victim)
        assert st.is_ok(), st
        peers = await cli.get_peers(c.group_id, c.conf)
        assert victim not in peers and len(peers) == 2
        st = await cli.add_peer(c.group_id, Configuration(peers), victim)
        assert st.is_ok(), st
        peers = await cli.get_peers(c.group_id, c.conf)
        assert victim in peers and len(peers) == 3


async def test_snapshot_via_cli(tmp_path):
    async with cluster3(tmp_path, snapshot=True) as c:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"x")
        cli = CliService(c.client_transport())
        st = await cli.snapshot(c.group_id, leader.server_id)
        assert st.is_ok(), st


async def test_cli_follows_leader_redirect(tmp_path):
    """Ops issued while the cached leader is stale must refresh + retry."""
    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        await cli.get_leader(c.group_id, c.conf)  # warm the cache
        target = next(p for p in c.peers if p != leader.server_id)
        assert (await leader.transfer_leadership_to(target)).is_ok()
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if c.nodes[target].is_leader():
                break
            await asyncio.sleep(0.05)
        third = next(p for p in c.peers
                     if p not in (leader.server_id, target))
        st = await cli.transfer_leader(c.group_id, c.conf, third)
        assert st.is_ok(), st


async def test_route_table_refresh(tmp_path):
    async with cluster3(tmp_path) as c:
        await c.wait_leader()
        rt = RouteTable()
        assert rt.update_configuration(
            c.group_id, ",".join(str(p) for p in c.peers))
        cli = CliService(c.client_transport())
        st = await rt.refresh_leader(cli, c.group_id)
        assert st.is_ok(), st
        leader = rt.select_leader(c.group_id)
        assert leader is not None and c.nodes[leader].is_leader()
        st = await rt.refresh_configuration(cli, c.group_id)
        assert st.is_ok(), st
        conf = rt.get_configuration(c.group_id)
        assert sorted(map(str, conf.list_all())) == sorted(map(str, c.peers))


async def test_route_table_unknown_group():
    rt = RouteTable()
    assert rt.select_leader("nope") is None
    st = await rt.refresh_leader(CliService(None), "nope")
    assert not st.is_ok()


async def test_cli_message_codec_roundtrip():
    from tpuraft.rpc.cli_messages import ChangePeersRequest, CliResponse
    from tpuraft.rpc.messages import decode_message, encode_message

    req = ChangePeersRequest(group_id="g", peer_id="1.2.3.4:80",
                             new_peers=["a:1", "b:2"])
    assert decode_message(encode_message(req)) == req
    resp = CliResponse(code=0, msg="", old_peers=["a:1"], new_peers=["b:2"])
    assert decode_message(encode_message(resp)) == resp


async def test_rebalance(tmp_path):
    async with cluster3(tmp_path) as c:
        await c.wait_leader()
        cli = CliService(c.client_transport())
        st = await cli.rebalance([c.group_id], c.conf)
        assert st.is_ok(), st


async def test_reset_learners_via_cli(tmp_path):
    """`[1.3+]` CliService#resetLearners: replace the whole learner set
    in one joint-consensus change."""
    from tpuraft.entity import PeerId

    async with cluster3(tmp_path) as c:
        leader = await c.wait_leader()
        cli = CliService(c.client_transport())
        l1 = PeerId.parse("127.0.0.1:5103")
        l2 = PeerId.parse("127.0.0.1:5104")
        c.peers.append(l1)
        await c.start(l1)
        c.peers.append(l2)
        await c.start(l2)
        st = await cli.add_learners(c.group_id, c.conf, [l1])
        assert st.is_ok(), st
        assert await cli.get_learners(c.group_id, c.conf) == [l1]
        # reset: l1 out, l2 in — one atomic change
        st = await cli.reset_learners(c.group_id, c.conf, [l2])
        assert st.is_ok(), st
        assert await cli.get_learners(c.group_id, c.conf) == [l2]
        # the new learner replicates; the removed one stops receiving
        st = await c.apply_ok(leader, b"post-reset")
        assert st.is_ok(), st
        for _ in range(100):
            if b"post-reset" in c.fsms[l2].logs:
                break
            await asyncio.sleep(0.02)
        assert b"post-reset" in c.fsms[l2].logs
        assert l2 in c.nodes[leader.server_id].list_learners()
        assert l1 not in c.nodes[leader.server_id].list_learners()


class _BusyLeaderTransport:
    """Fake wire: one fixed leader that answers change ops EBUSY a set
    number of times before accepting — the shape a leader mid-membership-
    change presents to the admin client."""

    def __init__(self, busy_answers: int):
        from tpuraft.errors import RaftError

        self.busy_left = busy_answers
        self.leader = "127.0.0.1:5100"
        self.op_calls = 0
        self._ebusy = int(RaftError.EBUSY)

    async def call(self, dst, method, req, timeout_ms=None):
        from tpuraft.rpc.cli_messages import CliResponse, GetLeaderResponse

        if method == "cli_get_leader":
            return GetLeaderResponse(leader_id=self.leader, success=True)
        self.op_calls += 1
        if self.busy_left > 0:
            self.busy_left -= 1
            return CliResponse(code=self._ebusy,
                               msg="another membership change in progress")
        return CliResponse(code=0)


async def test_cli_busy_backoff_retries_until_change_completes():
    """EBUSY is transient by contract: the CLI retries with its own
    bounded backoff budget (not max_retry), keeps the cached leader, and
    succeeds once the in-flight change drains."""
    from tpuraft.entity import PeerId
    from tpuraft.options import CliOptions

    t = _BusyLeaderTransport(busy_answers=3)
    cli = CliService(t, CliOptions(busy_max_retry=5, busy_backoff_ms=1,
                                   busy_backoff_max_ms=4))
    conf = Configuration([PeerId.parse(t.leader)])
    st = await cli.add_peer("g", conf, PeerId.parse("127.0.0.1:5101"))
    assert st.is_ok(), st
    assert t.op_calls == 4  # 3 busy answers + the accepted attempt
    # busy retries did NOT evict the leader cache
    assert cli._leaders.get("g") == PeerId.parse(t.leader)


async def test_cli_busy_budget_exhausted_returns_ebusy():
    """A persistently busy leader yields a structured EBUSY (so the
    operator knows to just retry later), not EAGAIN/EPERM."""
    from tpuraft.errors import RaftError
    from tpuraft.entity import PeerId
    from tpuraft.options import CliOptions

    t = _BusyLeaderTransport(busy_answers=99)
    cli = CliService(t, CliOptions(busy_max_retry=2, busy_backoff_ms=1,
                                   busy_backoff_max_ms=2))
    conf = Configuration([PeerId.parse(t.leader)])
    st = await cli.add_peer("g", conf, PeerId.parse("127.0.0.1:5101"))
    assert st.raft_error == RaftError.EBUSY, st
    assert "still busy" in st.error_msg
    assert t.op_calls == 3  # initial attempt + busy_max_retry retries


def test_describe_status_classifies_operator_outcomes():
    """describe_status: 'busy, retry' reads differently from 'your conf
    is wrong' — the admin CLI's exit-code policy builds on this."""
    from tpuraft.core.cli_service import describe_status
    from tpuraft.errors import RaftError, Status

    assert describe_status(Status.OK()) == "OK"
    busy = describe_status(Status.error(RaftError.EBUSY, "change in flight"))
    assert "EBUSY" in busy and "retry" in busy
    bad = describe_status(Status.error(RaftError.EINVAL, "dup peer"))
    assert "EINVAL" in bad and "configuration" in bad
    catchup = describe_status(Status.error(RaftError.ECATCHUP, "no"))
    assert "ECATCHUP" in catchup and "catch up" in catchup
