"""Write-plane batching tests (ISSUE 15): AppendBatcher rounds, the
store_append wire pair, event-driven (eager) commit advancement, and
the ack-at-commit pipelined apply.

Mirrors the shape of test_read_only.py's ReadConfirmBatcher battery:
scripted-transport unit tests for the batcher's round/window/fallback
mechanics, engine-level tests for the eager commit tally (incl. the
joint-consensus both-quorums rule), and real-cluster integration for
the safety edges (leader deposed mid-round, end-to-end replication
through store_append rounds)."""

from __future__ import annotations

import asyncio
from types import SimpleNamespace

import pytest

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.core.append_batcher import AppendBatcher
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions
from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    AppendEntriesResponse,
    ErrorResponse,
    StoreAppendRequest,
    StoreAppendResponse,
    decode_message,
    encode_message,
)
from tpuraft.rpc.transport import RpcError

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# unit plane: scripted transports + fake replicators
# ---------------------------------------------------------------------------


class _Rep:
    """Fake replicator: records resolutions, same submit contract."""

    def __init__(self, node, peer: PeerId):
        self._node = node
        self.peer = peer
        self.resolved: list[list] = []
        self.errors = 0

    async def on_batch_responses(self, acks: list) -> None:
        self.resolved.append(list(acks))

    async def on_batch_error(self) -> None:
        self.errors += 1


class _AppendTransport:
    """store_append stub: per-dst scripted acks (or exceptions)."""

    def __init__(self, fail_dst=None, no_method_dst=None):
        self.fail_dst = fail_dst or set()
        self.no_method_dst = no_method_dst or set()
        self.calls: list[tuple[str, str, int]] = []
        self.legacy_appends: list[tuple[str, str]] = []

    async def call(self, dst, method, request, timeout_ms=None):
        assert method == "store_append"
        self.calls.append((dst, method, len(request.rows)))
        if dst in self.no_method_dst:
            raise RpcError(Status.error(RaftError.ENOMETHOD, "old build"))
        if dst in self.fail_dst:
            raise RpcError(Status.error(RaftError.EHOSTDOWN, "dead"))
        return StoreAppendResponse(acks=[
            AppendEntriesResponse(term=r.term, success=True,
                                  last_log_index=r.prev_log_index
                                  + len(r.entries))
            for r in request.rows])

    async def append_entries(self, dst, req, timeout_ms=None):
        # legacy per-frame fallback path (sequential_appends)
        self.legacy_appends.append((dst, req.group_id))
        return AppendEntriesResponse(term=req.term, success=True,
                                     last_log_index=req.prev_log_index
                                     + len(req.entries))


def _node(transport) -> SimpleNamespace:
    return SimpleNamespace(transport=transport,
                           options=NodeOptions(election_timeout_ms=200))


def _req(gid: str, peer: PeerId, prev: int = 0) -> AppendEntriesRequest:
    return AppendEntriesRequest(
        group_id=gid, server_id="127.0.0.1:9000", peer_id=str(peer),
        term=3, prev_log_index=prev, prev_log_term=0, committed_index=0,
        entries=[])


def _peer(port: int) -> PeerId:
    return PeerId.parse(f"127.0.0.1:{port}")


async def test_batcher_amortizes_many_groups_into_one_round():
    """The tentpole: N groups' windows headed for the same follower
    endpoint cost ONE store_append RPC, not one RPC per group."""
    transport = _AppendTransport()
    node = _node(transport)
    dst_a, dst_b = _peer(9101), _peer(9102)
    reps = [_Rep(node, dst_a if i % 2 == 0 else dst_b) for i in range(16)]
    b = AppendBatcher()
    for i, rep in enumerate(reps):
        b.submit_append(rep, [_req(f"g{i}", rep.peer)])
    # wait for every rep to resolve
    for _ in range(200):
        if all(r.resolved for r in reps):
            break
        await asyncio.sleep(0.01)
    assert all(len(r.resolved) == 1 and len(r.resolved[0]) == 1
               for r in reps)
    # one RPC per destination, 8 groups' rows each
    assert sorted(transport.calls) == sorted(
        [(dst_a.endpoint, "store_append", 8),
         (dst_b.endpoint, "store_append", 8)])
    assert b.rounds == 2 and b.rows == 16


async def test_batcher_multi_frame_window_resolves_as_one_unit():
    transport = _AppendTransport()
    node = _node(transport)
    dst = _peer(9111)
    rep = _Rep(node, dst)
    b = AppendBatcher()
    b.submit_append(rep, [_req("g0", dst, prev=0), _req("g0", dst, prev=4)])
    for _ in range(100):
        if rep.resolved:
            break
        await asyncio.sleep(0.01)
    assert len(rep.resolved) == 1 and len(rep.resolved[0]) == 2
    assert transport.calls == [(dst.endpoint, "store_append", 2)]


class _StallTransport(_AppendTransport):
    """One destination is STALLED (not dead): RPCs block until
    release — the gray-failure shape a timeout never sees in time."""

    def __init__(self, stalled: set[str]):
        super().__init__()
        self.stalled = stalled
        self.release = asyncio.Event()

    async def call(self, dst, method, request, timeout_ms=None):
        if dst in self.stalled:
            self.calls.append((dst, method, len(request.rows)))
            await self.release.wait()
            return StoreAppendResponse(acks=[
                AppendEntriesResponse(term=r.term, success=True,
                                      last_log_index=r.prev_log_index
                                      + len(r.entries))
                for r in request.rows])
        return await super().call(dst, method, request, timeout_ms)


async def test_stalled_endpoint_delays_only_its_own_lane():
    """Windowing bound: a stalled destination's round keeps only ITS
    lane waiting — windows to healthy destinations submitted afterwards
    keep shipping round after round."""
    stalled_dst, fast_dst = _peer(9201), _peer(9210)
    transport = _StallTransport({stalled_dst.endpoint})
    node = _node(transport)
    b = AppendBatcher()
    stalled_rep = _Rep(node, stalled_dst)
    b.submit_append(stalled_rep, [_req("slow", stalled_dst)])
    await asyncio.sleep(0.05)   # its round is in flight, stalled
    assert not stalled_rep.resolved

    for i in range(5):
        rep = _Rep(node, fast_dst)
        b.submit_append(rep, [_req(f"fast{i}", fast_dst)])
        for _ in range(100):
            if rep.resolved:
                break
            await asyncio.sleep(0.01)
        assert rep.resolved, f"healthy window {i} convoyed behind stall"
    assert not stalled_rep.resolved
    transport.release.set()
    for _ in range(100):
        if stalled_rep.resolved:
            break
        await asyncio.sleep(0.01)
    assert stalled_rep.resolved


async def test_window_bounds_rounds_per_destination():
    """max_inflight_rounds stalled rounds on one lane: the next window
    waits for a slot (no unbounded RPC pileup at a limping endpoint)
    and ships the moment one opens."""
    dst = _peer(9301)
    transport = _StallTransport({dst.endpoint})
    node = _node(transport)
    b = AppendBatcher()
    assert b.max_inflight_rounds == 4
    reps = []
    for i in range(4):
        rep = _Rep(node, dst)
        reps.append(rep)
        b.submit_append(rep, [_req(f"g{i}", dst)])
        await asyncio.sleep(0.02)   # one round each, all stalled
    assert len(b._inflight[dst.endpoint]) == 4
    late = _Rep(node, dst)
    b.submit_append(late, [_req("late", dst)])
    await asyncio.sleep(0.05)
    assert len(transport.calls) == 4, "5th round ran past the window"
    transport.release.set()
    for _ in range(200):
        if late.resolved and all(r.resolved for r in reps):
            break
        await asyncio.sleep(0.01)
    assert late.resolved and all(r.resolved for r in reps)


async def test_enomethod_fallback_sticks_and_counts():
    """A receiver without store_append answers ENOMETHOD: the batch is
    resent as classic per-group append_entries and the endpoint stays
    legacy PERMANENTLY (no re-probe per round)."""
    dst = _peer(9401)
    transport = _AppendTransport(no_method_dst={dst.endpoint})
    node = _node(transport)
    b = AppendBatcher()
    rep = _Rep(node, dst)
    b.submit_append(rep, [_req("g0", dst)])
    for _ in range(100):
        if rep.resolved:
            break
        await asyncio.sleep(0.01)
    assert rep.resolved and rep.errors == 0
    assert b.fallbacks == 1 and b.legacy_rows == 1
    assert len(transport.calls) == 1          # one probe, then legacy
    assert transport.legacy_appends == [(dst.endpoint, "g0")]
    # second window: straight to legacy, no store_append attempt
    rep2 = _Rep(node, dst)
    b.submit_append(rep2, [_req("g1", dst)])
    for _ in range(100):
        if rep2.resolved:
            break
        await asyncio.sleep(0.01)
    assert rep2.resolved
    assert len(transport.calls) == 1
    assert b.legacy_rows == 2
    assert transport.legacy_appends[-1] == (dst.endpoint, "g1")


async def test_dead_endpoint_fails_batch_not_silence():
    dst = _peer(9501)
    transport = _AppendTransport(fail_dst={dst.endpoint})
    node = _node(transport)
    b = AppendBatcher()
    rep = _Rep(node, dst)
    b.submit_append(rep, [_req("g0", dst)])
    for _ in range(100):
        if rep.errors:
            break
        await asyncio.sleep(0.01)
    assert rep.errors == 1 and not rep.resolved
    assert b.round_errors == 1


async def test_short_reply_fails_whole_round():
    dst = _peer(9601)

    class ShortTransport(_AppendTransport):
        async def call(self, dst, method, request, timeout_ms=None):
            return StoreAppendResponse(acks=[])   # truncated

    transport = ShortTransport()
    node = _node(transport)
    b = AppendBatcher()
    rep = _Rep(node, dst)
    b.submit_append(rep, [_req("g0", dst)])
    for _ in range(100):
        if rep.errors:
            break
        await asyncio.sleep(0.01)
    assert rep.errors == 1
    assert b.round_errors == 1


async def test_deviating_and_rejected_row_counters():
    dst = _peer(9701)

    class MixedTransport(_AppendTransport):
        async def call(self, dst, method, request, timeout_ms=None):
            acks = [ErrorResponse(int(RaftError.EBUSY), "busy"),
                    AppendEntriesResponse(term=3, success=False,
                                          last_log_index=0)]
            return StoreAppendResponse(acks=acks)

    transport = MixedTransport()
    node = _node(transport)
    b = AppendBatcher()
    rep = _Rep(node, dst)
    b.submit_append(rep, [_req("g0", dst), _req("g0", dst, prev=1)])
    for _ in range(100):
        if rep.resolved:
            break
        await asyncio.sleep(0.01)
    assert rep.resolved    # resolution is the replicator's job
    assert b.deviating_rows == 1 and b.rejected_rows == 1


# ---------------------------------------------------------------------------
# wire plane: the store_append pair, both directions
# ---------------------------------------------------------------------------


def test_store_append_wire_roundtrip():
    rows = [_req("g0", _peer(9801)),
            AppendEntriesRequest(group_id="g1", server_id="a", peer_id="b",
                                 term=9, prev_log_index=4, prev_log_term=2,
                                 committed_index=3, entries=[],
                                 trace_ctx=b"\x01\x02")]
    req = decode_message(encode_message(StoreAppendRequest(rows=rows)))
    assert isinstance(req, StoreAppendRequest)
    assert [r.group_id for r in req.rows] == ["g0", "g1"]
    assert req.rows[1].trace_ctx == b"\x01\x02"
    acks = [AppendEntriesResponse(term=9, success=True, last_log_index=5,
                                  conflict_index=0, multi_hb=True),
            ErrorResponse(int(RaftError.EBUSY), "busy")]
    resp = decode_message(encode_message(StoreAppendResponse(acks=acks)))
    assert isinstance(resp, StoreAppendResponse)
    assert resp.acks[0].success and resp.acks[0].last_log_index == 5
    assert isinstance(resp.acks[1], ErrorResponse)


def test_store_append_rows_decode_old_format_frames():
    """Old→new: a row encoded by a PRE-trace-plane sender (no trailing
    trace_ctx bytes) decodes with the default — the nested-frame codec
    keeps mixed-fleet rounds decodable."""
    row = _req("g0", _peer(9802))
    blob = encode_message(row)
    # simulate the old sender: strip the trailing trace_ctx field
    # (4-byte length prefix + empty payload)
    old_blob = blob[:-4]
    import struct

    from tpuraft.rpc.messages import _pack_bytes

    inner = decode_message(old_blob)
    assert inner.trace_ctx == b""
    # and nested inside a round envelope built from such frames
    out = bytearray(struct.pack("<B", 21))   # StoreAppendRequest tid
    out += struct.pack("<I", 1)
    out += _pack_bytes(bytes(old_blob))
    req = decode_message(bytes(out))
    assert isinstance(req, StoreAppendRequest)
    assert req.rows[0].group_id == "g0" and req.rows[0].trace_ctx == b""


# ---------------------------------------------------------------------------
# engine plane: event-driven (eager) commit advancement
# ---------------------------------------------------------------------------


def _eager_engine(eager: bool = True):
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions

    return MultiRaftEngine(TickOptions(
        max_groups=8, max_peers=8, backend="numpy", eager_commit=eager))


def _voters(base: int, n: int = 3) -> list[PeerId]:
    return [PeerId.parse(f"127.0.0.1:{base + i}") for i in range(n)]


async def test_eager_commit_advances_on_the_completing_ack():
    """The quorum-completing ack advances commit ON THE ACK PATH — no
    tick in between."""
    eng = _eager_engine()
    peers = _voters(9900)
    conf = Configuration(list(peers))
    commits: list[int] = []
    box = eng.ballot_box_factory()(commits.append)
    box.update_conf(conf, Configuration())
    box.reset_pending_index(1)
    assert not box.commit_at(peers[0], 5, conf, Configuration())
    assert not commits, "1/3 acks must not commit"
    assert box.commit_at(peers[1], 5, conf, Configuration())
    assert commits == [5] and box.last_committed_index == 5
    assert eng.eager_commits == 1
    # the safety-net tick finds nothing left to advance
    assert eng.tick_once() == 0


async def test_eager_commit_joint_conf_tallies_both_quorums():
    """Joint consensus: a new-config-only majority must not advance the
    commit point — both electorates tally, exactly like the device
    reduce."""
    eng = _eager_engine()
    new = _voters(9910)
    old = [new[0]] + _voters(9950, 2)
    conf, old_conf = Configuration(list(new)), Configuration(list(old))
    commits: list[int] = []
    box = eng.ballot_box_factory()(commits.append)
    box.update_conf(conf, old_conf)
    box.reset_pending_index(1)
    # full NEW quorum acks; old config has only the shared peer
    for p in new:
        box.commit_at(p, 7, conf, old_conf)
    assert not commits, "new-only majority committed through a joint conf"
    # one more OLD voter completes the old quorum too
    assert box.commit_at(old[1], 7, conf, old_conf)
    assert commits == [7]


async def test_eager_commit_matches_tick_plane():
    """Equivalence: eager ack-path advancement lands exactly where the
    tick's device reduce would."""
    import numpy as np

    rng = np.random.default_rng(11)
    peers = _voters(9920, 5)
    conf = Configuration(list(peers))
    eng_e, eng_t = _eager_engine(True), _eager_engine(False)
    got_e: dict[int, int] = {}
    got_t: dict[int, int] = {}
    for g in range(6):
        be = eng_e.ballot_box_factory()(
            lambda idx, g=g: got_e.__setitem__(g, idx))
        bt = eng_t.ballot_box_factory()(
            lambda idx, g=g: got_t.__setitem__(g, idx))
        for b in (be, bt):
            b.update_conf(conf, Configuration())
            b.reset_pending_index(1)
        for p in peers:
            m = int(rng.integers(0, 50))
            be.commit_at(p, m, conf, Configuration())
            bt.commit_at(p, m, conf, Configuration())
    eng_t.tick_once()   # the tick plane needs its tick; eager did not
    assert got_e == got_t and len(got_t) > 0


async def test_eager_commit_off_waits_for_tick():
    eng = _eager_engine(False)
    peers = _voters(9930)
    conf = Configuration(list(peers))
    commits: list[int] = []
    box = eng.ballot_box_factory()(commits.append)
    box.update_conf(conf, Configuration())
    box.reset_pending_index(1)
    for p in peers:
        box.commit_at(p, 4, conf, Configuration())
    assert not commits, "eager_commit=False must defer to the tick"
    eng.tick_once()
    assert commits == [4] and eng.eager_commits == 0


# ---------------------------------------------------------------------------
# pipelined apply: ack at commit, apply behind
# ---------------------------------------------------------------------------


async def test_fsm_caller_eager_closure_fires_at_commit():
    from tpuraft.core.fsm_caller import FSMCaller
    from tpuraft.core.state_machine import StateMachine
    from tpuraft.entity import EntryType, LogEntry, LogId

    release = asyncio.Event()
    applied: list[int] = []

    class SlowFSM(StateMachine):
        async def on_apply(self, it):
            await release.wait()
            while it.valid():
                applied.append(it.index())
                it.next()

    entries = {i: LogEntry(type=EntryType.DATA, data=b"x",
                           id=LogId(i, 1)) for i in (1, 2)}
    lm = SimpleNamespace(get_entry=lambda i: entries.get(i),
                         set_applied_index=lambda i: None)
    fc = FSMCaller(SlowFSM(), lm)
    await fc.init(LogId(0, 0))
    eager_done: list[Status] = []
    late_done: list[Status] = []
    fc.append_pending_closure(1, eager_done.append, ack_at_commit=True)
    fc.append_pending_closure(2, late_done.append)
    fc.on_committed(2)
    # the eager closure fired synchronously AT commit; the normal one
    # waits for its apply, which is still blocked
    assert len(eager_done) == 1 and eager_done[0].is_ok()
    assert fc.eager_acked == 1
    assert not late_done and not applied
    release.set()
    for _ in range(100):
        if late_done:
            break
        await asyncio.sleep(0.01)
    assert late_done and late_done[0].is_ok()
    assert applied == [1, 2]
    assert fc.last_applied_index == 2
    await fc.shutdown()


async def test_fail_pending_clears_eager_queue():
    from tpuraft.core.fsm_caller import FSMCaller
    from tests.cluster import MockStateMachine
    from tpuraft.entity import LogId

    lm = SimpleNamespace(get_entry=lambda i: None,
                         set_applied_index=lambda i: None)
    fc = FSMCaller(MockStateMachine(), lm)
    await fc.init(LogId(0, 0))
    got: list[Status] = []
    fc.append_pending_closure(1, got.append, ack_at_commit=True)
    fc.fail_pending_closures(Status.error(RaftError.ENEWLEADER, "gone"))
    assert len(got) == 1 and not got[0].is_ok()
    fc.on_committed(1)
    assert len(got) == 1      # never double-fired
    assert fc.eager_acked == 0
    await fc.shutdown()


async def test_blind_writes_ack_at_commit_cas_waits_for_apply():
    """RaftRawKVStore eligibility: PUT/DELETE propose ack-at-commit
    tasks; CAS (result depends on store state) must wait for apply."""
    from tpuraft.rheakv.raft_store import _BLIND_OPS
    from tpuraft.rheakv.kv_operation import KVOp

    assert KVOp.PUT in _BLIND_OPS and KVOp.DELETE in _BLIND_OPS
    assert KVOp.COMPARE_PUT not in _BLIND_OPS
    assert KVOp.GET_AND_PUT not in _BLIND_OPS
    assert KVOp.GET_SEQUENCE not in _BLIND_OPS
    assert KVOp.KEY_LOCK not in _BLIND_OPS


# ---------------------------------------------------------------------------
# integration: real cluster through the batched write plane
# ---------------------------------------------------------------------------


async def test_cluster_replicates_through_store_append_rounds():
    c = TestCluster(3, append_batching=True)
    try:
        await c.start_all()
        leader = await c.wait_leader()
        for i in range(10):
            st = await c.apply_ok(leader, b"w%d" % i)
            assert st.is_ok(), st
        await c.wait_applied(10)
        b = c.batchers[leader.server_id]
        assert b.rounds > 0 and b.rows > 0 and b.entries >= 10
        assert b.fallbacks == 0 and b.round_errors == 0
    finally:
        await c.stop_all()


async def test_cluster_leader_deposed_mid_round_voids_rows():
    """Safety edge: rows of a round built under term T resolve AFTER
    the leader stepped down to T' > T — the replicator's term pin voids
    them (rollback, no commit advance), the proposer is failed with
    ENEWLEADER, and the deposed node never applies the entry."""
    c = TestCluster(3, append_batching=True, election_timeout_ms=500)
    try:
        await c.start_all()
        leader = await c.wait_leader()
        # stall every outbound append from the leader: rounds hang
        followers = [p for p in c.peers if p != leader.server_id]
        c.net.partition_one_way({leader.server_id.endpoint},
                                {p.endpoint for p in followers})
        st_box: list = []
        from tpuraft.entity import Task

        await leader.apply(Task(data=b"doomed",
                                done=lambda st: st_box.append(st)))
        await asyncio.sleep(0.1)   # round submitted, blackholed
        committed_before = leader.ballot_box.last_committed_index
        # depose: a higher term arrives (e.g. a vote response)
        await leader.step_down_on_higher_term(
            leader.current_term + 1, "test depose")
        c.net.heal()
        await asyncio.sleep(0.3)
        assert st_box and not st_box[0].is_ok()
        assert st_box[0].raft_error in (RaftError.ENEWLEADER,
                                        RaftError.ENODESHUTTING)
        # the old leader's commit never advanced past the depose point
        # on the voided round's acks
        assert leader.ballot_box.pending_index == 0
        assert len(c.fsms[leader.server_id].logs) == 0 or \
            b"doomed" not in c.fsms[leader.server_id].logs
        assert committed_before <= leader.ballot_box.last_committed_index
    finally:
        await c.stop_all()


async def test_cluster_mixed_fleet_endpoint_downgrades():
    """One follower's endpoint predates the write plane (its manager
    never registered store_append): the leader's batcher downgrades
    THAT endpoint permanently while the new endpoint keeps riding
    rounds — and replication stays correct on both."""
    c = TestCluster(3, append_batching=True)
    try:
        await c.start_all()
        leader = await c.wait_leader()
        followers = [p for p in c.peers if p != leader.server_id]
        old = followers[0]
        # simulate a pre-write-plane build on one endpoint
        del c.managers[old].server._handlers["store_append"]
        for i in range(6):
            st = await c.apply_ok(leader, b"m%d" % i)
            assert st.is_ok(), st
        await c.wait_applied(6)
        b = c.batchers[leader.server_id]
        assert b.fallbacks == 1, b.describe()
        assert b.legacy_rows > 0
        assert b._fast_ok.get(old.endpoint) is False
        assert b._fast_ok.get(followers[1].endpoint, True) is True
    finally:
        await c.stop_all()


async def test_kv_put_acked_at_commit_read_sees_applied_state():
    """End-to-end pipelined apply through the KV stack: a PUT acked at
    commit is observed by an immediately-following GET (the read fence
    waits for applied), and the eager counters prove the path ran."""
    from tests.test_kv_client import kv_client_cluster
    from tpuraft.rheakv.metadata import Region

    regions = [Region(id=1, start_key=b"", end_key=b"")]
    async with kv_client_cluster(regions=regions) as (c, kv):
        await c.wait_region_leader(1)
        for i in range(5):
            assert await kv.put(b"k%d" % i, b"v%d" % i)
            assert await kv.get(b"k%d" % i) == b"v%d" % i
        # CAS still round-trips through its apply (not eager) and
        # returns the state-dependent result
        assert await kv.compare_and_put(b"k0", b"v0", b"v0'") is True
        assert await kv.compare_and_put(b"k0", b"nope", b"x") is False
        eager = sum(re.node.fsm_caller.eager_acked
                    for s in c.stores.values()
                    for re in s._regions.values() if re.node)
        assert eager >= 5, "blind writes never took the eager path"


async def test_store_engine_append_batching_off_uses_send_plane():
    """The A/B knob: append_batching=False stores wire no batcher and
    replication still works through the legacy endpoint lane."""
    from tests.test_kv_client import kv_client_cluster
    from tpuraft.rheakv.metadata import Region

    regions = [Region(id=1, start_key=b"", end_key=b"")]
    async with kv_client_cluster(
            regions=regions,
            store_opts={"append_batching": False,
                        "ack_at_commit": False}) as (c, kv):
        await c.wait_region_leader(1)
        assert await kv.put(b"a", b"1")
        assert await kv.get(b"a") == b"1"
        for s in c.stores.values():
            assert s.append_batcher is None
            eager = sum(re.node.fsm_caller.eager_acked
                        for re in s._regions.values() if re.node)
            assert eager == 0
