"""ProcCluster: the multi-process test harness over examples.proc_supervisor.

The promoted form of the in-file ``NativeKVCluster`` from
test_kv_over_native_tcp.py: each store is a REAL OS process running the
``examples.rheakv_server`` main (own interpreter, own GIL, own loop),
reached over TCP, with readiness probes, SIGTERM drain, and SIGKILL +
supervised restart — so lifecycle tests exercise the exact serving
topology the committed cross-process bench rows use.

Usage::

    async with ProcCluster(tmp_path, stores=3, regions=2) as c:
        kv = await c.client()
        ...
        await c.sigkill(0); await c.restart(0)
"""

from __future__ import annotations

import contextlib

from examples.proc_supervisor import (
    ProcSupervisor,
    StoreProcess,
    free_endpoints,
    server_argv,
)
from examples.rheakv_server import client_for
from tpuraft.rheakv.client import RheaKVStore


class ProcCluster:
    def __init__(self, tmp_path, stores: int = 3, regions: int = 2,
                 transport: str = "tcp", store_kind: str = "memory",
                 eto_ms: int = 500, apply_lane: bool = False,
                 drain_timeout_s: float = 10.0,
                 boot_delay_s: dict[int, float] | None = None,
                 metrics: bool = False):
        self._tmp = tmp_path
        self.n_regions = regions
        self.transport_kind = transport
        self.endpoints = free_endpoints(stores)
        delays = boot_delay_s or {}
        self.sup = ProcSupervisor([
            StoreProcess(ep, server_argv(
                ep, self.endpoints, regions, str(tmp_path),
                transport=transport, store=store_kind, eto_ms=eto_ms,
                apply_lane=apply_lane, drain_timeout_s=drain_timeout_s,
                boot_delay_s=delays.get(i, 0.0),
                metrics_port=0 if metrics else None))
            for i, ep in enumerate(self.endpoints)])
        self._clients: list[RheaKVStore] = []
        self._transports: list = []

    @property
    def procs(self) -> list[StoreProcess]:
        return self.sup.procs

    async def __aenter__(self) -> "ProcCluster":
        await self.sup.start()
        return self

    async def __aexit__(self, *exc) -> None:
        for kv in self._clients:
            with contextlib.suppress(Exception):
                await kv.shutdown()
        for t in self._transports:
            with contextlib.suppress(Exception):
                await t.close()
        await self.sup.stop()

    def _make_transport(self):
        if self.transport_kind == "native":
            from tpuraft.rpc.native_tcp import NativeTcpTransport
            t = NativeTcpTransport()
        else:
            from tpuraft.rpc.tcp import TcpTransport
            t = TcpTransport()
        self._transports.append(t)
        return t

    async def client(self, **kw) -> RheaKVStore:
        kv = client_for(self.endpoints, self.n_regions,
                        transport=self._make_transport(), **kw)
        await kv.start()
        self._clients.append(kv)
        return kv

    # -- lifecycle controls ---------------------------------------------

    async def sigterm(self, i: int, timeout_s: float = 20.0) -> int:
        """Drain-stop store ``i``; returns its exit code."""
        p = self.procs[i]
        p.terminate()
        return await p.wait_exit(timeout_s)

    async def sigkill(self, i: int, timeout_s: float = 10.0) -> int:
        """Crash-stop store ``i`` (no drain); returns its exit code."""
        p = self.procs[i]
        p.kill()
        return await p.wait_exit(timeout_s)

    async def restart(self, i: int, ready_timeout_s: float = 30.0) -> dict:
        """Respawn a stopped store and await its READY probe."""
        p = self.procs[i]
        p.spawn()
        return await p.wait_ready(ready_timeout_s)
