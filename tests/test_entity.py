"""Unit tests: PeerId/LogId/LogEntry codec, Configuration, Status.

Mirrors the reference's pure-unit tier (SURVEY.md §5): test:entity/*,
test:conf/ConfigurationTest.
"""

import pytest

from tpuraft.conf import Configuration, ConfigurationEntry, ConfigurationManager
from tpuraft.entity import EntryType, LogEntry, LogId, PeerId
from tpuraft.errors import RaftError, Status


class TestPeerId:
    def test_parse_roundtrip(self):
        for s in ["127.0.0.1:8080", "10.0.0.1:9000:3", "10.0.0.1:9000:0:50"]:
            p = PeerId.parse(s)
            assert PeerId.parse(str(p)) == p

    def test_fields(self):
        p = PeerId.parse("10.1.2.3:8081:2:100")
        assert (p.ip, p.port, p.idx, p.priority) == ("10.1.2.3", 8081, 2, 100)
        assert p.endpoint == "10.1.2.3:8081"

    def test_invalid(self):
        with pytest.raises(ValueError):
            PeerId.parse("no-port")
        with pytest.raises(ValueError):
            PeerId.parse("a:1:2:3:4")

    def test_empty(self):
        assert PeerId().is_empty()
        assert not PeerId.parse("1.1.1.1:80").is_empty()


class TestLogId:
    def test_order_by_index(self):
        assert LogId(5, 1) > LogId(4, 9)

    def test_newer_than_term_first(self):
        assert LogId(4, 9).newer_than(LogId(5, 1))
        assert not LogId(5, 1).newer_than(LogId(4, 9))
        assert LogId(6, 2).newer_than(LogId(5, 2))


class TestLogEntryCodec:
    def test_data_roundtrip(self):
        e = LogEntry(type=EntryType.DATA, id=LogId(42, 7), data=b"hello raft")
        d = LogEntry.decode(e.encode())
        assert d.type == EntryType.DATA
        assert d.id == LogId(42, 7)
        assert d.data == b"hello raft"
        assert d.peers is None

    def test_conf_roundtrip(self):
        peers = [PeerId.parse("1.1.1.1:80"), PeerId.parse("2.2.2.2:80:1")]
        old = [PeerId.parse("3.3.3.3:80")]
        e = LogEntry(
            type=EntryType.CONFIGURATION,
            id=LogId(10, 2),
            peers=peers,
            old_peers=old,
            learners=[PeerId.parse("4.4.4.4:80")],
        )
        d = LogEntry.decode(e.encode())
        assert d.peers == peers
        assert d.old_peers == old
        assert d.learners == [PeerId.parse("4.4.4.4:80")]
        assert d.old_learners is None

    def test_crc_detects_corruption(self):
        raw = bytearray(LogEntry(type=EntryType.DATA, id=LogId(1, 1), data=b"x" * 100).encode())
        raw[-3] ^= 0xFF
        with pytest.raises(ValueError, match="crc"):
            LogEntry.decode(bytes(raw))

    def test_wire_decode_defers_crc_to_verify_crc(self):
        raw = bytearray(LogEntry(type=EntryType.DATA, id=LogId(1, 1),
                                 data=b"x" * 100).encode())
        raw[-3] ^= 0xFF
        # wire path skips the CRC — corruption decodes "successfully"...
        e = LogEntry.decode(bytes(raw), verify=False)
        # ...but the deferred staging-time check catches it
        with pytest.raises(ValueError, match="crc"):
            e.verify_crc()
        # a clean blob verifies once, then becomes a no-op
        good = LogEntry.decode(
            LogEntry(type=EntryType.DATA, id=LogId(2, 1), data=b"y").encode(),
            verify=False)
        good.verify_crc()
        good.verify_crc()
        # locally-built entries (fresh CRC at encode) are no-ops too
        LogEntry(type=EntryType.DATA, id=LogId(3, 1), data=b"z").verify_crc()

    def test_encoded_size(self):
        e = LogEntry(type=EntryType.DATA, id=LogId(1, 1), data=b"abc")
        assert e.encoded_size() == len(e.encode())


class TestConfiguration:
    def test_parse_and_str(self):
        c = Configuration.parse("1.1.1.1:80,2.2.2.2:81,3.3.3.3:82/learner")
        assert len(c.peers) == 2 and len(c.learners) == 1
        assert Configuration.parse(str(c)) == c

    def test_quorum(self):
        assert Configuration.parse("a" * 0 + "1.1.1.1:1").quorum() == 1
        assert Configuration.parse("1.1.1.1:1,1.1.1.1:2,1.1.1.1:3").quorum() == 2
        assert Configuration.parse("1.1.1.1:1,1.1.1.1:2,1.1.1.1:3,1.1.1.1:4").quorum() == 3

    def test_diff(self):
        a = Configuration.parse("1.1.1.1:1,1.1.1.1:2")
        b = Configuration.parse("1.1.1.1:2,1.1.1.1:3")
        added, removed = a.diff(b)
        assert added == {PeerId.parse("1.1.1.1:3")}
        assert removed == {PeerId.parse("1.1.1.1:1")}

    def test_valid(self):
        c = Configuration.parse("1.1.1.1:1,1.1.1.1:1")
        assert not c.is_valid()
        c2 = Configuration.parse("1.1.1.1:1,1.1.1.1:2/learner")
        assert c2.is_valid()
        c2.learners.append(PeerId.parse("1.1.1.1:1"))
        assert not c2.is_valid()


class TestConfigurationManager:
    def test_get_at_index(self):
        m = ConfigurationManager()
        c1 = ConfigurationEntry(LogId(5, 1), Configuration.parse("1.1.1.1:1"))
        c2 = ConfigurationEntry(LogId(9, 2), Configuration.parse("1.1.1.1:1,1.1.1.1:2"))
        assert m.add(c1) and m.add(c2)
        assert not m.add(c1)  # non-monotonic rejected
        assert m.get(7).id.index == 5
        assert m.get(100).id.index == 9
        assert m.get(1).id.index == 0  # falls to snapshot conf
        assert m.last().id.index == 9

    def test_truncate(self):
        m = ConfigurationManager()
        m.add(ConfigurationEntry(LogId(5, 1), Configuration.parse("1.1.1.1:1")))
        m.add(ConfigurationEntry(LogId(9, 2), Configuration.parse("1.1.1.1:2")))
        m.truncate_suffix(8)
        assert m.last().id.index == 5
        m.truncate_prefix(6)
        assert m.last().id.index == 0


class TestStatus:
    def test_ok(self):
        assert Status.OK().is_ok()
        assert bool(Status.OK())

    def test_error(self):
        s = Status.error(RaftError.ERAFTTIMEDOUT)
        assert not s.is_ok()
        assert s.raft_error is RaftError.ERAFTTIMEDOUT
        assert "ERAFTTIMEDOUT" in str(s)
