"""KVTestCluster: N StoreEngines in one process over loopback transport.

Mirrors the reference's RheaKV in-JVM multi-store test pattern
(SURVEY.md §5 "RheaKV integration"): real region raft groups, real KV
command processors, fault injection via the shared InProcNetwork.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from tests.cluster import TestCluster  # noqa: F401  (re-export convenience)
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
from tpuraft.rheakv.pd_server import (
    PlacementDriverOptions,
    PlacementDriverServer,
)
from tpuraft.rheakv.region_engine import RegionEngine
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


class KVTestCluster:
    __test__ = False

    def __init__(self, n_stores: int = 3, tmp_path=None,
                 regions: Optional[list[Region]] = None,
                 election_timeout_ms: int = 300,
                 multi_raft_engine_factory=None,
                 raw_store_factory=None,
                 read_only_option=None,
                 log_scheme: str = "file",
                 store_opts: Optional[dict] = None):
        # raw_store_factory: Callable[[endpoint], RawKVStore] — lets tests
        # swap the memory store for the native C++ engine per store
        self.net = InProcNetwork()
        self.endpoints = [f"127.0.0.1:{6000 + i}" for i in range(n_stores)]
        peers = list(self.endpoints)
        if regions is None:
            regions = [Region(id=1, peers=peers)]
        else:
            for r in regions:
                if not r.peers:
                    r.peers = list(peers)
        self.region_template = [r.copy() for r in regions]
        self.tmp_path = tmp_path
        self.election_timeout_ms = election_timeout_ms
        self.engine_factory = multi_raft_engine_factory
        self.raw_store_factory = raw_store_factory
        self.read_only_option = read_only_option
        self.log_scheme = log_scheme  # "file" | "multilog" (needs tmp_path)
        # extra StoreEngineOptions field overrides (e.g. the write-plane
        # A/B knobs append_batching / ack_at_commit)
        self.store_opts = dict(store_opts or {})
        if log_scheme != "file" and tmp_path is None:
            raise ValueError(f"log_scheme={log_scheme!r} needs a tmp_path")
        self.stores: dict[str, StoreEngine] = {}

    async def start_all(self) -> None:
        for ep in self.endpoints:
            await self.start_store(ep)

    async def start_store(self, endpoint: str) -> StoreEngine:
        server = RpcServer(endpoint)
        self.net.bind(server)
        self.net.start_endpoint(endpoint)
        transport = InProcTransport(self.net, endpoint)
        opts = StoreEngineOptions(
            server_id=endpoint,
            initial_regions=[r.copy() for r in self.region_template],
            data_path=str(self.tmp_path) if self.tmp_path else "",
            election_timeout_ms=self.election_timeout_ms,
            log_scheme=self.log_scheme,
        )
        if self.read_only_option is not None:
            opts.read_only_option = self.read_only_option
        for k, v in self.store_opts.items():
            setattr(opts, k, v)
        if self.raw_store_factory is not None:
            opts.raw_store_factory = (
                lambda ep=endpoint: self.raw_store_factory(ep))
        engine = self.engine_factory() if self.engine_factory else None
        store = StoreEngine(opts, server, transport, multi_raft_engine=engine)
        await store.start()
        self.stores[endpoint] = store
        return store

    async def stop_store(self, endpoint: str) -> None:
        self.net.stop_endpoint(endpoint)
        store = self.stores.pop(endpoint, None)
        if store:
            self.net.unbind(endpoint)
            await store.shutdown()

    async def stop_all(self) -> None:
        for ep in list(self.stores):
            await self.stop_store(ep)

    def client_transport(self, endpoint: str = "kvclient:0") -> InProcTransport:
        return InProcTransport(self.net, endpoint)

    async def wait_region_leader(self, region_id: int, timeout_s: float = 5.0
                                 ) -> RegionEngine:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [s.get_region_engine(region_id)
                       for s in self.stores.values()
                       if s.get_region_engine(region_id)
                       and s.get_region_engine(region_id).is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError(f"no leader for region {region_id} in {timeout_s}s")

    async def wait_region_on_all(self, region_id: int, timeout_s: float = 5.0
                                 ) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(s.get_region_engine(region_id) is not None
                   for s in self.stores.values()):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(f"region {region_id} not on all stores")


class PDTestCluster(KVTestCluster):
    """Stores + a PD raft cluster on the same loopback network.

    Mirrors the reference's pd-backed RheaKV tests: stores heartbeat to
    the PD; the PD answers routing and emits split instructions.
    """

    __test__ = False

    def __init__(self, n_stores: int = 3, n_pd: int = 3, tmp_path=None,
                 regions: Optional[list[Region]] = None,
                 election_timeout_ms: int = 300,
                 split_threshold_keys: int = 0,
                 heartbeat_interval_ms: int = 100,
                 balance_leaders: bool = False,
                 transfer_cooldown_s: float = 5.0,
                 pd_opts: Optional[dict] = None):
        super().__init__(n_stores, tmp_path=tmp_path, regions=regions,
                         election_timeout_ms=election_timeout_ms)
        self.pd_endpoints = [f"127.0.0.1:{7000 + i}" for i in range(n_pd)]
        self.split_threshold_keys = split_threshold_keys
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.balance_leaders = balance_leaders
        self.transfer_cooldown_s = transfer_cooldown_s
        # extra PlacementDriverOptions overrides (e.g. the lifecycle_*
        # knobs), applied via setattr like store_opts
        self.pd_opts = dict(pd_opts or {})
        self.pd_servers: dict[str, PlacementDriverServer] = {}

    async def start_all(self) -> None:
        for ep in self.pd_endpoints:
            await self.start_pd(ep)
        await super().start_all()

    async def start_pd(self, endpoint: str) -> PlacementDriverServer:
        server = RpcServer(endpoint)
        self.net.bind(server)
        self.net.start_endpoint(endpoint)
        transport = InProcTransport(self.net, endpoint)
        opts = PlacementDriverOptions(
            endpoints=list(self.pd_endpoints),
            election_timeout_ms=self.election_timeout_ms,
            data_path=str(self.tmp_path) if self.tmp_path else "",
            split_threshold_keys=self.split_threshold_keys,
            balance_leaders=self.balance_leaders,
            transfer_cooldown_s=self.transfer_cooldown_s,
            initial_regions=[r.copy() for r in self.region_template],
        )
        for k, v in self.pd_opts.items():
            setattr(opts, k, v)
        pd = PlacementDriverServer(opts, endpoint, server, transport)
        await pd.start()
        self.pd_servers[endpoint] = pd
        return pd

    async def stop_pd(self, endpoint: str) -> None:
        self.net.stop_endpoint(endpoint)
        pd = self.pd_servers.pop(endpoint, None)
        if pd:
            self.net.unbind(endpoint)
            await pd.shutdown()

    async def start_store(self, endpoint: str) -> StoreEngine:
        server = RpcServer(endpoint)
        self.net.bind(server)
        self.net.start_endpoint(endpoint)
        transport = InProcTransport(self.net, endpoint)
        opts = StoreEngineOptions(
            server_id=endpoint,
            initial_regions=[r.copy() for r in self.region_template],
            data_path=str(self.tmp_path) if self.tmp_path else "",
            election_timeout_ms=self.election_timeout_ms,
            heartbeat_interval_ms=self.heartbeat_interval_ms,
        )
        pd_client = RemotePlacementDriverClient(transport, self.pd_endpoints)
        store = StoreEngine(opts, server, transport, pd_client=pd_client)
        await store.start()
        self.stores[endpoint] = store
        return store

    async def stop_all(self) -> None:
        await super().stop_all()
        for ep in list(self.pd_servers):
            await self.stop_pd(ep)

    async def wait_pd_leader(self, timeout_s: float = 5.0
                             ) -> PlacementDriverServer:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [p for p in self.pd_servers.values()
                       if p.node and p.node.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError("no PD leader")

    def pd_client(self, endpoint: str = "pdclient:0"
                  ) -> RemotePlacementDriverClient:
        return RemotePlacementDriverClient(
            InProcTransport(self.net, endpoint), self.pd_endpoints)
