"""Send-plane tests (SURVEY.md §3.5 "batched per-tick (group, peer)
send matrices"): batched vote + AppendEntries dispatch via one
EndpointSender per endpoint pair, and the task-count collapse it exists
for.  Reference comparison: ``core:Replicator`` posts sends to shared
executors — here the batching is at the WIRE level too (one multi_append
RPC carries many groups), which the reference never does.
"""

import asyncio

import pytest

from tests.test_engine import MultiRaftCluster
from tpuraft.entity import Task

pytestmark = pytest.mark.asyncio


async def apply_ok(node, data: bytes, timeout=5.0):
    fut = asyncio.get_running_loop().create_future()

    def done(st):
        if not fut.done():
            fut.set_result(st)

    await node.apply(Task(data=data, done=done))
    st = await asyncio.wait_for(fut, timeout)
    assert st.is_ok(), st
    return st


async def test_appends_ride_batched_rpcs():
    """A burst across many groups coalesces into multi_append RPCs:
    items per RPC must exceed 1 on average by a wide margin."""
    c = MultiRaftCluster(3, 12, election_timeout_ms=1000)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        planes = [m.send_plane for m in
                  {id(n.node_manager): n.node_manager
                   for n in c.nodes.values()}.values()]

        def totals():
            return (sum(p.stats()["rpcs_sent"] for p in planes),
                    sum(p.stats()["items_sent"] for p in planes))

        rpcs0, items0 = totals()  # election votes: staggered, ~1/RPC
        # concurrent burst: every group applies at once
        await asyncio.gather(*(apply_ok(n, b"x%d" % i)
                               for i, n in enumerate(leaders)))
        rpcs, items = (a - b for a, b in zip(totals(), (rpcs0, items0)))
        assert items >= 24 and rpcs > 0, (items, rpcs)
        # 12 groups x 2 peers apply concurrently; far fewer RPCs than
        # items proves wire-level coalescing (exact ratio is timing-
        # dependent; >1.5x is already impossible without batching)
        assert items / rpcs > 1.5, (items, rpcs)
    finally:
        await c.stop_all()


async def test_standing_tasks_are_o_endpoints_not_o_groups():
    """The r4 contract: G groups on 3 endpoints must not hold standing
    per-(group, peer) tasks (pre-r4: ~4 tasks per group at idle)."""
    G = 24
    c = MultiRaftCluster(3, G, election_timeout_ms=1000)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        await asyncio.gather(*(apply_ok(n, b"w") for n in leaders))
        # let transients (response fan-out, FSM drains) finish
        await asyncio.sleep(1.0)
        tasks = len(asyncio.all_tasks())
        # engines (3) + test machinery + senders; generous bound that a
        # per-group loop (24+ tasks minimum) cannot meet
        assert tasks < 3 + G // 2, tasks
    finally:
        await c.stop_all()


async def test_elections_use_vote_batching_and_converge():
    """Kill a leader endpoint: every orphaned group re-elects through
    multi_vote batches (not per-group RPC fanouts)."""
    c = MultiRaftCluster(3, 8, election_timeout_ms=400)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        victim_ep = leaders[0].server_id
        victims = [g for g, n in zip(c.groups, leaders)
                   if n.server_id == victim_ep]
        c.net.stop_endpoint(victim_ep.endpoint)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 15
        from tpuraft.core.node import State

        for g in victims:
            while loop.time() < deadline:
                live = [n for (gg, ep), n in c.nodes.items()
                        if gg == g and ep != victim_ep
                        and n.state == State.LEADER]
                if live:
                    break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError(f"{g} did not re-elect")
    finally:
        await c.stop_all()


async def test_window_pipelines_within_one_batch():
    """max_inflight_msgs frames ride one batch: with 1-entry batches
    forced, a backlog ships as multiple frames per submit (the
    inflight_peak proof, plane edition)."""
    c = MultiRaftCluster(3, 1, election_timeout_ms=1500)
    await c.start_all()
    try:
        leader = await c.wait_leader(c.groups[0])
        await apply_ok(leader, b"warm")
        for n in c.nodes.values():
            n.options.raft_options.max_entries_size = 1
        c.net.set_delay_ms(10)
        futs = []
        loop = asyncio.get_running_loop()
        for i in range(40):
            fut = loop.create_future()
            await leader.apply(Task(
                data=b"p%03d" % i,
                done=lambda st, fut=fut: fut.done() or fut.set_result(st)))
            futs.append(fut)
        sts = await asyncio.wait_for(asyncio.gather(*futs), 30)
        c.net.set_delay_ms(0)
        assert all(st.is_ok() for st in sts)
        peaks = [r.inflight_peak for r in leader.replicators.all()]
        assert any(pk > 3 for pk in peaks), peaks
    finally:
        await c.stop_all()


async def test_legacy_fallback_for_receiver_without_batch_handlers():
    """An endpoint whose server predates the batch plane (no multi_*
    handlers) gets single RPCs after one failed batch probe."""
    from tests.cluster import TestCluster

    c = TestCluster(3, election_timeout_ms=1000)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        # strip the batch handlers from one follower's server to
        # simulate an old receiver
        follower_ep = next(p for p in c.peers if p != leader.server_id)
        server = c.managers[follower_ep].server
        server._handlers.pop("multi_append", None)
        server._handlers.pop("multi_vote", None)
        await c.apply_ok(leader, b"via-legacy")
        await c.wait_applied(1)
        sender = leader.node_manager.send_plane.sender(follower_ep.endpoint)
        assert sender._legacy is True
        # and replication still flows
        await c.apply_ok(leader, b"more")
        await c.wait_applied(2)
    finally:
        await c.stop_all()
