"""Send-plane tests (SURVEY.md §3.5 "batched per-tick (group, peer)
send matrices"): batched vote + AppendEntries dispatch via one
EndpointSender per endpoint pair, and the task-count collapse it exists
for.  Reference comparison: ``core:Replicator`` posts sends to shared
executors — here the batching is at the WIRE level too (one multi_append
RPC carries many groups), which the reference never does.
"""

import asyncio

import pytest

from tests.test_engine import MultiRaftCluster
from tpuraft.entity import Task

pytestmark = pytest.mark.asyncio


async def apply_ok(node, data: bytes, timeout=5.0):
    fut = asyncio.get_running_loop().create_future()

    def done(st):
        if not fut.done():
            fut.set_result(st)

    await node.apply(Task(data=data, done=done))
    st = await asyncio.wait_for(fut, timeout)
    assert st.is_ok(), st
    return st


async def test_appends_ride_batched_rpcs():
    """A burst across many groups coalesces into multi_append RPCs:
    items per RPC must exceed 1 on average by a wide margin."""
    c = MultiRaftCluster(3, 12, election_timeout_ms=1000)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        planes = [m.send_plane for m in
                  {id(n.node_manager): n.node_manager
                   for n in c.nodes.values()}.values()]

        def totals():
            return (sum(p.stats()["rpcs_sent"] for p in planes),
                    sum(p.stats()["items_sent"] for p in planes))

        rpcs0, items0 = totals()  # election votes: staggered, ~1/RPC
        # concurrent burst: every group applies at once
        await asyncio.gather(*(apply_ok(n, b"x%d" % i)
                               for i, n in enumerate(leaders)))
        rpcs, items = (a - b for a, b in zip(totals(), (rpcs0, items0)))
        assert items >= 24 and rpcs > 0, (items, rpcs)
        # 12 groups x 2 peers apply concurrently; far fewer RPCs than
        # items proves wire-level coalescing (exact ratio is timing-
        # dependent; >1.5x is already impossible without batching)
        assert items / rpcs > 1.5, (items, rpcs)
    finally:
        await c.stop_all()


async def test_standing_tasks_are_o_endpoints_not_o_groups():
    """The r4 contract: G groups on 3 endpoints must not hold standing
    per-(group, peer) tasks (pre-r4: ~4 tasks per group at idle)."""
    G = 24
    c = MultiRaftCluster(3, G, election_timeout_ms=1000)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        await asyncio.gather(*(apply_ok(n, b"w") for n in leaders))
        # let transients (response fan-out, FSM drains) finish — poll
        # rather than a fixed sleep: on a starved single-core host the
        # fan-out can outlive any fixed window, but STANDING tasks, the
        # thing under test, never settle below the bound
        deadline = asyncio.get_running_loop().time() + 8.0
        while True:
            tasks = len(asyncio.all_tasks())
            # engines (3) + test machinery + senders; generous bound
            # that a per-group loop (24+ tasks minimum) cannot meet
            if tasks < 3 + G // 2:
                break
            assert asyncio.get_running_loop().time() < deadline, tasks
            await asyncio.sleep(0.25)
    finally:
        await c.stop_all()


async def test_elections_use_vote_batching_and_converge():
    """Kill a leader endpoint: every orphaned group re-elects through
    multi_vote batches (not per-group RPC fanouts)."""
    c = MultiRaftCluster(3, 8, election_timeout_ms=400)
    await c.start_all()
    try:
        leaders = [await c.wait_leader(g) for g in c.groups]
        victim_ep = leaders[0].server_id
        victims = [g for g, n in zip(c.groups, leaders)
                   if n.server_id == victim_ep]
        c.net.stop_endpoint(victim_ep.endpoint)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 15
        from tpuraft.core.node import State

        for g in victims:
            while loop.time() < deadline:
                live = [n for (gg, ep), n in c.nodes.items()
                        if gg == g and ep != victim_ep
                        and n.state == State.LEADER]
                if live:
                    break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError(f"{g} did not re-elect")
    finally:
        await c.stop_all()


async def test_window_pipelines_within_one_batch():
    """max_inflight_msgs frames ride one batch: with 1-entry batches
    forced, a backlog ships as multiple frames per submit (the
    inflight_peak proof, plane edition)."""
    c = MultiRaftCluster(3, 1, election_timeout_ms=1500)
    await c.start_all()
    try:
        leader = await c.wait_leader(c.groups[0])
        await apply_ok(leader, b"warm")
        for n in c.nodes.values():
            n.options.raft_options.max_entries_size = 1
        c.net.set_delay_ms(10)
        futs = []
        loop = asyncio.get_running_loop()
        for i in range(40):
            fut = loop.create_future()
            await leader.apply(Task(
                data=b"p%03d" % i,
                done=lambda st, fut=fut: fut.done() or fut.set_result(st)))
            futs.append(fut)
        sts = await asyncio.wait_for(asyncio.gather(*futs), 30)
        c.net.set_delay_ms(0)
        assert all(st.is_ok() for st in sts)
        peaks = [r.inflight_peak for r in leader.replicators.all()]
        assert any(pk > 3 for pk in peaks), peaks
    finally:
        await c.stop_all()


# -- storm-path unit tests (VERDICT r4 weak #6): these paths carried
# 216K errors in the judge's 16Kx3 run — hot paths, not edge cases -----------

from types import SimpleNamespace  # noqa: E402

from tpuraft.core.send_plane import EndpointSender  # noqa: E402
from tpuraft.errors import RaftError, Status  # noqa: E402
from tpuraft.rpc.transport import RpcError  # noqa: E402


def _fake_node(transport, timeout_ms):
    return SimpleNamespace(
        transport=transport,
        options=SimpleNamespace(election_timeout_ms=timeout_ms),
        _meta=SimpleNamespace(SYNC_CHEAP=True),
    )


class _FakeRep:
    def __init__(self, node):
        self._node = node
        self.responses: list = []
        self.errors = 0

    async def on_batch_responses(self, acks):
        self.responses.append(list(acks))

    async def on_batch_error(self):
        self.errors += 1


class _RecordingTransport:
    """call() records (method, n_items, timeout_ms) and answers OK."""

    def __init__(self):
        self.calls: list[tuple[str, int, float]] = []

    async def call(self, dst, method, request, timeout_ms=None):
        self.calls.append((method, len(request.items), timeout_ms))
        from tpuraft.rpc.messages import BatchResponse
        return BatchResponse(items=[SimpleNamespace(ok=True)
                                    for _ in request.items])


async def test_vote_chunk_budget_covers_slowest_group():
    """Groups with DIFFERENT election timeouts sharing an endpoint: the
    co-batched vote RPC must budget for the slowest, not for whichever
    node happened to submit last (pre-r5: last-submitter-wins)."""
    tr = _RecordingTransport()
    fast = _fake_node(tr, 100)
    slow = _fake_node(tr, 2000)
    s = EndpointSender("ep")

    async def cb(resp):
        pass

    # queue both BEFORE kicking so they co-batch into one chunk (as an
    # election herd does); slow first, fast last — last-submitter-wins
    # would have budgeted the shared chunk at 100ms
    s._votes.append((slow, SimpleNamespace(), cb))
    s._votes.append((fast, SimpleNamespace(), cb))
    s._transport = tr
    s._kick_votes()
    await asyncio.sleep(0.05)
    votes = [c for c in tr.calls if c[0] == "multi_vote"]
    assert votes == [("multi_vote", 2, 2000)], tr.calls


async def test_append_chunk_budget_covers_slowest_group():
    tr = _RecordingTransport()
    fast, slow = _fake_node(tr, 100), _fake_node(tr, 3000)
    s = EndpointSender("ep")
    s.submit_append(_FakeRep(slow), [SimpleNamespace()])
    s.submit_append(_FakeRep(fast), [SimpleNamespace()])
    await asyncio.sleep(0.05)
    appends = [c for c in tr.calls if c[0] == "multi_append"]
    assert appends and max(t for _m, _n, t in appends) == 3000, tr.calls


async def test_stop_mid_round_fails_stranded_batches():
    """stop() during an in-flight round must resolve EVERY submitted
    batch through on_batch_error — stranding one leaves its replicator
    _pending forever (replication silently stops for the pair)."""
    gate = asyncio.Event()

    class BlockedTransport:
        async def call(self, dst, method, request, timeout_ms=None):
            await gate.wait()
            raise AssertionError("unreached")

    tr = BlockedTransport()
    node = _fake_node(tr, 1000)
    reps = [_FakeRep(node) for _ in range(3)]
    s = EndpointSender("ep")
    for r in reps:
        s.submit_append(r, [SimpleNamespace()])
    await asyncio.sleep(0.02)  # drain task is now blocked mid-round
    s.stop()
    await asyncio.sleep(0.02)
    gate.set()
    assert [r.errors for r in reps] == [1, 1, 1], [r.errors for r in reps]


async def test_legacy_fallback_matches_enomethod_code_not_wording():
    """A transport whose unknown-method error does NOT contain the words
    'no handler' must still trigger the per-item fallback — detection
    keys on RaftError.ENOMETHOD (ADVICE r4)."""
    vote_acks: list = []

    class OddWordedTransport:
        def __init__(self):
            self.single_appends = 0

        async def call(self, dst, method, request, timeout_ms=None):
            raise RpcError(Status.error(
                RaftError.ENOMETHOD, f"method not found: {method}"))

        async def append_entries(self, dst, req, timeout_ms=None):
            self.single_appends += 1
            return SimpleNamespace(success=True)

        async def request_vote(self, dst, req, timeout_ms=None):
            return SimpleNamespace(granted=True)

    tr = OddWordedTransport()
    node = _fake_node(tr, 500)
    rep = _FakeRep(node)
    s = EndpointSender("ep")

    async def vote_cb(resp):
        vote_acks.append(resp)

    s.submit_append(rep, [SimpleNamespace(), SimpleNamespace()])
    s.submit_vote(node, SimpleNamespace(), vote_cb)
    await asyncio.sleep(0.1)
    assert s._legacy is True
    assert tr.single_appends == 2
    assert rep.responses and len(rep.responses[0]) == 2
    assert len(vote_acks) == 1


async def test_multi_append_ebusy_cascade_under_stuck_node():
    """Receiver side: a node stuck past the half-election-timeout budget
    EBUSYs its remaining items in the batch AND answers later batches
    EBUSY immediately (no stacking of shielded handlers), while healthy
    nodes in the same batch are served normally."""
    from tpuraft.core.node_manager import NodeManager
    from tpuraft.rpc.messages import ErrorResponse
    from tpuraft.rpc.transport import RpcServer

    release = asyncio.Event()

    def mk_mgr_node(stuck):
        async def handle(req):
            if stuck:
                await release.wait()
            return SimpleNamespace(success=True)
        return SimpleNamespace(
            options=SimpleNamespace(election_timeout_ms=100),
            handle_append_entries=handle)

    mgr = NodeManager(RpcServer("ep"))
    mgr._nodes[("g-stuck", "p1")] = mk_mgr_node(True)
    mgr._nodes[("g-ok", "p1")] = mk_mgr_node(False)

    def item(gid):
        return SimpleNamespace(group_id=gid, peer_id="p1")

    req = SimpleNamespace(items=[item("g-stuck"), item("g-ok"),
                                 item("g-stuck"), item("g-ok")])
    resp = await mgr._handle_multi_append(req)
    stuck_acks = [resp.items[0], resp.items[2]]
    ok_acks = [resp.items[1], resp.items[3]]
    assert all(isinstance(a, ErrorResponse)
               and a.code == int(RaftError.EBUSY) for a in stuck_acks)
    assert all(getattr(a, "success", False) for a in ok_acks)
    # the stuck handler is still running: a follow-up batch must answer
    # EBUSY at once, without waiting out another budget
    t0 = asyncio.get_running_loop().time()
    resp2 = await mgr._handle_multi_append(
        SimpleNamespace(items=[item("g-stuck")]))
    assert asyncio.get_running_loop().time() - t0 < 0.05
    assert resp2.items[0].code == int(RaftError.EBUSY)
    release.set()  # let the shielded handler finish (clean teardown)
    await asyncio.sleep(0.01)


async def test_legacy_fallback_for_receiver_without_batch_handlers():
    """An endpoint whose server predates the batch plane (no multi_*
    handlers) gets single RPCs after one failed batch probe."""
    from tests.cluster import TestCluster

    c = TestCluster(3, election_timeout_ms=1000)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        # strip the batch handlers from one follower's server to
        # simulate an old receiver
        follower_ep = next(p for p in c.peers if p != leader.server_id)
        server = c.managers[follower_ep].server
        server._handlers.pop("multi_append", None)
        server._handlers.pop("multi_vote", None)
        await c.apply_ok(leader, b"via-legacy")
        await c.wait_applied(1)
        sender = leader.node_manager.send_plane.sender(follower_ep.endpoint)
        assert sender._legacy is True
        # and replication still flows
        await c.apply_ok(leader, b"more")
        await c.wait_applied(2)
    finally:
        await c.stop_all()
