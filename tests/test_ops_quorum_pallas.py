"""Pallas fused-quorum kernel vs the XLA oracle (tpuraft.ops.ballot).

The kernel runs under ``interpret=True`` here (CPU test mesh); on real
TPU hardware the same kernel body compiles via Mosaic.  Bit-equality is
required — the kernel replaces the oracle, it must not approximate it.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from tpuraft.ops.quorum_pallas import fused_quorum
from tpuraft.ops.tick import ROLE_LEADER, GroupState, TickParams, raft_tick


def _random_case(rng, G, P, joint_frac=0.3):
    match = jnp.asarray(rng.integers(-1, 100, (G, P)).astype(np.int32))
    ack = jnp.asarray(rng.integers(0, 10_000, (G, P)).astype(np.int32))
    granted = jnp.asarray(rng.random((G, P)) < 0.5)
    vm = jnp.asarray(rng.random((G, P)) < 0.6)
    ovm = jnp.asarray(
        (rng.random((G, P)) < 0.4) & (rng.random((G, 1)) < joint_frac))
    return match, granted, ack, vm, ovm


@pytest.mark.parametrize("g,p", [(1, 4), (7, 8), (130, 8), (700, 16)])
def test_kernel_matches_oracle(g, p):
    rng = np.random.default_rng(g * 31 + p)
    match, granted, ack, vm, ovm = _random_case(rng, g, p)
    ref = fused_quorum(match, granted, ack, vm, ovm, impl="xla")
    out = fused_quorum(match, granted, ack, vm, ovm, impl="pallas_interpret")
    for name, x, y in zip(("quorum_idx", "elected", "q_ack"), ref, out):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{name} G={g} P={p}")


def test_all_masked_and_single_voter():
    """Degenerate configurations: no voters (inactive slot rows) and
    single-voter groups (commit == own match, elected by self-vote)."""
    G, P = 8, 4
    match = jnp.arange(G * P, dtype=jnp.int32).reshape(G, P)
    ack = match * 2
    granted = jnp.ones((G, P), bool)
    vm = jnp.zeros((G, P), bool).at[4:, 0].set(True)  # rows 0-3: no voters
    ovm = jnp.zeros((G, P), bool)
    ref = fused_quorum(match, granted, ack, vm, ovm, impl="xla")
    out = fused_quorum(match, granted, ack, vm, ovm, impl="pallas_interpret")
    for x, y in zip(ref, out):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_raft_tick_same_under_both_impls():
    rng = np.random.default_rng(7)
    G, P = 64, 8
    state = GroupState.zeros(G, P)
    state.role = jnp.asarray(rng.integers(0, 3, (G,)).astype(np.int32))
    state.match_rel = jnp.asarray(rng.integers(0, 50, (G, P)).astype(np.int32))
    state.pending_rel = jnp.ones((G,), jnp.int32)
    state.granted = jnp.asarray(rng.random((G, P)) < 0.6)
    voter = np.zeros((G, P), bool)
    voter[:, :3] = True
    state.voter_mask = jnp.asarray(voter)
    state.last_ack = jnp.asarray(rng.integers(0, 2_000, (G, P)).astype(np.int32))
    params = TickParams.make(1000, 100, 900)
    s1, o1 = raft_tick(state, jnp.int32(1500), params, quorum_impl="xla")
    s2, o2 = raft_tick(state, jnp.int32(1500), params,
                       quorum_impl="pallas_interpret")
    for name in ("commit_rel", "commit_advanced", "elected", "election_due",
                 "step_down", "hb_due", "lease_valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(o1, name)), np.asarray(getattr(o2, name)),
            err_msg=name)
    np.testing.assert_array_equal(np.asarray(s1.commit_rel),
                                  np.asarray(s2.commit_rel))
