"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports,
so sharding tests exercise a realistic mesh without TPU hardware
(SURVEY.md §5 lesson: N real nodes, one process)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def event_loop_policy():
    return asyncio.DefaultEventLoopPolicy()
