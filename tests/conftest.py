"""Test env: force JAX onto CPU with 8 virtual devices so sharding tests
exercise a realistic mesh without TPU hardware (SURVEY.md §5 lesson:
N real nodes, one process).

Note: this machine's sitecustomize imports jax before pytest loads this
file, so env vars alone are too late — but the backend is not initialized
until the first jax.devices() call, so config.update still takes effect."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # for subprocesses
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the device-count knob as a config option; the
    # installed 0.4.37 doesn't have it and the XLA_FLAGS fallback above
    # already forces 8 host devices — collection must not die either way
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test via asyncio.run")


# Hard cap per async test so a protocol deadlock fails the one test loudly
# instead of wedging the whole suite (first JAX compiles can take ~40s;
# integration tests poll with 5s deadlines — 120s is comfortably above both).
ASYNC_TEST_TIMEOUT_S = 120


def pytest_pyfunc_call(pyfuncitem):
    """Minimal asyncio test support (pytest-asyncio is not in the image)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }

        async def capped():
            await asyncio.wait_for(func(**kwargs), ASYNC_TEST_TIMEOUT_S)

        asyncio.run(capped())
        return True
    return None
