"""Fleet observability plane (ISSUE 13): per-region heat telemetry,
PD cluster view, and device-tick profiling.

Covers the tracker's seeded decay/convergence math, the noise gate, the
heartbeat wire extension BOTH directions (old client <-> new PD and
vice versa), the unified ClusterStatsManager intake (ONE region-stats
path for keys + heat), hot-region detection through the flight
recorder, the PD cluster view over the real RPC, the metrics_text TTL
render cache, and the engine's tick-phase histograms / [G]-lane
occupancy gauges / --profile-ticks perfetto export.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from tpuraft.util.heat import (RegionHeatTracker, decode_heat_rows,
                               encode_heat_rows, heat_changed, heat_score)

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# RegionHeatTracker units (seeded, injectable clock)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t


def test_tracker_converges_to_offered_rate():
    """Constant offered load at a fixed fold cadence converges the EWMA
    to the true rate; two identically-driven trackers are bit-equal
    (seeded determinism — the bench A/B contract)."""
    def drive() -> RegionHeatTracker:
        clk = _Clock()
        t = RegionHeatTracker(half_life_s=2.0, clock=clk)
        for _ in range(60):
            t.note_write(7, ops=50, bytes_in=800)
            t.note_read(7, ops=25, bytes_out=400)
            clk.t += 1.0
            t.fold()
        return t

    a, b = drive(), drive()
    h = a.heat(7)
    # 60 folds at half_life 2s: the EWMA has fully settled
    assert h.writes_s == pytest.approx(50.0, rel=0.01)
    assert h.reads_s == pytest.approx(25.0, rel=0.01)
    assert h.bytes_in_s == pytest.approx(800.0, rel=0.01)
    assert h.bytes_out_s == pytest.approx(400.0, rel=0.01)
    hb = b.heat(7)
    assert (h.writes_s, h.reads_s, h.bytes_in_s, h.bytes_out_s) == \
        (hb.writes_s, hb.reads_s, hb.bytes_in_s, hb.bytes_out_s)
    assert a.counters() == b.counters()


def test_tracker_decays_idle_region_and_forgets_it():
    clk = _Clock()
    t = RegionHeatTracker(half_life_s=1.0, clock=clk)
    t.note_write(3, ops=100)
    clk.t += 1.0
    t.fold()
    assert t.heat(3).writes_s > 0
    # silence: each 1s fold halves the rate (half_life=1); after ~20
    # half-lives the region is below noise and gets forgotten
    for _ in range(25):
        clk.t += 1.0
        t.fold()
    assert t.heat(3).writes_s == 0.0
    assert 3 not in t.snapshot()
    assert t.gauges()["heat_regions_tracked"] == 0


def test_tracker_top_coldest_and_drop():
    clk = _Clock()
    t = RegionHeatTracker(half_life_s=5.0, clock=clk)
    for rid, ops in ((1, 5), (2, 500), (3, 50)):
        t.note_write(rid, ops=ops)
    clk.t += 1.0
    t.fold()
    assert [rid for rid, _ in t.top(2)] == [2, 3]
    assert [rid for rid, _ in t.coldest(1)] == [1]
    t.drop(2)
    assert 2 not in t.snapshot()
    assert [rid for rid, _ in t.top(2)] == [3, 1]
    assert "RegionHeatTracker" in t.describe()


def test_tracker_applied_lane_keeps_region_alive_but_off_the_score():
    """Follower-side apply traffic is tracked (local visibility) but
    does NOT contribute to the serving score the PD ranks on."""
    clk = _Clock()
    t = RegionHeatTracker(half_life_s=1.0, clock=clk)
    t.note_applied(9, ops=100)
    clk.t += 1.0
    t.fold()
    h = t.heat(9)
    assert h.applied_s > 0
    assert h.score == 0.0


def test_fold_zero_dt_is_noop():
    clk = _Clock()
    t = RegionHeatTracker(clock=clk)
    t.note_write(1, ops=10)
    assert t.fold() == 0.0          # clock didn't advance
    assert t.heat(1).writes_s == 0.0


# ---------------------------------------------------------------------------
# noise gate + score
# ---------------------------------------------------------------------------


def test_heat_changed_noise_gate():
    # sub-absolute moves are noise regardless of ratio
    assert not heat_changed(0.4, 0.0)
    # >= min_abs AND >= ~12.5% relative: reportable
    assert heat_changed(10.0, 0.0)
    assert heat_changed(85.0, 100.0)   # 15% move: past the ~12.5% gate
    # steady heat (tiny relative move) stays gated — the delta plane
    # must not re-dirty every heartbeat round
    assert not heat_changed(101.0, 100.0)
    assert not heat_changed(99.0, 100.0)
    # decays to cold are reportable once big enough
    assert heat_changed(0.0, 8.0)


def test_heat_score_single_definition():
    # ops dominate; payload weighs in at one op per 4KiB
    assert heat_score(2.0, 3.0, 0.0, 0.0) == 5.0
    assert heat_score(0.0, 0.0, 4096.0, 4096.0) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# wire codec + heartbeat wire-compat both directions
# ---------------------------------------------------------------------------


def test_heat_rows_codec_roundtrip_and_tolerance():
    rows = [(1, 10.0, 5.0, 100.0, 50.0), (77, 0.5, 0.25, 8.0, 4.0)]
    blob = encode_heat_rows(rows)
    got = decode_heat_rows(blob)
    assert [r[0] for r in got] == [1, 77]
    assert got[0][1] == pytest.approx(10.0)
    assert encode_heat_rows([]) == b""
    assert decode_heat_rows(b"") == []
    # a trailing partial row (torn frame) is dropped, not raised
    assert len(decode_heat_rows(blob[:-5])) == 1


def test_store_heartbeat_heat_wire_compat_both_directions():
    """StoreHeartbeatBatchRequest gained trailing heat/replicas fields.
    Old frames decode on new receivers with defaults; a new frame is a
    strict extension whose prefix an old decoder reads identically."""
    from tpuraft.rheakv.pd_messages import StoreHeartbeatBatchRequest
    from tpuraft.rpc.messages import decode_message, encode_message

    heat = encode_heat_rows([(4, 100.0, 10.0, 0.0, 0.0)])
    new = StoreHeartbeatBatchRequest(
        store_id=9, endpoint="127.0.0.1:1", deltas=[b"d0"], full=True,
        zone="z1", health="healthy", heat=heat,
        replicas=12, replicas_quiescent=5)
    wire = encode_message(new)
    got = decode_message(wire)
    assert got.heat == heat
    assert (got.replicas, got.replicas_quiescent) == (12, 5)
    assert decode_heat_rows(got.heat)[0][0] == 4
    # old sender -> new receiver: strip the trailing heat bytes field
    # (4-byte length prefix + payload) + two trailing i64s
    old_wire = wire[:-(4 + len(heat) + 8 + 8)]
    old_got = decode_message(old_wire)
    assert old_got.heat == b"" and old_got.replicas == 0
    assert old_got.deltas == [b"d0"] and old_got.health == "healthy"
    # new -> old receiver: the old-format prefix is byte-identical, so
    # an old decoder (which stops after health) reads the same values
    old_fmt = encode_message(StoreHeartbeatBatchRequest(
        store_id=9, endpoint="127.0.0.1:1", deltas=[b"d0"], full=True,
        zone="z1", health="healthy"))
    assert wire[:len(old_wire)] == old_fmt[:len(old_wire)]


def test_cluster_describe_messages_roundtrip():
    from tpuraft.rheakv.pd_messages import (ClusterDescribeRequest,
                                            ClusterDescribeResponse)
    from tpuraft.rpc.messages import decode_message, encode_message

    req = decode_message(encode_message(ClusterDescribeRequest(top_k=4)))
    assert req.top_k == 4
    resp = decode_message(encode_message(ClusterDescribeResponse(
        view_json='{"regions": 3}')))
    assert json.loads(resp.view_json) == {"regions": 3}


# ---------------------------------------------------------------------------
# ClusterStatsManager: ONE region-stats path (keys + heat)
# ---------------------------------------------------------------------------


def _stats(threshold=0):
    from tpuraft.rheakv.pd_server import ClusterStatsManager

    return ClusterStatsManager(split_threshold_keys=threshold)


def test_cluster_stats_unified_intake():
    s = _stats(threshold=100)
    s.record(1, 150)
    s.record_heat(1, 10.0, 5.0, 0.0, 0.0)
    # ONE record: the split policy reads keys, the view reads heat,
    # from the same entry
    ent = s.region_stats(1)
    assert ent.keys == 150 and ent.writes_s == 10.0
    assert s.last_keys(1) == 150
    assert s.should_split(1)
    s.mark_split_issued(1)
    # keys reset on split; the heat rates survive (load keeps landing
    # until clients re-route)
    assert s.last_keys(1) == 0
    assert s.region_stats(1).writes_s == 10.0


def test_cluster_stats_top_hot_and_cold():
    s = _stats()
    s.record_heat(1, 1.0, 0.0, 0.0, 0.0)
    s.record_heat(2, 50.0, 0.0, 0.0, 0.0)
    s.record(3, 10)  # keys only: zero heat
    assert [rid for rid, _ in s.top_hot(8)] == [2, 1]   # zero-score excluded
    assert [rid for rid, _ in s.top_cold(1)] == [3]


def test_hot_region_detection_fires_recorder_with_hysteresis():
    from tpuraft.util.trace import RECORDER

    s = _stats()
    s.hot_min_score = 5.0
    s.hot_factor = 2.0
    # background fleet: 20 cool regions
    for rid in range(10, 30):
        s.record_heat(rid, 0.5, 0.0, 0.0, 0.0)
    # one region goes hot past max(5.0, 2 x background p50)
    s._hot_recalc_at = 0.0  # sweep now sees the full population
    s.record_heat(1, 100.0, 0.0, 0.0, 0.0)
    assert 1 in s.hot_regions()
    assert s.hot_events == 1
    # recorder events are (ts, kind, group, detail) tuples
    evs = [e for e in RECORDER.events()
           if e[1] == "hot_region" and e[2] == "1"]
    assert evs and evs[-1][3]["score"] == pytest.approx(100.0)
    # staying hot does not re-fire
    s.record_heat(1, 110.0, 0.0, 0.0, 0.0)
    assert s.hot_events == 1
    # hysteresis: cools only below half the threshold
    s._hot_recalc_at = 0.0  # force a threshold refresh on next intake
    s.record_heat(1, s._hot_threshold * 0.75, 0.0, 0.0, 0.0)
    assert 1 in s.hot_regions()
    s.record_heat(1, 0.1, 0.0, 0.0, 0.0)
    assert 1 not in s.hot_regions()


def test_hot_detection_bootstrap_and_small_fleet_shape():
    """The two shapes the first-cut detector got wrong: a half-reported
    bootstrap fleet must not mass-flag off a floor threshold, and in a
    small fleet the hot set (which IS the score tail) must flag against
    the BACKGROUND median, not a tail percentile of itself."""
    s = _stats()
    # bootstrap: below hot_min_population heated regions, never flag
    for rid in range(4):
        s.record_heat(rid, 50.0, 0.0, 0.0, 0.0)
    assert s.hot_regions() == set()
    assert s.hot_events == 0
    # steady 3-hot-of-24 (the hotspot soak's shape): background at 10,
    # hot set at 300 — exactly the hot regions flag, none of the
    # background does, and a uniform fleet would flag nothing
    for rid in range(24):
        s.record_heat(rid, 10.0, 0.0, 0.0, 0.0)
    s._hot_recalc_at = 0.0
    for rid in (1, 5, 9):
        s.record_heat(rid, 300.0, 0.0, 0.0, 0.0)
    assert s.hot_regions() == {1, 5, 9}
    assert s.hot_events == 3


def test_hot_sweep_zeroes_stale_rates_and_cools_silent_regions():
    """A reporter that goes silent (leadership moved, region gone) must
    not leave standing rates in the view: the 1/s sweep zeroes rates
    older than heat_stale_s and re-judges flagged regions without
    waiting for an intake row the noise gate may never send."""
    import time as _time

    s = _stats()
    for rid in range(12):
        s.record_heat(rid, 10.0, 0.0, 0.0, 0.0)
    s._hot_recalc_at = 0.0
    s.record_heat(3, 500.0, 0.0, 0.0, 0.0)
    assert 3 in s.hot_regions()
    past = _time.monotonic() - (s.heat_stale_s + 1.0)
    for rid in range(12):
        s._stats[rid].heat_at = past
    s._hot_recalc_at = 0.0
    s.maybe_sweep()
    assert all(s.region_stats(r).writes_s == 0.0 for r in range(12))
    # the flagged region cooled via the sweep, not via an intake row
    assert s.hot_regions() == set()
    # keys survive staleness (matches the legacy keys-only intake)
    s.record(5, 77)
    s._stats[5].heat_at = past
    s._hot_recalc_at = 0.0
    s.maybe_sweep()
    assert s.last_keys(5) == 77


def test_hot_flags_survive_population_dip():
    """A brief reporter dropout (heated population below the gate)
    must neither erase live standing flags nor admit new ones — the
    hot_region signal must not flap on a population-count transient."""
    import time as _time

    s = _stats()
    for rid in range(12):
        s.record_heat(rid, 10.0, 0.0, 0.0, 0.0)
    s._hot_recalc_at = 0.0
    s.record_heat(3, 500.0, 0.0, 0.0, 0.0)
    assert 3 in s.hot_regions()
    events_before = s.hot_events
    # 9 of 12 reporters go stale -> heated dips below hot_min_population
    past = _time.monotonic() - (s.heat_stale_s + 1.0)
    for rid in range(12):
        if rid not in (1, 2, 3):
            s._stats[rid].heat_at = past
    s._hot_recalc_at = 0.0
    s.maybe_sweep()
    assert s._hot_threshold is None
    assert 3 in s.hot_regions()      # live flag survives the dip
    # intake during the dip neither flags nor cools
    s.record_heat(2, 400.0, 0.0, 0.0, 0.0)
    assert 2 not in s.hot_regions()
    s.record_heat(3, 450.0, 0.0, 0.0, 0.0)
    assert 3 in s.hot_regions()
    assert s.hot_events == events_before


async def test_heat_report_keepalive_re_reports_steady_heat(tmp_path):
    """Store side of the staleness pairing: the noise gate suppresses
    unchanged heat, so without the heat_refresh_s keepalive a steadily
    hot region would be expired by the PD's sweep and vanish from the
    view.  A row older than the refresh interval must re-report even
    with zero score movement."""
    import time as _time

    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

    net = InProcNetwork()
    ep = "127.0.0.1:6903"
    server = RpcServer(ep)
    net.bind(server)
    opts = StoreEngineOptions(
        server_id=ep,
        initial_regions=[Region(id=1, peers=[ep])],
        election_timeout_ms=200,
        data_path=str(tmp_path))
    store = StoreEngine(opts, server, InProcTransport(net, ep))
    await store.start()
    try:
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            if store.leader_region_ids() == [1]:
                break
            await asyncio.sleep(0.02)
        assert store.leader_region_ids() == [1]
        await asyncio.sleep(0.2)   # let a fold window accumulate time
        store.heat.note_write(1, ops=500, bytes_in=500)
        rows = store._heat_report(full=False)
        assert [r[0][0] for r in rows] == [1]   # first report: gate passes
        now = _time.monotonic()
        store._pd_heat_reported.update(
            {row[0]: (score, now) for row, score in rows})
        # steady heat: the very next round is noise-gated
        assert store._heat_report(full=False) == []
        # ...until the standing row ages past the keepalive interval
        score, _t = store._pd_heat_reported[1]
        store._pd_heat_reported[1] = (
            score, now - store.opts.heat_refresh_s - 1.0)
        rows = store._heat_report(full=False)
        assert [r[0][0] for r in rows] == [1]
    finally:
        await store.shutdown()


# ---------------------------------------------------------------------------
# PD intake + cluster view over the real RPC
# ---------------------------------------------------------------------------


async def test_pd_cluster_view_over_wire(tmp_path):
    """Heat rows + occupancy ride the heartbeat into the PD; the
    pd_cluster_describe RPC serves the folded view (top-K hot, zone
    rates, hibernation fraction, store roster)."""
    from tests.kv_cluster import PDTestCluster
    from tpuraft.rheakv.pd_messages import StoreHeartbeatBatchRequest
    from tpuraft.rheakv.pd_messages import encode_region_delta
    from tpuraft.rheakv.metadata import Region

    c = PDTestCluster(n_stores=0, n_pd=1, tmp_path=tmp_path)
    for ep in c.pd_endpoints:
        await c.start_pd(ep)
    try:
        await c.wait_pd_leader()
        pd_client = c.pd_client()
        r1 = Region(id=1, start_key=b"", end_key=b"m",
                    peers=["127.0.0.1:9001"])
        r2 = Region(id=2, start_key=b"m", end_key=b"",
                    peers=["127.0.0.1:9001"])
        req = StoreHeartbeatBatchRequest(
            store_id=1, endpoint="127.0.0.1:9001",
            deltas=[encode_region_delta(r.encode(), "127.0.0.1:9001", 10)
                    for r in (r1, r2)],
            full=True, zone="z-east", health="healthy",
            heat=encode_heat_rows([(1, 40.0, 10.0, 0.0, 0.0),
                                   (2, 1.0, 0.0, 0.0, 0.0)]),
            replicas=8, replicas_quiescent=6)
        resp = await pd_client._call("pd_store_heartbeat_batch", req)
        assert resp.success
        view = await pd_client.cluster_describe(top_k=2)
        assert view is not None
        assert view["regions"] == 2
        assert [r["region"] for r in view["hot"]] == [1, 2]
        assert view["hot"][0]["writes_s"] == pytest.approx(40.0)
        assert view["hot"][0]["keys"] == 10
        assert view["zone_rates"]["z-east"]["writes_s"] == \
            pytest.approx(41.0)
        assert view["hibernation"] == {
            "replicas": 8, "quiescent": 6, "fraction": 0.75}
        store_row = view["stores"][0]
        assert store_row["zone"] == "z-east"
        assert store_row["replicas_quiescent"] == 6
        # PD-side Prometheus text serves the same aggregates
        pd = await c.wait_pd_leader()
        text = pd.metrics_text()
        assert "tpuraft_pd_hb_heat_rows" in text
        assert "tpuraft_pd_hibernation_fraction" in text
        assert "tpuraft_pd_regions" in text
    finally:
        await c.stop_all()


async def test_cluster_describe_against_old_pd_returns_none():
    """A pre-observability PD has no pd_cluster_describe handler: the
    client's capability probe answers None instead of raising."""
    from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

    net = InProcNetwork()
    ep = "127.0.0.1:7999"
    server = RpcServer(ep)   # no handlers registered at all
    net.bind(server)
    net.start_endpoint(ep)
    client = RemotePlacementDriverClient(
        InProcTransport(net, "probe:0"), [ep])
    assert await client.cluster_describe() is None


# ---------------------------------------------------------------------------
# metrics_text TTL render cache
# ---------------------------------------------------------------------------


async def test_metrics_text_ttl_cache(tmp_path):
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

    net = InProcNetwork()
    ep = "127.0.0.1:6901"
    server = RpcServer(ep)
    net.bind(server)
    opts = StoreEngineOptions(
        server_id=ep,
        initial_regions=[Region(id=1, peers=[ep])],
        election_timeout_ms=200,
        data_path=str(tmp_path),
        metrics_cache_ttl_ms=10_000)
    store = StoreEngine(opts, server, InProcTransport(net, ep))
    await store.start()
    try:
        t1 = store.metrics_text()
        t2 = store.metrics_text()
        assert store.metrics_renders == 1
        assert store.metrics_cache_hits == 1
        # the cached render is served verbatim; only the age gauge moves
        base1 = t1.split("tpuraft_metrics_age_seconds")[0]
        base2 = t2.split("tpuraft_metrics_age_seconds")[0]
        assert base1 == base2
        assert "tpuraft_metrics_age_seconds" in t2
        # age stays bounded by the TTL
        age = float(t2.rsplit(" ", 1)[-1])
        assert 0.0 <= age <= 10.0
        # ttl=0 renders every call (tests/debugging knob)
        store.opts.metrics_cache_ttl_ms = 0
        store.metrics_text()
        store.metrics_text()
        assert store.metrics_renders == 3
        # the per-region aggregation the cache bounds is present
        assert "tpuraft_fsm_applied_entries" in t1
        assert "tpuraft_proposed_ops" in t1
        assert "tpuraft_heat_regions_tracked" in t1
    finally:
        await store.shutdown()


# ---------------------------------------------------------------------------
# device-tick profiling: phase histograms, lane gauges, perfetto export
# ---------------------------------------------------------------------------


def _numpy_engine(g: int = 8):
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions

    return MultiRaftEngine(TickOptions(max_groups=g, max_peers=3,
                                       backend="numpy"))


def test_tick_phase_histograms_count_ticks():
    e = _numpy_engine()
    for _ in range(5):
        e.tick_once()
    hists = e.tick_histograms()
    assert set(hists) == {"tick_total_ms", "tick_build_ms",
                          "tick_device_ms", "tick_apply_ms"}
    assert all(h["count"] == 5 for h in hists.values())
    assert hists["tick_total_ms"]["p99"] >= 0.0
    assert "tick_p99_ms" in e.describe()


def test_lane_stats_matches_engine_arrays():
    from tpuraft.ops.tick import ROLE_CANDIDATE, ROLE_FOLLOWER, ROLE_LEADER

    e = _numpy_engine(g=16)
    e.has_ctrl[:8] = True
    e.role[:4] = ROLE_LEADER
    e.role[4:6] = ROLE_FOLLOWER
    e.role[6] = ROLE_CANDIDATE
    e.quiescent[:3] = True
    # an uncontrolled slot must not count, quiescent or not
    e.role[12] = ROLE_LEADER
    e.quiescent[12] = True
    ls = e.lane_stats()
    assert ls["groups"] == 8
    assert ls["leaders"] == 4
    assert ls["followers"] == 2
    assert ls["candidates"] == 1
    assert ls["quiescent"] == 3
    assert ls["hibernation_fraction"] == pytest.approx(3 / 8)
    assert ls["q_ack_age_ms_p99"] >= 0.0


def test_profile_ticks_window_exports_perfetto_timeline(tmp_path):
    e = _numpy_engine()
    out = tmp_path / "ticks.json"
    assert e.export_tick_timeline(str(out)) == 0   # nothing armed
    e.profile_ticks(3)
    for _ in range(5):                              # window is 3 ticks
        e.tick_once()
    n = e.export_tick_timeline(str(out))
    assert n == 3 * 4   # root + build/device/apply per tick
    doc = json.loads(out.read_text())
    evs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    names = {ev["name"] for ev in evs}
    assert names == {"tick", "tick_build", "tick_device", "tick_apply"}
    roots = [ev for ev in evs if ev["name"] == "tick"]
    assert [r["args"]["seq"] for r in roots] == [1, 2, 3]
    # phase spans nest inside their tick span
    t0 = min(ev["ts"] for ev in evs)
    root0 = min(roots, key=lambda r: r["ts"])
    assert root0["ts"] == t0
    # disarmed after the window: later ticks record nothing more
    e.tick_once()
    assert e.export_tick_timeline(str(out)) == 3 * 4


async def test_tick_occupancy_matches_quiescent_count(tmp_path):
    """StoreEngine.tick_occupancy reports (controlled, quiescent) from
    the engine arrays — the pair the heartbeat ships to the PD."""
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

    net = InProcNetwork()
    ep = "127.0.0.1:6902"
    server = RpcServer(ep)
    net.bind(server)
    opts = StoreEngineOptions(
        server_id=ep,
        initial_regions=[Region(id=1, peers=[ep])],
        election_timeout_ms=200,
        data_path=str(tmp_path))
    store = StoreEngine(opts, server, InProcTransport(net, ep))
    await store.start()
    try:
        # timer mode: every hosted region counts, none hibernate
        assert store.tick_occupancy() == (1, 0)
        e = _numpy_engine(g=8)
        e.has_ctrl[:5] = True
        e.quiescent[1:3] = True
        e.quiescent[7] = True      # uncontrolled: not counted
        store.multi_raft_engine = e
        assert store.tick_occupancy() == (5, 2)
    finally:
        store.multi_raft_engine = None
        await store.shutdown()
