"""Scalar oracle: per-index Ballot semantics exactly as the reference
implements them (core:entity/Ballot, core:core/BallotBox) — used to
property-test the vectorized order-statistic kernels against.

Also the MEMBERSHIP oracle: quorum-intersection math and the legal
committed-configuration sequence (old -> joint -> new) that the
membership-churn chaos drives assert after every fault.
"""

from __future__ import annotations

from typing import Iterable


class OracleBallot:
    """One pending log index's quorum tracker (reference: Ballot#grant)."""

    def __init__(self, voters: set[int], old_voters: set[int] | None = None):
        self.voters = set(voters)
        self.old_voters = set(old_voters) if old_voters else set()
        self.granted: set[int] = set()

    def grant(self, peer: int) -> None:
        self.granted.add(peer)

    def is_granted(self) -> bool:
        new_ok = len(self.granted & self.voters) >= len(self.voters) // 2 + 1
        if not self.old_voters:
            return new_ok
        old_ok = len(self.granted & self.old_voters) >= len(self.old_voters) // 2 + 1
        return new_ok and old_ok


def oracle_commit_index(
    match: dict[int, int],
    voters: set[int],
    old_voters: set[int] | None,
    pending_index: int,
    last_log_index: int,
    current_commit: int,
) -> int:
    """Reference BallotBox#commitAt semantics, brute force:

    walk indexes [pending_index .. last_log_index]; index i commits iff a
    quorum of voters (and of old voters, in joint mode) have match >= i.
    Commit stops at the first non-granted index (ballots are consumed in
    order) and never regresses below current_commit.
    """
    commit = current_commit
    for i in range(pending_index, last_log_index + 1):
        b = OracleBallot(voters, old_voters)
        for p, m in match.items():
            if m >= i:
                b.grant(p)
        if b.is_granted():
            commit = max(commit, i)
        else:
            break
    return commit


# ---------------------------------------------------------------------------
# membership oracle
# ---------------------------------------------------------------------------


# the arithmetic lives in tpuraft/util/quorum.py so the soak's live
# invariant check (examples/soak.py, which can't import tests/) shares
# ONE implementation with this oracle — re-exported here for the tests
from tpuraft.util.quorum import (  # noqa: F401  (re-export)
    every_majority_has_data_peer,
    joint_quorums_intersect,
    majorities,
    majorities_intersect,
    witness_minority,
    witness_only_majorities,
)

# keyspace-coverage oracle (region lifecycle): the implementation lives
# in tpuraft/rheakv/keyspace.py for the same soak-shares-it reason
from tpuraft.rheakv.keyspace import (  # noqa: F401  (re-export)
    assert_covers,
    coverage_errors,
)


def check_conf_sequence(entries: Iterable[tuple[Iterable, Iterable]]) -> None:
    """Assert a committed CONFIGURATION-entry sequence is a legal chain
    of joint-consensus transitions.

    ``entries``: (peers, old_peers) tuples in commit order.  Invariants
    (the ISSUE's "committed conf is always one of {old, joint, new}"):

    - a joint entry's old side must equal the current stable conf;
    - a stable entry must be either the current stable conf re-committed
      (a new leader's no-op conf entry — legal only while NO joint is
      pending: once the joint entry commits, leader completeness bars
      any future leader from committing plain C_old again) or the new
      side of the pending joint;
    - every transition's quorum systems must intersect.
    """
    last_stable: frozenset | None = None
    pending: frozenset | None = None
    for i, (peers, old) in enumerate(entries):
        peers, old = frozenset(peers), frozenset(old)
        assert peers, f"entry {i}: empty voter set committed"
        if old:
            assert last_stable is None or old == last_stable, (
                f"entry {i}: joint leaves old={set(old)} but the stable "
                f"conf is {set(last_stable)}")
            assert joint_quorums_intersect(old, peers), (
                f"entry {i}: joint {set(old)}->{set(peers)} lacks quorum "
                f"intersection")
            pending = peers
            if last_stable is None:
                last_stable = old
        else:
            ok = (last_stable is None
                  or (pending is None and peers == last_stable)
                  or peers == pending)
            assert ok, (
                f"entry {i}: stable conf {set(peers)} is not "
                + (f"the pending new conf {set(pending)} (a stable "
                   f"C_old after the joint committed is a rollback)"
                   if pending is not None else
                   f"the current conf {set(last_stable)} re-committed"))
            if peers == pending:
                assert joint_quorums_intersect(last_stable, peers), (
                    f"entry {i}: transition {set(last_stable)} -> "
                    f"{set(peers)} lacks quorum intersection")
                last_stable = peers
                pending = None
            elif last_stable is None:
                last_stable = peers
