"""Scalar oracle: per-index Ballot semantics exactly as the reference
implements them (core:entity/Ballot, core:core/BallotBox) — used to
property-test the vectorized order-statistic kernels against.
"""

from __future__ import annotations


class OracleBallot:
    """One pending log index's quorum tracker (reference: Ballot#grant)."""

    def __init__(self, voters: set[int], old_voters: set[int] | None = None):
        self.voters = set(voters)
        self.old_voters = set(old_voters) if old_voters else set()
        self.granted: set[int] = set()

    def grant(self, peer: int) -> None:
        self.granted.add(peer)

    def is_granted(self) -> bool:
        new_ok = len(self.granted & self.voters) >= len(self.voters) // 2 + 1
        if not self.old_voters:
            return new_ok
        old_ok = len(self.granted & self.old_voters) >= len(self.old_voters) // 2 + 1
        return new_ok and old_ok


def oracle_commit_index(
    match: dict[int, int],
    voters: set[int],
    old_voters: set[int] | None,
    pending_index: int,
    last_log_index: int,
    current_commit: int,
) -> int:
    """Reference BallotBox#commitAt semantics, brute force:

    walk indexes [pending_index .. last_log_index]; index i commits iff a
    quorum of voters (and of old voters, in joint mode) have match >= i.
    Commit stops at the first non-granted index (ballots are consumed in
    order) and never regresses below current_commit.
    """
    commit = current_commit
    for i in range(pending_index, last_log_index + 1):
        b = OracleBallot(voters, old_voters)
        for p, m in match.items():
            if m >= i:
                b.grant(p)
        if b.is_granted():
            commit = max(commit, i)
        else:
            break
    return commit
