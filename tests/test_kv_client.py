"""RheaKVStore client tests: routing, retry, failover, multi-region ops.

Reference parity tier: ``rhea .../client/DefaultRheaKVStoreTest``
(SURVEY.md §5 "RheaKV integration").
"""

import asyncio
import contextlib

from tests.kv_cluster import KVTestCluster
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient


@contextlib.asynccontextmanager
async def kv_client_cluster(regions=None, tmp_path=None, batching=None,
                            **kw):
    c = KVTestCluster(3, tmp_path=tmp_path, regions=regions, **kw)
    await c.start_all()
    pd = FakePlacementDriverClient(c.region_template)
    # FakePD's static view lacks peers filled in by the cluster helper
    pd._regions = {r.id: r.copy() for s in [next(iter(c.stores.values()))]
                   for r in s.list_regions()}
    client = RheaKVStore(pd, c.client_transport(), batching=batching)
    await client.start()
    try:
        yield c, client
    finally:
        await client.shutdown()
        await c.stop_all()


async def test_client_basic_ops():
    async with kv_client_cluster() as (c, kv):
        assert await kv.put(b"k", b"v")
        assert await kv.get(b"k") == b"v"
        assert await kv.contains_key(b"k")
        assert not await kv.contains_key(b"nope")
        assert await kv.put_if_absent(b"k", b"w") == b"v"
        assert await kv.compare_and_put(b"k", b"v", b"v2")
        assert await kv.get_and_put(b"k", b"v3") == b"v2"
        assert await kv.merge(b"m", b"a") and await kv.merge(b"m", b"b")
        assert await kv.get(b"m") == b"a,b"
        assert await kv.delete(b"k")
        assert await kv.get(b"k") is None


async def test_client_two_region_routing():
    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    async with kv_client_cluster(regions=regions) as (c, kv):
        # keys on both sides of the split
        assert await kv.put(b"apple", b"1")
        assert await kv.put(b"zebra", b"2")
        got = await kv.multi_get([b"apple", b"zebra", b"miss"])
        assert got == {b"apple": b"1", b"zebra": b"2", b"miss": None}
        assert await kv.put_list([(b"aa", b"x"), (b"zz", b"y")])
        # scan spans regions in order
        out = await kv.scan(b"", b"")
        assert [k for k, _ in out] == [b"aa", b"apple", b"zebra", b"zz"]
        # limit respected across regions
        out = await kv.scan(b"", b"", limit=3)
        assert len(out) == 3
        rev = await kv.reverse_scan(b"", b"")
        assert [k for k, _ in rev] == [b"zz", b"zebra", b"apple", b"aa"]
        assert await kv.delete_range(b"a", b"z")
        assert [k for k, _ in await kv.scan(b"", b"")] == [b"zebra", b"zz"]
        assert await kv.delete_list([b"zebra", b"zz"])
        assert await kv.scan(b"", b"") == []


async def test_client_survives_split():
    async with kv_client_cluster() as (c, kv):
        for i in range(32):
            assert await kv.put(b"key%02d" % i, b"v%d" % i)
        # server-side split happens under the client's feet
        leader = await c.wait_region_leader(1)
        st = await leader.store_engine.apply_split(1, 2)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(2)
        await c.wait_region_leader(2)
        # stale-epoch requests must transparently refresh + re-route
        assert await kv.get(b"key00") == b"v0"
        assert await kv.get(b"key31") == b"v31"
        assert await kv.put(b"key31", b"updated")
        assert await kv.get(b"key31") == b"updated"
        # client discovered both regions
        assert len(kv.route_table.list_regions()) == 2
        # full scan still sees everything, in order
        out = await kv.scan(b"", b"")
        assert len(out) == 32


async def test_client_fails_over_on_leader_kill(tmp_path):
    async with kv_client_cluster(tmp_path=tmp_path) as (c, kv):
        for i in range(5):
            assert await kv.put(b"d%d" % i, b"v%d" % i)
        leader = await c.wait_region_leader(1)
        await c.stop_store(leader.store_engine.server_id.endpoint)
        await c.wait_region_leader(1)
        assert await kv.get(b"d3") == b"v3"
        assert await kv.put(b"after", b"failover")
        assert await kv.get(b"after") == b"failover"


async def test_client_sequences():
    async with kv_client_cluster() as (c, kv):
        s1 = await kv.get_sequence(b"ids", 10)
        s2 = await kv.get_sequence(b"ids", 10)
        assert (s1.start, s1.end, s2.start, s2.end) == (0, 10, 10, 20)
        assert await kv.get_latest_sequence(b"ids") == 20
        assert await kv.reset_sequence(b"ids")
        assert (await kv.get_sequence(b"ids", 1)).start == 0


async def test_client_distributed_lock():
    async with kv_client_cluster() as (c, kv):
        lock_a = kv.get_distributed_lock(b"resource", lease_ms=60_000)
        lock_b = kv.get_distributed_lock(b"resource", lease_ms=60_000)
        assert await lock_a.try_lock()
        assert lock_a.fencing_token > 0
        assert not await lock_b.try_lock()
        # blocking lock with timeout fails while held
        assert not await lock_b.lock(timeout_ms=300, retry_interval_ms=50)
        assert await lock_a.unlock()
        assert await lock_b.lock(timeout_ms=2000)
        assert lock_b.fencing_token > lock_a.fencing_token
        await lock_b.unlock()


async def test_client_lock_watchdog_renews_short_lease():
    async with kv_client_cluster() as (c, kv):
        lock = kv.get_distributed_lock(b"wd", lease_ms=600)
        other = kv.get_distributed_lock(b"wd", lease_ms=600)
        assert await lock.try_lock(watchdog=True)
        await asyncio.sleep(1.2)  # beyond the original lease
        assert not await other.try_lock()  # renewal kept it held
        await lock.unlock()
        assert await other.try_lock()
        await other.unlock()


async def test_chaos_rolling_store_kills_no_acked_loss(tmp_path):
    """Chaos tier (reference: rheakv ChaosTest): sustained client load
    across two regions while stores are killed and restarted one at a
    time.  Every acked put must be readable afterwards."""
    import random

    rng = random.Random(11)
    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    async with kv_client_cluster(regions=regions, tmp_path=tmp_path) as (c, kv):
        acked: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer():
            attempt = 0
            while not stop.is_set():
                # unique key per attempt: an attempt whose ack was lost
                # may still have committed, which must not confuse the
                # exactly-the-acked-set verification
                side = b"a" if attempt % 2 == 0 else b"z"
                k = side + b"-chaos-%06d" % attempt
                v = b"v%d" % attempt
                attempt += 1
                try:
                    if await asyncio.wait_for(kv.put(k, v), 3.0):
                        acked[k] = v
                except Exception:
                    pass
                await asyncio.sleep(0)

        wtask = asyncio.ensure_future(writer())
        try:
            for _round in range(3):
                await asyncio.sleep(0.4)
                victim = rng.choice(c.endpoints)
                if victim not in c.stores:
                    continue
                await c.stop_store(victim)
                await asyncio.sleep(0.4)
                await c.start_store(victim)
        finally:
            stop.set()
            await wtask

        assert len(acked) > 20, f"only {len(acked)} acked under chaos"
        await c.wait_region_leader(1)
        await c.wait_region_leader(2)
        for k, v in acked.items():
            got = await kv.get(k)
            assert got == v, (k, got, v)
        # range reads see every acked key too
        rows = dict(await kv.scan(b"", b""))
        for k, v in acked.items():
            assert rows.get(k) == v, k


async def test_client_side_batching_coalesces_rpcs():
    """BatchingOptions (reference: rhea client Batching ring buffers):
    concurrent put/get calls issued in one loop iteration coalesce into
    per-region put_list/multi_get RPCs, preserving per-call results."""
    from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    c = KVTestCluster(3, regions=regions)
    await c.start_all()
    pd = FakePlacementDriverClient(c.region_template)
    pd._regions = {r.id: r.copy() for s in [next(iter(c.stores.values()))]
                   for r in s.list_regions()}
    transport = c.client_transport()
    calls = []
    orig_call = transport.call

    async def counting_call(dst, method, req, timeout_ms=None):
        calls.append(method)
        return await orig_call(dst, method, req, timeout_ms)

    transport.call = counting_call
    kv = RheaKVStore(pd, transport,
                     batching=BatchingOptions(enabled=True))
    await kv.start()
    try:
        for rid in (1, 2):
            await c.wait_region_leader(rid)
        n0 = len(calls)
        oks = await asyncio.gather(
            *[kv.put(b"a%03d" % i, b"v%d" % i) for i in range(20)],
            *[kv.put(b"z%03d" % i, b"w%d" % i) for i in range(20)])
        assert all(oks)
        put_rpcs = len(calls) - n0
        # 40 concurrent puts over 2 regions: a handful of batch RPCs,
        # not one per key
        assert put_rpcs <= 6, f"{put_rpcs} RPCs for 40 batched puts"

        n1 = len(calls)
        got = await asyncio.gather(
            *[kv.get(b"a%03d" % i) for i in range(20)],
            kv.get(b"missing"))
        assert got[:20] == [b"v%d" % i for i in range(20)]
        assert got[20] is None
        get_rpcs = len(calls) - n1
        assert get_rpcs <= 4, f"{get_rpcs} RPCs for 21 batched gets"

        # unbatched path still works alongside
        assert await kv.compare_and_put(b"a000", b"v0", b"v0x")
        assert await kv.get(b"a000") == b"v0x"
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_chaos_rolling_kills_on_native_engine(tmp_path):
    """The KV chaos tier on the C++ storage engine: rolling store kills
    and restarts under sustained client load, with every store's
    regions durably backed by native/kvstore.cc. Every acked put must
    survive."""
    import random

    from tpuraft.rheakv.native_store import NativeRawKVStore, ensure_built

    ensure_built()
    rng = random.Random(5)
    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    async with kv_client_cluster(
            regions=regions, tmp_path=tmp_path,
            raw_store_factory=lambda ep: NativeRawKVStore(
                str(tmp_path / ("nkv_" + ep.replace(":", "_"))),
                checkpoint_wal_bytes=16384)) as (c, kv):
        acked: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer():
            attempt = 0
            while not stop.is_set():
                side = b"a" if attempt % 2 == 0 else b"z"
                k = side + b"-nchaos-%06d" % attempt
                v = b"v%d" % attempt
                attempt += 1
                try:
                    if await asyncio.wait_for(kv.put(k, v), 3.0):
                        acked[k] = v
                except Exception:
                    pass
                await asyncio.sleep(0)

        wtask = asyncio.ensure_future(writer())
        try:
            for _round in range(3):
                await asyncio.sleep(0.4)
                victim = rng.choice(c.endpoints)
                if victim not in c.stores:
                    continue
                await c.stop_store(victim)
                await asyncio.sleep(0.4)
                await c.start_store(victim)
        finally:
            stop.set()
            await wtask

        assert len(acked) > 20, f"only {len(acked)} acked under chaos"
        await c.wait_region_leader(1)
        await c.wait_region_leader(2)
        for k, v in acked.items():
            assert await kv.get(k) == v, k


async def test_learner_store_replicates_kv_data():
    """A region with a ``/learner`` replica: the learner store applies all
    KV data but never becomes leader, the client routes around it, and a
    split preserves the learner set (BASELINE config 5's feature tier:
    regions w/ learners + lease reads).

    Reference parity: learners at the RheaKV tier ride jraft-core's
    `[1.3+]` learner support (SURVEY.md §3.1) — the fork's region peers
    are voters only, so routing must simply never treat a learner as a
    leader candidate.
    """
    from tpuraft.options import ReadOnlyOption

    # lease reads from boot, as in the BASELINE config
    c = KVTestCluster(4, read_only_option=ReadOnlyOption.LEASE_BASED)
    voters, learner_ep = c.endpoints[:3], c.endpoints[3]
    c.region_template = [Region(
        id=1, peers=voters + [learner_ep + "/learner"])]
    await c.start_all()
    pd = FakePlacementDriverClient([r.copy() for r in c.region_template])
    kv = RheaKVStore(pd, c.client_transport())
    await kv.start()
    try:
        for i in range(24):
            assert await kv.put(b"lk%02d" % i, b"v%d" % i)
        assert await kv.get(b"lk07") == b"v7"

        # the learner's local store converges to the replicated data
        learner_store = c.stores[learner_ep]
        for _ in range(200):
            if learner_store.raw_store.get(b"lk23") == b"v23":
                break
            await asyncio.sleep(0.02)
        assert learner_store.raw_store.get(b"lk00") == b"v0"
        assert learner_store.raw_store.get(b"lk23") == b"v23"

        # the learner never leads its region
        eng = learner_store.get_region_engine(1)
        assert eng is not None and not eng.is_leader()
        leader = await c.wait_region_leader(1)
        assert leader.store_engine.server_id.endpoint != learner_ep

        # split preserves the learner replica on both halves
        st = await leader.store_engine.apply_split(1, 2)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(2)
        await c.wait_region_leader(2)
        for s in c.stores.values():
            for rid in (1, 2):
                region = s.get_region_engine(rid).region
                assert learner_ep + "/learner" in region.peers
        # and the cluster still serves reads+writes through the client
        assert await kv.put(b"after-split", b"ok")
        assert await kv.get(b"after-split") == b"ok"
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_read_preference_any_spreads_linearizable_reads():
    """read_preference='any': read-only ops round-robin over ALL
    replicas — follower and learner stores serve them via the readIndex
    barrier (forward to leader + wait for local apply), so results stay
    linearizable.  No reference counterpart: RheaKV routes every read
    through the leader."""
    import collections

    c = KVTestCluster(4)
    voters, learner_ep = c.endpoints[:3], c.endpoints[3]
    c.region_template = [Region(
        id=1, peers=voters + [learner_ep + "/learner"])]
    await c.start_all()
    pd = FakePlacementDriverClient([r.copy() for r in c.region_template])

    served = collections.Counter()
    base_transport = c.client_transport()

    class CountingTransport:
        def __init__(self, inner):
            self._inner = inner

        async def call(self, endpoint, method, req, timeout_ms=None):
            resp = await self._inner.call(endpoint, method, req, timeout_ms)
            # count successful SERVES, not attempts: a replica that
            # rejects (forcing failover to the leader) must not count,
            # or a silent regression to leader-only reads would pass
            if method == "kv_command" and resp.code == 0:
                served[endpoint] += 1
            return resp

        def __getattr__(self, name):
            return getattr(self._inner, name)

    kv = RheaKVStore(pd, CountingTransport(base_transport),
                     read_preference="any")
    await kv.start()
    try:
        await c.wait_region_leader(1)
        for i in range(8):
            assert await kv.put(b"rp%02d" % i, b"v%d" % i)
        served.clear()
        for _ in range(3):
            for i in range(8):
                assert await kv.get(b"rp%02d" % i) == b"v%d" % i
        # every replica served some reads — including the learner
        assert len(served) == 4, served
        assert served[learner_ep] > 0, served
        # writes still reach the leader only (reads didn't poison routing)
        assert await kv.put(b"rp-last", b"z")
        assert await kv.get(b"rp-last") == b"z"
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_spread_reads_are_linearizable_under_writes(tmp_path):
    """Concurrent writers + spread readers (follower/learner-served):
    the recorded history must still check out linearizable — the
    readIndex barrier is doing its job on every replica."""
    from tpuraft.util.linearizability import History, check_history

    c = KVTestCluster(4, tmp_path=tmp_path)
    voters, learner_ep = c.endpoints[:3], c.endpoints[3]
    c.region_template = [Region(
        id=1, peers=voters + [learner_ep + "/learner"])]
    await c.start_all()
    pd = FakePlacementDriverClient([r.copy() for r in c.region_template])
    kv = RheaKVStore(pd, c.client_transport(), max_retries=1,
                     read_preference="any")
    await kv.start()
    try:
        await c.wait_region_leader(1)
        h = History()
        stop = asyncio.Event()
        keys = [b"sr-%d" % i for i in range(3)]
        n_ok = [0]

        async def writer(cid):
            n = 0
            while not stop.is_set():
                n += 1
                key = keys[n % len(keys)]
                val = b"c%d-%d" % (cid, n)
                tok = h.invoke(cid, "w", (key, val))
                try:
                    await asyncio.wait_for(kv.put(key, val), 4.0)
                    h.complete(tok, True)
                    n_ok[0] += 1
                except Exception:
                    pass
                await asyncio.sleep(0.004)

        async def reader(cid):
            n = 0
            while not stop.is_set():
                n += 1
                key = keys[n % len(keys)]
                tok = h.invoke(cid, "r", (key,))
                try:
                    v = await asyncio.wait_for(kv.get(key), 4.0)
                    h.complete(tok, v)
                    n_ok[0] += 1
                except Exception:
                    pass
                await asyncio.sleep(0.002)

        tasks = [asyncio.ensure_future(writer(0)),
                 asyncio.ensure_future(writer(1)),
                 asyncio.ensure_future(reader(2)),
                 asyncio.ensure_future(reader(3)),
                 asyncio.ensure_future(reader(4))]
        await asyncio.sleep(2.5)
        stop.set()
        await asyncio.gather(*tasks)
        assert n_ok[0] > 100, f"only {n_ok[0]} ops completed"
        rep = check_history(h)
        assert rep.ok, str(rep)
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_route_refresh_cannot_regress_to_presplit_view():
    """A refresh answered only by lagging replicas (leader down, PD
    stale) must not replace a fresher post-split route view with the
    pre-split one: the fold is seeded with the cached table."""
    from tpuraft.rheakv.metadata import RegionEpoch
    from tpuraft.rheakv.kv_service import ListRegionsOnStoreResponse

    pre = Region(id=1, start_key=b"", end_key=b"",
                 epoch=RegionEpoch(conf_ver=1, version=1),
                 peers=["127.0.0.1:6000"])
    post1 = Region(id=1, start_key=b"", end_key=b"m",
                   epoch=RegionEpoch(conf_ver=1, version=2),
                   peers=["127.0.0.1:6000"])
    post2 = Region(id=2, start_key=b"m", end_key=b"",
                   epoch=RegionEpoch(conf_ver=1, version=1),
                   peers=["127.0.0.1:6000"])

    class StalePD:
        async def list_regions(self):
            return [pre.copy()]

    class StaleTransport:
        async def call(self, endpoint, method, req, timeout_ms=None):
            assert method == "kv_list_regions"
            return ListRegionsOnStoreResponse(regions=[pre.encode()])

    kv = RheaKVStore(StalePD(), StaleTransport())
    kv.route_table.reset([post1.copy(), post2.copy()])
    await kv._refresh_routes()
    got = {r.id: r for r in kv.route_table.list_regions()}
    assert set(got) == {1, 2}, got
    assert got[1].epoch.version == 2
    assert got[1].end_key == b"m"


async def test_client_paged_iterator_crosses_regions():
    """kv.iterator pages with buf_size-sized scans across region
    boundaries, in order, without skipping or duplicating (reference:
    DefaultRheaKVStore#iterator / RheaIterator)."""
    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    async with kv_client_cluster(regions=regions) as (c, kv):
        keys = [b"it%02d" % i for i in range(12)] + \
               [b"zz%02d" % i for i in range(9)]
        for i, k in enumerate(keys):
            assert await kv.put(k, b"v%d" % i)
        got = []
        async for k, v in kv.iterator(b"", b"", buf_size=4):
            got.append((k, v))
        assert [k for k, _ in got] == sorted(keys)
        assert dict(got) == {k: b"v%d" % i for i, k in enumerate(keys)}
        # keys-only mode and bounded range
        names = [k async for k, _ in kv.iterator(b"it", b"iz", buf_size=5,
                                                 return_value=False)]
        assert names == [b"it%02d" % i for i in range(12)]
