"""Trace plane: span recorder semantics, wire-context compatibility,
perfetto export, the flight recorder, and the metrics exposition layer.

Covers ISSUE 12's test satellites: ring bounds under churn, seeded
sampling determinism, slow-op force-retention, trace-context wire
compat BOTH directions (an old decoder sees a plain request), perfetto
JSON schema validity, recorder dump-on-anomaly on a forced SICK
transition — plus the end-to-end acceptance shape (one traced KV put =
client + leader + follower spans joined by the trailing wire context)
and the Prometheus exposition surfaces (metrics_text, the
describe_metrics admin RPC, the HTTP listener).
"""

from __future__ import annotations

import asyncio
import json
import threading

import pytest

from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    decode_message,
    encode_message,
)
from tpuraft.util.trace import (
    RECORDER,
    TRACER,
    FlightRecorder,
    Tracer,
    adopt_entry_ctx,
    entry_ctx,
    pack_ctx,
    unpack_ctx,
)


@pytest.fixture(autouse=True)
def _isolate_tracer():
    """The tracer is a module singleton: every test starts disabled and
    empty, and leaves it that way."""
    TRACER.configure(enabled=False)
    TRACER.reset()
    yield
    TRACER.configure(enabled=False)
    TRACER.reset()


# ---------------------------------------------------------------------------
# span recorder semantics
# ---------------------------------------------------------------------------


def _run_op(t: Tracer, dur_s: float = 0.0, spans: int = 0) -> int:
    tid = t.begin_op("op")
    if tid:
        import time

        base = time.perf_counter()
        for i in range(spans):
            t.span(tid, f"stage{i}", base, base + 1e-6)
        if dur_s:
            # synthesize the duration by back-dating the staged start
            t._staged[tid].t0 -= dur_s
        t.end_op(tid)
    return tid


def test_disabled_tracer_is_inert():
    t = Tracer()
    assert t.begin_op() == 0
    t.span(0, "x", 0.0, 1.0)
    assert t.end_op(0) == 0.0
    assert t.spans() == []
    assert t.counters()["trace_ops_seen"] == 0


def test_ring_bounds_under_churn():
    t = Tracer().configure(enabled=True, sample_rate=1.0, seed=1, ring=64)
    for _ in range(500):
        _run_op(t, spans=3)
    assert len(t.spans()) <= 64
    c = t.stats()
    assert c["trace_ring_spans"] <= 64
    assert c["trace_ops_seen"] == 500
    # the ring keeps the NEWEST spans
    assert t.spans()[-1]["name"] in ("op", "stage2")


def test_staging_bounded_and_abandoned_ops_evicted():
    t = Tracer().configure(enabled=True, sample_rate=1.0, seed=1)
    t._max_staged = 8
    for _ in range(100):
        t.begin_op()  # never ended
    assert len(t._staged) <= 8


def test_seeded_sampling_determinism():
    a = Tracer().configure(enabled=True, sample_rate=0.3, seed=42,
                           slow_trigger=False)
    b = Tracer().configure(enabled=True, sample_rate=0.3, seed=42,
                           slow_trigger=False)
    sampled_a = [bool(_run_op(a)) for _ in range(200)]
    sampled_b = [bool(_run_op(b)) for _ in range(200)]
    assert sampled_a == sampled_b
    assert 20 < sum(sampled_a) < 120  # ~30%
    c = Tracer().configure(enabled=True, sample_rate=0.3, seed=7,
                           slow_trigger=False)
    assert [bool(_run_op(c)) for _ in range(200)] != sampled_a


def test_slow_op_force_retention(monkeypatch):
    """Unsampled ops drop — unless slower than the rolling p99 EMA.
    A slow-retained op keeps its ROOT span (duration + slow flag);
    child attribution exists only for sampled ops (the overhead gate's
    budget: unsampled candidacy must cost a clock read, not a span
    pipeline).  Durations come from a fake clock: back-dating t0 over
    the real perf_counter adds the loop's wall time to every synthetic
    duration, and one host stall past warmup reads as a real slow op."""
    import tpuraft.util.trace as trace_mod

    clock = [0.0]
    monkeypatch.setattr(trace_mod, "_pc", lambda: clock[0])
    t = Tracer().configure(enabled=True, sample_rate=0.0, seed=1)
    t._warmup = 50
    for i in range(100):                   # ~1ms steady state; the mild
        tid = t.begin_op("op")             # decay keeps each dur strictly
        clock[0] += 0.001 - i * 1e-7       # below the EMA, as a real
        t.end_op(tid)                      # stream sits below its p99
    assert t.spans() == []                 # nothing sampled => dropped
    assert t.counters()["trace_ops_dropped"] == 100
    tid = t.begin_op("op")                 # 500x the EMA
    t.span(tid, "stage0", clock[0], clock[0])
    t.span(tid, "stage1", clock[0], clock[0])
    clock[0] += 0.5
    t.end_op(tid)
    spans = t.spans()
    assert spans, "slow op must be force-retained"
    assert {s["name"] for s in spans} == {"op"}   # root-only
    root = spans[-1]
    assert root["args"].get("slow") is True
    assert root["dur_s"] >= 0.4
    assert t.counters()["trace_ops_slow_retained"] == 1


def test_sampled_ops_keep_child_spans():
    t = Tracer().configure(enabled=True, sample_rate=1.0, seed=1)
    _run_op(t, spans=2)
    names = [s["name"] for s in t.spans()]
    assert names.count("op") == 1
    assert "stage0" in names and "stage1" in names


def test_wire_ctx_masks_unsampled():
    from tpuraft.util.trace import wire_ctx

    assert wire_ctx(0) == 0
    assert wire_ctx(0b101) == 0b101   # sampled rides the wire
    assert wire_ctx(0b100) == 0       # slow-candidate stays local


def test_remote_context_records_only_sampled():
    """A remote process records a wire-borne context iff the sampled
    bit is set (the slow-op trigger is client-local)."""
    t = Tracer().configure(enabled=True, sample_rate=1.0, seed=1)
    sampled_tid = 0b101   # seq 2, sampled
    unsampled_tid = 0b100  # seq 2, not sampled
    t.span(sampled_tid, "remote_stage", 0.0, 0.001, proc="storeX")
    t.span(unsampled_tid, "remote_stage", 0.0, 0.001, proc="storeX")
    spans = t.spans()
    assert len(spans) == 1
    assert spans[0]["trace_id"] == sampled_tid
    assert spans[0]["proc"] == "storeX"


# ---------------------------------------------------------------------------
# trace-context wire helpers + compat both directions
# ---------------------------------------------------------------------------


def test_pack_unpack_ctx_roundtrip_and_zero_cost():
    assert pack_ctx([0, 0, 0]) == b""          # untraced = no wire bytes
    blob = pack_ctx([0, 7, 0, 9])
    assert unpack_ctx(blob, 4) == [0, 7, 0, 9]
    assert unpack_ctx(b"", 3) == [0, 0, 0]     # old sender
    assert unpack_ctx(blob[:8], 4) == [0, 0, 0, 0]  # short blob = zeros


def test_entry_ctx_adoption():
    from tpuraft.entity import EntryType, LogEntry

    entries = [LogEntry(type=EntryType.DATA, data=b"a"),
               LogEntry(type=EntryType.DATA, data=b"b", trace_id=11)]
    blob = entry_ctx(entries)
    fresh = [LogEntry(type=EntryType.DATA, data=b"a"),
             LogEntry(type=EntryType.DATA, data=b"b")]
    adopt_entry_ctx(fresh, blob)
    assert [e.trace_id for e in fresh] == [0, 11]
    adopt_entry_ctx(fresh, b"")   # old sender: no-op
    assert [e.trace_id for e in fresh] == [0, 11]


def test_append_entries_trace_ctx_wire_compat_both_directions():
    """AppendEntriesRequest gained a trailing trace_ctx.  Old frames
    decode on new receivers with the default; a new frame is a strict
    extension whose prefix an old decoder reads identically."""
    from tpuraft.entity import EntryType, LogEntry

    e = LogEntry(type=EntryType.DATA, data=b"payload")
    e.id = e.id.__class__(3, 2)
    new = AppendEntriesRequest(
        group_id="g", server_id="a:1", peer_id="b:2", term=2,
        prev_log_index=2, prev_log_term=2, committed_index=1,
        entries=[e], trace_ctx=pack_ctx([5]))
    wire = encode_message(new)
    got = decode_message(wire)
    assert got.trace_ctx == pack_ctx([5])
    assert got.entries[0].data == b"payload"
    # old sender -> new receiver: strip the trailing bytes field
    # (4-byte length prefix + ctx payload); trace_ctx defaults
    old_wire = wire[:-(4 + len(new.trace_ctx))]
    old_got = decode_message(old_wire)
    assert old_got.trace_ctx == b""
    assert old_got.entries[0].data == b"payload"
    # new -> old receiver: the old-format prefix is byte-identical, so
    # an old decoder (which stops after entries) reads the same values
    old_fmt = encode_message(AppendEntriesRequest(
        group_id="g", server_id="a:1", peer_id="b:2", term=2,
        prev_log_index=2, prev_log_term=2, committed_index=1,
        entries=[e]))
    assert wire[:len(old_wire)] == old_fmt[:len(old_wire)]


def test_kv_batch_trace_ctx_wire_compat_both_directions():
    from tpuraft.rheakv.kv_service import KVCommandBatchRequest

    new = KVCommandBatchRequest(items=[b"item0", b"item1"],
                                trace_ctx=pack_ctx([0, 9]))
    wire = encode_message(new)
    assert decode_message(wire) == new
    old_wire = wire[:-(4 + len(new.trace_ctx))]
    got = decode_message(old_wire)      # old sender -> new receiver
    assert got.items == [b"item0", b"item1"]
    assert got.trace_ctx == b""
    # an untraced new frame differs from the old format only by the
    # empty trailing field an old decoder never reads
    untraced = encode_message(KVCommandBatchRequest(
        items=[b"item0", b"item1"]))
    assert untraced[:len(old_wire)] == old_wire


# ---------------------------------------------------------------------------
# perfetto export
# ---------------------------------------------------------------------------


def test_chrome_export_schema(tmp_path):
    t = Tracer().configure(enabled=True, sample_rate=1.0, seed=1)
    tid = t.begin_op("op", proc="client")
    import time

    base = time.perf_counter()
    t.span(tid, "stage", base, base + 0.001, proc="store:x")
    t.end_op(tid)
    path = str(tmp_path / "trace.json")
    n = t.export_chrome(path)
    assert n == 2
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert isinstance(evs, list)
    x = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(x) == 2 and len(metas) == 2   # two procs named
    for e in x:
        for key in ("name", "ph", "ts", "dur", "pid", "tid", "args"):
            assert key in e
        assert e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    # the two spans of one op share a tid row, on different pid rows
    assert x[0]["tid"] == x[1]["tid"]
    assert x[0]["pid"] != x[1]["pid"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_recorder_ring_bounds_and_dump():
    r = FlightRecorder(capacity=16)
    for i in range(100):
        r.record("step_down", f"g{i}", term=i)
    assert len(r.events()) == 16
    assert r.events_recorded == 100
    text = r.dump()
    assert "step_down" in text and "g99" in text
    assert "flight recorder" in text


def test_recorder_election_storm_anomaly():
    r = FlightRecorder()
    for _ in range(r.storm_threshold):
        r.record("election_start", "cluster--1", term=1)
    assert len(r.anomalies) == 1
    snap = r.anomaly_report()[0]
    assert snap["reason"] == "election_storm"
    assert "cluster--1" in snap["detail"]
    assert any("election_start" in line for line in snap["events"])
    # a storm keeps raging within the window: ONE snapshot, not N
    for _ in range(10):
        r.record("election_start", "cluster--1", term=2)
    assert len(r.anomalies) == 1


def test_recorder_dump_on_forced_sick_transition():
    """A SICK transition must record the health event AND snapshot the
    ring (the lead-up survives churn)."""
    from tpuraft.util.health import HealthOptions, HealthTracker, SICK

    RECORDER.record("election_start", "lead-up-group", term=9)
    opts = HealthOptions(worsen_after=2, recover_after=2)
    h = HealthTracker(opts, label="store-under-test")
    for _ in range(5):
        h.disk.note(10.0)   # 10s fsyncs: raw SICK
        assert h.evaluate() in ("healthy", "degraded", "sick")
    assert h.score() == SICK
    # the recorder is a process singleton and its anomaly buffer is
    # BOUNDED — earlier chaos tests may have filled it with real
    # election storms, so assert on the newest snapshot, not the count
    dumps = RECORDER.anomaly_report()
    assert dumps, "SICK transition must snapshot the ring"
    snap = dumps[-1]
    assert snap["reason"] == "sick_transition"
    assert "store-under-test" in snap["detail"]
    # the ring snapshot carries the lead-up event
    assert any("lead-up-group" in line for line in snap["events"])
    # the transition itself is an event too
    kinds = [k for _ts, k, _g, _d in RECORDER.events()]
    assert "health" in kinds


def test_recorder_coalesces_flood_kinds():
    """Request-rate kinds (shed, mass quiesce sweeps) must not evict
    the ring: one leading-edge event per window, the rest counted."""
    r = FlightRecorder(capacity=64)
    for _ in range(500):
        r.record_coalesced("shed", "s1", items=1)
    evs = [e for e in r.events() if e[1] == "shed"]
    assert len(evs) == 1
    # windows are per (kind, group): another store's first shed must
    # record immediately, not be swallowed by s1's window (its
    # suppressed count would otherwise surface attributed to s1)
    r.record_coalesced("shed", "s2", items=1)
    assert len([e for e in r.events()
                if e[1] == "shed" and e[2] == "s2"]) == 1
    r._coalesce[("shed", "s1")][0] -= 2.0   # expire the window
    r.record_coalesced("shed", "s1", items=1)
    evs = [e for e in r.events() if e[1] == "shed" and e[2] == "s1"]
    assert len(evs) == 2
    assert evs[-1][3].get("suppressed") == 499
    # sweep-shaped kinds (per_group=False): a hibernation sweep is
    # thousands of DISTINCT groups each quiescing once — per-group
    # windows would make every one a leading edge and flood the ring,
    # so they share one window per kind
    for i in range(500):
        r.record_coalesced("quiesce", f"g{i}", per_group=False, role="x")
    assert len([e for e in r.events() if e[1] == "quiesce"]) == 1


def test_recorder_thread_safety():
    r = FlightRecorder(capacity=256)
    errs = []

    def hammer(tag):
        try:
            for i in range(500):
                r.record("evt", f"g{tag}", i=i)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert r.events_recorded == 2000


# ---------------------------------------------------------------------------
# metrics: histogram fixes + registry thread safety + prometheus text
# ---------------------------------------------------------------------------


def test_histogram_ring_replaces_oldest_first():
    from tpuraft.util.metrics import Histogram

    h = Histogram(max_samples=4)
    for v in (1, 2, 3, 4):
        h.update(v)
    h.update(5)   # must replace slot 0 (oldest), not skew to slot 1
    assert sorted(h._samples) == [2, 3, 4, 5]
    h.update(6)
    assert sorted(h._samples) == [3, 4, 5, 6]


def test_histogram_percentile_rounding():
    from tpuraft.util.metrics import Histogram

    h = Histogram()
    for v in range(1, 101):   # 1..100
        h.update(v)
    assert h.percentile(99) == 99
    assert h.percentile(50) == 50
    assert h.percentile(100) == 100
    small = Histogram()
    for v in (10, 20, 30, 40):
        small.update(v)
    assert small.percentile(50) == 20     # 2nd of 4, not 3rd
    assert small.percentile(99) == 40
    one = Histogram()
    one.update(7)
    assert one.percentile(99) == 7


def test_histogram_cached_sort_invalidation():
    from tpuraft.util.metrics import Histogram

    h = Histogram()
    h.update(5)
    assert h.percentile(50) == 5
    h.update(1)   # must invalidate the cached sort
    assert h.percentile(50) == 1
    assert h.snapshot()["max"] == 5


def test_metric_registry_thread_safety():
    from tpuraft.util.metrics import MetricRegistry

    reg = MetricRegistry()
    errs = []

    def hammer():
        try:
            for i in range(2000):
                reg.counter("c")
                reg.update("h", float(i % 50))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert reg.counters["c"] == 8000
    assert reg.histograms["h"].count == 8000
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 8000


def test_prometheus_text_rendering():
    from tpuraft.util.metrics import Histogram, prometheus_text

    h = Histogram()
    for v in (1.0, 2.0, 3.0):
        h.update(v)
    text = prometheus_text({"kv.batch-rpcs": 7}, {"regions": 3},
                           {"flush_ms": h.snapshot()},
                           labels={"store": "127.0.0.1:6000"})
    assert 'tpuraft_kv_batch_rpcs{store="127.0.0.1:6000"} 7' in text
    assert 'tpuraft_regions{store="127.0.0.1:6000"} 3' in text
    assert '# TYPE tpuraft_kv_batch_rpcs counter' in text
    assert 'quantile="0.99"' in text
    assert 'tpuraft_flush_ms_count{store="127.0.0.1:6000"} 3' in text
    # every sample line parses as name{labels} value
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name.startswith("tpuraft_")


# ---------------------------------------------------------------------------
# end-to-end: one traced KV put spans client + leader + follower
# ---------------------------------------------------------------------------


async def _kv_cluster():
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    c = KVTestCluster(3)
    await c.start_all()
    pd = FakePlacementDriverClient(c.region_template)
    kv = RheaKVStore(pd, c.client_transport(),
                     batching=BatchingOptions(enabled=True))
    await kv.start()
    await c.wait_region_leader(1)
    return c, kv


async def test_traced_put_end_to_end(tmp_path):
    """The acceptance shape: ONE traced put produces >= 7 stage spans
    spanning the client, the leader store and at least one follower —
    joined across 'processes' by the trailing wire context — and the
    export is perfetto-loadable."""
    c, kv = await _kv_cluster()
    try:
        assert await kv.put(b"warm", b"w")        # untraced warm-up
        TRACER.configure(enabled=True, sample_rate=1.0, seed=0)
        assert await kv.put(b"k1", b"v1")
        # follower appends resolve off the ack path: give stragglers a
        # beat to land their spans before asserting
        for _ in range(50):
            spans = TRACER.spans()
            if sum(1 for s in spans
                   if s["name"] == "follower_append") >= 1:
                break
            await asyncio.sleep(0.02)
        TRACER.enabled = False
        spans = TRACER.spans()
        roots = [s for s in spans if s["name"] == "kv_op"]
        assert roots, "root op span missing"
        tid = roots[-1]["trace_id"]
        mine = [s for s in spans if s["trace_id"] == tid]
        assert len(mine) >= 7, [s["name"] for s in mine]
        procs = {s["proc"] for s in mine}
        names = {s["name"] for s in mine}
        assert "client" in procs
        store_procs = {p for p in procs if p.startswith("store:")}
        assert len(store_procs) >= 2, procs  # leader + >=1 follower
        for stage in ("client_queue", "kv_batch_rpc", "srv_validate",
                      "srv_propose", "quorum_commit", "log_flush",
                      "fsm_apply", "follower_append"):
            assert stage in names, (stage, names)
        path = str(tmp_path / "put.json")
        TRACER.export_chrome(path)
        with open(path) as f:
            doc = json.load(f)
        assert any(e["ph"] == "X" and e["name"] == "follower_append"
                   for e in doc["traceEvents"])
    finally:
        TRACER.configure(enabled=False)
        TRACER.reset()
        await kv.shutdown()
        await c.stop_all()


async def test_traced_get_has_fence_and_serve_stages():
    c, kv = await _kv_cluster()
    try:
        assert await kv.put(b"k1", b"v1")
        TRACER.configure(enabled=True, sample_rate=1.0, seed=0)
        TRACER.reset()
        assert await kv.get(b"k1") == b"v1"
        TRACER.enabled = False
        names = {s["name"] for s in TRACER.spans()}
        for stage in ("kv_op", "srv_read_fence", "srv_read_serve"):
            assert stage in names, names
    finally:
        TRACER.configure(enabled=False)
        TRACER.reset()
        await kv.shutdown()
        await c.stop_all()


async def test_untraced_put_records_nothing():
    """Zero-cost sanity: with the tracer disabled, a full serving-path
    op leaves no spans, no staging, no wire context."""
    c, kv = await _kv_cluster()
    try:
        assert await kv.put(b"k", b"v")
        assert TRACER.spans() == []
        assert TRACER._staged == {}
        assert TRACER.counters()["trace_ops_seen"] == 0
    finally:
        await kv.shutdown()
        await c.stop_all()


# ---------------------------------------------------------------------------
# live metrics exposition (metrics_text / admin RPC / HTTP listener)
# ---------------------------------------------------------------------------


async def test_metrics_text_and_describe_metrics_rpc():
    from tpuraft.core.cli_service import CliService

    c, kv = await _kv_cluster()
    try:
        assert await kv.put(b"k", b"v")
        store = next(iter(c.stores.values()))
        text = store.metrics_text()
        assert "tpuraft_kv_batch_rpcs" in text
        assert "tpuraft_regions" in text
        assert f'store="{store.server_id}"' in text
        # counter/gauge semantics: monotonic series are counters,
        # ring occupancy / toggles / EMAs are gauges (a decrease on a
        # Prometheus counter reads as a reset)
        assert "# TYPE tpuraft_recorder_events counter" in text
        assert "# TYPE tpuraft_trace_ring_spans gauge" in text
        assert "# TYPE tpuraft_trace_slow_ema_ms gauge" in text
        # over the wire: the admin scrape returns the same rendering
        cli = CliService(c.client_transport("admin:0"))
        remote = await cli.describe_metrics(str(store.server_id))
        assert "tpuraft_kv_batch_rpcs" in remote
        assert f'store="{store.server_id}"' in remote
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_metrics_http_listener(tmp_path):
    """The optional stdlib HTTP listener serves Prometheus text on
    GET /metrics (port 0 = ephemeral bind)."""
    import urllib.error
    import urllib.request

    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

    net = InProcNetwork()
    ep = "127.0.0.1:6900"
    server = RpcServer(ep)
    net.bind(server)
    opts = StoreEngineOptions(
        server_id=ep,
        initial_regions=[Region(id=1, peers=[ep])],
        election_timeout_ms=200,
        metrics_port=0)
    store = StoreEngine(opts, server, InProcTransport(net, ep))
    await store.start()
    try:
        assert store.metrics_http_port
        url = f"http://127.0.0.1:{store.metrics_http_port}/metrics"
        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(
            None, lambda: urllib.request.urlopen(url, timeout=5).read())
        text = body.decode()
        assert "tpuraft_regions" in text
        assert "# TYPE" in text
        # non-metrics paths 404
        with pytest.raises(urllib.error.HTTPError):
            await loop.run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{store.metrics_http_port}/nope",
                    timeout=5).read())
    finally:
        await store.shutdown()
