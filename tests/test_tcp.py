"""TCP transport tests: framing/pipelining/reconnect, then a full raft
cluster over real loopback sockets (the reference's TestCluster runs real
Bolt TCP servers on localhost ports — SURVEY.md §5)."""

import asyncio

import pytest

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors
from tpuraft.core.node import Node, State
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId, Task
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions
from tpuraft.rpc.messages import GetFileRequest, GetFileResponse, ReadIndexResponse
from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport
from tpuraft.rpc.transport import RpcError

from tests.cluster import MockStateMachine


def _rir(i: int) -> ReadIndexResponse:
    """Any registered message works as a request payload on the wire."""
    return ReadIndexResponse(index=i, success=True)


async def _start_server(server_cls=TcpRpcServer):
    """Start an ephemeral-port server and pin its real endpoint."""
    srv = server_cls("127.0.0.1:0")
    await srv.start()
    srv.endpoint = f"127.0.0.1:{srv.bound_port}"
    return srv


class TestTcpRpc:
    @pytest.mark.asyncio
    async def test_roundtrip_and_error(self):
        srv = await _start_server()

        async def echo(req):
            return ReadIndexResponse(index=req.index, success=True)

        async def boom(req):
            raise RpcError(Status.error(RaftError.EPERM, "not leader"))

        srv.register("echo", echo)
        srv.register("boom", boom)
        t = TcpTransport()
        resp = await t.call(srv.endpoint, "echo",
                            _rir(42))
        assert resp.index == 42 and resp.success
        with pytest.raises(RpcError) as ei:
            await t.call(srv.endpoint, "boom", _rir(0))
        assert ei.value.status.code == int(RaftError.EPERM)
        # unknown method -> EINTERNAL, connection survives
        with pytest.raises(RpcError):
            await t.call(srv.endpoint, "nope", _rir(0))
        resp = await t.call(srv.endpoint, "echo",
                            _rir(7))
        assert resp.index == 7
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_pipelining_out_of_order_completion(self):
        """Slow first request must not block later ones (concurrent
        dispatch), and responses correlate by seq, not arrival order."""
        srv = await _start_server()

        async def slow(req):
            await asyncio.sleep(0.2)
            return ReadIndexResponse(index=req.index, success=True)

        async def fast(req):
            return ReadIndexResponse(index=req.index, success=True)

        srv.register("slow", slow)
        srv.register("fast", fast)
        t = TcpTransport()
        t_slow = asyncio.ensure_future(
            t.call(srv.endpoint, "slow", _rir(1),
                   timeout_ms=2000))
        t_fast = asyncio.ensure_future(
            t.call(srv.endpoint, "fast", _rir(2)))
        fast_resp = await asyncio.wait_for(t_fast, 0.15)  # before slow is done
        assert fast_resp.index == 2
        assert (await t_slow).index == 1
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_timeout_and_reconnect_after_restart(self):
        srv = await _start_server()
        endpoint = srv.endpoint

        async def hang(req):
            await asyncio.sleep(10)

        async def ok(req):
            return ReadIndexResponse(index=5, success=True)

        srv.register("hang", hang)
        srv.register("ok", ok)
        t = TcpTransport()
        with pytest.raises(RpcError) as ei:
            await t.call(endpoint, "hang", _rir(0), timeout_ms=100)
        assert ei.value.status.code == int(RaftError.ETIMEDOUT)
        await srv.stop()
        # down -> EHOSTDOWN-ish failure
        with pytest.raises(RpcError):
            await t.call(endpoint, "ok", _rir(0), timeout_ms=200)
        # restart on the SAME port; pooled transport must reconnect
        srv2 = TcpRpcServer(endpoint)
        await srv2.start()
        srv2.register("ok", ok)
        resp = await t.call(endpoint, "ok", _rir(0),
                            timeout_ms=1000)
        assert resp.index == 5
        await t.close()
        await srv2.stop()

    @pytest.mark.asyncio
    async def test_large_payload(self):
        srv = await _start_server()

        async def echo(req):
            return ReadIndexResponse(index=len(req.data), success=True)

        srv.register("echo", echo)
        t = TcpTransport()
        blob = bytes(range(256)) * (4 * 1024 * 16)  # 4 MB
        resp = await t.call(srv.endpoint, "echo",
                            GetFileResponse(eof=False, data=blob),
                            timeout_ms=5000)
        assert resp.index == len(blob)
        await t.close()
        await srv.stop()


class TcpCluster:
    """3 full raft nodes over real TCP sockets on ephemeral ports."""

    server_cls = TcpRpcServer
    transport_cls = TcpTransport

    def __init__(self, tmp_path=None, snapshot: bool = False):
        if snapshot and tmp_path is None:
            raise ValueError("snapshot=True needs a tmp_path (snapshot "
                             "storage is file-based)")
        self.snapshot = snapshot
        self.nodes: dict[PeerId, Node] = {}
        self.fsms: dict[PeerId, MockStateMachine] = {}
        self.servers: dict[PeerId, TcpRpcServer] = {}
        self.transports: dict[PeerId, TcpTransport] = {}
        self.peers: list[PeerId] = []
        self.conf = Configuration()
        self.tmp_path = tmp_path

    async def start(self, n: int) -> None:
        servers = []
        for _ in range(n):
            servers.append(await _start_server(self.server_cls))
        self.peers = [PeerId.parse(s.endpoint) for s in servers]
        self.conf = Configuration(list(self.peers))
        for peer, srv in zip(self.peers, servers):
            await self._boot(peer, srv)

    async def _boot(self, peer: PeerId, srv: TcpRpcServer) -> None:
        fsm = self.fsms.setdefault(peer, MockStateMachine())
        manager = NodeManager(srv)
        CliProcessors(manager)
        transport = self.transport_cls(endpoint=peer.endpoint)
        opts = NodeOptions(election_timeout_ms=300,
                           initial_conf=self.conf.copy(), fsm=fsm)
        if self.tmp_path is not None:
            base = f"{self.tmp_path}/{peer.ip}_{peer.port}"
            opts.log_uri = f"file://{base}/log"
            opts.raft_meta_uri = f"file://{base}/meta"
            if self.snapshot:
                opts.snapshot_uri = f"file://{base}/snapshot"
        else:
            opts.log_uri = "memory://"
            opts.raft_meta_uri = "memory://"
        opts.snapshot.interval_secs = 0
        node = Node("tcp_group", peer, opts, transport)
        node.node_manager = manager
        manager.add(node)
        assert await node.init()
        self.nodes[peer] = node
        self.servers[peer] = srv
        self.transports[peer] = transport

    async def crash(self, peer: PeerId) -> None:
        await self.servers[peer].stop()
        await self.transports[peer].close()
        node = self.nodes.pop(peer)
        await node.shutdown()

    async def restart(self, peer: PeerId) -> None:
        srv = self.server_cls(peer.endpoint)
        await srv.start()
        # fresh FSM recorder: the node replays its durable log from the
        # start on init, so a reused recorder would hold duplicates and
        # make entry-count waits pass before catch-up actually finishes
        self.fsms.pop(peer, None)
        await self._boot(peer, srv)

    async def stop_all(self) -> None:
        for peer in list(self.nodes):
            await self.crash(peer)

    async def wait_leader(self, timeout_s: float = 8.0) -> Node:
        import time
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [x for x in self.nodes.values()
                       if x.state == State.LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError("no leader over tcp")

    async def apply_ok(self, node: Node, data: bytes) -> Status:
        fut = asyncio.get_running_loop().create_future()
        await node.apply(Task(data=data, done=lambda st: fut.set_result(st)))
        return await asyncio.wait_for(fut, 8.0)

    async def wait_applied(self, count: int, timeout_s: float = 8.0) -> None:
        import time
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(len(self.fsms[p].logs) >= count for p in self.nodes):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"applied: { {str(p): len(self.fsms[p].logs) for p in self.nodes} }")


class TestRaftOverTcp:
    @pytest.mark.asyncio
    async def test_elect_replicate_failover(self, tmp_path):
        c = TcpCluster(tmp_path)
        await c.start(3)
        try:
            leader = await c.wait_leader()
            for i in range(5):
                st = await c.apply_ok(leader, b"cmd%d" % i)
                assert st.is_ok(), st
            await c.wait_applied(5)
            # kill the leader: remaining two elect a new one and keep going
            dead = leader.server_id
            await c.crash(dead)
            leader2 = await c.wait_leader()
            assert leader2.server_id != dead
            st = await c.apply_ok(leader2, b"after-failover")
            assert st.is_ok(), st
            # restart the crashed node: it recovers from disk and catches up
            await c.restart(dead)
            await c.wait_applied(6)
            assert c.fsms[dead].logs[-1] == b"after-failover"
        finally:
            await c.stop_all()
