"""Unit coverage for the fault plumbing itself: run_nemesis error
paths (heal-after-apply-failure, SkipFault, post-heal checks) and
FaultInjectingTransport block/drop/heal semantics — the machinery every
chaos drive and soak stands on.
"""

import asyncio
import random

from tpuraft.errors import RaftError
from tpuraft.rpc.fault import FaultInjectingTransport
from tpuraft.rpc.transport import RpcError, TransportBase
from tpuraft.util.nemesis import NemesisAction, SkipFault, run_nemesis


def _rng(seed=0):
    return random.Random(seed)


async def test_nemesis_applies_dwells_heals():
    events = []

    async def apply():
        events.append("apply")

    async def heal():
        events.append("heal")

    a = NemesisAction("a", apply, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.2, rng=_rng(),
                                 pause_s=0.05)
    assert a.applied >= 1 and len(timeline) == a.applied
    # strict alternation: every applied fault healed before the next
    assert events == ["apply", "heal"] * a.applied


async def test_nemesis_heals_after_apply_failure():
    """apply() may PARTIALLY take effect before raising: the nemesis
    must heal best-effort so a botched fault can't linger, and the
    drive keeps going."""
    state = {"applied": 0, "healed": 0}

    async def bad_apply():
        state["applied"] += 1
        raise RuntimeError("fault half-applied")

    async def heal():
        state["healed"] += 1

    a = NemesisAction("bad", bad_apply, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.15, rng=_rng(),
                                 pause_s=0.03)
    assert state["applied"] >= 1
    assert state["healed"] == state["applied"]   # healed on EVERY failure
    assert timeline == [] and a.applied == 0     # never recorded as applied


async def test_nemesis_check_runs_on_apply_failure_path_too():
    """A recovery failure that a best-effort heal swallowed must still
    abort the drive via the check hook — not hide in a log line."""
    async def bad_apply():
        raise RuntimeError("apply died half-way")

    async def heal():
        pass

    async def check():
        raise AssertionError("store never recovered")

    a = NemesisAction("pl", bad_apply, heal, dwell_s=0.0, check=check)
    try:
        await run_nemesis([a], duration_s=5.0, rng=_rng(), pause_s=0.01)
        raise AssertionError("swallowed recovery failure did not abort")
    except AssertionError as e:
        assert "never recovered" in str(e)


async def test_nemesis_heal_failure_after_apply_error_is_swallowed():
    async def bad_apply():
        raise RuntimeError("apply blew up")

    async def bad_heal():
        raise RuntimeError("heal blew up too")

    a = NemesisAction("worse", bad_apply, bad_heal, dwell_s=0.0)
    # neither error may escape: the drive rides through
    timeline = await run_nemesis([a], duration_s=0.1, rng=_rng(),
                                 pause_s=0.03)
    assert timeline == []


async def test_nemesis_skipfault_does_not_heal():
    healed = []

    async def skip():
        raise SkipFault

    async def heal():
        healed.append(1)

    a = NemesisAction("skip", skip, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.1, rng=_rng(),
                                 pause_s=0.03)
    assert timeline == [] and not healed and a.applied == 0


async def test_nemesis_check_runs_after_heal_and_aborts_on_violation():
    order = []

    async def apply():
        order.append("apply")

    async def heal():
        order.append("heal")

    async def check():
        order.append("check")
        if order.count("check") == 2:
            raise AssertionError("recovery invariant violated")

    a = NemesisAction("chk", apply, heal, dwell_s=0.0, check=check)
    try:
        await run_nemesis([a], duration_s=5.0, rng=_rng(), pause_s=0.01)
        raise AssertionError("invariant violation did not abort the drive")
    except AssertionError as e:
        assert "recovery invariant" in str(e)
    assert order == ["apply", "heal", "check"] * 2


# ---------------------------------------------------------------------------
# FaultInjectingTransport
# ---------------------------------------------------------------------------


class _EchoTransport(TransportBase):
    def __init__(self):
        self.endpoint = "127.0.0.1:1"
        self.calls = []
        self.closed = False

    async def call(self, dst, method, request, timeout_ms=None):
        self.calls.append((dst, method, request))
        return ("ok", dst, request)

    async def close(self):
        self.closed = True


async def test_fault_transport_block_is_one_way_per_destination():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=1)
    t.block("b:1")
    try:
        await t.call("b:1", "m", 1, timeout_ms=10)
        raise AssertionError("blocked dst answered")
    except RpcError as e:
        assert e.status.code == RaftError.EHOSTDOWN
    # other destinations unaffected
    assert (await t.call("c:1", "m", 2))[1] == "c:1"
    # unblock restores exactly the named destination
    t.unblock("b:1")
    assert (await t.call("b:1", "m", 3))[1] == "b:1"
    assert [c[0] for c in inner.calls] == ["c:1", "b:1"]


async def test_fault_transport_drop_rate_and_heal():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=7)
    t.set_drop_rate(1.0)
    for _ in range(3):
        try:
            await t.call("d:1", "m", 0, timeout_ms=5)
            raise AssertionError("100% drop rate let a call through")
        except RpcError:
            pass
    assert inner.calls == []
    t.set_drop_rate(0.0)
    assert (await t.call("d:1", "m", 1))[0] == "ok"

    # heal() clears every partition at once
    t.block("x:1")
    t.block("y:1")
    t.heal()
    await t.call("x:1", "m", 2)
    await t.call("y:1", "m", 3)
    assert len(inner.calls) == 3


async def test_fault_transport_delay_and_close_passthrough():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=3)
    t.set_delay_ms(5)
    t0 = asyncio.get_running_loop().time()
    await t.call("z:1", "m", 1)
    assert asyncio.get_running_loop().time() - t0 >= 0.004
    await t.close()
    assert inner.closed


async def test_fault_transport_duplicates_execute_twice_at_receiver():
    """Duplication semantics: the receiver EXECUTES both copies (raft
    handlers must be idempotent); the caller sees exactly one response."""
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=5)
    t.set_duplicate_rate(1.0)
    resp = await t.call("d:1", "m", 42, timeout_ms=50)
    assert resp[0] == "ok"           # one response to the caller
    await asyncio.sleep(0.01)        # let the duplicate task land
    assert len(inner.calls) == 2, "duplicate was not delivered"
    assert inner.calls[0] == inner.calls[1] == ("d:1", "m", 42)
    # turning it off restores exactly-once delivery
    t.set_duplicate_rate(0.0)
    await t.call("d:1", "m", 43, timeout_ms=50)
    await asyncio.sleep(0.01)
    assert len(inner.calls) == 3


class _SlowEchoTransport(_EchoTransport):
    """Echo with a tiny service time so reorder delays actually let a
    later frame overtake an earlier one."""

    async def call(self, dst, method, request, timeout_ms=None):
        await asyncio.sleep(0.001)
        return await super().call(dst, method, request, timeout_ms)


async def test_fault_transport_bounded_reordering():
    """A held frame is overtaken by later frames — but delivery stays
    bounded: with reordering off again, order is restored."""
    inner = _SlowEchoTransport()
    t = FaultInjectingTransport(inner, seed=1)
    t.set_reorder(1.0, max_delay_ms=30.0)

    async def one(i):
        await t.call("r:1", "m", i, timeout_ms=200)

    # submit 0 first, then (reordering only 0's window) 1..3 with
    # per-submit jitter: the seeded holds shuffle arrival order
    await asyncio.gather(*(one(i) for i in range(4)))
    arrived = [req for (_dst, _m, req) in inner.calls]
    assert sorted(arrived) == [0, 1, 2, 3], "frames lost or duplicated"
    assert arrived != [0, 1, 2, 3], \
        "reorder_rate=1.0 delivered strictly in order (seed=1)"
    # bounded: disable and confirm in-order delivery resumes
    inner.calls.clear()
    t.set_reorder(0.0)
    for i in range(3):
        await t.call("r:1", "m", i, timeout_ms=200)
    assert [req for (_d, _m, req) in inner.calls] == [0, 1, 2]


async def test_inproc_network_duplication_and_reordering():
    """The in-proc fabric (TestCluster / soak) exposes the same two
    faults so the churn soak's noise action covers both fabrics."""
    from tpuraft.rpc.transport import InProcNetwork, RpcServer

    net = InProcNetwork()
    server = RpcServer("s:1")
    seen = []

    async def handler(req):
        seen.append(req)
        return req

    server.register("echo", handler)
    net.bind(server)
    net.set_duplicate_rate(1.0)
    resp = await net.call("c:1", "s:1", "echo", 7, timeout_ms=100)
    assert resp == 7
    await asyncio.sleep(0.01)
    assert seen == [7, 7], "in-proc duplicate not delivered"

    seen.clear()
    net.set_duplicate_rate(0.0)
    net.set_reorder(1.0, max_delay_ms=25.0)
    await asyncio.gather(*(net.call("c:1", "s:1", "echo", i,
                                    timeout_ms=300) for i in range(4)))
    assert sorted(seen) == [0, 1, 2, 3]
    assert seen != [0, 1, 2, 3], \
        "in-proc reorder_rate=1.0 delivered strictly in order"
    net.set_reorder(0.0)


# ---------------------------------------------------------------------------
# NetworkTopology: per-link geo shaping + heal()/heal_topology() split
# ---------------------------------------------------------------------------


def _geo_topology(seed=0, clock=None):
    from tpuraft.rpc.topology import LinkProfile, NetworkTopology

    kw = {"seed": seed}
    if clock is not None:
        kw["clock"] = clock
    topo = NetworkTopology(**kw)
    topo.set_zone("a:1", "z0")
    topo.set_zone("b:1", "z1")
    topo.set_zone("c:1", "z1")
    topo.set_link("z0", "z1", LinkProfile(latency_ms=20.0), symmetric=False)
    topo.set_link("z1", "z0", LinkProfile(latency_ms=5.0), symmetric=False)
    return topo


def test_topology_asymmetric_latency_and_zone_lookup():
    topo = _geo_topology()
    assert topo.zone_of("a:1") == "z0" and topo.zone_of("c:1") == "z1"
    d_fwd, drop_fwd = topo.plan("a:1", "b:1")
    d_rev, drop_rev = topo.plan("b:1", "a:1")
    assert not drop_fwd and not drop_rev
    assert abs(d_fwd - 0.020) < 1e-9, "z0->z1 must take the 20ms row"
    assert abs(d_rev - 0.005) < 1e-9, "z1->z0 must take the ASYMMETRIC 5ms row"
    # intra-zone rides the (zero) default link
    d_local, _ = topo.plan("b:1", "c:1")
    assert d_local == 0.0


def test_topology_one_way_zone_partition_and_degrade():
    topo = _geo_topology()
    topo.partition_zone("z0", one_way=True)
    _, dropped = topo.plan("a:1", "b:1")
    assert dropped, "z0 outbound must drop under one-way partition"
    _, dropped_in = topo.plan("b:1", "a:1")
    assert not dropped_in, "one-way partition must let inbound flow"
    topo.heal_events()
    _, dropped = topo.plan("a:1", "b:1")
    assert not dropped
    # degrade-WAN multiplies inter-zone latency, base shape untouched
    topo.degrade_wan(latency_x=10.0, extra_loss=0.0)
    d, _ = topo.plan("a:1", "b:1")
    assert abs(d - 0.200) < 1e-9
    topo.heal_events()
    d, _ = topo.plan("a:1", "b:1")
    assert abs(d - 0.020) < 1e-9


def test_topology_bandwidth_bucket_queues_bursts():
    now = [0.0]
    topo = _geo_topology(clock=lambda: now[0])
    from tpuraft.rpc.topology import LinkProfile

    # 8 kbps = 1000 bytes/s: a 500-byte frame serializes in 0.5s
    topo.set_link("z0", "z1", LinkProfile(bandwidth_kbps=8.0))
    d1, _ = topo.plan("a:1", "b:1", nbytes=500)
    d2, _ = topo.plan("a:1", "b:1", nbytes=500)
    assert abs(d1 - 0.5) < 1e-9
    assert abs(d2 - 1.0) < 1e-9, "second frame queues behind the first"
    now[0] += 2.0  # bucket drains with wall time
    d3, _ = topo.plan("a:1", "b:1", nbytes=500)
    assert abs(d3 - 0.5) < 1e-9
    assert topo.counters["shaped_bytes"] == 1500


def test_topology_flap_square_wave():
    now = [0.0]
    from tpuraft.rpc.topology import NetworkTopology

    topo = NetworkTopology(seed=3, clock=lambda: now[0])
    topo.set_zone("a:1", "z0")
    topo.set_zone("b:1", "z1")
    topo.flap("z0", "z1", period_s=1.0, duty=0.5)
    # scan a full period: must see BOTH up and down phases
    outcomes = set()
    for i in range(10):
        now[0] = i * 0.1
        _, dropped = topo.plan("a:1", "b:1")
        outcomes.add(dropped)
    assert outcomes == {True, False}, "flap must alternate up/down"
    topo.heal_events()
    now[0] = 0.35
    for i in range(10):
        now[0] += 0.1
        assert topo.plan("a:1", "b:1")[1] is False


async def test_fault_transport_heal_does_not_stomp_topology():
    """The satellite contract: nemesis-layer heal() and topology
    shaping compose.  heal() clears blocks but leaves topology events;
    heal_topology() clears topology events but leaves blocks."""
    inner = _EchoTransport()
    inner.endpoint = "a:1"
    t = FaultInjectingTransport(inner, seed=2)
    topo = _geo_topology()
    t.set_topology(topo)
    topo.partition_zone("z0", one_way=True)
    t.block("c:1")

    async def dropped(dst):
        try:
            await t.call(dst, "m", 0, timeout_ms=5)
            return False
        except RpcError:
            return True

    assert await dropped("b:1")          # topology partition
    assert await dropped("c:1")          # nemesis block (c is z1: also
    #                                      partitioned — check after heal)
    t.heal()                             # nemesis heal...
    assert await dropped("b:1"), "heal() must NOT clear the zone partition"
    t.heal_topology()                    # ...then topology heal
    assert not await dropped("b:1")
    # now only the nemesis block could remain — heal() already cleared
    # it; re-block and verify heal_topology leaves it alone
    t.block("c:1")
    topo.partition_zone("z0", one_way=True)
    t.heal_topology()
    assert await dropped("c:1"), "heal_topology() must NOT clear blocks"
    t.unblock("c:1")
    assert not await dropped("c:1")


async def test_inproc_network_topology_and_heal_split():
    """Same composition contract on the in-proc fabric the soak uses."""
    from tpuraft.rpc.transport import InProcNetwork, RpcServer

    net = InProcNetwork()
    server = RpcServer("b:1")
    server.register("echo", _async_identity)
    net.bind(server)
    topo = _geo_topology()
    net.set_topology(topo)
    t0 = asyncio.get_running_loop().time()
    assert await net.call("a:1", "b:1", "echo", 7, timeout_ms=500) == 7
    assert asyncio.get_running_loop().time() - t0 >= 0.018, \
        "inter-zone call must pay the 20ms base latency"
    topo.partition_zone("z0", one_way=True)
    net.partition_one_way({"x:1"}, {"b:1"})
    try:
        await net.call("a:1", "b:1", "echo", 8, timeout_ms=20)
        raise AssertionError("partitioned zone answered")
    except RpcError:
        pass
    net.heal()      # nemesis heal keeps the zone partition
    try:
        await net.call("a:1", "b:1", "echo", 9, timeout_ms=20)
        raise AssertionError("heal() cleared the topology partition")
    except RpcError:
        pass
    net.heal_topology()
    assert await net.call("a:1", "b:1", "echo", 10, timeout_ms=500) == 10


async def _async_identity(req):
    return req


def test_topology_seeded_determinism_and_describe():
    from tpuraft.rpc.topology import LinkProfile, NetworkTopology

    def run(seed):
        topo = NetworkTopology(seed=seed)
        topo.set_zone("a:1", "z0")
        topo.set_zone("b:1", "z1")
        topo.set_link("z0", "z1",
                      LinkProfile(latency_ms=1.0, jitter_ms=5.0, loss=0.3))
        return [topo.plan("a:1", "b:1") for _ in range(50)], topo

    outs1, topo = run(11)
    outs2, _ = run(11)
    outs3, _ = run(12)
    assert outs1 == outs2, "same seed must replay byte-identically"
    assert outs1 != outs3
    assert any(d for _, d in outs1) and any(not d for _, d in outs1)
    text = topo.describe()
    assert "zone z0" in text and "counters" in text and "loss=0.3" in text
