"""Unit coverage for the fault plumbing itself: run_nemesis error
paths (heal-after-apply-failure, SkipFault, post-heal checks) and
FaultInjectingTransport block/drop/heal semantics — the machinery every
chaos drive and soak stands on.
"""

import asyncio
import random

from tpuraft.errors import RaftError
from tpuraft.rpc.fault import FaultInjectingTransport
from tpuraft.rpc.transport import RpcError, TransportBase
from tpuraft.util.nemesis import NemesisAction, SkipFault, run_nemesis


def _rng(seed=0):
    return random.Random(seed)


async def test_nemesis_applies_dwells_heals():
    events = []

    async def apply():
        events.append("apply")

    async def heal():
        events.append("heal")

    a = NemesisAction("a", apply, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.2, rng=_rng(),
                                 pause_s=0.05)
    assert a.applied >= 1 and len(timeline) == a.applied
    # strict alternation: every applied fault healed before the next
    assert events == ["apply", "heal"] * a.applied


async def test_nemesis_heals_after_apply_failure():
    """apply() may PARTIALLY take effect before raising: the nemesis
    must heal best-effort so a botched fault can't linger, and the
    drive keeps going."""
    state = {"applied": 0, "healed": 0}

    async def bad_apply():
        state["applied"] += 1
        raise RuntimeError("fault half-applied")

    async def heal():
        state["healed"] += 1

    a = NemesisAction("bad", bad_apply, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.15, rng=_rng(),
                                 pause_s=0.03)
    assert state["applied"] >= 1
    assert state["healed"] == state["applied"]   # healed on EVERY failure
    assert timeline == [] and a.applied == 0     # never recorded as applied


async def test_nemesis_check_runs_on_apply_failure_path_too():
    """A recovery failure that a best-effort heal swallowed must still
    abort the drive via the check hook — not hide in a log line."""
    async def bad_apply():
        raise RuntimeError("apply died half-way")

    async def heal():
        pass

    async def check():
        raise AssertionError("store never recovered")

    a = NemesisAction("pl", bad_apply, heal, dwell_s=0.0, check=check)
    try:
        await run_nemesis([a], duration_s=5.0, rng=_rng(), pause_s=0.01)
        raise AssertionError("swallowed recovery failure did not abort")
    except AssertionError as e:
        assert "never recovered" in str(e)


async def test_nemesis_heal_failure_after_apply_error_is_swallowed():
    async def bad_apply():
        raise RuntimeError("apply blew up")

    async def bad_heal():
        raise RuntimeError("heal blew up too")

    a = NemesisAction("worse", bad_apply, bad_heal, dwell_s=0.0)
    # neither error may escape: the drive rides through
    timeline = await run_nemesis([a], duration_s=0.1, rng=_rng(),
                                 pause_s=0.03)
    assert timeline == []


async def test_nemesis_skipfault_does_not_heal():
    healed = []

    async def skip():
        raise SkipFault

    async def heal():
        healed.append(1)

    a = NemesisAction("skip", skip, heal, dwell_s=0.0)
    timeline = await run_nemesis([a], duration_s=0.1, rng=_rng(),
                                 pause_s=0.03)
    assert timeline == [] and not healed and a.applied == 0


async def test_nemesis_check_runs_after_heal_and_aborts_on_violation():
    order = []

    async def apply():
        order.append("apply")

    async def heal():
        order.append("heal")

    async def check():
        order.append("check")
        if order.count("check") == 2:
            raise AssertionError("recovery invariant violated")

    a = NemesisAction("chk", apply, heal, dwell_s=0.0, check=check)
    try:
        await run_nemesis([a], duration_s=5.0, rng=_rng(), pause_s=0.01)
        raise AssertionError("invariant violation did not abort the drive")
    except AssertionError as e:
        assert "recovery invariant" in str(e)
    assert order == ["apply", "heal", "check"] * 2


# ---------------------------------------------------------------------------
# FaultInjectingTransport
# ---------------------------------------------------------------------------


class _EchoTransport(TransportBase):
    def __init__(self):
        self.endpoint = "127.0.0.1:1"
        self.calls = []
        self.closed = False

    async def call(self, dst, method, request, timeout_ms=None):
        self.calls.append((dst, method, request))
        return ("ok", dst, request)

    async def close(self):
        self.closed = True


async def test_fault_transport_block_is_one_way_per_destination():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=1)
    t.block("b:1")
    try:
        await t.call("b:1", "m", 1, timeout_ms=10)
        raise AssertionError("blocked dst answered")
    except RpcError as e:
        assert e.status.code == RaftError.EHOSTDOWN
    # other destinations unaffected
    assert (await t.call("c:1", "m", 2))[1] == "c:1"
    # unblock restores exactly the named destination
    t.unblock("b:1")
    assert (await t.call("b:1", "m", 3))[1] == "b:1"
    assert [c[0] for c in inner.calls] == ["c:1", "b:1"]


async def test_fault_transport_drop_rate_and_heal():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=7)
    t.set_drop_rate(1.0)
    for _ in range(3):
        try:
            await t.call("d:1", "m", 0, timeout_ms=5)
            raise AssertionError("100% drop rate let a call through")
        except RpcError:
            pass
    assert inner.calls == []
    t.set_drop_rate(0.0)
    assert (await t.call("d:1", "m", 1))[0] == "ok"

    # heal() clears every partition at once
    t.block("x:1")
    t.block("y:1")
    t.heal()
    await t.call("x:1", "m", 2)
    await t.call("y:1", "m", 3)
    assert len(inner.calls) == 3


async def test_fault_transport_delay_and_close_passthrough():
    inner = _EchoTransport()
    t = FaultInjectingTransport(inner, seed=3)
    t.set_delay_ms(5)
    t0 = asyncio.get_running_loop().time()
    await t.call("z:1", "m", 1)
    assert asyncio.get_running_loop().time() - t0 >= 0.004
    await t.close()
    assert inner.closed
