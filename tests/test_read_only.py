"""ReadOnlyService + the amortized read plane (ISSUE 10).

Unit coverage for the service itself (none existed before): the
batch-drain invariant, shutdown cancelling an in-flight round, the
term-first-index safety gate, the witness guard, and the retryable
forward path with leader-hint re-probe.  Plus the store-wide
ReadConfirmBatcher (one beat-plane round confirms many groups), the
kv_command_batch read-fence dedupe, lease reads not waking hibernating
groups, and ReadIndexResponse wire compatibility both directions.
"""

from __future__ import annotations

import asyncio
from types import SimpleNamespace

import pytest

from tpuraft.core.read_only import ReadIndexError, ReadOnlyService
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import NodeOptions, ReadOnlyOption
from tpuraft.rpc.messages import (
    BatchResponse,
    BeatAck,
    ReadIndexResponse,
    decode_message,
    encode_message,
)
from tpuraft.rpc.transport import RpcError


# ---------------------------------------------------------------------------
# stubs
# ---------------------------------------------------------------------------


class _Ctrl:
    def __init__(self, eto_ms: int):
        self._eto_ms = eto_ms
        self.activity = 0

    def note_activity(self) -> None:
        self.activity += 1


class _Fsm:
    def __init__(self):
        self.applied = 1 << 50   # everything applied unless a test lowers it

    async def wait_applied(self, idx: int) -> None:
        while self.applied < idx:
            await asyncio.sleep(0.005)


class _Replicators:
    def __init__(self, acks: int = 2):
        self.acks = acks
        self.rounds = 0
        self.gate: asyncio.Event | None = None

    async def heartbeat_round(self) -> int:
        self.rounds += 1
        if self.gate is not None:
            await self.gate.wait()
        return self.acks


class _Transport:
    """read_index forward stub: endpoint -> response or exception."""

    def __init__(self, answers: dict):
        self.answers = answers
        self.calls: list[str] = []

    async def read_index(self, endpoint, req, timeout_ms=None):
        self.calls.append(endpoint)
        ans = self.answers[endpoint]
        if isinstance(ans, Exception):
            raise ans
        return ans


def _stub_node(leader: bool = True, voters: int = 3, eto_ms: int = 200,
               witness: bool = False,
               read_opt: ReadOnlyOption = ReadOnlyOption.SAFE):
    opts = NodeOptions(election_timeout_ms=eto_ms)
    opts.witness = witness
    opts.raft_options.read_only_option = read_opt
    peers = [PeerId.parse(f"127.0.0.1:{7100 + i}") for i in range(voters)]
    node = SimpleNamespace(
        group_id="g0",
        server_id=peers[0],
        options=opts,
        is_leader=lambda: leader,
        ballot_box=SimpleNamespace(last_committed_index=10),
        _term_first_index=5,
        fsm_caller=_Fsm(),
        _ctrl=_Ctrl(eto_ms),
        conf_entry=SimpleNamespace(
            conf=SimpleNamespace(peers=peers),
            old_conf=SimpleNamespace(peers=[])),
        replicators=_Replicators(acks=voters - 1),
        leader_id=peers[1],
        transport=None,
        leader_lease_is_valid=lambda: False,
        current_term=3,
    )
    return node


# ---------------------------------------------------------------------------
# ReadOnlyService units
# ---------------------------------------------------------------------------


async def test_batch_drain_invariant_follow_up_round():
    """Readers enqueued WHILE a round is resolving must get a follow-up
    round — and must NOT share the in-flight round's confirmation (their
    RPCs must be sent after their invoke)."""
    node = _stub_node()
    node.replicators.gate = asyncio.Event()
    svc = ReadOnlyService(node)
    r1 = asyncio.ensure_future(svc.leader_confirm_read_index())
    await asyncio.sleep(0.02)       # round 1 is blocked on the gate
    assert node.replicators.rounds == 1
    r2 = asyncio.ensure_future(svc.leader_confirm_read_index())
    await asyncio.sleep(0.02)
    assert node.replicators.rounds == 1, "r2 must wait for the NEXT round"
    node.replicators.gate.set()
    assert await asyncio.wait_for(r1, 2) == 10
    assert await asyncio.wait_for(r2, 2) == 10
    assert node.replicators.rounds == 2, "mid-round reader needs its own round"


async def test_shutdown_cancels_in_flight_round_and_fails_readers():
    node = _stub_node()
    node.replicators.gate = asyncio.Event()   # never set: round hangs
    svc = ReadOnlyService(node)
    r1 = asyncio.ensure_future(svc.leader_confirm_read_index())
    await asyncio.sleep(0.02)
    round_task = svc._round_task
    assert round_task is not None and not round_task.done()
    await svc.shutdown()
    with pytest.raises(ReadIndexError) as ei:
        await asyncio.wait_for(r1, 2)
    assert ei.value.status.code == int(RaftError.ENODESHUTTING)
    await asyncio.sleep(0.02)
    assert round_task.done(), "in-flight round must be cancelled"


async def test_term_first_index_gate_fails_closed():
    """A fresh leader whose commit index still lags its own term's no-op
    must NOT serve reads (they could miss acked writes of the previous
    leadership)."""
    node = _stub_node(eto_ms=80)
    node.ballot_box.last_committed_index = 4   # < _term_first_index = 5
    node.fsm_caller.applied = 0                # the no-op never applies
    svc = ReadOnlyService(node)
    with pytest.raises(ReadIndexError) as ei:
        await asyncio.wait_for(svc.leader_confirm_read_index(), 5)
    assert ei.value.status.code == int(RaftError.ERAFTTIMEDOUT)
    # once the term's first entry commits, the same service serves
    node.ballot_box.last_committed_index = 6
    node.fsm_caller.applied = 1 << 50
    assert await asyncio.wait_for(svc.leader_confirm_read_index(), 5) == 6


async def test_witness_never_serves_reads():
    node = _stub_node(witness=True)
    svc = ReadOnlyService(node)
    with pytest.raises(ReadIndexError) as ei:
        await svc.read_index()
    assert ei.value.status.code == int(RaftError.EPERM)


async def test_forward_rejection_is_retryable_and_follows_hint():
    """Satellite: a leader-rejected forward must re-probe the hinted
    leader inside the round, and exhaustion must surface a RETRYABLE
    status (EAGAIN) — not the old terminal EPERM."""
    node = _stub_node(leader=False)
    stale = node.leader_id                    # believed leader (stale)
    real = node.conf_entry.conf.peers[2]      # where it actually moved
    node.transport = _Transport({
        stale.endpoint: ReadIndexResponse(index=0, success=False, term=4,
                                          leader_hint=str(real)),
        real.endpoint: ReadIndexResponse(index=42, success=True, term=4),
    })
    svc = ReadOnlyService(node)
    assert await asyncio.wait_for(svc.read_index(), 5) == 42
    assert svc.fwd_redirects == 1
    assert node.transport.calls == [stale.endpoint, real.endpoint]

    # no hint anywhere -> retryable EAGAIN after the bounded chain
    node.transport = _Transport({
        stale.endpoint: ReadIndexResponse(index=0, success=False, term=4),
    })
    svc2 = ReadOnlyService(node)
    with pytest.raises(ReadIndexError) as ei:
        await asyncio.wait_for(svc2.read_index(), 5)
    assert ei.value.status.code == int(RaftError.EAGAIN)


async def test_forward_rpc_error_stays_timeout():
    node = _stub_node(leader=False)
    node.transport = _Transport({
        node.leader_id.endpoint: RpcError(
            Status.error(RaftError.EHOSTDOWN, "down")),
    })
    svc = ReadOnlyService(node)
    with pytest.raises(ReadIndexError) as ei:
        await asyncio.wait_for(svc.read_index(), 5)
    assert ei.value.status.code == int(RaftError.ETIMEDOUT)


async def test_lease_read_serves_without_wake_and_safe_wakes():
    """LEASE_BASED + valid lease: no quorum round, no note_activity (a
    hibernating leader stays hibernated).  Lease lapsed: the SAFE
    fallback round runs and wakes the group with its followers."""
    node = _stub_node(read_opt=ReadOnlyOption.LEASE_BASED)
    node.leader_lease_is_valid = lambda: True
    svc = ReadOnlyService(node)
    assert await asyncio.wait_for(svc.leader_confirm_read_index(), 5) == 10
    assert node.replicators.rounds == 0
    assert node._ctrl.activity == 0, "lease read must not wake the group"
    assert svc.lease_serves == 1

    node.leader_lease_is_valid = lambda: False
    assert await asyncio.wait_for(svc.leader_confirm_read_index(), 5) == 10
    assert node.replicators.rounds == 1, "lapsed lease falls back to SAFE"
    assert node._ctrl.activity == 1, "SAFE round must wake with followers"


async def test_safe_mode_read_wakes_exactly_on_quorum_round():
    node = _stub_node(read_opt=ReadOnlyOption.SAFE)
    svc = ReadOnlyService(node)
    assert await asyncio.wait_for(svc.leader_confirm_read_index(), 5) == 10
    assert node._ctrl.activity == 1
    assert node.replicators.rounds == 1
    assert svc.safe_rounds == 1


async def test_budget_tracks_density_floor_adopted_eto():
    """Satellite: the post-election wait budget must derive from the
    ADOPTED election timeout (engine density floor), not the value the
    options were constructed with."""
    node = _stub_node(eto_ms=100)
    node._ctrl._eto_ms = 4000    # density floor raised it after init
    svc = ReadOnlyService(node)
    assert svc._effective_eto_ms() == 4000
    node.options.election_timeout_ms = 8000   # host-side adoption wins too
    assert svc._effective_eto_ms() == 8000


# ---------------------------------------------------------------------------
# ReadConfirmBatcher (store-wide amortization)
# ---------------------------------------------------------------------------


class _Rep:
    def __init__(self, peer: PeerId, fast: bool = True):
        self.peer = peer
        self.peer_multi_hb = fast
        self._matched = True
        self.match_index = 1 << 40
        self.last_rpc_ack = 0.0
        self.classic_beats = 0
        self.classic_ok = True

    async def send_heartbeat(self) -> bool:
        self.classic_beats += 1
        return self.classic_ok


class _BatchTransport:
    """multi_beat_fast stub: per-dst scripted acks (or exceptions)."""

    def __init__(self, ok_by_dst=None, fail_dst=None):
        self.ok_by_dst = ok_by_dst or {}
        self.fail_dst = fail_dst or set()
        self.calls: list[tuple[str, int]] = []

    async def call(self, dst, method, request, timeout_ms=None):
        assert method == "multi_beat_fast"
        self.calls.append((dst, len(request.items)))
        if dst in self.fail_dst:
            raise RpcError(Status.error(RaftError.EHOSTDOWN, "dead"))
        ok = self.ok_by_dst.get(dst, True)
        return BatchResponse(items=[BeatAck(ok=ok, term=b.term)
                                    for b in request.items])


def _batcher_node(gid: str, transport, voters: list[PeerId],
                  fast: bool = True):
    opts = NodeOptions(election_timeout_ms=200)
    reps = [_Rep(p, fast=fast) for p in voters[1:]]
    node = SimpleNamespace(
        group_id=gid,
        server_id=voters[0],
        options=opts,
        is_leader=lambda: True,
        current_term=7,
        ballot_box=SimpleNamespace(last_committed_index=3),
        conf_entry=SimpleNamespace(
            conf=SimpleNamespace(peers=list(voters)),
            old_conf=SimpleNamespace(peers=[])),
        replicators=SimpleNamespace(all=lambda reps=reps: list(reps)),
        transport=transport,
        on_peer_ack=lambda peer, when: None,
        acked_log=[],
    )
    node.on_peer_ack = lambda peer, when: node.acked_log.append(peer)
    return node


def _voters(base: int) -> list[PeerId]:
    return [PeerId.parse(f"127.0.0.1:{base + i}") for i in range(3)]


async def test_batcher_amortizes_many_groups_into_one_beat_round():
    """The tentpole: N groups' SAFE confirmations sharing the same two
    follower endpoints cost ONE multi_beat_fast RPC per endpoint, not
    one heartbeat round per group."""
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    transport = _BatchTransport()
    voters = _voters(7200)
    nodes = [_batcher_node(f"g{i}", transport, voters) for i in range(8)]
    b = ReadConfirmBatcher()
    outs = await asyncio.wait_for(
        asyncio.gather(*(b.confirm(n) for n in nodes)), 5)
    assert all(outs)
    assert b.confirms == 8
    assert b.rounds == 1
    # one RPC per distinct follower endpoint, each carrying 8 fences
    assert sorted(transport.calls) == sorted(
        [(voters[1].endpoint, 8), (voters[2].endpoint, 8)])
    assert b.beat_rpcs == 2
    assert b.beats == 16


async def test_batcher_quorum_failure_returns_false():
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    voters = _voters(7300)
    transport = _BatchTransport(
        fail_dst={voters[1].endpoint, voters[2].endpoint})
    node = _batcher_node("g0", transport, voters)
    # classic fallback also fails (dead followers)
    for r in node.replicators.all():
        r.classic_ok = False
    b = ReadConfirmBatcher()
    assert await asyncio.wait_for(b.confirm(node), 5) is False
    assert b.failed == 1


async def test_batcher_ok_false_falls_back_to_classic_beat():
    """A deviating fast ack (follower restarted / committed behind) must
    get the full-semantics classic beat, whose in-term ack still counts
    toward the fence."""
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    voters = _voters(7400)
    transport = _BatchTransport(ok_by_dst={voters[1].endpoint: False,
                                           voters[2].endpoint: False})
    node = _batcher_node("g0", transport, voters)
    b = ReadConfirmBatcher()
    assert await asyncio.wait_for(b.confirm(node), 5) is True
    assert b.classic_beats == 2
    assert all(r.classic_beats == 1 for r in node.replicators.all())


async def test_batcher_deposed_mid_round_voids_fence():
    """Acks landing after a step-down (or a term change) must not
    confirm the old fence."""
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    voters = _voters(7500)

    class DeposingTransport(_BatchTransport):
        def __init__(self, node_box):
            super().__init__()
            self.node_box = node_box

        async def call(self, dst, method, request, timeout_ms=None):
            self.node_box[0].is_leader = lambda: False   # deposed mid-RPC
            return await super().call(dst, method, request, timeout_ms)

    box: list = [None]
    transport = DeposingTransport(box)
    node = _batcher_node("g0", transport, voters)
    box[0] = node
    b = ReadConfirmBatcher()
    assert await asyncio.wait_for(b.confirm(node), 5) is False


async def test_batcher_joint_conf_requires_both_quorums():
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    voters = _voters(7600)
    old = [voters[0]] + [PeerId.parse(f"127.0.0.1:{7650 + i}")
                         for i in range(2)]
    # new-config followers ack; old-config followers are DEAD
    transport = _BatchTransport(fail_dst={p.endpoint for p in old[1:]})
    node = _batcher_node("g0", transport, voters)
    node.conf_entry.old_conf = SimpleNamespace(peers=list(old))
    node.replicators = SimpleNamespace(
        all=lambda: [_Rep(p) for p in voters[1:]]
        + [_Rep(p) for p in old[1:]])
    for r in node.replicators.all():
        r.classic_ok = False
    b = ReadConfirmBatcher()
    assert await asyncio.wait_for(b.confirm(node), 5) is False, \
        "a new-config-only majority must not confirm a joint-conf fence"


class _StallTransport(_BatchTransport):
    """multi_beat_fast stub where one destination is STALLED (not
    dead): its RPCs block on an event and only answer after release —
    the gray-failure shape a timeout never sees in time."""

    def __init__(self, stalled: set[str]):
        super().__init__()
        self.stalled = stalled
        self.release = asyncio.Event()

    async def call(self, dst, method, request, timeout_ms=None):
        if dst in self.stalled:
            await self.release.wait()
        return await super().call(dst, method, request, timeout_ms)


async def test_batcher_stalled_endpoint_delays_only_its_own_round():
    """The max_inflight_rounds windowing claim, proven under a STALLED
    (not dead) endpoint: the round whose destination stalls keeps only
    ITS stragglers waiting — fences for groups on healthy endpoints
    submitted afterwards keep resolving round after round, they never
    convoy behind the stalled RPC."""
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    slow_voters = [PeerId.parse("127.0.0.1:7801"),
                   PeerId.parse("127.0.0.1:7898"),
                   PeerId.parse("127.0.0.1:7899")]
    fast_voters = _voters(7810)
    transport = _StallTransport({p.endpoint for p in slow_voters[1:]})
    slow_node = _batcher_node("slow", transport, slow_voters)
    b = ReadConfirmBatcher()

    stalled_fut = asyncio.ensure_future(b.confirm(slow_node))
    await asyncio.sleep(0.05)   # round 1 is now in flight, stalled
    assert not stalled_fut.done()

    # healthy-endpoint fences submitted AFTER the stall keep resolving
    for i in range(5):
        fast_node = _batcher_node(f"fast{i}", transport, fast_voters)
        ok = await asyncio.wait_for(b.confirm(fast_node), 1.0)
        assert ok, f"healthy fence {i} failed behind a stalled round"
    assert not stalled_fut.done(), "stalled round resolved early?"

    transport.release.set()
    assert await asyncio.wait_for(stalled_fut, 2.0) is True
    assert b.rounds >= 6


async def test_batcher_window_bounds_concurrent_stalled_rounds():
    """With max_inflight_rounds stalled rounds already in flight, the
    NEXT fence waits for a slot (bounded task pileup) — and gets it the
    moment any round completes."""
    from tpuraft.rheakv.store_engine import ReadConfirmBatcher

    voters_sets = [[PeerId.parse(f"127.0.0.1:{7900 + 10 * i}"),
                    PeerId.parse(f"127.0.0.1:{7901 + 10 * i}"),
                    PeerId.parse(f"127.0.0.1:{7902 + 10 * i}")]
                   for i in range(5)]
    stalled_eps = {p.endpoint for vs in voters_sets[:4] for p in vs[1:]}
    transport = _StallTransport(stalled_eps)
    b = ReadConfirmBatcher()
    assert b.max_inflight_rounds == 4
    stalled = []
    for i in range(4):
        node = _batcher_node(f"s{i}", transport, voters_sets[i])
        stalled.append(asyncio.ensure_future(b.confirm(node)))
        await asyncio.sleep(0.02)   # one round each, all stalled
    assert len(b._rounds_inflight) == 4
    fast_node = _batcher_node("fast", transport, voters_sets[4])
    waiting = asyncio.ensure_future(b.confirm(fast_node))
    await asyncio.sleep(0.05)
    assert not waiting.done(), "5th round ran past the window bound"
    transport.release.set()   # frees the stalled rounds -> slot opens
    assert await asyncio.wait_for(waiting, 2.0) is True
    for fut in stalled:
        assert await asyncio.wait_for(fut, 2.0) is True


# ---------------------------------------------------------------------------
# integration: fence dedupe + batcher through the KV stack
# ---------------------------------------------------------------------------


async def test_kv_batch_reads_share_one_fence():
    """A kv_command_batch with N GETs for one region costs ONE
    read_index confirmation, not N."""
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.kv_operation import KVOp, KVOperation
    from tpuraft.rheakv.kv_service import (
        KVCommandBatchRequest,
        decode_batch_reply,
        decode_result,
        encode_batch_item,
    )

    c = KVTestCluster(3)
    await c.start_all()
    try:
        leader_engine = await c.wait_region_leader(1)
        store = leader_engine.store_engine
        rs = leader_engine.raft_store
        for i in range(6):
            await rs.put(b"rf-%d" % i, b"v%d" % i)
        region = leader_engine.region
        items = [encode_batch_item(
            region.id, region.epoch.conf_ver, region.epoch.version,
            KVOperation(KVOp.GET, b"rf-%d" % i).encode())
            for i in range(6)]
        fences0 = store.kv_processor.read_fences
        resp = await store.kv_processor.handle_batch(
            KVCommandBatchRequest(items=items))
        assert len(resp.items) == 6
        for i, blob in enumerate(resp.items):
            code, _msg, result, _meta = decode_batch_reply(blob)
            assert code == 0
            assert decode_result(result) == b"v%d" % i
        assert store.kv_processor.read_fences == fences0 + 1
        assert store.kv_processor.fenced_reads >= 6
        # and the store-level batcher carried the confirmation
        assert store.read_batcher is not None
        assert store.read_batcher.confirms >= 1
    finally:
        await c.stop_all()


async def test_read_from_follower_serves_without_touching_leader_cache():
    """read_from='follower': GETs route to a follower store (served
    there after a forwarded-ReadIndex fence) and the client's leader
    cache is not poisoned by read routing."""
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    c = KVTestCluster(3)
    await c.start_all()
    pd = FakePlacementDriverClient([r.copy() for r in c.region_template])
    kv = RheaKVStore(pd, c.client_transport(),
                     batching=BatchingOptions(enabled=True),
                     read_from="follower")
    await kv.start()
    try:
        await c.wait_region_leader(1)
        for i in range(4):
            assert await kv.put(b"ff-%d" % i, b"w%d" % i)
        for _ in range(3):
            for i in range(4):
                assert await kv.get(b"ff-%d" % i) == b"w%d" % i
        served = kv.read_serves
        assert served["follower"] > 0, served
        # writes kept committing through the leader the whole time
        assert await kv.put(b"ff-last", b"z")
        assert await kv.get(b"ff-last") == b"z"
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_lease_reads_leave_hibernating_groups_hibernated():
    """Tentpole: with LEASE_BASED reads, a pure-read load against a
    hibernated engine-backed group serves linearizably while every
    replica STAYS quiescent (hub wake counters flat)."""
    from tests.test_quiescence import QuiesceCluster, _all_quiescent, \
        _commit, _wait
    from tpuraft.options import ReadOnlyOption as RO

    c = QuiesceCluster(3, 2, election_timeout_ms=400)
    await c.start_all()
    for node in c.nodes.values():
        node.options.raft_options.read_only_option = RO.LEASE_BASED
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _commit(leader, b"seed")
        await _wait(lambda: _all_quiescent(c, gid), 10.0, "group quiescent")
        hubs = [c.nodes[(gid, ep)].node_manager.heartbeat_hub
                for ep in c.endpoints]
        woken0 = sum(h.groups_woken for h in hubs)
        for _ in range(20):
            idx = await asyncio.wait_for(leader.read_index(), 5)
            assert idx >= 1
        assert _all_quiescent(c, gid), \
            "lease reads must not wake a hibernating group"
        assert sum(h.groups_woken for h in hubs) == woken0
        assert leader.read_only_service.lease_serves >= 1
    finally:
        await c.stop_all()


# ---------------------------------------------------------------------------
# wire compatibility (trailing read-plane extensions)
# ---------------------------------------------------------------------------


def test_read_index_response_wire_compat_both_directions():
    """ReadIndexResponse gained trailing (term, leader_hint).  Old
    frames (index, success only) must decode on new receivers with the
    defaults; new frames must be a strict extension an old decoder
    would simply stop before."""
    new = ReadIndexResponse(index=9, success=False, term=4,
                            leader_hint="127.0.0.1:7001")
    wire = encode_message(new)
    assert decode_message(wire) == new            # new <-> new
    # old sender -> new receiver: the old format is exactly
    # tid (u8) + index (i64) + success (u8); trailing term/leader_hint
    # take their defaults on decode
    old_wire = wire[:1 + 8 + 1]
    got = decode_message(old_wire)
    assert got == ReadIndexResponse(index=9, success=False,
                                    term=0, leader_hint="")
    # new -> old receiver: the old field prefix is byte-identical, so an
    # old decoder (which stops after success) reads the same values
    assert wire[:len(old_wire)] == old_wire
    # a genuinely truncated REQUIRED field still fails loudly
    with pytest.raises(Exception):
        decode_message(old_wire[:-1])


# ---------------------------------------------------------------------------
# check_stale_reads (the read-mix soak's targeted assertion)
# ---------------------------------------------------------------------------


def _h(ops_spec):
    """Build a History from (client, kind, args, invoke, ret, result)."""
    from tpuraft.util.linearizability import History

    h = History()
    for client, kind, args, invoke, ret, result in ops_spec:
        tok = h.invoke(client, kind, args, now=invoke)
        if ret is not None:
            h.complete(tok, result, now=ret)
    return h


def _seq(v):
    return int(v[1:]) if isinstance(v, bytes) and v[:1] == b"s" else -1


def test_stale_read_detected():
    from tpuraft.util.linearizability import check_stale_reads

    k = b"k"
    h = _h([
        (0, "w", (k, b"s1"), 1.0, 1.1, True),
        (0, "w", (k, b"s2"), 2.0, 2.1, True),     # acked at 2.1
        (1, "r", (k,), 3.0, 3.1, b"s1"),          # issued after: STALE
    ])
    v = check_stale_reads(h.ops(), _seq)
    assert len(v) == 1 and "stale read" in v[0]


def test_fresh_read_and_pending_write_explanation_pass():
    from tpuraft.util.linearizability import check_stale_reads

    k = b"k"
    h = _h([
        (0, "w", (k, b"s1"), 1.0, 1.1, True),
        (0, "w", (k, b"s2"), 2.0, None, None),    # timed out: maybe applied
        (0, "w", (k, b"s3"), 3.0, 3.1, True),     # acked
        (1, "r", (k,), 4.0, 4.1, b"s3"),          # fresh: ok
        # s2 landing in the log after s3 is linearizable (pending write
        # may take effect at any point after its invoke) — not stale
        (1, "r", (k,), 5.0, 5.1, b"s2"),
    ])
    assert check_stale_reads(h.ops(), _seq) == []


def test_read_concurrent_with_write_may_see_either():
    from tpuraft.util.linearizability import check_stale_reads

    k = b"k"
    h = _h([
        (0, "w", (k, b"s1"), 1.0, 1.1, True),
        (0, "w", (k, b"s2"), 2.0, 2.5, True),
        (1, "r", (k,), 2.2, 2.3, b"s1"),   # overlaps s2's window: ok
    ])
    assert check_stale_reads(h.ops(), _seq) == []
