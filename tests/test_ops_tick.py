"""Tests for the fused multi-group tick kernel (tpuraft.ops.tick)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpuraft.ops.tick import (  # noqa: E402
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_INACTIVE,
    ROLE_LEADER,
    GroupState,
    TickParams,
    raft_tick,
)

P = 4
PARAMS = TickParams.make(election_timeout_ms=1000, heartbeat_ms=100, lease_ms=900)


def mk_state(g=3):
    s = GroupState.zeros(g, P)
    return s


def test_leader_commit_advances():
    s = mk_state(2)
    s.role = jnp.array([ROLE_LEADER, ROLE_FOLLOWER], jnp.int32)
    s.voter_mask = jnp.array([[1, 1, 1, 0]] * 2, bool)
    s.pending_rel = jnp.array([1, 1], jnp.int32)
    # leader self slot 0 at 10, peers at 8 and 3 -> quorum idx 8
    s.match_rel = jnp.array([[10, 8, 3, 0], [10, 8, 3, 0]], jnp.int32)
    s.last_ack = jnp.zeros((2, P), jnp.int32)
    ns, out = raft_tick(s, jnp.int32(0), PARAMS)
    assert int(out.commit_rel[0]) == 8
    assert bool(out.commit_advanced[0])
    # follower's quorum math never advances commit on device
    assert int(out.commit_rel[1]) == 0
    assert not bool(out.commit_advanced[1])


def test_commit_gated_by_pending_index():
    """Entries from a previous leadership (below pending) never commit on
    quorum math alone — Raft §5.4.2 via pending_rel gate."""
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    s.voter_mask = jnp.ones((1, P), bool)
    s.pending_rel = jnp.array([20], jnp.int32)
    s.match_rel = jnp.array([[15, 15, 15, 15]], jnp.int32)
    _, out = raft_tick(s, jnp.int32(0), PARAMS)
    assert int(out.commit_rel[0]) == 0
    assert not bool(out.commit_advanced[0])


def test_commit_monotone():
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    s.voter_mask = jnp.array([[1, 1, 1, 0]], bool)
    s.pending_rel = jnp.array([1], jnp.int32)
    s.commit_rel = jnp.array([9], jnp.int32)
    s.match_rel = jnp.array([[5, 5, 5, 0]], jnp.int32)
    _, out = raft_tick(s, jnp.int32(0), PARAMS)
    assert int(out.commit_rel[0]) == 9  # never regresses


def test_candidate_elected():
    s = mk_state(2)
    s.role = jnp.array([ROLE_CANDIDATE, ROLE_CANDIDATE], jnp.int32)
    s.voter_mask = jnp.array([[1, 1, 1, 0]] * 2, bool)
    s.granted = jnp.array([[1, 1, 0, 0], [1, 0, 0, 0]], bool)
    _, out = raft_tick(s, jnp.int32(0), PARAMS)
    assert bool(out.elected[0])
    assert not bool(out.elected[1])


def test_election_due_and_inactive_silent():
    s = mk_state(3)
    s.role = jnp.array([ROLE_FOLLOWER, ROLE_FOLLOWER, ROLE_INACTIVE], jnp.int32)
    s.elect_deadline = jnp.array([100, 5000, 0], jnp.int32)
    _, out = raft_tick(s, jnp.int32(200), PARAMS)
    assert bool(out.election_due[0])
    assert not bool(out.election_due[1])
    assert not bool(out.election_due[2])


def test_leader_step_down_on_dead_quorum():
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    s.voter_mask = jnp.ones((1, P), bool)
    # self slot acked recently; all others stale -> quorum(3) ack is stale
    s.last_ack = jnp.array([[5000, 100, 90, 80]], jnp.int32)
    _, out = raft_tick(s, jnp.int32(5000), PARAMS)
    assert bool(out.step_down[0])
    assert not bool(out.lease_valid[0])


def test_joint_step_down_when_old_config_quorum_dead():
    """During joint consensus the lease needs BOTH configs responsive
    (NodeImpl#checkDeadNodes walks conf AND oldConf): a leader whose
    old-config quorum is dead must step down even if the new config is
    fully live (ADVICE r2: q_ack previously used voter_mask only)."""
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    # new config = slots {0,1}, old config = slots {2,3}
    s.voter_mask = jnp.array([[1, 1, 0, 0]], bool)
    s.old_voter_mask = jnp.array([[0, 0, 1, 1]], bool)
    # new-config voters fresh, old-config voters stale beyond eto
    s.last_ack = jnp.array([[5000, 5000, 100, 90]], jnp.int32)
    _, out = raft_tick(s, jnp.int32(5000), PARAMS)
    assert bool(out.step_down[0])
    assert not bool(out.lease_valid[0])
    # same ack state outside joint mode: new config alone holds the lease
    s.old_voter_mask = jnp.zeros((1, P), bool)
    _, out2 = raft_tick(s, jnp.int32(5000), PARAMS)
    assert not bool(out2.step_down[0])
    assert bool(out2.lease_valid[0])


def test_leader_lease_valid_with_live_quorum():
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    s.voter_mask = jnp.ones((1, P), bool)
    s.last_ack = jnp.array([[5000, 4900, 4800, 100]], jnp.int32)
    _, out = raft_tick(s, jnp.int32(5000), PARAMS)
    assert not bool(out.step_down[0])
    assert bool(out.lease_valid[0])


def test_heartbeat_scheduling():
    s = mk_state(1)
    s.role = jnp.array([ROLE_LEADER], jnp.int32)
    s.voter_mask = jnp.ones((1, P), bool)
    s.last_ack = jnp.full((1, P), 5000, jnp.int32)
    s.hb_deadline = jnp.array([4000], jnp.int32)
    ns, out = raft_tick(s, jnp.int32(5000), PARAMS)
    assert bool(out.hb_due[0])
    assert int(ns.hb_deadline[0]) == 5100
    # next tick before new deadline: not due
    _, out2 = raft_tick(ns, jnp.int32(5050), PARAMS)
    assert not bool(out2.hb_due[0])


def test_jit_and_large_g():
    G = 2048
    s = GroupState.zeros(G, 8)
    rng = np.random.default_rng(0)
    s.role = jnp.asarray(rng.integers(0, 3, G).astype(np.int32))
    s.voter_mask = jnp.asarray(rng.random((G, 8)) < 0.6)
    s.match_rel = jnp.asarray(rng.integers(0, 1000, (G, 8)).astype(np.int32))
    tick = jax.jit(raft_tick)
    ns, out = tick(s, jnp.int32(123), PARAMS)
    assert out.commit_rel.shape == (G,)
    assert ns.match_rel.shape == (G, 8)


def test_numpy_twin_matches_device_tick_randomized():
    """The engine's no-jax fallback (MultiRaftEngine._np_tick) must stay
    BIT-IDENTICAL to ops.tick.raft_tick — quorum semantics now live in
    several formulations (jnp kernel, numpy twin, scalar BallotBox) and
    this differential test is the drift tripwire for the first two."""
    import numpy as np

    from tpuraft.core.engine import MultiRaftEngine, _NEG_I32
    from tpuraft.options import TickOptions
    from tpuraft.ops.tick import GroupState, TickParams, raft_tick

    rng = np.random.default_rng(42)
    G, P = 64, 5
    for trial in range(10):
        eng = MultiRaftEngine(TickOptions(
            max_groups=G, max_peers=P, backend="numpy"))
        # per-group protocol params ([G] rows, VERDICT r2 #5): the twin
        # and the device tick must agree under MIXED timeouts too
        eng.eto_ms = rng.integers(200, 2000, G)
        eng.hb_ms = rng.integers(20, 200, G)
        eng.lease_ms = rng.integers(100, 1800, G)
        eng.role = rng.integers(0, 4, G).astype(np.int32)
        eng.pending_rel = rng.integers(1, 20, G).astype(np.int32)
        eng.voter_mask = rng.random((G, P)) < 0.7
        eng.old_voter_mask = np.where(
            (rng.random(G) < 0.2)[:, None], rng.random((G, P)) < 0.5, False)
        eng.granted = rng.random((G, P)) < 0.4
        eng.elect_deadline = rng.integers(0, 2000, G)
        eng.hb_deadline = rng.integers(0, 2000, G)
        eng.last_ack = np.where(rng.random((G, P)) < 0.8,
                                rng.integers(0, 1500, (G, P)), _NEG_I32)
        # quiescence lane: hibernating groups must suppress hb_due /
        # election_due identically in both formulations (step_down and
        # lease_valid stay LIVE for quiescent leaders)
        eng.quiescent = rng.random(G) < 0.3
        # witness lane (ISSUE 19): witness columns clamp the commit
        # reduce to the best data-replica match in both formulations
        eng.witness_mask = rng.random((G, P)) < 0.2
        eng._n_witness_slots = int(eng.witness_mask.any(axis=1).sum())
        # stepdown/priority + read-fence lanes
        eng.stepdown_deadline = rng.integers(0, 2000, G)
        eng.fence_start = np.where(rng.random(G) < 0.4,
                                   rng.integers(0, 1500, G), _NEG_I32)
        rel = rng.integers(0, 100, (G, P)).astype(np.int32)
        commit_now = rng.integers(0, 40, G).astype(np.int32)
        now = int(rng.integers(500, 1500))

        np_out = eng._np_tick(rel, commit_now, now)

        state = GroupState(
            role=eng.role.copy(),
            commit_rel=commit_now.copy(),
            pending_rel=eng.pending_rel.copy(),
            match_rel=rel.copy(),
            granted=eng.granted.copy(),
            voter_mask=eng.voter_mask.copy(),
            old_voter_mask=eng.old_voter_mask.copy(),
            elect_deadline=eng.elect_deadline.astype(np.int32),
            hb_deadline=eng.hb_deadline.astype(np.int32),
            last_ack=eng.last_ack.astype(np.int32),
            snap_deadline=eng.snap_deadline.astype(np.int32),
            quiescent=eng.quiescent.copy(),
            witness_mask=eng.witness_mask.copy(),
            stepdown_deadline=eng.stepdown_deadline.astype(np.int32),
            fence_start=eng.fence_start.astype(np.int32),
        )
        _, dev_out = raft_tick(state, np.int32(now),
                               TickParams.make(eng.eto_ms, eng.hb_ms,
                                               eng.lease_ms, eng.snap_ms))
        for field in ("commit_rel", "commit_advanced", "elected",
                      "election_due", "step_down", "hb_due",
                      "lease_valid", "snap_due", "q_ack",
                      "stepdown_due", "fence_ok"):
            np.testing.assert_array_equal(
                np.asarray(getattr(dev_out, field)),
                np.asarray(getattr(np_out, field)),
                err_msg=f"trial {trial}: {field} diverged")


def test_witness_clamp_enumeration_matches_host_and_quorum_math():
    """Enumerate EVERY witness subset of 3..6-voter confs (plus seeded
    joint-consensus variants) and cross-check the three formulations of
    the witness commit clamp against each other:

    - the device kernel: ops.ballot.joint_quorum_match_index followed
      by ops.ballot.witness_commit_clamp, batched as one [G] row per
      enumerated case;
    - the scalar host oracle: ballot_box.commit_point (the BallotBox
      data-clamp the device plane mirrors since ISSUE 19);
    - util.quorum's enumeration-by-majorities classification: for any
      VALID conf (witness_minority) every majority holds a data peer,
      so the clamp provably never binds — and for degenerate
      witness-majority rows (witness_only_majorities non-empty) the
      clamped commit never exceeds the best data-replica match.
    """
    from itertools import combinations

    from tpuraft.conf import Configuration
    from tpuraft.core.ballot_box import commit_point
    from tpuraft.entity import PeerId
    from tpuraft.ops.ballot import (
        joint_quorum_match_index,
        witness_commit_clamp,
    )
    from tpuraft.util import quorum as uq

    rng = np.random.default_rng(19)
    COLS = 8
    peers = [PeerId(f"10.0.0.{i + 1}", 80, 0) for i in range(COLS)]
    col = {p: i for i, p in enumerate(peers)}

    cases = []  # (conf, old_conf, match row)
    for n in range(3, 7):
        voters = peers[:n]
        for wn in range(0, n + 1):
            for wit in combinations(range(n), wn):
                for _ in range(2):
                    conf = Configuration(
                        list(voters), witnesses=[voters[i] for i in wit])
                    cases.append((conf, Configuration(),
                                  rng.integers(0, 30, COLS)))
    # joint variants: overlapping old/new windows, independent subsets
    for _ in range(60):
        n_new, n_old = int(rng.integers(3, 6)), int(rng.integers(3, 6))
        lo = int(rng.integers(0, 3))
        new_v, old_v = peers[:n_new], peers[lo:lo + n_old]
        conf = Configuration(
            list(new_v), witnesses=[p for p in new_v if rng.random() < 0.3])
        old = Configuration(
            list(old_v), witnesses=[p for p in old_v if rng.random() < 0.3])
        cases.append((conf, old, rng.integers(0, 30, COLS)))

    G = len(cases)
    match_m = np.zeros((G, COLS), np.int32)
    vm = np.zeros((G, COLS), bool)
    ovm = np.zeros((G, COLS), bool)
    wm = np.zeros((G, COLS), bool)
    for g, (conf, old, match) in enumerate(cases):
        match_m[g] = match
        for p in conf.peers:
            vm[g, col[p]] = True
        for p in old.peers:
            ovm[g, col[p]] = True
        for p in list(conf.witnesses) + list(old.witnesses):
            wm[g, col[p]] = True

    unclamped = np.asarray(joint_quorum_match_index(
        jnp.asarray(match_m), jnp.asarray(vm), jnp.asarray(ovm)))
    clamped = np.asarray(witness_commit_clamp(
        jnp.asarray(unclamped), jnp.asarray(match_m), jnp.asarray(vm),
        jnp.asarray(ovm), jnp.asarray(wm)))

    for g, (conf, old, match) in enumerate(cases):
        md = {p: int(match[col[p]])
              for p in set(conf.peers) | set(old.peers)}
        want = commit_point(md, conf, old)
        assert clamped[g] == want, (
            f"case {g}: device clamp {clamped[g]} != host commit_point "
            f"{want} (conf={conf}, old={old}, match={md})")
        if not old.is_empty():
            continue  # the majority classification below is single-conf
        voters, wits = set(conf.peers), set(conf.witnesses)
        if uq.witness_minority(voters, wits):
            # valid conf: every majority has a data peer (by
            # enumeration), so the q-th-largest match is always covered
            # by some data replica and the clamp must be a NO-OP
            assert uq.every_majority_has_data_peer(voters, wits)
            assert not uq.witness_only_majorities(voters, wits)
            assert clamped[g] == unclamped[g], (
                f"case {g}: clamp bound on a witness_minority conf "
                f"(conf={conf}, match={md})")
        elif wits:
            # degenerate witness-majority row (set_conf does not
            # validate; node-level is_valid() does): whatever commits
            # must be held by a data replica — never a witness-only
            # certification
            data_best = max((md[p] for p in conf.data_peers()), default=0)
            assert clamped[g] <= data_best

    # deterministic binding case (the bench_multichip clamp probe in
    # miniature): 1 data voter at 3, 2 witnesses at 9 -> the unclamped
    # order statistic says 9, the clamp must pin commit to 3
    probe_match = jnp.asarray([[3, 9, 9]], jnp.int32)
    probe_vm = jnp.ones((1, 3), bool)
    probe_ovm = jnp.zeros((1, 3), bool)
    probe_wm = jnp.asarray([[False, True, True]])
    q_idx = joint_quorum_match_index(probe_match, probe_vm, probe_ovm)
    assert int(q_idx[0]) == 9
    assert int(witness_commit_clamp(
        q_idx, probe_match, probe_vm, probe_ovm, probe_wm)[0]) == 3
