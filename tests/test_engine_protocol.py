"""The engine-driven protocol control plane (VERDICT r1 #1): elections,
leases, step-down, and heartbeat scheduling for ALL groups come from the
fused device tick's masks — no per-group RepeatedTimers, no _peer_acks
dicts anywhere on the engine path.

Scale proof: thousands of groups in ONE process elect and commit through
one engine, where round 1's host control plane (O(G) asyncio timers)
documented needing multi-second timeouts at just 64 groups.
"""

import asyncio
import time

import pytest

from tests.cluster import MockStateMachine
from tests.test_engine import MultiRaftCluster
from tpuraft.conf import Configuration
from tpuraft.core.engine import EngineControl, MultiRaftEngine
from tpuraft.core.node import Node, State, TimerControl
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId, Task
from tpuraft.options import NodeOptions, TickOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


async def _apply_ok(node: Node, data: bytes, timeout_s: float = 10.0):
    fut = asyncio.get_running_loop().create_future()
    await node.apply(Task(data=data, done=lambda st: fut.set_result(st)))
    st = await asyncio.wait_for(fut, timeout_s)
    assert st.is_ok(), st
    return st


async def _apply_retry(c: MultiRaftCluster, gid: str, data: bytes,
                       timeout_s: float = 20.0):
    """Apply through the CURRENT leader, retrying across step-downs —
    on a loaded 1-core host, dead-quorum step-downs mid-test are
    protocol-correct behavior, not failures."""
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        leader = await c.wait_leader(gid, timeout_s=max(
            1.0, deadline - time.monotonic()))
        fut = asyncio.get_running_loop().create_future()
        await leader.apply(Task(data=data, done=fut.set_result))
        try:
            last = await asyncio.wait_for(
                fut, max(0.5, deadline - time.monotonic()))
        except asyncio.TimeoutError:
            continue
        if last.is_ok():
            return last
        await asyncio.sleep(0.1)
    raise AssertionError(f"apply never committed: {last}")


async def test_engine_elects_4k_groups_one_process(tmp_path):
    """4096 single-voter groups on one engine: every election is fired
    by the device tick's election_due mask and won through the engine
    vote plane; every commit flows through the batched quorum reduce.
    No RepeatedTimer exists on any node."""
    G = 4096
    net = InProcNetwork()
    ep = PeerId.parse("127.0.0.1:7000")
    server = RpcServer(ep.endpoint)
    manager = NodeManager(server)
    net.bind(server)
    transport = InProcTransport(net, ep.endpoint)
    engine = MultiRaftEngine(TickOptions(
        max_groups=G, max_peers=4, tick_interval_ms=20))
    await engine.start()
    factory = engine.ballot_box_factory()
    nodes: list[Node] = []
    fsms: list[MockStateMachine] = []
    try:
        t0 = time.monotonic()
        for k in range(G):
            fsm = MockStateMachine()
            opts = NodeOptions(
                election_timeout_ms=300,
                initial_conf=Configuration([ep]),
                fsm=fsm, log_uri="memory://", raft_meta_uri="memory://")
            node = Node(f"g{k}", ep, opts, transport,
                        ballot_box_factory=factory)
            node.node_manager = manager
            manager.add(node)
            assert await node.init()
            nodes.append(node)
            fsms.append(fsm)
        init_s = time.monotonic() - t0

        # every node runs the engine control plane — RepeatedTimers and
        # _peer_acks are structurally absent from the engine path
        assert all(isinstance(n._ctrl, EngineControl) for n in nodes)
        assert not any(hasattr(n, "_election_timer") or
                       hasattr(n, "_peer_acks") for n in nodes)

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            n_lead = sum(1 for n in nodes if n.state == State.LEADER)
            if n_lead == G:
                break
            await asyncio.sleep(0.1)
        n_lead = sum(1 for n in nodes if n.state == State.LEADER)
        assert n_lead == G, f"{n_lead}/{G} leaders after 60s"

        # force a MASS re-election: step every leader down, then every
        # one of the 4096 elections must fire from the device tick's
        # election_due mask (single-voter init elects immediately, so
        # this is the pass that actually proves mask-driven elections)
        from tpuraft.errors import RaftError, Status
        for n in nodes:
            async with n._lock:
                await n._step_down(n.current_term, Status.error(
                    RaftError.ERAFTTIMEDOUT, "test: mass step-down"))
        assert all(n.state == State.FOLLOWER for n in nodes)
        t1 = time.monotonic()
        deadline = t1 + 60
        while time.monotonic() < deadline:
            n_lead = sum(1 for n in nodes if n.state == State.LEADER)
            if n_lead == G:
                break
            await asyncio.sleep(0.1)
        n_lead = sum(1 for n in nodes if n.state == State.LEADER)
        elect_s = time.monotonic() - t1
        assert n_lead == G, \
            f"{n_lead}/{G} re-elected via election_due after 60s"

        # commit one entry per group across a sample (full G would be an
        # apply-throughput test, not a control-plane test)
        sample = nodes[:: G // 64]
        await asyncio.gather(*(_apply_ok(n, b"x") for n in sample))
        assert engine.ticks > 0
        assert engine.commit_advances + engine.eager_commits >= len(sample)
        print(f"4k groups: init {init_s:.1f}s, all elected +{elect_s:.1f}s, "
              f"ticks={engine.ticks}")
    finally:
        for n in nodes:
            await n.shutdown()
        await engine.shutdown()


async def test_per_group_timeouts_one_engine():
    """VERDICT r2 #5: protocol params are [G] rows on the device plane —
    two nodes with different election_timeout_ms in ONE engine each
    honor their own timeouts (a PD group + region groups in one process
    no longer run the first registrant's constants)."""
    net = InProcNetwork()
    ep = PeerId.parse("127.0.0.1:7100")
    server = RpcServer(ep.endpoint)
    manager = NodeManager(server)
    net.bind(server)
    transport = InProcTransport(net, ep.endpoint)
    engine = MultiRaftEngine(TickOptions(
        max_groups=4, max_peers=4, tick_interval_ms=20))
    await engine.start()
    factory = engine.ballot_box_factory()
    nodes: dict[str, Node] = {}
    try:
        for gid, eto in (("fast", 500), ("slow", 30_000)):
            opts = NodeOptions(
                election_timeout_ms=eto,
                initial_conf=Configuration([ep]),
                fsm=MockStateMachine(), log_uri="memory://",
                raft_meta_uri="memory://")
            node = Node(gid, ep, opts, transport,
                        ballot_box_factory=factory)
            node.node_manager = manager
            manager.add(node)
            assert await node.init()
            nodes[gid] = node
        fast, slow = nodes["fast"], nodes["slow"]
        assert isinstance(fast._ctrl, EngineControl)
        # the engine's [G] param rows carry each node's own constants
        assert int(engine.eto_ms[fast._ctrl.slot]) == 500
        assert int(engine.eto_ms[slow._ctrl.slot]) == 30_000

        for n in (fast, slow):
            deadline = time.monotonic() + 20
            while n.state != State.LEADER and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert n.state == State.LEADER

        # step both down; only the fast group's election_due mask may
        # fire within its (short) timeout window — the slow group must
        # still be a follower when the fast one is back in charge
        from tpuraft.errors import RaftError, Status
        for n in (fast, slow):
            async with n._lock:
                await n._step_down(n.current_term, Status.error(
                    RaftError.ERAFTTIMEDOUT, "test: step-down"))
        assert fast.state == State.FOLLOWER
        assert slow.state == State.FOLLOWER
        deadline = time.monotonic() + 20
        while fast.state != State.LEADER and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert fast.state == State.LEADER, \
            "fast group never re-elected from its 500ms timeout"
        assert slow.state == State.FOLLOWER, \
            "slow group elected way before its 30s election timeout"
    finally:
        for n in nodes.values():
            await n.shutdown()
        await engine.shutdown()


async def test_engine_mask_driven_failover():
    """3 endpoints x 8 groups: kill the leader endpoint's node of one
    group; the remaining replicas re-elect purely via engine masks
    (election_due -> pre-vote -> elected mask -> becomeLeader)."""
    c = MultiRaftCluster(3, 8, election_timeout_ms=1200)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        assert isinstance(leader._ctrl, EngineControl)
        await _apply_retry(c, gid, b"before")
        # re-resolve: the retry may have ridden out a step-down, and
        # killing a stale ex-leader would make the failover vacuous
        leader = await c.wait_leader(gid)
        # crash the leader (unbind its endpoint for this group only:
        # shut down the node; other groups on the endpoint stay up)
        dead_ep = leader.server_id
        del c.nodes[(gid, dead_ep)]
        await leader.shutdown()
        new_leader = await c.wait_leader(gid, timeout_s=20)
        assert new_leader.server_id != dead_ep
        await _apply_retry(c, gid, b"after")
    finally:
        await c.stop_all()


async def test_engine_step_down_mask_on_quorum_loss():
    """Leader loses both followers: the device tick's step_down mask
    (quorum-ack age >= election timeout) demotes it — the stepDownTimer
    analog, with no timer."""
    c = MultiRaftCluster(3, 1, election_timeout_ms=800)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        for ep in c.endpoints:
            if ep != leader.server_id:
                c.net.stop_endpoint(ep.endpoint)
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if leader.state != State.LEADER:
                break
            await asyncio.sleep(0.05)
        assert leader.state != State.LEADER, \
            "leader kept leading without a quorum"
    finally:
        for ep in c.endpoints:
            c.net.start_endpoint(ep.endpoint)
        await c.stop_all()


async def test_engine_lease_from_ack_plane():
    """LEASE_BASED validity comes from the engine's last_ack rows (the
    same rows the device lease_valid mask reduces): healthy -> valid;
    followers silenced -> expires within the lease window."""
    c = MultiRaftCluster(3, 1, election_timeout_ms=500)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _apply_retry(c, gid, b"x")
        leader = await c.wait_leader(gid)
        # heartbeats keep the quorum-ack age low
        await asyncio.sleep(0.3)
        assert leader.leader_lease_is_valid()
        for ep in c.endpoints:
            if ep != leader.server_id:
                c.net.stop_endpoint(ep.endpoint)
        deadline = asyncio.get_running_loop().time() + 3
        while asyncio.get_running_loop().time() < deadline:
            if not leader.leader_lease_is_valid():
                break
            await asyncio.sleep(0.05)
        assert not leader.leader_lease_is_valid()
    finally:
        for ep in c.endpoints:
            c.net.start_endpoint(ep.endpoint)
        await c.stop_all()


async def test_adaptive_tick_commit_ack_not_quantized():
    """VERDICT r1 #5: with a 250ms idle tick cap, a commit ack still
    arrives in a few ms — the dirty mark fires the tick immediately
    instead of waiting out the interval."""
    c = MultiRaftCluster(3, 1, election_timeout_ms=2000, tick_ms=250)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _apply_ok(leader, b"warm")   # compile + warm the path
        lats = []
        for i in range(5):
            t0 = time.perf_counter()
            await _apply_ok(leader, b"m%d" % i)
            lats.append(time.perf_counter() - t0)
            await asyncio.sleep(0.05)
        best = min(lats)
        assert best < 0.125, \
            f"ack min latency {best * 1e3:.1f}ms — tick-quantized? {lats}"
    finally:
        await c.stop_all()


async def test_apply_batch_semantics():
    """apply_batch (NodeImpl#executeApplyingTasks parity): one lock/flush
    round stages N entries; every task acks individually; stale
    expected_term tasks are rejected without poisoning the batch."""
    from tpuraft.errors import RaftError

    # generous timeout: a mid-batch step-down under full-suite load on
    # a 1-core host would fail tasks legitimately and flake the test
    c = MultiRaftCluster(3, 1, election_timeout_ms=2000)
    await c.start_all()
    try:
        leader = await c.wait_leader(c.groups[0])
        loop = asyncio.get_running_loop()
        futs = [loop.create_future() for _ in range(40)]
        stale = loop.create_future()
        tasks = [Task(data=b"b%d" % i, done=futs[i].set_result)
                 for i in range(40)]
        tasks.insert(20, Task(data=b"stale", expected_term=999,
                              done=stale.set_result))
        await leader.apply_batch(tasks)
        sts = await asyncio.wait_for(asyncio.gather(*futs), 15)
        assert all(st.is_ok() for st in sts), \
            [str(st) for st in sts if not st.is_ok()]
        st = await asyncio.wait_for(stale, 5)
        assert st.raft_error == RaftError.EPERM
        # replicas converge on the same 40 entries (stale one excluded)
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            logs = [f.logs for f in c.fsms.values()]
            if all(len(lg) >= 40 for lg in logs):
                break
            await asyncio.sleep(0.05)
        logs = [f.logs for f in c.fsms.values()]
        assert all(lg == logs[0] for lg in logs)
        assert len(logs[0]) == 40 and b"stale" not in logs[0]
    finally:
        await c.stop_all()


async def test_timer_mode_unchanged_without_engine():
    """Nodes without an engine box still run the reference-parity
    TimerControl (per-group timers)."""
    from tests.cluster import TestCluster

    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        assert isinstance(leader._ctrl, TimerControl)
        st = await c.apply_ok(leader, b"x")
        assert st.is_ok()
    finally:
        await c.stop_all()


async def test_protocol_plane_on_mesh_sharded_engine():
    """BASELINE config 4 with the FULL protocol: engines shard their
    [G, P] planes over the 8-device CPU mesh (mesh_devices=8) and the
    cluster still elects through the election_due/elected masks and
    commits through the SPMD quorum reduce."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")

    class MeshCluster(MultiRaftCluster):
        def _tick_options(self):
            return TickOptions(max_groups=16, max_peers=8,
                               tick_interval_ms=self.tick_ms,
                               mesh_devices=8)

    c = MeshCluster(3, 8, election_timeout_ms=2000)
    await c.start_all()
    try:
        for gid in c.groups:
            leader = await c.wait_leader(gid, timeout_s=20)
            assert isinstance(leader._ctrl, EngineControl)
        await asyncio.gather(*(
            _apply_retry(c, gid, b"mesh-%s" % gid.encode())
            for gid in c.groups))
        # the sharded tick really ran
        assert all(e.ticks > 0 for e in c.engines.values())
        # convergence across replicas: wait on the equality predicate
        # itself — a retried apply may commit duplicate entries, so
        # "every fsm has >= 1" is not convergence
        def converged():
            for gid in c.groups:
                logs = [c.fsms[(gid, ep)].logs for ep in c.endpoints]
                if not logs[0] or any(lg != logs[0] for lg in logs):
                    return False
            return True

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not converged():
            await asyncio.sleep(0.05)
        assert converged(), {
            (g, str(ep)): len(f.logs) for (g, ep), f in c.fsms.items()}
    finally:
        await c.stop_all()


async def test_engine_scheduled_snapshot_cadence(tmp_path):
    """The reference's 4th timer (snapshotTimer) folded into the device
    tick (VERDICT r3 #4): engine-backed nodes create NO per-group
    RepeatedTimer; the [G] snap_deadline row fires snapshots staggered
    by jitter, so G groups never snapshot as one herd."""
    G = 6
    net = InProcNetwork()
    ep = PeerId.parse("127.0.0.1:6400")
    server = RpcServer(ep.endpoint)
    manager = NodeManager(server)
    net.bind(server)
    transport = InProcTransport(net, ep.endpoint)
    engine = MultiRaftEngine(TickOptions(
        max_groups=G + 2, max_peers=4, tick_interval_ms=5, backend="jax"))
    await engine.start()
    factory = engine.ballot_box_factory()
    nodes, fsms = [], []
    for k in range(G):
        fsm = MockStateMachine()
        opts = NodeOptions(
            election_timeout_ms=300,
            initial_conf=Configuration([ep]),
            fsm=fsm, log_uri="memory://", raft_meta_uri="memory://",
            snapshot_uri=f"file://{tmp_path}/snap_g{k}")
        opts.snapshot.interval_secs = 1
        node = Node(f"g{k}", ep, opts, transport,
                    ballot_box_factory=factory)
        node.node_manager = manager
        manager.add(node)
        assert await node.init()
        nodes.append(node)
        fsms.append(fsm)
    try:
        # NO host snapshot timers on engine-backed nodes
        assert all(n._snapshot_timer is None for n in nodes)
        # the deadline row is jitter-staggered at registration: the
        # spread across groups must cover a meaningful slice of the
        # interval (an unstaggered herd would all share one deadline)
        slots = [n._ctrl.slot for n in nodes]
        dl = engine.snap_deadline[slots]
        assert (dl > 0).all()
        assert dl.max() - dl.min() > 100, dl  # >10% of the 1s interval
        for n in nodes:
            while not n.is_leader():
                await asyncio.sleep(0.02)
        for i, n in enumerate(nodes):
            fut = asyncio.get_running_loop().create_future()
            await n.apply(Task(data=b"x%d" % i,
                               done=lambda st, fut=fut:
                               fut.done() or fut.set_result(st)))
            assert (await asyncio.wait_for(fut, 5)).is_ok()
        # within ~2.5 intervals every group's engine-driven snapshot fired
        # AND landed in the log manager (the FSM counter bumps before the
        # executor's done-path calls log_manager.set_snapshot — polling on
        # the counter alone races the tail of the save pipeline)
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline:
            if (all(f.snapshots_saved >= 1 for f in fsms)
                    and all(n.log_manager.last_snapshot_id().index >= 1
                            for n in nodes)):
                break
            await asyncio.sleep(0.1)
        assert all(f.snapshots_saved >= 1 for f in fsms), \
            [f.snapshots_saved for f in fsms]
        assert all(n.log_manager.last_snapshot_id().index >= 1
                   for n in nodes), \
            [n.log_manager.last_snapshot_id().index for n in nodes]
    finally:
        for n in nodes:
            await n.shutdown()
        await engine.shutdown()
