"""graftcheck (tpuraft.analysis) — analyzer fixture tests + the tier-1
whole-tree gate.

Three layers:
  1. fixture tests: every checker catches its seeded violations in
     tests/fixtures/graftcheck/, honors `# graftcheck: allow` escapes,
     and stays silent on the clean shapes next to them;
  2. the meta-test: the committed wire_schema.lock.json matches the LIVE
     ``_MSG_TYPES`` registry (proves the AST extraction faithful — if
     the two ever disagree, the checker is linting a fiction);
  3. the gate: ``python -m tpuraft.analysis`` over the real tree is
     clean and fast — the same invocation `make lint` runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

from tpuraft.analysis import lanes, lock_order, wire_schema
from tpuraft.analysis.callgraph import ProjectIndex
from tpuraft.analysis.core import load_modules, run_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftcheck")


def _findings(path: str, *more: str, **kw):
    paths = [os.path.join(FIXTURES, p) for p in (path,) + more]
    mods, errs = load_modules(paths)
    assert not errs
    return run_checkers(mods, **kw)


def _lines_with(findings, rule, needle=""):
    return [f for f in findings
            if f.rule == rule and needle in f.message]


# ---- 1. fixture tests -------------------------------------------------------


class TestGuardedBy:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_guarded_by.py")

    def test_catches_unlocked_read_and_write(self, found):
        assert _lines_with(found, "guarded-by", "read in bad_unlocked_read")
        assert _lines_with(found, "guarded-by",
                           "written in bad_unlocked_write")

    def test_writes_mode_allows_reads(self, found):
        assert not _lines_with(found, "guarded-by", "ok_writes_mode_read")

    def test_locked_access_clean(self, found):
        assert not _lines_with(found, "guarded-by", "ok_locked_access")

    def test_waiver_honored(self, found):
        assert not _lines_with(found, "guarded-by", "waived_access")

    def test_closure_resets_held_set(self, found):
        # the `later` closure runs after the with-block exits: its access
        # must be flagged even though it is lexically inside the block
        # (reported against the defining method)
        assert _lines_with(found, "guarded-by",
                           "read in bad_closure_in_with")

    def test_holds_call_site_rule(self, found):
        assert _lines_with(found, "guarded-by",
                           "bad_call_without_lock() calls it without")
        assert not _lines_with(found, "guarded-by", "ok_call_with_lock")

    def test_trailing_annotation_does_not_leak(self, found):
        assert _lines_with(found, "guarded-by", "bad_touch_a")
        assert not _lines_with(found, "guarded-by", "ok_touch_b")

    def test_module_global_closure_reset(self, found):
        # review finding: the module-global checker must reset the held
        # set at closure boundaries exactly like the class checker
        assert _lines_with(found, "guarded-by",
                           "module global _mod_registry")
        assert not any("ok_module_locked" in f.message for f in found)

    def test_loop_confined(self, found):
        assert _lines_with(found, "loop-confined", "bad_thread_primitive")
        assert _lines_with(found, "loop-confined", "bad_sleep")

    def test_loop_confined_multiline_annotation_registers(self, found):
        # regression: the marker on the FIRST line of a wrapped
        # multi-line comment above the class used to be invisible
        # (single-line lookback) — every such annotation in the tree
        # was dead
        assert _lines_with(found, "loop-confined", "bad_sleep_multiline")

    def test_loop_confined_covers_init(self, found):
        # review finding: a confined class's __init__ is not exempt
        assert _lines_with(found, "loop-confined", "__init__")

    def test_loop_confined_decorated_class_annotation_registers(self, found):
        # review catch (graftcheck v2): the block-above walk must anchor
        # at the DECORATOR line, or an annotation above `@dataclass
        # class X` is silently dead
        assert _lines_with(found, "loop-confined", "bad_sleep_decorated")

    def test_expected_totals(self, found):
        # exactly the seeded violations, nothing else.  6 guarded-by:
        # bad_unlocked_read, bad_unlocked_write, bad_closure_in_with,
        # bad_call_without_lock (call-site rule), bad_module_closure,
        # bad_touch_a.  5 loop-confined: Confined.__init__ sleep,
        # bad_thread_primitive, bad_sleep, bad_sleep_multiline,
        # bad_sleep_decorated.
        by_rule = {}
        for f in found:
            by_rule.setdefault(f.rule, []).append(f)
        assert len(by_rule.get("guarded-by", [])) == 6, found
        assert len(by_rule.get("loop-confined", [])) == 5, found


class TestLockOrder:
    def test_cycle_detected(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "lock_order.json")
        found = lock_order.check(mods, record=True, path=lockfile)
        cyc = _lines_with(found, "lock-order", "cycle")
        assert cyc and "Engine._alock" in cyc[0].message \
            and "Engine._block" in cyc[0].message

    def test_call_resolution_edge_recorded(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "lock_order.json")
        lock_order.record(mods, path=lockfile)
        edges = lock_order.load_sanctioned(lockfile)
        assert any(a.endswith("_reg_lock") and b.endswith("Engine._alock")
                   for a, b in edges), edges

    def test_unsanctioned_edge_fails_until_recorded(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "empty.json")
        with open(lockfile, "w") as f:
            json.dump({"edges": []}, f)
        found = lock_order.check(mods, path=lockfile)
        assert _lines_with(found, "lock-order", "unsanctioned lock nesting")


class TestBlockingCalls:
    @pytest.fixture(scope="class")
    def found(self):
        mods, _ = load_modules([FIXTURES])
        from tpuraft.analysis import blocking_calls
        return blocking_calls.check(mods)

    def test_lock_held_contexts(self, found):
        assert _lines_with(found, "blocking-call",
                           "time.sleep() while holding _lock")
        assert _lines_with(found, "blocking-call",
                           "untimed fut.result()")

    def test_timed_result_clean(self, found):
        assert not any("ok_timed_result" in f.message or f.line in
                       _def_lines("seeded_blocking.py",
                                  "ok_timed_result_under_lock")
                       for f in found)

    def test_plain_sync_helper_clean(self, found):
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_sleep_no_context")
                       for f in found)

    def test_coroutine_sleep_flagged_result_not(self, found):
        assert any(f.line in _def_lines("seeded_blocking.py",
                                        "bad_sleep_in_coroutine")
                   for f in found)
        # .result() on a done task in a coroutine is idiomatic asyncio
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_result_of_done_task")
                       for f in found)

    def test_executor_reference_clean(self, found):
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_executor_reference")
                       for f in found)

    def test_lambda_body_not_lock_context(self, found):
        # review finding: run_in_executor(None, lambda: time.sleep(...))
        # under a lock is the sanctioned OFF-loop pattern — clean
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_lambda_off_loop")
                       for f in found)

    def test_async_with_lock_context(self, found):
        # review finding: 'async with <lock>' counts as lock-held — the
        # wedged-waiter class under the asyncio node lock must be caught
        assert _lines_with(found, "blocking-call",
                           "untimed fut.result() (wedged-waiter class: "
                           "pass timeout=) while holding _alock")

    def test_socket_under_lock(self, found):
        assert _lines_with(found, "blocking-call", "server_sock.accept")

    def test_fsm_class_contexts(self, found):
        assert len([f for f in found
                    if "FSM apply path" in f.message]) >= 2

    def test_tick_plane_contexts(self, found):
        ticks = [f for f in found if "tick-plane" in f.message]
        assert len(ticks) == 2 and all("ops" in f.path for f in ticks)


class TestFutureLeaks:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_future_leak.py",
                         rules={"future-leak"})

    def test_straight_line_completion_flagged(self, found):
        assert _lines_with(found, "future-leak",
                           "bad_straight_line_completion")

    def test_never_completed_flagged(self, found):
        assert _lines_with(found, "future-leak", "bad_never_completed")

    def test_annassign_creation_flagged(self, found):
        # review finding: the annotated form (fut: asyncio.Future = ...)
        # must not exempt the rule — the tree uses it (tcp.py)
        assert _lines_with(found, "future-leak",
                           "bad_annotated_straight_line")

    def test_covered_and_escaping_clean(self, found):
        assert len(found) == 3, found  # ONLY the three seeded violations


class TestTransitiveBlocking:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_transitive.py", "seeded_transitive_dep.py",
                         rules={"transitive-blocking"})

    def test_coroutine_chain_flagged_with_full_chain(self, found):
        hits = _lines_with(found, "transitive-blocking", "call to hop()")
        assert any("hop -> sleeper -> time.sleep()" in f.message
                   for f in hits), found

    def test_cross_module_propagation(self, found):
        assert any("seeded_transitive_dep.py" in f.message
                   and "remote_pause" in f.message for f in found), found

    def test_under_lock_call_flagged(self, found):
        assert _lines_with(found, "transitive-blocking",
                           "while holding self._lock")

    def test_fsm_path_reaches_untimed_result(self, found):
        assert _lines_with(found, "transitive-blocking",
                           "on the FSM apply path")

    def test_await_under_sync_lock_flagged(self, found):
        assert _lines_with(found, "transitive-blocking",
                           "awaits while holding sync lock box.state_lock")

    def test_coroutine_result_helper_clean(self, found):
        # the soft coroutine contract carries over: untimed .result()
        # via a helper in a coroutine is the done-task idiom
        assert not any("ok_result_via_helper" in f.message for f in found)

    def test_plain_sync_caller_clean_and_waiver_honored(self, found):
        assert not any("ok_outside_lock" in f.message for f in found)
        assert not any("waived_coro_transitive" in f.message for f in found)

    def test_exact_totals(self, found):
        # coroutine hop, coroutine cross-module, under-lock hop, FSM
        # result, await-under-lock — and nothing else
        assert len(found) == 5, found


class TestLoopAffinity:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_affinity.py", rules={"loop-affinity"})

    def test_direct_executor_target_write(self, found):
        assert _lines_with(found, "loop-affinity",
                           "_bad_refresh() runs off-loop")

    def test_transitive_callee_write(self, found):
        # _outer is the run_in_executor target; _inner inherits off-loop
        assert _lines_with(found, "loop-affinity",
                           "_inner() runs off-loop")

    def test_submit_target_write(self, found):
        assert _lines_with(found, "loop-affinity",
                           "executor.submit() target")

    def test_guarded_field_is_the_sanctioned_channel(self, found):
        # the PR 11/12 flush-timing shape: off-loop writes to a
        # guarded-by field are exactly what the lock is for
        assert not any("_ok_probe" in f.message for f in found)

    def test_transitive_thread_spawn(self, found):
        assert _lines_with(found, "loop-affinity",
                           "spawn_worker() which transitively reaches")

    def test_unconfined_class_free(self, found):
        assert not any("UnconfinedWorkerOwner" in f.message for f in found)

    def test_exact_totals(self, found):
        assert len(found) == 4, found


class TestCalledUnderHolds:
    """The holds() call-site rule, one hop further: cross-object calls
    into holds-annotated methods need the receiver's lock or a
    called-under class declaration."""

    _SRC = '''
import threading


class Owner:
    def __init__(self):
        self._lock = threading.Lock()
        self.term = 0   # guarded-by: _lock

    def _refresh(self):  # graftcheck: holds(_lock)
        self.term += 1


class BadDriver:
    def drive(self, owner):
        owner._refresh()        # VIOLATION: no lock, no declaration


class OkLexicalDriver:
    def drive(self, owner):
        with owner._lock:
            owner._refresh()    # clean: receiver lock held lexically


# graftcheck: called-under(_lock) — fixture: driven from locked paths
class OkDeclaredDriver:
    def drive(self, owner):
        owner._refresh()        # clean: class-level declaration
'''

    def test_cross_object_holds_rule(self, tmp_path):
        p = tmp_path / "holds_fixture.py"
        p.write_text(self._SRC)
        mods, _ = load_modules([str(p)])
        found = [f for f in run_checkers(mods)
                 if "holds annotation" in f.message]
        assert len(found) == 1, found
        assert "BadDriver.drive" in found[0].message
        assert "called-under(_lock)" in found[0].message

    def test_real_node_ctx_convention_is_mechanized(self, tmp_path):
        # the historical prose convention ("every _ConfigurationCtx
        # method runs under the node lock") is now a checked
        # annotation: removing it must surface the ctx's cross-object
        # calls into Node._step_down / _refresh_target_priority
        node_py = os.path.join(REPO, "tpuraft", "core", "node.py")
        with open(node_py) as f:
            src = f.read()
        marker = "# graftcheck: called-under(_lock)"
        assert marker in src
        mutated = src.replace(marker, "# (called-under removed by probe)")
        p = tmp_path / "node_probe.py"
        p.write_text(mutated)
        mods, _ = load_modules([str(p)])
        found = [f for f in run_checkers(mods)
                 if "holds annotation" in f.message]
        assert len(found) == 3, found
        assert all("_ConfigurationCtx" in f.message for f in found)
        # and the live tree is clean (the annotation covers them)
        mods, _ = load_modules([node_py])
        assert [f for f in run_checkers(mods)
                if "holds annotation" in f.message] == []


class TestLaneCoverage:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_lane_site.py", rules={"lane-coverage"})

    def test_missing_free_site(self, found):
        assert _lines_with(found, "lane-coverage",
                           "'bad_free_lane' (declared line 19) is not "
                           "covered at the free site")

    def test_missing_conf_site(self, found):
        assert _lines_with(found, "lane-coverage",
                           "'bad_conf_lane' (declared line 20) is not "
                           "covered at the conf site")

    def test_reasoned_waiver_honored(self, found):
        assert not any("waived_lane" in f.message for f in found)

    def test_reasonless_waiver_flagged(self, found):
        assert _lines_with(found, "lane-coverage",
                           "'bad_waiver_lane': waiver carries no "
                           "justification")

    def test_unknown_site_token_flagged(self, found):
        assert _lines_with(found, "lane-coverage",
                           "unknown waiver site 'no-grift'")

    def test_call_resolution_covers_release_helper(self, found):
        # bad_waiver_lane is reset through self._reset_extra(slot):
        # one level of intra-class call resolution must count it
        assert not any("bad_waiver_lane" in f.message
                       and "free site" in f.message for f in found)

    def test_p_shaped_row_is_not_a_lane(self, found):
        assert not any("not_a_lane" in f.message for f in found)

    def test_exact_totals(self, found):
        assert len(found) == 4, found


class TestLaneProbeHistorical:
    """Satellite: the PR 10 review-catch class, mechanized — reintroduce
    the historical tick_q_ack wiring minus its set_conf invalidation
    and the lane lint must report exactly that site."""

    ENGINE = os.path.join(REPO, "tpuraft", "core", "engine.py")
    INVALIDATION = "self.tick_q_ack[slot] = _NEG_I32"

    def _lane_findings(self, path):
        mods, errs = load_modules([path])
        assert not errs
        found = lanes.check(mods, ProjectIndex(mods))
        return [f for f in found if f.rule == "lane-coverage"]

    def test_live_engine_lane_contract_clean(self):
        assert self._lane_findings(self.ENGINE) == []

    def test_missing_set_conf_invalidation_reported_exactly(self, tmp_path):
        with open(self.ENGINE) as f:
            src = f.read()
        assert src.count(self.INVALIDATION) == 1, \
            "set_conf invalidation line moved — update the probe"
        p = tmp_path / "engine_probe.py"
        p.write_text(src.replace(
            self.INVALIDATION, "pass  # probe: invalidation omitted"))
        found = self._lane_findings(str(p))
        assert len(found) == 1, found
        assert "tick_q_ack" in found[0].message
        assert "conf site" in found[0].message


class TestStateParity:
    _DRIFTED = '''
from dataclasses import dataclass

import jax.numpy as jnp


@dataclass
class TickOutputs:
    commit_rel: jnp.ndarray
    q_ack: jnp.ndarray


class _NpOutputs:
    __slots__ = ("commit_rel",)


def ok_build():
    return TickOutputs(commit_rel=1, q_ack=2)


def bad_build():
    return TickOutputs(commit_rel=1)
'''

    def test_twin_and_construction_drift_caught(self, tmp_path):
        p = tmp_path / "parity_fixture.py"
        p.write_text(self._DRIFTED)
        mods, _ = load_modules([str(p)])
        found = lanes.check(mods, ProjectIndex(mods))
        msgs = "\n".join(f.message for f in found)
        assert "_NpOutputs.__slots__ drifted" in msgs and "q_ack" in msgs
        assert "construction misses lane field(s) ['q_ack']" in msgs
        assert len(found) == 2, found

    def test_real_device_plane_parity_clean(self):
        paths = [os.path.join(REPO, "tpuraft", p) for p in
                 (os.path.join("ops", "tick.py"),
                  os.path.join("core", "engine.py"),
                  os.path.join("parallel", "mesh.py"))]
        mods, _ = load_modules(paths)
        found = lanes.check(mods, ProjectIndex(mods))
        assert [f for f in found if "lane field" in f.message
                or "drifted" in f.message] == []


class TestHostSync:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings(os.path.join("ops", "seeded_host_sync.py"),
                         rules={"host-sync"})

    def test_item_asarray_int_flagged(self, found):
        msgs = "\n".join(f.message for f in found)
        assert ".item() in a jitted body" in msgs
        assert "np.asarray() in a jitted body" in msgs
        assert "int() of traced value" in msgs

    def test_data_dependent_branching_flagged(self, found):
        msgs = "\n".join(f.message for f in found)
        assert "Python `if` on a traced value" in msgs
        assert "Python `while` on a traced value" in msgs

    def test_static_argname_branch_clean(self, found):
        # `if flavor == "x"` — flavor is in static_argnames
        assert len([f for f in found if "`if`" in f.message]) == 1, found

    def test_reached_through_root_transitively(self, found):
        assert any("helper_sync" in f.message
                   and "float() of traced value" in f.message
                   for f in found), found

    def test_host_probe_outside_jit_clean(self, found):
        assert not any("ok_host_probe" in f.message for f in found)

    def test_exact_totals(self, found):
        assert len(found) == 6, found


class TestDonatedRead:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_donated_read.py", rules={"donated-read"})

    def test_read_after_donation_flagged(self, found):
        assert len(found) == 1, found
        f = found[0]
        assert "bad_read_after_donate" in f.message
        assert "step_donating" in f.message

    def test_rebind_and_no_read_clean(self, found):
        assert not any("ok_rebind" in f.message
                       or "ok_no_later_read" in f.message for f in found)


class TestJsonOutput:
    def test_json_findings_shape(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpuraft.analysis", "--json",
             os.path.join("tests", "fixtures", "graftcheck",
                          "seeded_lane_site.py")],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1   # findings present
        rows = json.loads(proc.stdout)
        assert len(rows) == 4
        for row in rows:
            assert set(row) == {"file", "line", "rule", "message"}
            assert row["rule"] == "lane-coverage"
            assert row["file"].endswith("seeded_lane_site.py")
            assert isinstance(row["line"], int) and row["line"] > 0

    def test_json_clean_tree_is_empty_array(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("X = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tpuraft.analysis", "--json", str(p)],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0
        assert json.loads(proc.stdout) == []


def _def_lines(fixture: str, fn_name: str) -> range:
    import ast
    with open(os.path.join(FIXTURES, fixture)) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            return range(node.lineno, node.end_lineno + 1)
    raise AssertionError(f"{fn_name} not in {fixture}")


# ---- wire-schema drift (fixture pair: v1 recorded, v2 drifted) --------------


_WIRE_V1 = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    name: str = ""

@dataclass
class Pong:
    term: int

register_message(200, Ping)
register_message(201, Pong)
'''

_WIRE_V2_BREAKING = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    epoch: int          # INSERTED mid-struct: wire-breaking
    name: str = ""

@dataclass
class Pong:
    term: int
    extra: bytes        # new TRAILING field but NO default: breaking

register_message(200, Ping)
register_message(201, Pong)
'''

_WIRE_V2_COMPAT = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    name: str = ""
    lease_ms: int = 0   # trailing + defaulted: compatible, needs --record

@dataclass
class Pong:
    term: int

register_message(200, Ping)
register_message(201, Pong)
'''


class TestWireSchema:
    def _mods(self, tmp_path, src):
        p = tmp_path / "wire_fixture.py"
        p.write_text(src)
        mods, _ = load_modules([str(p)])
        return mods

    def test_clean_when_recorded(self, tmp_path):
        mods = self._mods(tmp_path, _WIRE_V1)
        lockfile = str(tmp_path / "wire.lock.json")
        assert wire_schema.check(mods, record=True, path=lockfile) == []
        assert wire_schema.check(mods, path=lockfile) == []

    def test_breaking_drift_caught(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        found = wire_schema.check(self._mods(tmp_path, _WIRE_V2_BREAKING),
                                  path=lockfile)
        msgs = "\n".join(f.message for f in found)
        assert "insertion/reorder" in msgs         # Ping.epoch mid-struct
        assert "no default" in msgs                # Pong.extra trailing

    def test_compatible_extension_requires_record(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        found = wire_schema.check(self._mods(tmp_path, _WIRE_V2_COMPAT),
                                  path=lockfile)
        assert len(found) == 1 and "compatible extension" in found[0].message
        # --record clears it
        mods = self._mods(tmp_path, _WIRE_V2_COMPAT)
        assert wire_schema.check(mods, record=True, path=lockfile) == []

    def test_removal_caught(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        only_ping = _WIRE_V1.replace("register_message(201, Pong)", "")
        found = wire_schema.check(self._mods(tmp_path, only_ping),
                                  path=lockfile)
        assert any("removed" in f.message for f in found)

    def test_new_tid_requires_record(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        plus = _WIRE_V1 + (
            "\n@dataclass\nclass Probe:\n    n: int = 0\n\n"
            "register_message(202, Probe)\n")
        found = wire_schema.check(self._mods(tmp_path, plus), path=lockfile)
        assert any("new message type 202" in f.message for f in found)


class TestWaiverSelfBypass:
    def test_allow_waiver_cannot_silence_reasonless_waivers(self, tmp_path):
        # review finding: 'allow(waiver)' must not suppress the
        # reasonless-waiver finding it annotates
        p = tmp_path / "sneaky.py"
        p.write_text(
            "# graftcheck: allow(waiver)\n"
            "def f():\n"
            "    return 1  # graftcheck: allow(guarded-by)\n")
        mods, _ = load_modules([str(p)])
        found = run_checkers(mods)
        assert any(f.rule == "waiver" and "no justification" in f.message
                   for f in found), found


class TestSubsetRuns:
    def test_targeted_lint_does_not_report_phantom_removals(self):
        # review finding: linting a path that registers no messages must
        # not diff the full lockfile as 56 'removed' findings
        mods, _ = load_modules(
            [os.path.join(REPO, "tpuraft", "core", "ballot_box.py")])
        found = wire_schema.check(mods)
        assert found == [], found


# ---- 2. the meta-test: committed lockfile == live registry ------------------


class TestCommittedSchemaMatchesLiveRegistry:
    @pytest.fixture(scope="class")
    def live(self):
        # importing these populates the full registry
        import tpuraft.rheakv.kv_service      # noqa: F401
        import tpuraft.rheakv.pd_messages     # noqa: F401
        import tpuraft.rpc.cli_messages       # noqa: F401
        from tpuraft.rpc.messages import _MSG_TYPES
        # the lint gate covers tpuraft/ — example/test code (e.g.
        # examples/counter.py, imported by pytest collection) may
        # register demo types that the committed schema rightly omits
        return {tid: cls for tid, cls in _MSG_TYPES.items()
                if cls.__module__.startswith("tpuraft.")}

    @pytest.fixture(scope="class")
    def lock(self):
        lock = wire_schema.load_lock()
        assert lock is not None, "wire_schema.lock.json missing — run " \
            "`python -m tpuraft.analysis --record`"
        return lock

    def test_same_tids(self, live, lock):
        assert set(live) == set(lock)

    def test_same_classes_and_fields(self, live, lock):
        for tid, cls in live.items():
            entry = lock[tid]
            assert entry["cls"] == cls.__name__, tid
            live_fields = dataclasses.fields(cls)
            locked = entry["fields"]
            assert [f.name for f in live_fields] \
                == [f["name"] for f in locked], cls
            for lf, kf in zip(live_fields, locked):
                has_default = (lf.default is not dataclasses.MISSING
                               or lf.default_factory is not dataclasses.MISSING)
                assert has_default == (kf["default"] is not None), \
                    f"{cls.__name__}.{lf.name}: default presence drifted"

    def test_trailing_default_invariant_holds_live(self, live):
        # the decode contract itself: once a field has a default, every
        # LATER field must too (otherwise decode's trailing-fill breaks)
        for tid, cls in live.items():
            seen_default = False
            for f in dataclasses.fields(cls):
                has = (f.default is not dataclasses.MISSING
                       or f.default_factory is not dataclasses.MISSING)
                assert not (seen_default and not has), \
                    f"{cls.__name__}.{f.name} (tid {tid}): non-default " \
                    f"field after a defaulted one"
                seen_default = seen_default or has


# ---- 3. the whole-tree gate -------------------------------------------------


class TestTreeGate:
    def test_tree_is_clean_and_fast(self):
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "tpuraft.analysis"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        dt = time.monotonic() - t0
        assert proc.returncode == 0, \
            f"graftcheck found violations:\n{proc.stdout}"
        # the ~10s lint budget (ISSUE 7); generous headroom for slow CI
        assert dt < 30, f"lint took {dt:.1f}s"

    def test_lock_order_file_current(self):
        mods, _ = load_modules([os.path.join(REPO, "tpuraft")])
        graph = lock_order.derive_graph(mods)
        sanctioned = lock_order.load_sanctioned()
        assert set(graph) <= sanctioned, \
            "lock_order.json stale — review + `python -m tpuraft.analysis" \
            " --record`"

    def test_every_waiver_has_a_reason(self):
        mods, _ = load_modules([os.path.join(REPO, "tpuraft")])
        for m in mods:
            for w in m.waivers:
                assert w.reason, f"{m.rel}:{w.line}: allow({w.rule}) " \
                    f"without justification"


# ---- 4. raw-clock (ISSUE 18: injectable time plane) -------------------------


class TestRawClock:
    """Direct real-clock reads inside the clock-disciplined tree are
    findings; waivers (including multi-line comment blocks) and
    perf_counter are exempt; out-of-scope files are never flagged."""

    _SRC = '''import time


class Consumer:
    def bad_monotonic(self):
        return time.monotonic()          # finding

    def bad_wall(self):
        return time.time()               # finding

    def bad_loop(self, loop):
        return loop.time()               # finding

    def fine_perf(self):
        return time.perf_counter()       # exempt: trace-only timing

    def waived_inline(self):
        # graftcheck: allow(raw-clock) — fixture: real-time by design
        return time.monotonic()

    def waived_block(self):
        # graftcheck: allow(raw-clock) — fixture: a wrapped multi-line
        # justification whose marker sits on the FIRST comment line
        return time.monotonic()
'''

    def _check(self, rel):
        from tpuraft.analysis import raw_clock
        from tpuraft.analysis.core import Module

        mod = Module("/dev/null", rel, self._SRC)
        return raw_clock.check([mod])

    def test_in_scope_raw_reads_are_findings(self):
        found = self._check("tpuraft/core/fixture_probe.py")
        msgs = [(f.rule, f.message) for f in found]
        assert len(found) == 3, msgs
        assert all(f.rule == "raw-clock" for f in found)
        assert any("time.monotonic" in f.message for f in found)
        assert any("time.time" in f.message for f in found)
        assert any("loop.time" in f.message for f in found)

    def test_rheakv_and_health_are_in_scope(self):
        assert self._check("tpuraft/rheakv/fixture_probe.py")
        assert self._check("tpuraft/util/health.py")

    def test_out_of_scope_is_clean(self):
        assert self._check("tpuraft/util/trace.py") == []
        assert self._check("examples/soak.py") == []

    def test_tree_baseline_is_zero(self):
        mods, _ = load_modules([os.path.join(REPO, "tpuraft")])
        found = [f for f in run_checkers(mods, rules={"raw-clock"})]
        assert found == [], [str(f) for f in found]
