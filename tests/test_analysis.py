"""graftcheck (tpuraft.analysis) — analyzer fixture tests + the tier-1
whole-tree gate.

Three layers:
  1. fixture tests: every checker catches its seeded violations in
     tests/fixtures/graftcheck/, honors `# graftcheck: allow` escapes,
     and stays silent on the clean shapes next to them;
  2. the meta-test: the committed wire_schema.lock.json matches the LIVE
     ``_MSG_TYPES`` registry (proves the AST extraction faithful — if
     the two ever disagree, the checker is linting a fiction);
  3. the gate: ``python -m tpuraft.analysis`` over the real tree is
     clean and fast — the same invocation `make lint` runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

from tpuraft.analysis import lock_order, wire_schema
from tpuraft.analysis.core import load_modules, run_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftcheck")


def _findings(path: str, **kw):
    mods, errs = load_modules([os.path.join(FIXTURES, path)])
    assert not errs
    return run_checkers(mods, **kw)


def _lines_with(findings, rule, needle=""):
    return [f for f in findings
            if f.rule == rule and needle in f.message]


# ---- 1. fixture tests -------------------------------------------------------


class TestGuardedBy:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_guarded_by.py")

    def test_catches_unlocked_read_and_write(self, found):
        assert _lines_with(found, "guarded-by", "read in bad_unlocked_read")
        assert _lines_with(found, "guarded-by",
                           "written in bad_unlocked_write")

    def test_writes_mode_allows_reads(self, found):
        assert not _lines_with(found, "guarded-by", "ok_writes_mode_read")

    def test_locked_access_clean(self, found):
        assert not _lines_with(found, "guarded-by", "ok_locked_access")

    def test_waiver_honored(self, found):
        assert not _lines_with(found, "guarded-by", "waived_access")

    def test_closure_resets_held_set(self, found):
        # the `later` closure runs after the with-block exits: its access
        # must be flagged even though it is lexically inside the block
        # (reported against the defining method)
        assert _lines_with(found, "guarded-by",
                           "read in bad_closure_in_with")

    def test_holds_call_site_rule(self, found):
        assert _lines_with(found, "guarded-by",
                           "bad_call_without_lock() calls it without")
        assert not _lines_with(found, "guarded-by", "ok_call_with_lock")

    def test_trailing_annotation_does_not_leak(self, found):
        assert _lines_with(found, "guarded-by", "bad_touch_a")
        assert not _lines_with(found, "guarded-by", "ok_touch_b")

    def test_module_global_closure_reset(self, found):
        # review finding: the module-global checker must reset the held
        # set at closure boundaries exactly like the class checker
        assert _lines_with(found, "guarded-by",
                           "module global _mod_registry")
        assert not any("ok_module_locked" in f.message for f in found)

    def test_loop_confined(self, found):
        assert _lines_with(found, "loop-confined", "bad_thread_primitive")
        assert _lines_with(found, "loop-confined", "bad_sleep")

    def test_loop_confined_multiline_annotation_registers(self, found):
        # regression: the marker on the FIRST line of a wrapped
        # multi-line comment above the class used to be invisible
        # (single-line lookback) — every such annotation in the tree
        # was dead
        assert _lines_with(found, "loop-confined", "bad_sleep_multiline")

    def test_loop_confined_covers_init(self, found):
        # review finding: a confined class's __init__ is not exempt
        assert _lines_with(found, "loop-confined", "__init__")

    def test_expected_totals(self, found):
        # exactly the seeded violations, nothing else.  6 guarded-by:
        # bad_unlocked_read, bad_unlocked_write, bad_closure_in_with,
        # bad_call_without_lock (call-site rule), bad_module_closure,
        # bad_touch_a.  4 loop-confined: Confined.__init__ sleep,
        # bad_thread_primitive, bad_sleep, bad_sleep_multiline.
        by_rule = {}
        for f in found:
            by_rule.setdefault(f.rule, []).append(f)
        assert len(by_rule.get("guarded-by", [])) == 6, found
        assert len(by_rule.get("loop-confined", [])) == 4, found


class TestLockOrder:
    def test_cycle_detected(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "lock_order.json")
        found = lock_order.check(mods, record=True, path=lockfile)
        cyc = _lines_with(found, "lock-order", "cycle")
        assert cyc and "Engine._alock" in cyc[0].message \
            and "Engine._block" in cyc[0].message

    def test_call_resolution_edge_recorded(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "lock_order.json")
        lock_order.record(mods, path=lockfile)
        edges = lock_order.load_sanctioned(lockfile)
        assert any(a.endswith("_reg_lock") and b.endswith("Engine._alock")
                   for a, b in edges), edges

    def test_unsanctioned_edge_fails_until_recorded(self, tmp_path):
        mods, _ = load_modules([os.path.join(FIXTURES,
                                             "seeded_lock_order.py")])
        lockfile = str(tmp_path / "empty.json")
        with open(lockfile, "w") as f:
            json.dump({"edges": []}, f)
        found = lock_order.check(mods, path=lockfile)
        assert _lines_with(found, "lock-order", "unsanctioned lock nesting")


class TestBlockingCalls:
    @pytest.fixture(scope="class")
    def found(self):
        mods, _ = load_modules([FIXTURES])
        from tpuraft.analysis import blocking_calls
        return blocking_calls.check(mods)

    def test_lock_held_contexts(self, found):
        assert _lines_with(found, "blocking-call",
                           "time.sleep() while holding _lock")
        assert _lines_with(found, "blocking-call",
                           "untimed fut.result()")

    def test_timed_result_clean(self, found):
        assert not any("ok_timed_result" in f.message or f.line in
                       _def_lines("seeded_blocking.py",
                                  "ok_timed_result_under_lock")
                       for f in found)

    def test_plain_sync_helper_clean(self, found):
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_sleep_no_context")
                       for f in found)

    def test_coroutine_sleep_flagged_result_not(self, found):
        assert any(f.line in _def_lines("seeded_blocking.py",
                                        "bad_sleep_in_coroutine")
                   for f in found)
        # .result() on a done task in a coroutine is idiomatic asyncio
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_result_of_done_task")
                       for f in found)

    def test_executor_reference_clean(self, found):
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_executor_reference")
                       for f in found)

    def test_lambda_body_not_lock_context(self, found):
        # review finding: run_in_executor(None, lambda: time.sleep(...))
        # under a lock is the sanctioned OFF-loop pattern — clean
        assert not any(f.line in _def_lines("seeded_blocking.py",
                                            "ok_lambda_off_loop")
                       for f in found)

    def test_async_with_lock_context(self, found):
        # review finding: 'async with <lock>' counts as lock-held — the
        # wedged-waiter class under the asyncio node lock must be caught
        assert _lines_with(found, "blocking-call",
                           "untimed fut.result() (wedged-waiter class: "
                           "pass timeout=) while holding _alock")

    def test_socket_under_lock(self, found):
        assert _lines_with(found, "blocking-call", "server_sock.accept")

    def test_fsm_class_contexts(self, found):
        assert len([f for f in found
                    if "FSM apply path" in f.message]) >= 2

    def test_tick_plane_contexts(self, found):
        ticks = [f for f in found if "tick-plane" in f.message]
        assert len(ticks) == 2 and all("ops" in f.path for f in ticks)


class TestFutureLeaks:
    @pytest.fixture(scope="class")
    def found(self):
        return _findings("seeded_future_leak.py",
                         rules={"future-leak"})

    def test_straight_line_completion_flagged(self, found):
        assert _lines_with(found, "future-leak",
                           "bad_straight_line_completion")

    def test_never_completed_flagged(self, found):
        assert _lines_with(found, "future-leak", "bad_never_completed")

    def test_annassign_creation_flagged(self, found):
        # review finding: the annotated form (fut: asyncio.Future = ...)
        # must not exempt the rule — the tree uses it (tcp.py)
        assert _lines_with(found, "future-leak",
                           "bad_annotated_straight_line")

    def test_covered_and_escaping_clean(self, found):
        assert len(found) == 3, found  # ONLY the three seeded violations


def _def_lines(fixture: str, fn_name: str) -> range:
    import ast
    with open(os.path.join(FIXTURES, fixture)) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            return range(node.lineno, node.end_lineno + 1)
    raise AssertionError(f"{fn_name} not in {fixture}")


# ---- wire-schema drift (fixture pair: v1 recorded, v2 drifted) --------------


_WIRE_V1 = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    name: str = ""

@dataclass
class Pong:
    term: int

register_message(200, Ping)
register_message(201, Pong)
'''

_WIRE_V2_BREAKING = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    epoch: int          # INSERTED mid-struct: wire-breaking
    name: str = ""

@dataclass
class Pong:
    term: int
    extra: bytes        # new TRAILING field but NO default: breaking

register_message(200, Ping)
register_message(201, Pong)
'''

_WIRE_V2_COMPAT = '''
from dataclasses import dataclass, field
from tpuraft.rpc.messages import register_message

@dataclass
class Ping:
    term: int
    name: str = ""
    lease_ms: int = 0   # trailing + defaulted: compatible, needs --record

@dataclass
class Pong:
    term: int

register_message(200, Ping)
register_message(201, Pong)
'''


class TestWireSchema:
    def _mods(self, tmp_path, src):
        p = tmp_path / "wire_fixture.py"
        p.write_text(src)
        mods, _ = load_modules([str(p)])
        return mods

    def test_clean_when_recorded(self, tmp_path):
        mods = self._mods(tmp_path, _WIRE_V1)
        lockfile = str(tmp_path / "wire.lock.json")
        assert wire_schema.check(mods, record=True, path=lockfile) == []
        assert wire_schema.check(mods, path=lockfile) == []

    def test_breaking_drift_caught(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        found = wire_schema.check(self._mods(tmp_path, _WIRE_V2_BREAKING),
                                  path=lockfile)
        msgs = "\n".join(f.message for f in found)
        assert "insertion/reorder" in msgs         # Ping.epoch mid-struct
        assert "no default" in msgs                # Pong.extra trailing

    def test_compatible_extension_requires_record(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        found = wire_schema.check(self._mods(tmp_path, _WIRE_V2_COMPAT),
                                  path=lockfile)
        assert len(found) == 1 and "compatible extension" in found[0].message
        # --record clears it
        mods = self._mods(tmp_path, _WIRE_V2_COMPAT)
        assert wire_schema.check(mods, record=True, path=lockfile) == []

    def test_removal_caught(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        only_ping = _WIRE_V1.replace("register_message(201, Pong)", "")
        found = wire_schema.check(self._mods(tmp_path, only_ping),
                                  path=lockfile)
        assert any("removed" in f.message for f in found)

    def test_new_tid_requires_record(self, tmp_path):
        lockfile = str(tmp_path / "wire.lock.json")
        wire_schema.record(self._mods(tmp_path, _WIRE_V1), path=lockfile)
        plus = _WIRE_V1 + (
            "\n@dataclass\nclass Probe:\n    n: int = 0\n\n"
            "register_message(202, Probe)\n")
        found = wire_schema.check(self._mods(tmp_path, plus), path=lockfile)
        assert any("new message type 202" in f.message for f in found)


class TestWaiverSelfBypass:
    def test_allow_waiver_cannot_silence_reasonless_waivers(self, tmp_path):
        # review finding: 'allow(waiver)' must not suppress the
        # reasonless-waiver finding it annotates
        p = tmp_path / "sneaky.py"
        p.write_text(
            "# graftcheck: allow(waiver)\n"
            "def f():\n"
            "    return 1  # graftcheck: allow(guarded-by)\n")
        mods, _ = load_modules([str(p)])
        found = run_checkers(mods)
        assert any(f.rule == "waiver" and "no justification" in f.message
                   for f in found), found


class TestSubsetRuns:
    def test_targeted_lint_does_not_report_phantom_removals(self):
        # review finding: linting a path that registers no messages must
        # not diff the full lockfile as 56 'removed' findings
        mods, _ = load_modules(
            [os.path.join(REPO, "tpuraft", "core", "ballot_box.py")])
        found = wire_schema.check(mods)
        assert found == [], found


# ---- 2. the meta-test: committed lockfile == live registry ------------------


class TestCommittedSchemaMatchesLiveRegistry:
    @pytest.fixture(scope="class")
    def live(self):
        # importing these populates the full registry
        import tpuraft.rheakv.kv_service      # noqa: F401
        import tpuraft.rheakv.pd_messages     # noqa: F401
        import tpuraft.rpc.cli_messages       # noqa: F401
        from tpuraft.rpc.messages import _MSG_TYPES
        # the lint gate covers tpuraft/ — example/test code (e.g.
        # examples/counter.py, imported by pytest collection) may
        # register demo types that the committed schema rightly omits
        return {tid: cls for tid, cls in _MSG_TYPES.items()
                if cls.__module__.startswith("tpuraft.")}

    @pytest.fixture(scope="class")
    def lock(self):
        lock = wire_schema.load_lock()
        assert lock is not None, "wire_schema.lock.json missing — run " \
            "`python -m tpuraft.analysis --record`"
        return lock

    def test_same_tids(self, live, lock):
        assert set(live) == set(lock)

    def test_same_classes_and_fields(self, live, lock):
        for tid, cls in live.items():
            entry = lock[tid]
            assert entry["cls"] == cls.__name__, tid
            live_fields = dataclasses.fields(cls)
            locked = entry["fields"]
            assert [f.name for f in live_fields] \
                == [f["name"] for f in locked], cls
            for lf, kf in zip(live_fields, locked):
                has_default = (lf.default is not dataclasses.MISSING
                               or lf.default_factory is not dataclasses.MISSING)
                assert has_default == (kf["default"] is not None), \
                    f"{cls.__name__}.{lf.name}: default presence drifted"

    def test_trailing_default_invariant_holds_live(self, live):
        # the decode contract itself: once a field has a default, every
        # LATER field must too (otherwise decode's trailing-fill breaks)
        for tid, cls in live.items():
            seen_default = False
            for f in dataclasses.fields(cls):
                has = (f.default is not dataclasses.MISSING
                       or f.default_factory is not dataclasses.MISSING)
                assert not (seen_default and not has), \
                    f"{cls.__name__}.{f.name} (tid {tid}): non-default " \
                    f"field after a defaulted one"
                seen_default = seen_default or has


# ---- 3. the whole-tree gate -------------------------------------------------


class TestTreeGate:
    def test_tree_is_clean_and_fast(self):
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "tpuraft.analysis"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        dt = time.monotonic() - t0
        assert proc.returncode == 0, \
            f"graftcheck found violations:\n{proc.stdout}"
        # the ~10s lint budget (ISSUE 7); generous headroom for slow CI
        assert dt < 30, f"lint took {dt:.1f}s"

    def test_lock_order_file_current(self):
        mods, _ = load_modules([os.path.join(REPO, "tpuraft")])
        graph = lock_order.derive_graph(mods)
        sanctioned = lock_order.load_sanctioned()
        assert set(graph) <= sanctioned, \
            "lock_order.json stale — review + `python -m tpuraft.analysis" \
            " --record`"

    def test_every_waiver_has_a_reason(self):
        mods, _ = load_modules([os.path.join(REPO, "tpuraft")])
        for m in mods:
            for w in m.waivers:
                assert w.reason, f"{m.rel}:{w.line}: allow({w.rule}) " \
                    f"without justification"
