"""Multi-process PD-backed RheaKV: a standalone placement-driver OS
process + 3 store OS processes heartbeating to it, a PD-routed client,
and a PD-ordered auto-split — all over real TCP.

The deepest deployment shape (reference: PlacementDriverServer + stores
+ RemotePlacementDriverClient on separate machines — SURVEY.md §3.2).
"""

import asyncio
import os
import socket
import struct
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.asyncio
async def test_pd_backed_multiprocess_cluster_with_auto_split(tmp_path):
    ports = _free_ports(4)
    pd_ep = f"127.0.0.1:{ports[0]}"
    stores = [f"127.0.0.1:{p}" for p in ports[1:]]
    env = dict(os.environ, PYTHONPATH=REPO)
    procs: list[subprocess.Popen] = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "examples.pd_server",
             "--serve", pd_ep, "--pd", pd_ep,
             "--data", str(tmp_path / "pd"),
             "--split-keys", "48"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        for ep in stores:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "examples.rheakv_server",
                 "--serve", ep, "--stores", ",".join(stores),
                 "--regions", "1", "--data",
                 str(tmp_path / ep.replace(":", "_")),
                 "--pd", pd_ep],
                cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

        from tpuraft.rheakv.client import RheaKVStore
        from tpuraft.rheakv.pd_client import RemotePlacementDriverClient
        from tpuraft.rpc.tcp import TcpTransport

        transport = TcpTransport()
        pd = RemotePlacementDriverClient(transport, [pd_ep])
        kv = RheaKVStore(pd, transport, timeout_ms=3000)
        await kv.start()
        try:
            # ride out interpreter boots + elections; the client routes
            # through the PD, which learns regions from store heartbeats
            deadline = time.monotonic() + 90
            ok = False
            while time.monotonic() < deadline:
                try:
                    ok = await kv.put(struct.pack(">I", 1), b"boot")
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert ok, "PD-routed cluster never became writable"

            # load enough keys to cross the PD's split threshold
            for i in range(2, 202):
                k = struct.pack(">I", (i * 2654435761) & 0xFFFFFFFF)
                for _ in range(10):
                    try:
                        assert await kv.put(k, b"v%d" % i)
                        break
                    except Exception:
                        await asyncio.sleep(0.3)

            # the PD orders a RANGE_SPLIT; the store splits; the PD
            # learns the new region from subsequent heartbeats
            deadline = time.monotonic() + 60
            n_regions = 1
            while time.monotonic() < deadline:
                try:
                    regions = await pd.list_regions()
                    n_regions = len(regions)
                    if n_regions >= 2:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.5)
            assert n_regions >= 2, "PD never ordered/learned the split"

            # data still fully served after the split, via PD routing
            misses = 0
            for i in range(2, 202):
                k = struct.pack(">I", (i * 2654435761) & 0xFFFFFFFF)
                got = None
                for _ in range(10):
                    try:
                        got = await kv.get(k)
                        break
                    except Exception:
                        await asyncio.sleep(0.3)
                if got != b"v%d" % i:
                    misses += 1
            assert misses == 0, f"{misses} keys unreadable after split"
        finally:
            await kv.shutdown()
            await transport.close()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for proc in procs:
            proc.wait()
