"""Placement driver tests: metadata, heartbeats, failover, auto-split.

Reference parity tier: PD server tests + chaos-style region scheduling
(SURVEY.md §3.2 "PD server", §5 "RheaKV integration").
"""

import asyncio
import contextlib
import time

from tpuraft.rheakv.metadata import Region
from tests.kv_cluster import PDTestCluster
from tpuraft.rheakv.client import RheaKVStore


@contextlib.asynccontextmanager
async def pd_cluster(**kw):
    c = PDTestCluster(**kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


async def test_legacy_batch_fallback_decomposes_and_requests_full():
    """The legacy (pre-batch / PD-less) store_heartbeat_batch fallback
    must decompose deltas into per-region heartbeats AND answer
    need_full=True: a legacy PD runs its split/balance policy off the
    per-region reports and cannot request a resync, so delta-only
    reporting would starve it and a failed-over legacy PD leader would
    stay cold forever.  need_full=True makes every store round carry
    every led region — exactly the pre-batch wire behavior."""
    from tpuraft.rheakv.metadata import StoreMeta
    from tpuraft.rheakv.pd_client import PlacementDriverClient

    class Recorder(PlacementDriverClient):
        def __init__(self):
            self.store_rounds = []
            self.region_reports = []

        async def store_heartbeat(self, meta):
            self.store_rounds.append([r.id for r in meta.regions])

        async def region_heartbeat(self, region, leader, metrics=None):
            self.region_reports.append(
                (region.id, leader, (metrics or {}).get("approximate_keys")))
            return [("split-order", region.id)]

    pd = Recorder()
    regions = [Region(id=i, start_key=bytes([i]), end_key=bytes([i + 1]))
               for i in (1, 2, 3)]
    meta = StoreMeta(id=7, endpoint="127.0.0.1:9001", regions=[])
    instructions, need_full = await pd.store_heartbeat_batch(
        meta, [(r, "127.0.0.1:9001", 10 * r.id) for r in regions])
    assert need_full, "legacy fallback must force full rounds"
    assert pd.store_rounds == [[1, 2, 3]]
    assert pd.region_reports == [(1, "127.0.0.1:9001", 10),
                                 (2, "127.0.0.1:9001", 20),
                                 (3, "127.0.0.1:9001", 30)]
    # per-region instructions surface through the batched return
    assert instructions == [("split-order", 1), ("split-order", 2),
                            ("split-order", 3)]


async def test_pd_tracks_stores_and_regions():
    async with pd_cluster() as c:
        await c.wait_pd_leader()
        pd = c.pd_client()
        # heartbeats flow on a 100ms cadence; PD learns the layout
        deadline = time.monotonic() + 5
        stores, regions = [], []
        while time.monotonic() < deadline:
            stores = await pd.get_store_metas()
            regions = await pd.list_regions()
            if len(stores) == 3 and len(regions) >= 1:
                break
            await asyncio.sleep(0.1)
        assert len(stores) == 3
        assert {s.endpoint for s in stores} == set(c.endpoints)
        assert any(r.id == 1 for r in regions)


async def test_pd_region_id_allocation():
    async with pd_cluster() as c:
        await c.wait_pd_leader()
        from tpuraft.rheakv.pd_messages import CreateRegionIdRequest

        pd = c.pd_client()
        r1 = await pd._call("pd_create_region_id", CreateRegionIdRequest())
        r2 = await pd._call("pd_create_region_id", CreateRegionIdRequest())
        assert r2.region_id == r1.region_id + 1 >= 1024


async def test_pd_leader_failover():
    async with pd_cluster() as c:
        leader = await c.wait_pd_leader()
        pd = c.pd_client()
        assert await pd.list_regions() is not None
        await c.stop_pd(leader.server_id.endpoint)
        await c.wait_pd_leader()
        # client redirects to the new PD leader
        regions = await pd.list_regions()
        assert any(r.id == 1 for r in regions)
        # store heartbeats also recover; metadata keeps flowing
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(await pd.get_store_metas()) == 3:
                break
            await asyncio.sleep(0.1)
        assert len(await pd.get_store_metas()) == 3


async def test_pd_ordered_auto_split():
    """Write past the threshold; the PD orders a split on heartbeat."""
    async with pd_cluster(split_threshold_keys=24) as c:
        await c.wait_pd_leader()
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        for i in range(40):
            await rs.put(b"auto%03d" % i, b"v")
        # heartbeat reports ~40 keys -> PD issues RANGE_SPLIT
        await c.wait_region_on_all(1024, timeout_s=10)
        l2 = await c.wait_region_leader(1024)
        assert l2.region.start_key != b""
        # PD metadata reflects the split (split report or heartbeats)
        pd = c.pd_client()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            regions = await pd.list_regions()
            if len(regions) >= 2:
                break
            await asyncio.sleep(0.1)
        assert len(regions) >= 2


async def test_split_decision_survives_pd_failover():
    """VERDICT r1 #8: the split DECISION is replicated PD state.  Order
    a split, kill the PD leader before the store reports completion —
    the new leader must re-issue the SAME child region id, never
    allocate a duplicate."""

    from tpuraft.rheakv.pd_messages import (Instruction,
                                            RegionHeartbeatRequest)
    from tpuraft.rheakv.metadata import Region, RegionEpoch

    async with pd_cluster(split_threshold_keys=1000) as c:
        leader = await c.wait_pd_leader()
        region = Region(id=7, start_key=b"", end_key=b"",
                        peers=list(c.endpoints),
                        epoch=RegionEpoch(1, 1))
        pd = c.pd_client()

        async def beat(keys: int) -> list[Instruction]:
            # route to whoever currently leads the PD group
            for srv in list(c.pd_servers.values()):
                node = srv.node
                if node is not None and node.is_leader():
                    resp = await srv._region_heartbeat(
                        RegionHeartbeatRequest(
                            region=region.encode(),
                            leader=c.endpoints[0],
                            approximate_keys=keys))
                    return [Instruction.decode(b)
                            for b in resp.instructions]
            return []

        # oversize region -> exactly one split instruction
        ins = await beat(5000)
        assert len(ins) == 1 and ins[0].kind == Instruction.KIND_SPLIT
        child_id = ins[0].new_region_id
        assert child_id >= 1024

        # the decision must be durable in the FSM before the kill
        assert leader.fsm.pending_splits.get(7) == child_id

        # PD leader dies before the store executes the split
        await c.stop_pd(leader.server_id.endpoint)
        new_leader = await c.wait_pd_leader()
        # replicated decision survived the failover
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                new_leader.fsm.pending_splits.get(7) != child_id:
            await asyncio.sleep(0.05)
        assert new_leader.fsm.pending_splits.get(7) == child_id

        # still-oversize heartbeats at the NEW leader re-issue the SAME
        # child id — no duplicate allocation, ever
        ids = set()
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and not ids:
            for i in await beat(5000):
                if i.kind == Instruction.KIND_SPLIT:
                    ids.add(i.new_region_id)
            await asyncio.sleep(0.2)
        assert ids == {child_id}, ids

        # the split completing clears the decision; future splits allowed
        parent_done = Region(id=7, start_key=b"", end_key=b"m",
                             peers=list(c.endpoints),
                             epoch=RegionEpoch(1, 2))
        child_done = Region(id=child_id, start_key=b"m", end_key=b"",
                            peers=list(c.endpoints),
                            epoch=RegionEpoch(1, 2))
        from tpuraft.rheakv.pd_messages import ReportSplitRequest

        for srv in list(c.pd_servers.values()):
            node = srv.node
            if node is not None and node.is_leader():
                await srv._report_split(ReportSplitRequest(
                    parent=parent_done.encode(), child=child_done.encode()))
        assert new_leader.fsm.pending_splits.get(7) is None


async def test_client_with_remote_pd():
    async with pd_cluster() as c:
        await c.wait_pd_leader()
        await c.wait_region_leader(1)
        kv = RheaKVStore(c.pd_client(), c.client_transport())
        await kv.start()
        assert await kv.put(b"via-pd", b"yes")
        assert await kv.get(b"via-pd") == b"yes"
        s = await kv.get_sequence(b"pd-seq", 5)
        assert (s.start, s.end) == (0, 5)
        await kv.shutdown()


async def test_pd_balances_leaders():
    """PD leader balancing (reference: PD-stats-driven rebalance): all
    regions' leaders piled onto one store get TRANSFER_LEADER
    instructions until counts even out."""
    regions = [Region(id=i + 1,
                      start_key=bytes([i * 40]) if i else b"",
                      end_key=bytes([(i + 1) * 40]) if i < 5 else b"")
               for i in range(6)]
    async with pd_cluster(regions=regions, balance_leaders=True,
                          transfer_cooldown_s=1.5) as c:
        def leader_counts():
            counts = {ep: 0 for ep in c.endpoints}
            for rid in range(1, 7):
                for ep, s in c.stores.items():
                    eng = s.get_region_engine(rid)
                    if eng is not None and eng.is_leader():
                        counts[ep] += 1
            return counts

        await c.wait_pd_leader()
        for rid in range(1, 7):
            await c.wait_region_leader(rid)
        # pile every region's leadership onto store 0
        target = c.endpoints[0]
        for rid in range(1, 7):
            for _ in range(4):
                leader = await c.wait_region_leader(rid)
                if leader.store_engine.server_id.endpoint == target:
                    break
                from tpuraft.entity import PeerId
                st = await leader.transfer_leadership_to(
                    PeerId.parse(target))
                await asyncio.sleep(0.2)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            leader0 = sum(
                1 for rid in range(1, 7)
                for s in [c.stores[target].get_region_engine(rid)]
                if s is not None and s.is_leader())
            if leader0 >= 5:
                break
            await asyncio.sleep(0.1)
        # PD heartbeats should now spread leadership back out (generous
        # deadline: under full-suite CPU contention the per-region
        # transfer cooldown stretches each balancing round)
        deadline = time.monotonic() + 45
        spread = None
        trajectory = []
        while time.monotonic() < deadline:
            counts = leader_counts()
            spread = max(counts.values()) - min(counts.values())
            if not trajectory or trajectory[-1][1] != counts:
                trajectory.append((round(time.monotonic() - deadline + 45, 1),
                                   dict(counts)))
            if sum(counts.values()) == 6 and spread <= 2:
                break
            await asyncio.sleep(0.2)
        assert spread is not None and spread <= 2, \
            f"final={counts} trajectory={trajectory}"
        # stability: pending-move overlay must prevent the rebalance
        # from overshooting into oscillation (regression: wholesale
        # leadership rotation every cooldown period)
        worst = 0
        samples = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5:
            counts = leader_counts()
            if sum(counts.values()) == 6:
                samples += 1
                worst = max(worst, max(counts.values()) - min(counts.values()))
            await asyncio.sleep(0.2)
        assert samples > 0, "no fully-led sample in the stability window"
        assert worst <= 2, f"balancer thrashing: worst spread {worst}"


async def test_balancer_cooldown_survives_pd_failover():
    """VERDICT r2 #9: transfer cooldowns are leader-local, so the new PD
    leader rebuilds them deterministically on takeover — every region
    starts the new term on one full cooldown, and a region transferred
    seconds before the failover is never immediately re-transferred."""
    from tpuraft.rheakv.metadata import Region, RegionEpoch
    from tpuraft.rheakv.pd_messages import (Instruction,
                                            RegionHeartbeatRequest)

    # every time budget below DERIVES from this one knob — fixed
    # sleeps made the test fail ~2/5 under host load (the 1.5s
    # no-retransfer window kept asserting past the 3s grace whenever
    # the event loop lagged)
    cooldown_s = 3.0
    async with pd_cluster(balance_leaders=True,
                          transfer_cooldown_s=cooldown_s) as c:
        await c.wait_pd_leader()

        regions = {
            rid: Region(id=rid, start_key=b"", end_key=b"",
                        peers=list(c.endpoints), epoch=RegionEpoch(1, 1))
            for rid in (41, 42, 43, 44)}

        async def beat(rid: int, leader_ep: str) -> list:
            for srv in list(c.pd_servers.values()):
                node = srv.node
                if node is not None and node.is_leader():
                    resp = await srv._region_heartbeat(
                        RegionHeartbeatRequest(
                            region=regions[rid].encode(),
                            leader=leader_ep, approximate_keys=1))
                    return [Instruction.decode(b)
                            for b in resp.instructions]
            return []

        async def beat_until_transfer(budget_s: float):
            """Poll all regions until a transfer is ordered; budget is
            derived from the configured cooldown, not a magic sleep."""
            deadline = time.monotonic() + budget_s
            while time.monotonic() < deadline:
                for rid in regions:
                    for i in await beat(rid, ep0):
                        if i.kind == Instruction.KIND_TRANSFER_LEADER:
                            return (rid, i.target_peer)
                await asyncio.sleep(min(0.1, cooldown_s / 20))
            return None

        # pile 4 regions' leadership onto endpoint 0 in the replicated
        # leader map; keep beating until the balancer's startup grace
        # (one cooldown from first leadership) passes and it orders a
        # transfer
        ep0 = c.endpoints[0]
        ordered = await beat_until_transfer(6 * cooldown_s + 10)
        assert ordered is not None, "balancer never ordered a transfer"

        # PD leader dies right after ordering the move
        leader = await c.wait_pd_leader()
        await c.stop_pd(leader.server_id.endpoint)
        await c.wait_pd_leader()

        # the moved region still heartbeats from ep0 (the store has not
        # executed the transfer yet): the NEW leader's fresh stats would
        # re-order the move instantly pre-fix; the post-failover grace
        # must suppress every transfer for one full cooldown.  The
        # grace clock starts at the FIRST post-failover policy beat, so
        # t0 taken before that beat is a safe lower bound — and each
        # round only ASSERTS if it finished inside cooldown/2 of t0
        # (a host-load stall past the window stops checking instead of
        # asserting against an expired grace).
        t0 = time.monotonic()
        checked_rounds = 0
        while time.monotonic() - t0 < 0.5 * cooldown_s:
            round_ins = []
            for rid in regions:
                round_ins.append((rid, await beat(rid, ep0)))
            if time.monotonic() - t0 >= 0.5 * cooldown_s:
                break  # this round overran the safe window: inconclusive
            for rid, ins in round_ins:
                kinds = [i.kind for i in ins]
                assert Instruction.KIND_TRANSFER_LEADER not in kinds, \
                    f"immediate re-transfer of region {rid} after failover"
            checked_rounds += 1
            await asyncio.sleep(min(0.2, cooldown_s / 15))
        assert checked_rounds > 0, \
            "host too slow to observe the grace window at all"

        # after the grace window the balancer resumes
        resumed = await beat_until_transfer(6 * cooldown_s + 10)
        assert resumed is not None, \
            "balancer never resumed after the grace window"
