"""Joint consensus under chaos: the reconfiguration-plane sibling of
test_storage_fault.py's crash-consistency harness.

Covers the invariants the membership-churn soak (examples/soak.py
--churn) asserts live, as deterministic seeded single-process tests:

- the committed conf is always one of {old, joint, new} and quorum
  intersection holds across the change (oracle.check_conf_sequence);
- a crash mid-joint is recovered by the NEXT leader resuming the change
  (_ConfigurationCtx.resume_joint at becomeLeader);
- a reboot mid-change recovers the correct conf from log+snapshot,
  including a snapshot taken while joint;
- a stuck catch-up aborts with a clean EBUSY-free retry path instead of
  wedging _conf_ctx forever, and a step-down racing a catch-up
  completion cannot append a joint entry to a follower's log;
- a voter removed from the conf cannot depose the remaining cluster
  (removed-server disruption guard), and reset_learners of a current
  voter is rejected, not silently demoted;
- transfer_leadership_to under faults: target crashed before
  timeout_now, transfer vs concurrent conf change (EBUSY both ways),
  and the _transfer_watchdog restoring availability.
"""

from __future__ import annotations

import asyncio
import random
import time

import pytest

from tests.cluster import TestCluster
from tests.oracle import (
    check_conf_sequence,
    joint_quorums_intersect,
    majorities_intersect,
)
from tpuraft.conf import Configuration
from tpuraft.core.ballot_box import BallotBox
from tpuraft.core.node import State, _ConfigurationCtx
from tpuraft.entity import EntryType, LogEntry, LogId, PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import AppendEntriesRequest, RequestVoteRequest


async def poll(cond, timeout_s: float = 5.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


async def start_joiner(c: TestCluster, peer: PeerId):
    """Boot a node with an empty conf: it learns membership via
    replication (the reference's joiner pattern)."""
    c.peers.append(peer)
    save = c.conf
    c.conf = Configuration()
    await c.start(peer)
    c.conf = save


# ---------------------------------------------------------------------------
# removed-server disruption guard
# ---------------------------------------------------------------------------


async def test_votes_from_non_member_candidate_rejected():
    """Pre-votes from a candidate outside the conf are refused outright;
    a real vote with a huge term must not depose a leader whose lease
    holds (pre-fix: handle_request_vote stepped down unconditionally)."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"x")
    term = leader.current_term
    last = leader.log_manager.last_log_id()
    outsider = "127.0.0.1:5099"

    pre = RequestVoteRequest(
        group_id=c.group_id, server_id=outsider,
        peer_id=str(leader.server_id), term=term + 1,
        last_log_index=last.index + 10, last_log_term=term + 5,
        pre_vote=True)
    resp = await leader.handle_request_vote(pre)
    assert not resp.granted

    real = RequestVoteRequest(
        group_id=c.group_id, server_id=outsider,
        peer_id=str(leader.server_id), term=term + 5,
        last_log_index=last.index + 10, last_log_term=term + 5,
        pre_vote=False)
    resp = await leader.handle_request_vote(real)
    assert not resp.granted
    assert leader.state == State.LEADER, "non-member vote deposed the leader"
    assert leader.current_term == term, "non-member vote bumped the term"

    follower = next(n for n in c.nodes.values() if n is not leader)
    resp = await follower.handle_request_vote(real)
    assert not resp.granted
    assert follower.current_term == term
    await c.stop_all()


async def test_non_member_prevote_allowed_when_no_live_leader():
    """The recovery escape, mirroring the real-vote guard: a voter whose
    conf is STALE (it never received the entry adding the candidate)
    must still grant pre-vote once no leader is alive — otherwise a
    {A,B,D} group where only B lags at {A,B,C} can never elect D after
    A dies.  While a leader IS alive the same pre-vote stays refused."""
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"x")
    follower = next(n for n in c.nodes.values() if n is not leader)
    term = follower.current_term
    last = follower.log_manager.last_log_id()
    pre = RequestVoteRequest(
        group_id=c.group_id, server_id="127.0.0.1:5099",
        peer_id=str(follower.server_id), term=term + 5,
        last_log_index=last.index + 10, last_log_term=term + 5,
        pre_vote=True)
    resp = await follower.handle_request_vote(pre)
    assert not resp.granted, "non-member pre-vote granted under a live leader"
    # isolate the follower (its own pre-votes fail, so no term bumps)
    # and let its leader lease lapse
    c.net.isolate(follower.server_id.endpoint)
    await asyncio.sleep(0.5)
    resp = await follower.handle_request_vote(pre)
    assert resp.granted, "stale-conf voter blocked recovery pre-vote"
    c.net.heal()
    await c.stop_all()


async def test_removed_voter_cannot_depose_leader():
    """A voter removed while partitioned from the leader never learns
    its removal and keeps electioneering with ever-growing terms; the
    survivors must stay stable (reference: Raft §4.2.3 disruption)."""
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"a")
    victim = next(p for p in c.peers if p != leader.server_id)
    vnode = c.nodes[victim]
    survivors = {p.endpoint for p in c.peers if p != victim}
    # victim receives nothing (never sees the conf entry removing it)
    # but its own calls still reach the survivors
    c.net.partition_one_way(survivors, {victim.endpoint})
    st = await asyncio.wait_for(leader.remove_peer(victim), 10)
    assert st.is_ok(), str(st)
    term = leader.current_term
    # worst case: the stale victim skips pre-vote entirely (lease-expiry
    # edge) and solicits real votes at term+1, repeatedly
    for _ in range(3):
        async with vnode._lock:
            if vnode.state in (State.FOLLOWER, State.CANDIDATE):
                await vnode._elect_self()
        await asyncio.sleep(0.25)
    assert leader.state == State.LEADER, \
        "removed voter deposed the remaining cluster"
    assert leader.current_term == term, \
        "removed voter's elections bumped the survivors' term"
    st = await c.apply_ok(leader, b"b")
    assert st.is_ok(), str(st)
    c.net.heal()
    await c.stop_all()


async def test_reset_learners_of_current_voter_rejected():
    """reset_learners/add_learners naming a CURRENT VOTER must be
    rejected (EINVAL), not silently demote it out of the quorum."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader(10)
    voter = next(p for p in c.peers if p != leader.server_id)
    st = await asyncio.wait_for(leader.reset_learners([voter]), 10)
    assert st.raft_error == RaftError.EINVAL, str(st)
    assert voter in leader.list_peers(), "voter silently demoted"
    assert voter not in leader.list_learners()
    st = await asyncio.wait_for(leader.add_learners([voter]), 10)
    assert st.raft_error == RaftError.EINVAL, str(st)
    assert voter in leader.list_peers()
    await c.stop_all()


# ---------------------------------------------------------------------------
# catch-up abort / EBUSY-free retry
# ---------------------------------------------------------------------------


async def test_catchup_timeout_aborts_cleanly_and_retry_succeeds():
    """Adding an unreachable peer must fail ECATCHUP after the bounded
    catch-up window, tear down the provisioned replicator, clear
    _conf_ctx, and leave the node immediately ready for the next change
    — no EBUSY wedge, no zombie replicator."""
    c = TestCluster(3, election_timeout_ms=150)
    await c.start_all()
    leader = await c.wait_leader(10)
    ghost = PeerId.parse("127.0.0.1:5009")  # never started
    st = await asyncio.wait_for(leader.add_peer(ghost), 15)
    assert st.raft_error == RaftError.ECATCHUP, str(st)
    assert leader._conf_ctx is None, "_conf_ctx wedged after catch-up abort"
    assert leader.replicators.get(ghost) is None, \
        "catch-up replicator leaked after abort"
    assert ghost not in leader.list_peers()
    # EBUSY-free retry path: a subsequent change starts right away
    joiner = PeerId.parse("127.0.0.1:5003")
    await start_joiner(c, joiner)
    st = await asyncio.wait_for(leader.add_peer(joiner), 15)
    assert st.is_ok(), str(st)
    assert joiner in leader.list_peers()
    await c.stop_all()


async def test_cancelled_change_peers_tears_down_catchup_replicator():
    """The CALLER abandons change_peers (operator timeout) while the new
    peer is still catching up: the abort must tear down the provisioned
    replicator like the ECATCHUP path does — a leaked one keeps shipping
    to a non-member, and a retry of the change would reuse its stale
    match_index and pass catch-up instantly even after a peer wipe."""
    c = TestCluster(3, election_timeout_ms=150)
    await c.start_all()
    leader = await c.wait_leader(10)
    ghost = PeerId.parse("127.0.0.1:5009")  # never started
    with pytest.raises(asyncio.TimeoutError):
        # far below the ~10-election-timeout catch-up window: the caller
        # gives up first
        await asyncio.wait_for(leader.add_peer(ghost), 0.3)
    await poll(lambda: leader._conf_ctx is None,
               what="ctx cleared after caller cancellation")
    assert leader.replicators.get(ghost) is None, \
        "catch-up replicator leaked after caller cancellation"
    assert ghost not in leader.list_peers()
    # retry path stays clean: a real joiner is added from scratch
    joiner = PeerId.parse("127.0.0.1:5003")
    await start_joiner(c, joiner)
    st = await asyncio.wait_for(leader.add_peer(joiner), 15)
    assert st.is_ok(), str(st)
    await c.stop_all()


async def test_stale_catchup_completion_after_abort_cannot_enter_joint():
    """The zombie-joint race: catch-up waiters resolve True concurrently
    with a step-down; the aborted ctx must NOT re-enter _enter_joint and
    append a joint entry to what is now a follower's log."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader(10)
    new_conf = leader.conf_entry.conf.copy()
    new_conf.peers.append(PeerId.parse("127.0.0.1:5008"))
    ctx = _ConfigurationCtx(leader, leader.conf_entry.conf.copy(), new_conf)
    ctx._set_stage("catching_up")
    # the step-down lands first (it marks the stage terminal)...
    ctx.fail(Status.error(RaftError.ENEWLEADER, "leader stepped down"))
    assert ctx.stage == "aborted"
    before = leader.log_manager.last_log_index()
    # ...then the catch-up completion arrives with all-True results
    done: asyncio.Future = asyncio.get_running_loop().create_future()
    done.set_result(True)
    await ctx._wait_catchup([done])
    assert leader.log_manager.last_log_index() == before, \
        "aborted ctx appended a joint entry"
    assert ctx.stage == "aborted"
    await c.stop_all()


# ---------------------------------------------------------------------------
# crash mid-joint: the next leader resumes and completes the change
# ---------------------------------------------------------------------------


def _freeze_at_joint(node):
    """Stage listener that freezes the node's conf change the moment it
    enters joint: the joint entry still commits and applies cluster-wide
    but the ctx never advances to stable — modeling a leader that dies
    between the two commit rounds."""
    box = {}

    def listener(n, stage):
        if stage == "joint" and n is node and n._conf_ctx is not None:
            ctx = n._conf_ctx
            box["ctx"] = ctx

            async def _noop(entry):
                return None

            ctx.on_committed = _noop

    node.conf_stage_listener = listener
    return box


async def test_leader_crash_mid_joint_next_leader_completes_change():
    """The old leader dies with the joint conf committed but the stable
    entry never appended.  The next elected leader must ADOPT the joint
    (ConfigurationCtx resume at becomeLeader) and drive it to the new
    conf — without the fix the group stays joint forever and every
    subsequent change_peers returns EBUSY."""
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"pre")
    joiner = PeerId.parse("127.0.0.1:5003")
    await start_joiner(c, joiner)
    target = set(c.peers)

    _freeze_at_joint(leader)
    new_conf = Configuration(list(c.peers))
    task = asyncio.ensure_future(leader.change_peers(new_conf))
    followers = [n for n in c.nodes.values()
                 if n is not leader and n.server_id != joiner]
    await poll(lambda: all(not f.conf_entry.old_conf.is_empty()
                           for f in followers),
               what="joint conf replicated to followers")
    dead = leader.server_id
    await c.stop(dead)
    st = await task
    assert not st.is_ok()  # the change's initiator died with it

    new_leader = await c.wait_leader(10)
    # note: conf_entry turns stable when the stable entry is STAGED; the
    # ctx clears when it COMMITS — poll for both
    await poll(lambda: new_leader.conf_entry.old_conf.is_empty()
               and set(new_leader.conf_entry.conf.peers) == target
               and new_leader._conf_ctx is None,
               timeout_s=10,
               what="resumed change completed to the new conf")
    # availability: the new conf carries writes (quorum 3/4 with 1 dead);
    # a re-election racing the probe (ENEWLEADER) is retried — duplicate
    # application of the probe write is harmless here
    for _ in range(3):
        st = await c.apply_ok(new_leader, b"post")
        if st.is_ok():
            break
        new_leader = await c.wait_leader(10)
    assert st.is_ok(), str(st)
    # a fresh change is accepted — no EBUSY wedge from the resume
    st = await asyncio.wait_for(new_leader.remove_peer(dead), 15)
    assert st.is_ok(), str(st)
    await c.stop_all()


async def test_reboot_mid_change_recovers_joint_conf_from_snapshot(tmp_path):
    """A snapshot taken WHILE JOINT must carry the joint conf in its
    meta (peers + old_peers), and a node rebooted from it — with the
    joint log entry compacted away — must come back in the joint conf,
    then complete the change once the cluster reassembles."""
    c = TestCluster(3, tmp_path=str(tmp_path), snapshot=True,
                    election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader(10)
    for i in range(4):
        await c.apply_ok(leader, b"w%d" % i)
    joiner = PeerId.parse("127.0.0.1:5003")
    await start_joiner(c, joiner)
    old_set = set(leader.conf_entry.conf.peers)
    target = set(c.peers)

    box = _freeze_at_joint(leader)
    task = asyncio.ensure_future(
        leader.change_peers(Configuration(list(c.peers))))
    await poll(lambda: "ctx" in box, what="change entered joint")
    joint_index = box["ctx"]._joint_index
    await poll(lambda: leader.fsm_caller.last_applied_index >= joint_index,
               what="joint entry committed+applied on the leader")

    # snapshot while joint, compacting the joint entry out of the log
    leader.options.snapshot.log_index_margin = 0
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    meta = leader.snapshot_executor._storage.open().load_meta()
    assert set(PeerId.parse(p) for p in meta.old_peers) == old_set, \
        "snapshot taken while joint lost old_peers in its meta"
    assert set(PeerId.parse(p) for p in meta.peers) == target
    await poll(lambda: leader.log_manager.first_log_index() > joint_index,
               what="joint entry compacted out of the log")

    # power down the whole cluster mid-change
    dead = leader.server_id
    await c.stop(dead)
    st = await task
    assert not st.is_ok()
    for p in list(c.nodes):
        await c.stop(p)

    # reboot the ex-leader ALONE: recovery must come from ITS disk
    node = await c.start(dead)
    assert set(node.conf_entry.conf.peers) == target, \
        "rebooted node lost the joint conf"
    assert set(node.conf_entry.old_conf.peers) == old_set, \
        "rebooted node lost the OLD side of the joint conf"

    # reassemble; some leader resumes and completes the change
    for p in c.peers:
        if p not in c.nodes:
            await c.start(p)
    new_leader = await c.wait_leader(10)
    await poll(lambda: new_leader.conf_entry.old_conf.is_empty()
               and set(new_leader.conf_entry.conf.peers) == target,
               timeout_s=10, what="change completed after full reboot")
    # liveness probe: the freshly reassembled cluster may re-elect once
    # more right under the apply (ENEWLEADER) — duplicate application of
    # the probe write is harmless, so retry through the next leader
    for _ in range(3):
        st = await c.apply_ok(new_leader, b"alive")
        if st.is_ok():
            break
        new_leader = await c.wait_leader(10)
    assert st.is_ok(), str(st)
    await c.stop_all()


# ---------------------------------------------------------------------------
# follower conf must track log truncation
# ---------------------------------------------------------------------------


async def test_follower_conf_rolls_back_when_joint_entry_truncated():
    """A joint CONFIGURATION entry appended (uncommitted) on a follower
    is later truncated by the next leader's conflict resolution: the
    follower's conf must roll back to what the log actually holds, not
    keep a phantom joint membership."""
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"x")
    await c.wait_applied(1)
    follower = next(n for n in c.nodes.values() if n is not leader)
    other = next(p for p in c.peers
                 if p != leader.server_id and p != follower.server_id)
    c.net.isolate(follower.server_id.endpoint)  # keep real traffic out
    orig = set(follower.conf_entry.conf.peers)
    last = follower.log_manager.last_log_id()
    t1 = follower.current_term + 1

    joint = LogEntry(
        type=EntryType.CONFIGURATION,
        peers=sorted(orig) + [PeerId.parse("127.0.0.1:5007")],
        old_peers=sorted(orig),
        id=LogId(last.index + 1, t1))
    req = AppendEntriesRequest(
        group_id=c.group_id, server_id=str(other),
        peer_id=str(follower.server_id), term=t1,
        prev_log_index=last.index, prev_log_term=last.term,
        committed_index=follower.ballot_box.last_committed_index,
        entries=[joint])
    resp = await follower.handle_append_entries(req)
    assert resp.success
    assert not follower.conf_entry.old_conf.is_empty(), \
        "joint conf not adopted from the appended entry"

    # a NEW leader overwrites that suffix with a DATA entry at term+2
    data = LogEntry(type=EntryType.DATA, data=b"z",
                    id=LogId(last.index + 1, t1 + 1))
    req2 = AppendEntriesRequest(
        group_id=c.group_id, server_id=str(leader.server_id),
        peer_id=str(follower.server_id), term=t1 + 1,
        prev_log_index=last.index, prev_log_term=last.term,
        committed_index=follower.ballot_box.last_committed_index,
        entries=[data])
    resp = await follower.handle_append_entries(req2)
    assert resp.success
    assert follower.conf_entry.old_conf.is_empty(), \
        "phantom joint conf survived its entry's truncation"
    assert set(follower.conf_entry.conf.peers) == orig, \
        "conf did not roll back to the last conf the log holds"
    c.net.heal()
    await c.stop_all()


# ---------------------------------------------------------------------------
# ballot box: dual-quorum accounting under churn
# ---------------------------------------------------------------------------


def test_ballot_box_prunes_stale_match_of_removed_peer():
    """A voter removed, wiped, and re-added must re-earn its matchIndex:
    its stale pre-removal row must not advance the commit point (the
    re-added peer's log is empty — counting the old row commits entries
    a 'quorum' never stored)."""
    committed: list[int] = []
    box = BallotBox(committed.append)
    p1, p2, p3 = (PeerId.parse(f"1.1.1.1:{i}") for i in (1, 2, 3))
    conf3 = Configuration([p1, p2, p3])
    empty = Configuration()
    box.reset_pending_index(1)
    box.commit_at(p3, 10, conf3, empty)     # p3 acked through 10
    assert box.last_committed_index == 0    # no quorum yet
    box.update_conf(Configuration([p1, p2]), empty)   # p3 removed (wiped)
    box.update_conf(conf3, empty)                     # p3 re-added, empty log
    box.commit_at(p1, 5, conf3, empty)
    assert box.last_committed_index == 0, \
        "stale match row of a removed+re-added peer advanced the commit"
    assert committed == []
    box.commit_at(p3, 5, conf3, empty)      # the reborn peer re-earns it
    assert box.last_committed_index == 5
    assert committed == [5]


def test_membership_oracle_math():
    """The quorum-intersection oracle itself: known-good and known-bad
    voter-set pairs, and legal/illegal conf sequences."""
    a3 = frozenset({1, 2, 3})
    a4 = frozenset({1, 2, 3, 4})
    disjointish = frozenset({4, 5, 6})
    assert majorities_intersect(a3, a3)
    # single-server add: majorities of {1,2,3} and {1,2,3,4} always meet
    assert majorities_intersect(a3, a4)
    # but {1,2,3} vs {1,2,4} admits the disjoint pair {1,3} / {2,4} —
    # exactly why a swap must go through joint consensus
    assert not majorities_intersect(a3, frozenset({1, 2, 4}))
    assert not majorities_intersect(a3, disjointish)
    assert joint_quorums_intersect(a3, disjointish)  # dual quorum saves it
    check_conf_sequence([
        (a3, ()),                  # bootstrap
        (a3, ()),                  # re-commit at a new term
        (frozenset({1, 2, 3, 4}), a3),   # joint out
        (frozenset({1, 2, 3, 4}), a3),   # resumed joint after crash
        ((1, 2, 3, 4), ()),        # stable new
        ((1, 2, 4), (1, 2, 3, 4)),  # next change
        ((1, 2, 4), ()),
    ])
    with pytest.raises(AssertionError):
        check_conf_sequence([
            (a3, ()),
            (disjointish, ()),     # stable jump with no joint between
        ])
    with pytest.raises(AssertionError):
        check_conf_sequence([
            (a3, ()),
            (frozenset({1, 2, 5}), frozenset({1, 2, 4})),
            # ^ joint leaving a conf we never had
        ])
    with pytest.raises(AssertionError):
        check_conf_sequence([
            (a3, ()),
            (a4, a3),     # joint committed...
            (a3, ()),     # ...then stable C_old again: a rollback —
        ])                # leader completeness forbids this


# ---------------------------------------------------------------------------
# leadership transfer under faults
# ---------------------------------------------------------------------------


async def test_transfer_to_crashed_target_restores_leadership():
    """The transfer target crashes before timeout_now reaches it: the
    _transfer_watchdog must return the node to LEADER and the group to
    availability within an election timeout."""
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader(10)
    await c.apply_ok(leader, b"a")
    target = next(p for p in c.peers if p != leader.server_id)
    await c.stop(target)
    st = await leader.transfer_leadership_to(target)
    assert st.is_ok(), str(st)  # initiation is accepted; delivery fails
    await poll(lambda: leader.state == State.LEADER, timeout_s=3,
               what="watchdog restored leadership")
    st = await c.apply_ok(leader, b"b")
    assert st.is_ok(), str(st)
    await c.stop_all()


async def test_transfer_rejected_while_conf_change_in_flight():
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    leader = await c.wait_leader(10)
    ghost = PeerId.parse("127.0.0.1:5009")
    task = asyncio.ensure_future(leader.add_peer(ghost))  # stuck catching up
    await poll(lambda: leader._conf_ctx is not None,
               what="change entered catch-up")
    target = next(p for p in c.peers if p != leader.server_id)
    st = await leader.transfer_leadership_to(target)
    assert st.raft_error == RaftError.EBUSY, str(st)
    assert leader.state == State.LEADER
    st = await task
    assert st.raft_error == RaftError.ECATCHUP
    await c.stop_all()


async def test_stale_transfer_watchdog_cannot_end_a_newer_transfer():
    """A watchdog armed for an EARLIER transfer (the leader was deposed,
    re-elected, and started a new transfer while it slept) must not flip
    TRANSFERRING back to LEADER under the newer transfer — that would
    re-open change_peers while the new target's TimeoutNow is armed."""
    c = TestCluster(3, election_timeout_ms=250)
    await c.start_all()
    leader = await c.wait_leader(10)
    peers = [p for p in c.peers if p != leader.server_id]
    target = peers[0]
    # hold the target's match below the transfer index so TRANSFERRING
    # persists long enough to observe
    c.net.partition({target.endpoint},
                    {p.endpoint for p in c.peers if p != target})
    st = await c.apply_ok(leader, b"x")
    assert st.is_ok()
    st = await leader.transfer_leadership_to(target)
    assert st.is_ok(), str(st)
    assert leader.state == State.TRANSFERRING
    # a watchdog pinned to a PREVIOUS term is a no-op...
    await leader._transfer_watchdog(target, leader.current_term - 1)
    assert leader.state == State.TRANSFERRING, \
        "stale watchdog ended a transfer it did not start"
    # ...while the real one (armed by transfer_leadership_to) recovers
    await poll(lambda: leader.state == State.LEADER, timeout_s=3,
               what="current-term watchdog restored leadership")
    c.net.heal()
    await c.stop_all()


async def test_change_peers_rejected_while_transferring_then_recovers():
    """change_peers racing a transfer gets a clean EBUSY (not a half-run
    change under a TRANSFERRING leader); after the watchdog restores
    leadership the same change succeeds."""
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    leader = await c.wait_leader(10)
    peers = [p for p in c.peers if p != leader.server_id]
    target, third = peers[0], peers[1]
    # hold the target's match below the transfer index so TRANSFERRING
    # persists until the watchdog fires
    c.net.partition({target.endpoint},
                    {p.endpoint for p in c.peers if p != target})
    st = await c.apply_ok(leader, b"x")
    assert st.is_ok()
    st = await leader.transfer_leadership_to(target)
    assert st.is_ok(), str(st)
    assert leader.state == State.TRANSFERRING
    st = await leader.change_peers(
        Configuration([leader.server_id, third]))
    assert st.raft_error == RaftError.EBUSY, str(st)
    # the partition holds, so the transfer cannot complete — only the
    # watchdog can end TRANSFERRING
    await poll(lambda: leader.state == State.LEADER, timeout_s=3,
               what="watchdog restored leadership")
    c.net.heal()
    st = await asyncio.wait_for(
        leader.change_peers(Configuration([leader.server_id, third])), 15)
    assert st.is_ok(), str(st)
    assert set(leader.list_peers()) == {leader.server_id, third}
    await c.stop_all()


# ---------------------------------------------------------------------------
# seeded chaos drive: churn + kills + partitions, invariants after every step
# ---------------------------------------------------------------------------


async def test_seeded_membership_chaos_drive(tmp_path):
    """A compressed in-pytest version of the soak's --churn drive:
    12 seeded rounds of membership ops with kills and one-way partitions
    interleaved, client writes throughout; afterwards every node's
    committed-configuration sequence must be a legal joint-consensus
    chain (oracle.check_conf_sequence) and all live nodes must converge
    on one stable conf."""
    rng = random.Random(11)
    c = TestCluster(5, tmp_path=str(tmp_path), election_timeout_ms=200)
    c.conf = Configuration(list(c.peers[:3]))  # 2 standbys for churn
    await c.start_all()

    sequences: list[list] = []

    def record(node):
        seq: list = []
        sequences.append(seq)
        orig = node.fsm_caller.on_configuration_applied

        async def wrapped(entry):
            seq.append((tuple(entry.peers or ()),
                        tuple(entry.old_peers or ())))
            await orig(entry)

        node.fsm_caller.on_configuration_applied = wrapped

    for n in c.nodes.values():
        record(n)

    async def change(op_coro):
        """Drive one membership op with bounded EBUSY retry."""
        for _ in range(20):
            try:
                st = await asyncio.wait_for(op_coro(), 15)
            except (TimeoutError, asyncio.TimeoutError):
                return None
            if st.is_ok():
                return st
            if st.raft_error != RaftError.EBUSY:
                return st
            await asyncio.sleep(0.1)
        return st

    completed = 0
    for rnd in range(12):
        leader = await c.wait_leader(10)
        for k in range(3):
            await c.apply_ok(leader, b"r%d-%d" % (rnd, k), timeout_s=10)
        leader = await c.wait_leader(10)
        voters = list(leader.conf_entry.conf.peers)
        spare = [p for p in c.peers if p not in voters]
        menu = []
        if spare and len(voters) < 4:
            menu.append("add")
        if len(voters) > 2:
            menu.append("remove")
        op = rng.choice(menu)
        if op == "add":
            pick = rng.choice(spare)
            st = await change(lambda: leader.add_peer(pick))
        else:
            pick = rng.choice(voters)
            st = await change(lambda: leader.remove_peer(pick))
        if st is not None and st.is_ok():
            completed += 1
        # interleaved faults: kill+restart a random node, or a one-way
        # partition healed next round
        if rnd % 3 == 2:
            victim = rng.choice(c.peers)
            if victim in c.nodes:
                await c.stop(victim)
                record(await c.start(victim))
        elif rnd % 3 == 0:
            a, b = rng.sample([p.endpoint for p in c.peers], 2)
            c.net.partition_one_way({a}, {b})
        else:
            c.net.heal()
    c.net.heal()

    assert completed >= 3, f"only {completed} conf changes completed"
    leader = await c.wait_leader(10)
    await poll(lambda: leader.conf_entry.old_conf.is_empty(),
               timeout_s=15, what="final change settled")
    final = set(leader.conf_entry.conf.peers)
    # every voter of the final conf converges to it
    await poll(lambda: all(
        set(c.nodes[p].conf_entry.conf.peers) == final
        and c.nodes[p].conf_entry.old_conf.is_empty()
        for p in final if p in c.nodes),
        timeout_s=15, what="voters converged on the final conf")
    st = await c.apply_ok(leader, b"final")
    assert st.is_ok(), str(st)
    # the committed conf sequence every node observed is a legal chain
    checked = 0
    for seq in sequences:
        if seq:
            check_conf_sequence(seq)
            checked += 1
    assert checked >= 3, "too few conf sequences recorded to mean anything"
    await c.stop_all()


# ---------------------------------------------------------------------------
# region-lifecycle churn under the keyspace-coverage oracle
# ---------------------------------------------------------------------------


async def test_region_lifecycle_churn_keeps_keyspace_tiled():
    """Seeded split/merge churn on a live KV cluster: after EVERY
    lifecycle op settles, each store's region set must still tile the
    keyspace (tests.oracle.coverage_errors — the invariant the
    --lifecycle soak asserts live), and every key written before the
    churn must still be served by exactly the region covering it."""
    from tests.kv_cluster import KVTestCluster
    from tests.oracle import coverage_errors
    from tpuraft.rheakv.metadata import Region

    rng = random.Random(20)
    c = KVTestCluster(3, regions=[Region(id=1, start_key=b"",
                                         end_key=b"")])
    await c.start_all()
    try:
        leader = await c.wait_region_leader(1)
        keys = [b"%03d" % i for i in range(0, 128)]
        for k in keys:
            assert await leader.raft_store.put(k, b"v" + k)

        def tilings():
            # every store's live view of the region set
            return [[e.region for e in s._regions.values()]
                    for s in c.stores.values()]

        async def settle_and_check(what):
            async def _ok():
                views = tilings()
                return (len({len(v) for v in views}) == 1
                        and all(not coverage_errors(v) for v in views))
            deadline = time.monotonic() + 10.0
            while not await _ok():
                assert time.monotonic() < deadline, (
                    f"after {what}: stores never converged on a clean "
                    f"tiling: "
                    + "; ".join("; ".join(coverage_errors(v)) or "ok"
                                for v in tilings()))
                await asyncio.sleep(0.05)

        next_id, splits_done, merges_done = 2, 0, 0
        for _ in range(8):
            regions = sorted(tilings()[0], key=lambda r: r.start_key)
            if rng.random() < 0.5 or len(regions) < 2:
                # SPLIT a random region (needs >= 2 resident keys)
                parent = rng.choice(regions)
                l = await c.wait_region_leader(parent.id)
                st = await l.store_engine.apply_split(parent.id, next_id)
                if st.is_ok():
                    await c.wait_region_on_all(next_id, timeout_s=10.0)
                    await settle_and_check(f"split {parent.id}")
                    next_id += 1
                    splits_done += 1
            else:
                # MERGE a random adjacent pair (left absorbs into right)
                i = rng.randrange(len(regions) - 1)
                src, tgt = regions[i], regions[i + 1]
                ls = await c.wait_region_leader(src.id)
                lt = await c.wait_region_leader(tgt.id)
                st = await ls.store_engine.apply_merge(
                    src.id, tgt.id, str(lt.node.server_id))
                if st.is_ok():
                    await poll(lambda: all(
                        s.get_region_engine(src.id) is None
                        for s in c.stores.values()),
                        timeout_s=10.0,
                        what=f"retirement of merged region {src.id}")
                    await settle_and_check(f"merge {src.id}->{tgt.id}")
                    merges_done += 1
        assert splits_done >= 1 and merges_done >= 1, (
            f"churn too tame: {splits_done} splits, {merges_done} merges")
        # every pre-churn key is served by the region covering it
        final = sorted(tilings()[0], key=lambda r: r.start_key)
        assert coverage_errors(final) == []
        for k in keys:
            owner = next(r for r in final
                         if r.start_key <= k and (r.end_key == b""
                                                  or k < r.end_key))
            l = await c.wait_region_leader(owner.id)
            assert await l.raft_store.get(k) == b"v" + k
    finally:
        await c.stop_all()
