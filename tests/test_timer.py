"""RepeatedTimer unit tests (reference: test:util/RepeatedTimerTest —
SURVEY.md §5 "Pure unit")."""

import asyncio

import pytest

from tpuraft.util.timer import RepeatedTimer


@pytest.mark.asyncio
async def test_fires_repeatedly_and_stops():
    fires = []
    t = RepeatedTimer("t", 10, lambda: _record(fires))
    t.start()
    await asyncio.sleep(0.08)
    t.stop()
    count = len(fires)
    assert count >= 3
    await asyncio.sleep(0.05)
    assert len(fires) == count  # no fires after stop


async def _record(lst):
    lst.append(1)


@pytest.mark.asyncio
async def test_stop_from_within_handler_does_not_kill_handler():
    """Regression: a handler stopping its OWN timer (the way _elect_self
    stops the election timer that fired it) must finish executing — the
    old implementation cancelled the in-flight task, silently killing
    the handler at its next await point."""
    done = asyncio.Event()
    t = None

    async def handler():
        t.stop()
        await asyncio.sleep(0)  # the await the cancel used to land on
        done.set()

    t = RepeatedTimer("self-stop", 10, handler)
    t.start()
    await asyncio.wait_for(done.wait(), 2.0)
    assert not t.running


@pytest.mark.asyncio
async def test_restart_from_within_handler_single_generation():
    """A restart() from inside the handler must not double-schedule:
    only the fresh generation keeps firing."""
    fires = []
    t = None

    async def handler():
        fires.append(1)
        if len(fires) == 1:
            t.restart()
        if len(fires) >= 4:
            t.stop()

    t = RepeatedTimer("restart", 10, handler)
    t.start()
    await asyncio.sleep(0.25)
    count = len(fires)
    assert count >= 4
    await asyncio.sleep(0.1)
    # stopped, and no runaway extra generation kept firing
    assert len(fires) == count


@pytest.mark.asyncio
async def test_random_adjust_bounds():
    for _ in range(100):
        v = RepeatedTimer.random_adjust(100)
        assert 100 <= v < 200
