"""Linearizability checker: unit histories + a real chaos history.

Goes beyond the reference's latch-style chaos asserts (SURVEY.md §5):
records true invoke/return windows of concurrent clients against a
KVTestCluster under rolling leader kills and proves the observed
results admit a legal sequential order.
"""

import asyncio
import contextlib

from tests.kv_cluster import KVTestCluster
from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.util.linearizability import History, check_history


def _h(*rows):
    """rows: (client, kind, args, invoke, ret_or_None, result)"""
    h = History()
    toks = []
    for client, kind, args, inv, ret, res in rows:
        tok = h.invoke(client, kind, args, now=inv)
        toks.append(tok)
        if ret is not None:
            h.complete(tok, res, now=ret)
    return h


K = b"x"


def test_sequential_history_accepts():
    h = _h((0, "w", (K, b"1"), 0, 1, True),
           (0, "r", (K,), 2, 3, b"1"),
           (0, "w", (K, b"2"), 4, 5, True),
           (0, "r", (K,), 6, 7, b"2"))
    rep = check_history(h)
    assert rep.ok, str(rep)
    assert rep.keys[K].witness == [0, 1, 2, 3]


def test_concurrent_writes_reorder_to_satisfy_read():
    # two writes racing in [0,10]; a later read sees the "first" one —
    # legal iff the checker orders w2 before w1
    h = _h((0, "w", (K, b"1"), 0, 10, True),
           (1, "w", (K, b"2"), 0, 10, True),
           (2, "r", (K,), 11, 12, b"1"))
    assert check_history(h).ok


def test_stale_read_rejected():
    h = _h((0, "w", (K, b"1"), 0, 1, True),
           (0, "w", (K, b"2"), 2, 3, True),
           (1, "r", (K,), 4, 5, b"1"))     # already overwritten: stale
    rep = check_history(h)
    assert not rep.ok
    assert rep.keys[K].stuck_ops


def test_read_inversion_rejected():
    # r1 observes the in-flight write, then a later r2 un-observes it
    h = _h((0, "w", (K, b"1"), 0, 10, True),
           (1, "r", (K,), 1, 2, b"1"),
           (1, "r", (K,), 3, 4, None))
    assert not check_history(h).ok


def test_double_cas_success_rejected():
    # both CAS(None -> _) succeed: impossible on one register
    h = _h((0, "cas", (K, None, b"a"), 0, 1, True),
           (1, "cas", (K, None, b"b"), 2, 3, True))
    assert not check_history(h).ok


def test_cas_chain_accepts():
    h = _h((0, "cas", (K, None, b"a"), 0, 1, True),
           (1, "cas", (K, b"a", b"b"), 2, 3, True),
           (2, "cas", (K, b"a", b"c"), 4, 5, False),
           (0, "r", (K,), 6, 7, b"b"))
    assert check_history(h).ok


def test_put_if_absent_semantics():
    h = _h((0, "pia", (K, b"a"), 0, 1, None),      # wrote
           (1, "pia", (K, b"b"), 2, 3, b"a"),      # lost: returns prior
           (2, "r", (K,), 4, 5, b"a"))
    assert check_history(h).ok
    h2 = _h((0, "pia", (K, b"a"), 0, 1, None),
            (1, "pia", (K, b"b"), 2, 3, None))     # both claim to write
    assert not check_history(h2).ok


def test_pending_op_may_apply_or_not():
    pending_applied = _h((0, "w", (K, b"1"), 0, 1, True),
                         (1, "w", (K, b"2"), 2, None, None),  # no ack
                         (0, "r", (K,), 10, 11, b"2"))
    assert check_history(pending_applied).ok
    pending_dropped = _h((0, "w", (K, b"1"), 0, 1, True),
                         (1, "w", (K, b"2"), 2, None, None),
                         (0, "r", (K,), 10, 11, b"1"))
    assert check_history(pending_dropped).ok
    # but a pending op cannot linearize BEFORE its invoke
    too_early = _h((0, "r", (K,), 0, 1, b"2"),
                   (1, "w", (K, b"2"), 2, None, None))
    assert not check_history(too_early).ok


def test_concurrent_read_sees_old_or_new():
    h = _h((0, "w", (K, b"1"), 0, 10, True),
           (1, "r", (K,), 1, 2, None),    # before the write linearizes
           (1, "r", (K,), 3, 4, b"1"))    # after
    assert check_history(h).ok


def test_keys_checked_independently():
    h = _h((0, "w", (b"a", b"1"), 0, 1, True),
           (0, "w", (b"b", b"9"), 2, 3, True),
           (1, "r", (b"a",), 4, 5, b"1"),
           (1, "r", (b"b",), 6, 7, b"9"))
    rep = check_history(h)
    assert rep.ok and set(rep.keys) == {b"a", b"b"}


def test_deep_concurrency_terminates():
    # 12 fully-overlapping writes + a read: exercises memoization
    rows = [(i, "w", (K, b"v%d" % i), 0, 100, True) for i in range(12)]
    rows.append((99, "r", (K,), 101, 102, b"v7"))
    assert check_history(_h(*rows)).ok


def test_witness_replays_to_observed_results():
    h = _h((0, "w", (K, b"1"), 0, 10, True),
           (1, "w", (K, b"2"), 0, 10, True),
           (2, "r", (K,), 2, 3, b"2"),
           (2, "r", (K,), 11, 12, b"1"))
    rep = check_history(h)
    assert rep.ok
    # replay the witness order through the model: reads must match
    ops = {o.op_id: o for o in h.ops()}
    state = None
    for op_id in rep.keys[K].witness:
        o = ops[op_id]
        if o.kind == "w":
            state = o.args[1]
        elif o.kind == "r":
            assert o.result == state
    assert state == b"1"


# ---------------------------------------------------------------------------
# the real thing: concurrent clients + leader kills, recorded history
# ---------------------------------------------------------------------------

@contextlib.asynccontextmanager
async def _cluster(tmp_path):
    c = KVTestCluster(3, tmp_path=tmp_path)
    await c.start_all()
    pd = FakePlacementDriverClient([r.copy() for r in c.region_template])
    # max_retries=1: a client-level retry could re-apply an op outside
    # its recorded window; with one attempt, every failure is recorded
    # as pending ("maybe applied") and the history stays sound
    kv = RheaKVStore(pd, c.client_transport(), max_retries=1)
    await kv.start()
    try:
        yield c, kv
    finally:
        await kv.shutdown()
        await c.stop_all()


async def test_chaos_history_is_linearizable(tmp_path):
    async with _cluster(tmp_path) as (c, kv):
        h = History()
        stop = asyncio.Event()
        keys = [b"lin-%d" % i for i in range(4)]
        seq = [0]

        async def worker(cid: int):
            while not stop.is_set():
                key = keys[(cid + seq[0]) % len(keys)]
                mode = seq[0] % 3
                seq[0] += 1
                if mode == 0:
                    val = b"c%d-%d" % (cid, seq[0])   # unique values
                    tok = h.invoke(cid, "w", (key, val))
                    try:
                        ok = await asyncio.wait_for(kv.put(key, val), 5.0)
                        if ok:
                            h.complete(tok, True)
                        # ok=False never happens (put raises on failure);
                        # leave pending if it somehow does
                    except Exception:
                        pass                          # pending: maybe applied
                else:
                    tok = h.invoke(cid, "r", (key,))
                    try:
                        val = await asyncio.wait_for(kv.get(key), 5.0)
                        h.complete(tok, val)
                    except Exception:
                        pass
                await asyncio.sleep(0)

        workers = [asyncio.ensure_future(worker(i)) for i in range(4)]
        try:
            for _round in range(2):
                await asyncio.sleep(0.5)
                leader = await c.wait_region_leader(1, timeout_s=15)
                ep = leader.store_engine.server_id.endpoint
                await c.stop_store(ep)
                await asyncio.sleep(0.5)
                await c.start_store(ep)
        finally:
            stop.set()
            await asyncio.gather(*workers)

        ops = h.ops()
        n_done = sum(1 for o in ops if o.ret is not None)
        assert n_done > 50, f"only {n_done}/{len(ops)} ops completed"
        rep = check_history(h)
        assert rep.ok, str(rep)


async def test_checker_catches_stale_follower_reads(tmp_path):
    """Negative control at the system level: reads served from an
    isolated follower's local store (bypassing raft) are stale by
    construction — the checker must reject that history."""
    async with _cluster(tmp_path) as (c, kv):
        key = b"stale-key"
        leader = await c.wait_region_leader(1)
        for _ in range(20):   # single-attempt client: ride out settling
            try:
                assert await kv.put(key, b"v0")
                break
            except Exception:
                await asyncio.sleep(0.1)
        else:
            raise AssertionError("setup put never succeeded")
        lep = leader.store_engine.server_id.endpoint
        follower_ep = next(ep for ep in c.endpoints if ep != lep)
        # wait until the follower holds v0, then cut it off
        fstore = c.stores[follower_ep].raw_store
        for _ in range(200):
            if fstore.get(key) == b"v0":
                break
            await asyncio.sleep(0.02)
        assert fstore.get(key) == b"v0"
        c.net.isolate(follower_ep)
        try:
            h = History()
            tok = h.invoke(0, "w", (key, b"v1"))
            assert await kv.put(key, b"v1")       # quorum of the other two
            h.complete(tok, True)
            # a "store" that answers from the cut-off follower: stale
            tok = h.invoke(1, "r", (key,))
            h.complete(tok, fstore.get(key))
            rep = check_history(h)
            assert not rep.ok, "stale follower read went undetected"
            # the same read through the raft path (readIndex on the
            # live quorum) returns v1: that history IS linearizable
            h2 = History()
            tok = h2.invoke(0, "w", (key, b"v1"))
            h2.complete(tok, True)
            tok = h2.invoke(1, "r", (key,))
            h2.complete(tok, await kv.get(key))
            rep2 = check_history(h2)
            assert rep2.ok, str(rep2)
        finally:
            c.net.heal()
