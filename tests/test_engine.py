"""MultiRaftEngine integration: many groups per process, one batched
commit plane (the north-star configuration at test scale)."""

import asyncio

import pytest

from tests.cluster import MockStateMachine
from tpuraft.conf import Configuration
from tpuraft.core.engine import MultiRaftEngine, TpuBallotBox
from tpuraft.core.node import Node, State
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId, Task
from tpuraft.options import NodeOptions, TickOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


class MultiRaftCluster:
    """N endpoints x G groups; each endpoint hosts one replica of every
    group and ONE MultiRaftEngine batching all its groups' commits."""

    coalesce_heartbeats = False
    quiesce_after_rounds = 0  # >0: idle groups hibernate (quiescence)

    def __init__(self, n_endpoints: int, n_groups: int,
                 election_timeout_ms: int = 300, tick_ms: int = 5):
        self.net = InProcNetwork()
        self.endpoints = [PeerId.parse(f"127.0.0.1:{6000 + i}")
                          for i in range(n_endpoints)]
        self.conf = Configuration(list(self.endpoints))
        self.groups = [f"g{k}" for k in range(n_groups)]
        self.engines: dict[str, MultiRaftEngine] = {}
        self.nodes: dict[tuple[str, PeerId], Node] = {}
        self.fsms: dict[tuple[str, PeerId], MockStateMachine] = {}
        self.election_timeout_ms = election_timeout_ms
        self.tick_ms = tick_ms

    def _tick_options(self) -> TickOptions:
        # backend pinned to jax: conftest forces a CPU default backend,
        # where "auto" resolves to numpy — these tests exist to cover
        # the jax tick path.  Subclasses override for mesh sharding etc.
        return TickOptions(
            max_groups=len(self.groups) + 4, max_peers=8,
            tick_interval_ms=self.tick_ms, backend="jax")

    async def start_all(self):
        for ep in self.endpoints:
            server = RpcServer(ep.endpoint)
            manager = NodeManager(server)
            self.net.bind(server)
            transport = InProcTransport(self.net, ep.endpoint)
            engine = MultiRaftEngine(self._tick_options())
            await engine.start()
            self.engines[ep.endpoint] = engine
            factory = engine.ballot_box_factory()
            for gid in self.groups:
                fsm = MockStateMachine()
                self.fsms[(gid, ep)] = fsm
                opts = NodeOptions(
                    election_timeout_ms=self.election_timeout_ms,
                    initial_conf=self.conf.copy(),
                    fsm=fsm, log_uri="memory://", raft_meta_uri="memory://")
                opts.raft_options.coalesce_heartbeats = \
                    self.coalesce_heartbeats
                opts.raft_options.quiesce_after_rounds = \
                    self.quiesce_after_rounds
                node = Node(gid, ep, opts, transport,
                            ballot_box_factory=factory)
                node.node_manager = manager
                manager.add(node)
                assert await node.init()
                self.nodes[(gid, ep)] = node

    async def stop_all(self):
        for node in self.nodes.values():
            await node.shutdown()
        for engine in self.engines.values():
            await engine.shutdown()

    async def wait_leader(self, gid: str, timeout_s: float = 8.0) -> Node:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while loop.time() < deadline:
            leaders = [n for (g, ep), n in self.nodes.items()
                       if g == gid and n.state == State.LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError(f"no leader for {gid}")


async def test_engine_backed_cluster_replicates():
    c = MultiRaftCluster(3, 8)
    await c.start_all()
    try:
        leaders = {}
        for gid in c.groups:
            leaders[gid] = await c.wait_leader(gid)
        # apply one batch to every group's leader concurrently
        async def apply(gid, i):
            fut = asyncio.get_running_loop().create_future()
            await leaders[gid].apply(Task(
                data=b"%s-%d" % (gid.encode(), i),
                done=lambda st: fut.set_result(st)))
            st = await asyncio.wait_for(fut, 10)
            assert st.is_ok(), f"{gid}: {st}"

        await asyncio.gather(*[apply(g, i) for g in c.groups for i in range(5)])
        # every replica of every group applied all 5 entries
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 10
        def done():
            return all(len(f.logs) >= 5 for f in c.fsms.values())
        while loop.time() < deadline and not done():
            await asyncio.sleep(0.05)
        assert done(), {k: len(f.logs) for k, f in c.fsms.items()}
        for gid in c.groups:
            sets = [sorted(c.fsms[(gid, ep)].logs) for ep in c.endpoints]
            assert sets[0] == sets[1] == sets[2]
            assert len(sets[0]) == 5
        # the engine actually ticked and advanced commits (ack-path
        # eager advances + tick-discovered batch advances are both the
        # engine plane's work)
        assert any(e.ticks > 0
                   and e.commit_advances + e.eager_commits > 0
                   for e in c.engines.values())
    finally:
        await c.stop_all()


async def test_engine_failover():
    c = MultiRaftCluster(3, 4)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        fut = asyncio.get_running_loop().create_future()
        await leader.apply(Task(data=b"x", done=fut.set_result))
        assert (await asyncio.wait_for(fut, 10)).is_ok()
        # kill the whole endpoint hosting this group's leader (all its groups!)
        dead_ep = leader.server_id
        c.net.stop_endpoint(dead_ep.endpoint)
        for g in c.groups:
            n = c.nodes.pop((g, dead_ep))
            await n.shutdown()
        await c.engines.pop(dead_ep.endpoint).shutdown()
        self_net = c.net
        self_net.unbind(dead_ep.endpoint)
        leader2 = await c.wait_leader(gid, timeout_s=10)
        assert leader2.server_id != dead_ep
        fut2 = asyncio.get_running_loop().create_future()
        await leader2.apply(Task(data=b"y", done=fut2.set_result))
        assert (await asyncio.wait_for(fut2, 10)).is_ok()
    finally:
        await c.stop_all()


async def test_tpu_ballot_box_membership_conf_sync():
    """TpuBallotBox voter masks must track conf changes (remove_peer)."""
    c = MultiRaftCluster(3, 1)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        victim = next(ep for ep in c.endpoints if ep != leader.server_id)
        st = await asyncio.wait_for(leader.remove_peer(victim), 15)
        assert st.is_ok(), str(st)
        fut = asyncio.get_running_loop().create_future()
        await leader.apply(Task(data=b"post-change", done=fut.set_result))
        assert (await asyncio.wait_for(fut, 10)).is_ok()
        eng = c.engines[leader.server_id.endpoint]
        slot = leader.ballot_box.slot
        assert eng.voter_mask[slot].sum() == 2
    finally:
        await c.stop_all()


async def test_engine_scale_64_groups():
    """Multi-group scale tier (SURVEY.md §8 step 4: G in the thousands
    per process; test-scale 64): 3 endpoints x 64 groups = 192 nodes in
    one process, every endpoint batching all its groups' quorum math
    through ONE engine tick plane. One write per group, all of them
    committing through the batched [G, P] reduce."""
    c = MultiRaftCluster(3, 64, election_timeout_ms=400, tick_ms=2)
    await c.start_all()
    try:
        leaders = {}
        for gid in c.groups:
            leaders[gid] = await c.wait_leader(gid, timeout_s=20.0)

        async def put(gid, leader):
            fut = asyncio.get_running_loop().create_future()
            await leader.apply(Task(data=b"w-" + gid.encode(),
                                    done=lambda st: fut.set_result(st)))
            return await asyncio.wait_for(fut, 10.0)

        results = await asyncio.gather(
            *[put(g, ld) for g, ld in leaders.items()])
        assert all(st.is_ok() for st in results), \
            [str(s) for s in results if not s.is_ok()][:3]

        # every group's write must reach every replica's FSM
        deadline = asyncio.get_running_loop().time() + 15.0
        def done():
            return all(len(c.fsms[(g, ep)].logs) >= 1
                       for g in c.groups for ep in c.endpoints)
        while asyncio.get_running_loop().time() < deadline and not done():
            await asyncio.sleep(0.05)
        assert done()
        for g in c.groups:
            for ep in c.endpoints:
                assert c.fsms[(g, ep)].logs[-1] == b"w-" + g.encode()

        # the commits actually flowed through the engine plane (eager
        # ack-path advances + tick-discovered batch advances)
        total_advances = sum(e.commit_advances + e.eager_commits
                             for e in c.engines.values())
        assert total_advances >= len(c.groups), total_advances
    finally:
        await c.stop_all()


async def test_engine_mesh_sharded_quorum_matches_numpy():
    """mesh_devices shards the engine's [G, P] planes over the 8-device
    CPU mesh along the group axis; the SPMD quorum reduce must agree
    with the numpy oracle path for identical state."""
    import numpy as np

    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    G, P = 64, 8
    peers = [PID.parse(f"127.0.0.1:{7000 + i}") for i in range(3)]
    conf = Configuration(list(peers))

    def build(opts):
        eng = MultiRaftEngine(opts)
        boxes, commits = [], {}
        factory = eng.ballot_box_factory()
        for g in range(G):
            box = factory(lambda idx, g=g: commits.__setitem__(g, idx))
            box.update_conf(conf, Configuration())
            box.reset_pending_index(1)
            boxes.append(box)
        rng = np.random.default_rng(42)
        for g, box in enumerate(boxes):
            for p in peers:
                box.commit_at(p, int(rng.integers(0, 100)), conf,
                              Configuration())
        return eng, boxes, commits

    # eager_commit off: these tests pin the DEVICE reduce against the
    # numpy oracle — ack-path eager advances would commit everything
    # before either tick runs and collapse the comparison
    opts_np = TickOptions(max_groups=G, max_peers=P, backend="numpy",
                          eager_commit=False)
    eng_np, _, commits_np = build(opts_np)
    eng_np.tick_once()

    opts_mesh = TickOptions(max_groups=G, max_peers=P, backend="jax",
                            mesh_devices=8, eager_commit=False)
    eng_mesh, _, commits_mesh = build(opts_mesh)
    await eng_mesh.start()
    try:
        eng_mesh.tick_once()
        assert commits_mesh == commits_np
        assert len(commits_mesh) > 0  # something actually committed
    finally:
        await eng_mesh.shutdown()


async def test_engine_64k_groups_mesh_sharded_with_learners():
    """BASELINE config 5 at dry-run scale: 65536 groups (the 64K-region
    target), each 3 voters + 1 learner slot, quorum plane sharded over
    the 8-device CPU mesh — SPMD reduce must stay bit-identical to the
    numpy oracle across ticks, learner acks never counting toward
    quorum."""
    import numpy as np

    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    G, P = 65536, 8
    peers = [PID.parse(f"127.0.0.1:{7000 + i}") for i in range(3)]
    learner = PID.parse("127.0.0.1:7100")
    conf = Configuration(list(peers), [learner])

    def build(opts):
        eng = MultiRaftEngine(opts)
        commits = {}
        factory = eng.ballot_box_factory()
        boxes = []
        rng = np.random.default_rng(42)
        for g in range(G):
            box = factory(lambda idx, g=g: commits.__setitem__(g, idx))
            box.update_conf(conf, Configuration())
            box.reset_pending_index(1)
            boxes.append(box)
        for box in boxes:
            for p in peers:
                box.commit_at(p, int(rng.integers(0, 100)), conf,
                              Configuration())
            # learner acks far ahead of everyone: must not move quorum
            box.commit_at(learner, 10_000, conf, Configuration())
        return eng, boxes, commits

    opts_np = TickOptions(max_groups=G, max_peers=P, backend="numpy",
                          eager_commit=False)
    eng_np, boxes_np, commits_np = build(opts_np)
    eng_np.tick_once()

    opts_mesh = TickOptions(max_groups=G, max_peers=P, backend="jax",
                            mesh_devices=8, eager_commit=False)
    eng_mesh, boxes_mesh, commits_mesh = build(opts_mesh)
    await eng_mesh.start()
    try:
        eng_mesh.tick_once()
        assert commits_mesh == commits_np
        assert len(commits_mesh) > G * 0.99
        # learner-only progress on one group: quorum must not advance
        g_probe = 17
        before = commits_mesh.get(g_probe)
        for boxes, eng in ((boxes_np, eng_np), (boxes_mesh, eng_mesh)):
            boxes[g_probe].commit_at(learner, 20_000, conf, Configuration())
            eng.tick_once()
        assert commits_mesh.get(g_probe) == before
        assert commits_np.get(g_probe) == before
        # voter progress on a stride of groups: both planes agree again
        rng = np.random.default_rng(7)
        advances = {g: (100 + int(rng.integers(0, 50)),
                        100 + int(rng.integers(0, 50)))
                    for g in range(0, G, 5)}
        for boxes, eng in ((boxes_np, eng_np), (boxes_mesh, eng_mesh)):
            for g, (a, b) in advances.items():
                boxes[g].commit_at(peers[1], a, conf, Configuration())
                boxes[g].commit_at(peers[2], b, conf, Configuration())
            eng.tick_once()
        assert commits_mesh == commits_np
    finally:
        await eng_mesh.shutdown()


async def test_engine_adversarial_network_invariants():
    """The adversarial soak on the ENGINE plane: all groups' quorum math
    runs through the batched [G, P] device tick while the network drops,
    delays and one-way-partitions under sustained writes.  Invariants:
    election safety per group (never two leaders in one term), and at
    the end identical logs per group containing every acked entry
    exactly once."""
    import random
    import time
    from collections import Counter

    rng = random.Random(7)
    c = MultiRaftCluster(3, 6, election_timeout_ms=400)
    await c.start_all()
    try:
        for gid in c.groups:
            await c.wait_leader(gid)
        c.net.set_delay_ms(2)
        c.net.set_drop_rate(0.04)

        violations: list[str] = []
        stop = False

        async def monitor():
            while not stop:
                for gid in c.groups:
                    by_term: dict[int, list[str]] = {}
                    for (g, ep), n in c.nodes.items():
                        if g == gid and n.state == State.LEADER:
                            by_term.setdefault(n.current_term,
                                               []).append(str(ep))
                    for t, ls in by_term.items():
                        if len(ls) > 1:
                            violations.append(
                                f"{gid}: two leaders in term {t}: {ls}")
                await asyncio.sleep(0.01)

        acked: dict[str, list[bytes]] = {g: [] for g in c.groups}

        async def writer(gid, wid):
            i = 0
            while not stop:
                try:
                    leader = await c.wait_leader(gid, 3.0)
                    fut = asyncio.get_running_loop().create_future()
                    data = b"%s-w%d-%05d" % (gid.encode(), wid, i)
                    # done() guard: an entry may commit after wait_for
                    # gave up on (and cancelled) the future
                    await leader.apply(Task(
                        data=data,
                        done=lambda st: fut.done() or fut.set_result(st)))
                    st = await asyncio.wait_for(fut, 3.0)
                    if st.is_ok():
                        acked[gid].append(data)
                except Exception:
                    pass
                i += 1
                await asyncio.sleep(0.004)

        mon = asyncio.ensure_future(monitor())
        writers = [asyncio.ensure_future(writer(g, 0)) for g in c.groups]
        t0 = time.monotonic()
        while time.monotonic() - t0 < 8:
            await asyncio.sleep(1.5)
            a, b = rng.choice(c.endpoints), rng.choice(c.endpoints)
            if a != b:
                c.net.partition_one_way({a.endpoint}, {b.endpoint})
                await asyncio.sleep(0.5)
                c.net.heal()
        stop = True
        await asyncio.gather(*writers)
        mon.cancel()
        c.net.set_drop_rate(0)
        c.net.set_delay_ms(0)

        assert not violations, violations[:3]
        total_acked = sum(len(v) for v in acked.values())
        assert total_acked > 60, total_acked
        deadline = time.monotonic() + 20
        converged = set()
        while time.monotonic() < deadline and len(converged) < len(c.groups):
            for gid in c.groups:
                if gid in converged:
                    continue
                logs = [c.fsms[(gid, ep)].logs for ep in c.endpoints]
                if logs[0] == logs[1] == logs[2] \
                        and set(acked[gid]) <= set(logs[0]):
                    counts = Counter(logs[0])
                    if all(counts[a] == 1 for a in acked[gid]):
                        converged.add(gid)
            await asyncio.sleep(0.1)
        assert len(converged) == len(c.groups), \
            f"groups failed to converge: {set(c.groups) - converged}"
        # the device plane did the work: every engine ticked and advanced
        assert all(e.ticks > 0 for e in c.engines.values())
        assert any(e.commit_advances + e.eager_commits > 0
                   for e in c.engines.values())
    finally:
        await c.stop_all()


async def test_engine_grows_capacity_on_demand():
    """A full engine doubles its [G, P] planes instead of refusing the
    next group (region splits mint groups at runtime).  Existing slots'
    state must survive the growth and new slots must commit."""
    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    peers = [PID.parse(f"127.0.0.1:{7100 + i}") for i in range(3)]
    conf = Configuration(list(peers))
    for backend in ("numpy", "jax"):
        eng = MultiRaftEngine(TickOptions(
            max_groups=2, max_peers=4, backend=backend))
        await eng.start()
        try:
            commits: dict[int, int] = {}
            factory = eng.ballot_box_factory()
            boxes = []
            for g in range(5):          # 2 -> grows to 4 -> grows to 8
                box = factory(lambda idx, g=g: commits.__setitem__(g, idx))
                box.update_conf(conf, Configuration())
                box.reset_pending_index(1)
                boxes.append(box)
            assert eng.G == 8
            for g, box in enumerate(boxes):
                for p in peers:
                    box.commit_at(p, 10 + g, conf, Configuration())
            eng.tick_once()
            assert commits == {g: 10 + g for g in range(5)}, commits
            # slots released by shut-down groups are reused before growth
            eng.release(boxes[0])
            box5 = factory(lambda idx: commits.__setitem__(5, idx))
            assert eng.G == 8
            box5.update_conf(conf, Configuration())
            box5.reset_pending_index(1)
            for p in peers:
                box5.commit_at(p, 99, conf, Configuration())
            eng.tick_once()
            assert commits[5] == 99
        finally:
            await eng.shutdown()


async def test_engine_grows_under_mesh_sharding():
    """Growth preserves mesh divisibility: 8 groups over 8 devices grows
    to 16 and the SPMD reduce still matches the numpy oracle."""
    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    peers = [PID.parse(f"127.0.0.1:{7200 + i}") for i in range(3)]
    conf = Configuration(list(peers))
    eng = MultiRaftEngine(TickOptions(
        max_groups=8, max_peers=4, backend="jax", mesh_devices=8,
        eager_commit=False))
    ref = MultiRaftEngine(TickOptions(
        max_groups=8, max_peers=4, backend="numpy",
        eager_commit=False))
    await eng.start()
    try:
        got: dict[int, int] = {}
        want: dict[int, int] = {}
        for g in range(12):             # exceeds 8: grow to 16
            b1 = eng.ballot_box_factory()(
                lambda idx, g=g: got.__setitem__(g, idx))
            b2 = ref.ballot_box_factory()(
                lambda idx, g=g: want.__setitem__(g, idx))
            for b in (b1, b2):
                b.update_conf(conf, Configuration())
                b.reset_pending_index(1)
                for i, p in enumerate(peers):
                    b.commit_at(p, 3 * g + i, conf, Configuration())
        assert eng.G == 16
        eng.tick_once()
        ref.tick_once()
        assert got == want and len(got) == 12
    finally:
        await eng.shutdown()
        await ref.shutdown()


async def test_engine_profile_trace_written(tmp_path):
    """TickOptions.profile_dir captures an XLA profiler trace of the
    device ticks (SURVEY.md §6 tracing: jax.profiler for the device
    plane) — TensorBoard/Perfetto-viewable files appear on shutdown."""
    import os

    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    peers = [PID.parse(f"127.0.0.1:{7400 + i}") for i in range(3)]
    conf = Configuration(list(peers))
    eng = MultiRaftEngine(TickOptions(
        max_groups=4, max_peers=4, backend="jax",
        profile_dir=str(tmp_path / "trace")))
    await eng.start()
    try:
        box = eng.ballot_box_factory()(lambda idx: None)
        box.update_conf(conf, Configuration())
        box.reset_pending_index(1)
        for p in peers:
            box.commit_at(p, 7, conf, Configuration())
        eng.tick_once()
    finally:
        await eng.shutdown()
    found = []
    for root, _dirs, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "no profiler trace files written"


async def test_engine_describe():
    eng = MultiRaftEngine(TickOptions(max_groups=4, max_peers=4,
                                      backend="numpy"))
    await eng.start()
    try:
        eng.ballot_box_factory()(lambda idx: None)
        d = eng.describe()
        assert "G=4" in d and "used=1" in d and "backend=numpy" in d
    finally:
        await eng.shutdown()


async def test_engine_in_sigusr2_dump_and_second_trace_harmless(tmp_path):
    """Engines appear in the describer dump (the SIGUSR2 surface), and a
    second engine with profile_dir in the same process degrades to a
    warning instead of failing startup."""
    from tpuraft.util import describer

    e1 = MultiRaftEngine(TickOptions(max_groups=2, max_peers=4,
                                     backend="jax",
                                     profile_dir=str(tmp_path / "t1")))
    e2 = MultiRaftEngine(TickOptions(max_groups=2, max_peers=4,
                                     backend="jax",
                                     profile_dir=str(tmp_path / "t2")))
    await e1.start()
    await e2.start()          # must not raise despite the active trace
    try:
        dump = describer.dump_all()
        assert dump.count("MultiRaftEngine<") >= 2, dump
    finally:
        await e2.shutdown()
        await e1.shutdown()
    assert describer.dump_all().count("MultiRaftEngine<") == 0


async def test_engine_1k_groups_5_replicas():
    """BASELINE config 3: 1K groups x 5 voters, batched TpuBallotBox —
    the 5-replica quorum (3 of 5) through the jax tick matches the
    numpy oracle, including a minority (2-ack) stall case."""
    import numpy as np

    from tpuraft.conf import Configuration
    from tpuraft.entity import PeerId as PID

    G = 1024
    peers = [PID.parse(f"127.0.0.1:{7500 + i}") for i in range(5)]
    conf = Configuration(list(peers))

    def build(opts):
        eng = MultiRaftEngine(opts)
        commits = {}
        factory = eng.ballot_box_factory()
        rng = np.random.default_rng(3)
        boxes = []
        for g in range(G):
            box = factory(lambda idx, g=g: commits.__setitem__(g, idx))
            box.update_conf(conf, Configuration())
            box.reset_pending_index(1)
            # half the groups: all 5 ack; other half: only 2 ack (stall)
            ackers = peers if g % 2 == 0 else peers[:2]
            for p in ackers:
                box.commit_at(p, int(rng.integers(1, 90)), conf,
                              Configuration())
            boxes.append(box)
        return eng, commits

    # eager_commit off: the jax-vs-oracle reduce comparison is the
    # point (ack-path eager advances would pre-empt both ticks)
    eng_np, commits_np = build(TickOptions(
        max_groups=G, max_peers=8, backend="numpy",
        eager_commit=False))
    eng_np.tick_once()
    eng_jax, commits_jax = build(TickOptions(
        max_groups=G, max_peers=8, backend="jax",
        eager_commit=False))
    await eng_jax.start()
    try:
        eng_jax.tick_once()
        assert commits_jax == commits_np
        # exactly the all-ack half committed (2 of 5 is no quorum)
        assert len(commits_jax) == G // 2, len(commits_jax)
        assert all(g % 2 == 0 for g in commits_jax)
    finally:
        await eng_jax.shutdown()
        await eng_np.shutdown()


def test_set_conf_grace_window_for_added_peers():
    """A peer added mid-leadership gets a grace ack stamp: a NEG column
    would pin the joint q_ack reduce at NEG_INF ("no data"), so a dead
    NEW config could never fire step_down (r3 review finding)."""
    from tpuraft.core.engine import _NEG_I32

    eng = MultiRaftEngine(TickOptions(
        max_groups=4, max_peers=4, backend="numpy"))
    slot = eng.alloc_slot()
    a, b, c = (PeerId.parse(f"127.0.0.1:{p}") for p in (9001, 9002, 9003))
    eng.set_conf(slot, Configuration([a, b]), Configuration())
    from tpuraft.ops.tick import ROLE_LEADER

    eng.role[slot] = ROLE_LEADER
    eng.last_ack[slot, :2] = 5000  # established leadership acks
    # joint change adds c: its fresh column must be stamped, not NEG
    eng.set_conf(slot, Configuration([a, b, c]), Configuration([a, b]))
    col = eng.peer_col(slot, c)
    assert eng.last_ack[slot, col] > _NEG_I32
    # a follower slot's columns are untouched (grace is leader-only)
    slot2 = eng.alloc_slot()
    eng.set_conf(slot2, Configuration([a, b]), Configuration())
    assert (eng.last_ack[slot2, :2] <= _NEG_I32).all()


# -- density-aware timeout floors (ISSUE 4 tentpole part 4) ------------------

def test_density_floor_math_and_slot_application():
    """The derived floor must scale with registered group count and the
    configured per-beat cost, and raising a slot must scale hb/lease
    proportionally (the factor and ratio survive the raise)."""
    eng = MultiRaftEngine(TickOptions(
        max_groups=64, max_peers=4, backend="numpy", beat_cost_us=2000.0))
    eng.has_ctrl[:32] = True
    eng.voter_mask[:32, :3] = True
    eng.req_eto_ms[:32] = 1000
    eng.req_hb_ms[:32] = 100
    eng.req_lease_ms[:32] = 900
    floor = eng._density_floor_ms()
    # beat term: 32 groups x 2 followers x factor 10 x 2000us / (10% of
    # one core) = 12.8s — far above the requested 1s
    assert floor == 12800, floor
    eng._floor_applied_ms = floor
    eng._apply_floor_slot(0)
    assert int(eng.eto_ms[0]) == 12800
    assert int(eng.hb_ms[0]) == 1280      # factor 10 preserved
    assert int(eng.lease_ms[0]) == 11520  # 0.9 ratio preserved
    # a slot REQUESTING above the floor keeps its own values
    eng.req_eto_ms[1] = 60_000
    eng.req_hb_ms[1] = 6000
    eng.req_lease_ms[1] = 54_000
    eng._apply_floor_slot(1)
    assert int(eng.eto_ms[1]) == 60_000
    # disabled: floor is 0 regardless of density
    eng2 = MultiRaftEngine(TickOptions(
        max_groups=64, max_peers=4, backend="numpy",
        density_aware_timeouts=False, beat_cost_us=2000.0))
    eng2.has_ctrl[:32] = True
    eng2.voter_mask[:32, :3] = True
    assert eng2._density_floor_ms() == 0


def test_ctrl_count_survives_double_unregister_and_bare_boxes():
    """graftcheck-v2 burn regression: a controlled node's shutdown
    reaches unregister_ctrl TWICE (EngineControl.shutdown, then
    ballot_box.close -> release), and a bare commit-plane box releases
    without ever registering.  The unconditional decrement drifted
    _n_ctrls negative under churn, silencing the density-floor
    recompute trigger while real controlled density kept growing."""
    from tpuraft.entity import PeerId

    eng = MultiRaftEngine(TickOptions(max_groups=8, max_peers=3,
                                      backend="numpy"))
    # bare box (drive_protocol off / commit plane only): release must
    # not decrement a registration that never happened
    bare = eng.ballot_box_factory()(lambda i: None)
    bare.close()
    assert eng._n_ctrls == 0

    box = eng.ballot_box_factory()(lambda i: None)

    class _StubCtrl:
        slot = box.slot

        def _adopt_eto(self, eff):
            pass

    eng.register_ctrl(_StubCtrl(), PeerId.parse("127.0.0.1:6000"),
                      eto_ms=1000, hb_ms=100, lease_ms=900)
    assert eng._n_ctrls == 1
    eng.unregister_ctrl(box.slot)       # EngineControl.shutdown path
    assert eng._n_ctrls == 0
    box.close()                         # release path unregisters again
    assert eng._n_ctrls == 0, \
        "double unregister must not double-decrement"
    # the floor trigger keeps firing for later registration waves
    box2 = eng.ballot_box_factory()(lambda i: None)

    class _StubCtrl2:
        slot = box2.slot

        def _adopt_eto(self, eff):
            pass

    eng.register_ctrl(_StubCtrl2(), PeerId.parse("127.0.0.1:6001"),
                      eto_ms=1000, hb_ms=100, lease_ms=900)
    assert eng._n_ctrls == 1


async def test_density_floor_raises_live_cluster_timeouts():
    """End to end: groups registering into a dense engine must come up
    with RAISED effective timeouts (node options adopted, device rows
    scaled) — no hand-tuned 60s timeout — and still elect + commit."""

    class DenseCluster(MultiRaftCluster):
        # beat_cost cranked so even 8 groups x 3 replicas breaches the
        # budget: floor = 8 x 2 x 10 x 5000us / 100 = 8s > requested 300ms
        def _tick_options(self):
            opts = super()._tick_options()
            opts.beat_cost_us = 5000.0
            return opts

    c = DenseCluster(3, 8, election_timeout_ms=300)
    await c.start_all()
    try:
        gid = c.groups[0]
        node = c.nodes[(gid, c.endpoints[0])]
        eng = c.engines[c.endpoints[0].endpoint]
        slot = node._ctrl.slot
        assert int(eng.eto_ms[slot]) >= 8000, \
            "density floor did not raise the device row"
        assert node.options.election_timeout_ms >= 8000, \
            "node options did not adopt the raised timeout"
        assert node._ctrl._eto_ms == node.options.election_timeout_ms
        # the raised cluster still elects and commits (elections ride
        # the engine's boot deadlines, not a wall-clock 8s wait: the
        # initial elect_deadline was pushed pre-raise at ~300ms scale)
        leader = await c.wait_leader(gid, timeout_s=30.0)
        fut = asyncio.get_running_loop().create_future()
        await leader.apply(Task(data=b"dense", done=fut.set_result))
        assert (await asyncio.wait_for(fut, 15)).is_ok()
    finally:
        await c.stop_all()
