"""YAML config layer (SURVEY §6 "dataclass tree + YAML"; closes the
round-1 partial on the config/flag row)."""

import pytest

from tpuraft.config import hydrate, load_node_options, node_options_from_dict
from tpuraft.options import NodeOptions, RaftOptions, ReadOnlyOption


def test_nested_hydration_and_enums(tmp_path):
    p = tmp_path / "cluster.yaml"
    p.write_text("""
node:
  election_timeout_ms: 1500
  log_uri: multilog:///data/mlog#g1
  initial_conf: "127.0.0.1:8001,127.0.0.1:8002,127.0.0.1:8003"
  raft_options:
    max_inflight_msgs: 128
    coalesce_heartbeats: true
    read_only_option: lease_based
  tick:
    max_groups: 4096
    backend: auto
    pace_factor: 1
  snapshot:
    interval_secs: 600
""")
    opts = load_node_options(str(p))
    assert isinstance(opts, NodeOptions)
    assert opts.election_timeout_ms == 1500
    assert opts.log_uri == "multilog:///data/mlog#g1"
    assert len(opts.initial_conf.peers) == 3
    assert opts.raft_options.max_inflight_msgs == 128
    assert opts.raft_options.coalesce_heartbeats is True
    assert opts.raft_options.read_only_option is ReadOnlyOption.LEASE_BASED
    assert opts.tick.max_groups == 4096
    assert opts.tick.pace_factor == 1.0  # int -> float coercion
    assert opts.snapshot.interval_secs == 600
    # untouched fields keep dataclass defaults
    assert opts.raft_options.max_entries_size == \
        RaftOptions().max_entries_size


def test_unknown_key_raises():
    with pytest.raises(KeyError, match="election_timeout_msX"):
        node_options_from_dict({"election_timeout_msX": 5})
    with pytest.raises(KeyError, match="raft_options.max_inflightX"):
        node_options_from_dict(
            {"raft_options": {"max_inflightX": 1}})


def test_type_and_enum_errors():
    with pytest.raises(TypeError, match="election_timeout_ms"):
        node_options_from_dict({"election_timeout_ms": "soon"})
    with pytest.raises(ValueError, match="read_only_option"):
        node_options_from_dict(
            {"raft_options": {"read_only_option": "psychic"}})
    # YAML 1.1 'on'/'yes' -> True; booleans must not hydrate int/float
    with pytest.raises(TypeError, match="max_inflight_msgs"):
        node_options_from_dict(
            {"raft_options": {"max_inflight_msgs": True}})


def test_sibling_toplevel_keys_rejected(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("node:\n  election_timeout_ms: 500\ntick:\n"
                 "  max_groups: 64\n")
    with pytest.raises(KeyError, match="misindented"):
        load_node_options(str(p))


def test_hydrate_arbitrary_dataclass():
    from tpuraft.rheakv.pd_server import PlacementDriverOptions

    opts = hydrate(PlacementDriverOptions, {
        "endpoints": ["127.0.0.1:7001"],
        "split_threshold_keys": 5000,
        "balance_leaders": True,
    })
    assert opts.split_threshold_keys == 5000 and opts.balance_leaders
