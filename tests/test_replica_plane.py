"""Replica-axis collective commit plane (VERDICT r1 #6): co-located
replicas on a 2D (replica, groups) CPU mesh, commits computed by
tpuraft.parallel.collective's all_gather + order-statistic from each
replica's DURABLE log state over many real protocol steps."""

import asyncio

import numpy as np
import pytest

from tpuraft.entity import Task
from tpuraft.parallel.replica_cluster import ReplicaPlaneCluster
from tpuraft.parallel.replica_plane import ReplicatedClusterPlane


async def _apply_ok(node, data, t=10.0):
    fut = asyncio.get_running_loop().create_future()
    await node.apply(Task(data=data, done=fut.set_result))
    st = await asyncio.wait_for(fut, t)
    assert st.is_ok(), st


def _mesh_2d():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    return Mesh(np.array(devs[:8]).reshape(2, 4), ("replica", "groups"))


async def test_multi_step_commits_through_collectives():
    """The VERDICT done-when: a MULTI-STEP CPU-mesh cluster commits real
    entries through collective.py — 4 replicas x 8 groups, 3 waves of
    writes, every commit decided by the replica-axis all_gather."""
    mesh = _mesh_2d()
    c = ReplicaPlaneCluster(4, 8, mesh=mesh)
    await c.start_all()
    try:
        leaders = {g: await c.wait_leader(g) for g in c.groups}
        for wave in range(3):
            await asyncio.gather(*(
                _apply_ok(leaders[g], b"%s-w%d-%d" % (g.encode(), wave, i))
                for g in c.groups for i in range(5)))
        # the plane's collective tick drove the commits over many steps
        assert c.plane.ticks >= 3
        assert c.plane.commit_advances >= len(c.groups)
        # all replicas converge
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            if all(len(f.logs) >= 15 for f in c.fsms.values()):
                break
            await asyncio.sleep(0.05)
        for g in c.groups:
            logs = [c.fsms[(g, ep)].logs for ep in c.endpoints]
            assert all(lg == logs[0] for lg in logs)
            assert len(logs[0]) == 15
    finally:
        await c.stop_all()


async def test_commits_survive_replica_loss_quorum_math():
    """Kill one of 4 replicas: the collective order statistic still
    finds a 3/4 quorum; kill two: commits stall (no quorum)."""
    mesh = _mesh_2d()
    c = ReplicaPlaneCluster(4, 4, mesh=mesh)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _apply_ok(leader, b"before")
        # crash a non-leader replica endpoint entirely
        victim = next(ep for ep in c.endpoints if ep != leader.server_id)
        c.net.stop_endpoint(victim.endpoint)
        for (g, ep) in [k for k in c.nodes if k[1] == victim]:
            await c.nodes.pop((g, ep)).shutdown()
        await _apply_ok(leader, b"with-3-of-4", t=10)
        # second loss: 2/4 cannot commit
        victim2 = next(ep for ep in c.endpoints
                       if ep != leader.server_id and ep != victim)
        c.net.stop_endpoint(victim2.endpoint)
        for (g, ep) in [k for k in c.nodes if k[1] == victim2]:
            await c.nodes.pop((g, ep)).shutdown()
        fut = asyncio.get_running_loop().create_future()
        await leader.apply(Task(data=b"stalls", done=fut.set_result))
        try:
            st = await asyncio.wait_for(fut, 1.5)
            # the dead-quorum step-down may fail the entry first —
            # either way it must NOT commit
            assert not st.is_ok(), f"committed without quorum: {st}"
        except asyncio.TimeoutError:
            pass
    finally:
        await c.stop_all()


async def test_unattested_rows_never_count():
    """SAFETY: a replica whose accepted_term does not match the leader's
    lineage is masked out of the reduce even with a high durable tip
    (the stale-divergent-suffix hazard)."""
    plane = ReplicatedClusterPlane(3, 2, mesh=None)

    committed = []
    factory = plane.ballot_box_factory("g0", 0)
    box = factory(committed.append)
    box.note_attested(5)                 # leader at term 5
    box.reset_pending_index(11)          # own entries start at 11
    plane.match[0, 0] = 15               # leader durable through 15
    # replica 1: attested to term 5, durable through 15 -> quorum of 2
    plane.accepted_term[1, 0] = 5
    plane.match[1, 0] = 15
    # replica 2: STALE-HIGH row from a divergent suffix, attested to an
    # older term -> must not count
    plane.accepted_term[2, 0] = 3
    plane.match[2, 0] = 40
    plane.tick_once()
    assert committed and committed[-1] == 15, committed
    # now break replica 1's attestation too: commit must NOT advance
    plane.accepted_term[1, 0] = 0
    plane.match[0, 0] = 20
    plane.match[1, 0] = 20
    before = list(committed)
    plane.tick_once()
    assert committed == before, "unattested rows advanced the commit"
    # re-attest -> advances
    plane.accepted_term[1, 0] = 5
    plane.tick_once()
    assert committed[-1] == 20


async def test_truncation_lowers_match_row():
    """SAFETY regression: a suffix truncation must LOWER the plane row —
    exact-tip on_stable semantics, not a monotone max (else the reduce
    counts truncated entries toward the quorum)."""
    from tpuraft.entity import EntryType, LogEntry, LogId
    from tpuraft.storage.log_manager import LogManager
    from tpuraft.storage.log_storage import MemoryLogStorage

    plane = ReplicatedClusterPlane(3, 1, mesh=None)
    box = plane.ballot_box_factory("g0", 1)(lambda i: None)
    lm = LogManager(MemoryLogStorage())
    await lm.init()
    box.attach_log_manager(lm)
    entries = [LogEntry(type=EntryType.DATA, id=LogId(i, 2), data=b"x")
               for i in range(1, 41)]
    await lm.append_entries_leader(entries, term=2)
    await lm.flush_staged(40)
    assert plane.match[1, 0] == 40
    # new leader truncates the divergent suffix via a follower append
    ok = await lm.append_entries_follower(
        10, 2, [LogEntry(type=EntryType.DATA, id=LogId(11, 3), data=b"y")])
    assert ok
    assert plane.match[1, 0] == 11, plane.match[1, 0]
    await lm.shutdown()


async def test_numpy_fallback_matches_mesh_path():
    """The plane without a mesh (numpy oracle) and with the CPU mesh
    produce identical commit points on random state."""
    mesh = _mesh_2d()
    rng = np.random.default_rng(0)
    R, G = 4, 8
    for trial in range(5):
        match = rng.integers(0, 100, (R, G))
        p_np = ReplicatedClusterPlane(R, G, mesh=None)
        p_mx = ReplicatedClusterPlane(R, G, mesh=mesh)
        from tpuraft.parallel.collective import replicated_tick

        p_mx._fn = replicated_tick(mesh, R)
        for p in (p_np, p_mx):
            p.match[:, :] = match
            p.accepted_term[:, :] = 7
            p.leader_replica[:] = 0
        commits = []
        for p in (p_np, p_mx):
            # leader boxes on replica 0 for every group
            for g in range(G):
                b = p.ballot_box_factory(f"t{trial}g{g}", 0)(lambda i: None)
                b.pending_index = 1
            p.tick_once()
            commits.append(p.commit_abs.copy())
        np.testing.assert_array_equal(commits[0], commits[1])


async def test_transport_seam_tcp():
    """The protocol plane above the replica-axis collective rides real
    sockets (VERDICT r3 #8): same cluster, loopback TCP transport,
    including a replica crash + failover."""
    c = ReplicaPlaneCluster(3, 4, election_timeout_ms=600,
                            transport="tcp", base_port=7750)
    await c.start_all()
    try:
        leaders = {g: await c.wait_leader(g) for g in c.groups}
        await asyncio.gather(*(
            c.apply_ok(leaders[g], b"%s-tcp" % g.encode())
            for g in c.groups))
        # crash one replica endpoint; groups fail over over TCP
        lead_count = {ep.endpoint: 0 for ep in c.endpoints}
        for g in c.groups:
            lead_count[leaders[g].server_id.endpoint] += 1
        victim = min(c.endpoints, key=lambda ep: lead_count[ep.endpoint])
        await c.stop_replica(victim)
        for g in c.groups:
            n = await c.wait_leader(g, timeout_s=20)
            await c.apply_ok(n, b"%s-post" % g.encode())
    finally:
        await c.stop_all()
