"""True multi-process end-to-end: 3 CounterServer OS processes over real
TCP, a client in this process, and a kill -9 of the LEADER process.

The strongest tier above the in-process TestCluster pattern: separate
interpreters, real sockets, real crash (SIGKILL, no graceful shutdown),
durable on-disk state. Reference analog: running CounterServer mains on
three machines (example:counter — SURVEY.md §3.3).
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.asyncio
async def test_three_process_cluster_kill9_leader(tmp_path):
    ports = _free_ports(3)
    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs: dict[int, subprocess.Popen] = {}
    env = dict(os.environ, PYTHONPATH=REPO)
    try:
        for p in ports:
            procs[p] = subprocess.Popen(
                [sys.executable, "-m", "examples.counter",
                 "--serve", f"127.0.0.1:{p}", "--peers", peers,
                 "--data", str(tmp_path / str(p))],
                cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        from examples.counter import CounterClient
        from tpuraft.conf import Configuration

        conf = Configuration.parse(peers)
        client = CounterClient(conf)
        try:
            # interpreter start is ~2s each (sitecustomize imports jax);
            # the client retry loop rides out boot + first election.
            # The client's retry on a timed-out (but applied) increment
            # is NOT idempotent, so assert monotonicity + linearizable
            # read agreement rather than exact values.
            deadline = time.monotonic() + 60
            value = None
            while time.monotonic() < deadline:
                try:
                    value = await client.increment_and_get()
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert value is not None and value >= 1, value
            for _ in range(4):
                nxt = await client.increment_and_get()
                assert nxt > value, (nxt, value)
                value = nxt
            assert await client.get() == value

            # find the leader process and SIGKILL it — no graceful path
            leader = await client._find_leader()
            procs[leader.port].send_signal(signal.SIGKILL)
            procs[leader.port].wait()
            client._leader = None

            # survivors re-elect; acked state survives the hard crash
            deadline = time.monotonic() + 30
            v = None
            while time.monotonic() < deadline:
                try:
                    v = await client.increment_and_get(10)
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert v is not None and v >= value + 10, (v, value)
            assert await client.get() == v
        finally:
            await client.transport.close()
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs.values():
            proc.wait()
