"""The KV engine's LSM tier (VERDICT r1 #7 — the RocksDB >RAM role):
memtable spills to immutable sorted runs at a byte budget, reads merge
memtable -> runs newest-first with point/range tombstones, background
compaction folds runs, and recovery replays at most one memtable of WAL.

The headline proof: a dataset SEVERAL TIMES the memtable budget passes
point reads, forward/reverse scans, and kill -9 recovery.
"""

import os
import random
import signal
import subprocess
import sys
import time

import pytest

from tpuraft.rheakv.native_store import NativeRawKVStore, ensure_built


@pytest.fixture(scope="module", autouse=True)
def built():
    ensure_built()


BUDGET = 64 * 1024  # tiny budget so tests hit many spills fast


def mk(tmp_path, budget=BUDGET, max_runs=4, sync=False):
    return NativeRawKVStore(str(tmp_path / "lsm"), sync=sync,
                            memtable_budget_bytes=budget, max_runs=max_runs)


def test_dataset_many_times_budget(tmp_path):
    """~16x the memtable budget: spills + compactions happen, every key
    reads back, scans see the merged ordered view."""
    s = mk(tmp_path)
    try:
        n = 4096
        val = b"v" * 200  # ~220B/entry -> ~900KB total vs 64KB budget
        for i in range(n):
            s.put(b"k%06d" % i, val + b"%06d" % i)
        assert s.run_count >= 1, "no spill happened"
        assert s.mem_bytes < BUDGET * 2
        # point reads across the whole keyspace (mem + every run era)
        for i in (0, 1, 777, 2048, 4000, n - 1):
            assert s.get(b"k%06d" % i) == val + b"%06d" % i
        assert s.get(b"nope") is None
        # merged forward scan: ordered, complete
        rows = s.scan(b"k002000", b"k002100")
        assert [k for k, _ in rows] == [b"k%06d" % i
                                        for i in range(2000, 2100)]
        # reverse scan through run files
        rows = s.reverse_scan(b"k000100", b"k000110")
        assert [k for k, _ in rows] == [b"k%06d" % i
                                        for i in range(109, 99, -1)]
    finally:
        s.close()


def test_overwrites_and_tombstones_across_runs(tmp_path):
    s = mk(tmp_path)
    try:
        # era 1: keys 0..499 -> spilled
        for i in range(500):
            s.put(b"x%04d" % i, b"old" + b"." * 200)
        s.checkpoint()  # force spill
        r1 = s.run_count
        # era 2: overwrite evens, delete multiples of 5
        for i in range(0, 500, 2):
            s.put(b"x%04d" % i, b"new%04d" % i)
        for i in range(0, 500, 5):
            s.delete(b"x%04d" % i)
        s.checkpoint()
        assert s.run_count > r1
        # merged truth
        assert s.get(b"x0004") == b"new0004"
        assert s.get(b"x0005") is None           # deleted (odd, /5)
        assert s.get(b"x0010") is None           # deleted (even, /5)
        assert s.get(b"x0003") == b"old" + b"." * 200  # untouched odd
        live = {k for k, _ in s.scan(b"x", b"y")}
        want = {b"x%04d" % i for i in range(500) if i % 5 != 0}
        assert live == want
    finally:
        s.close()


def test_delete_range_masks_older_runs(tmp_path):
    s = mk(tmp_path)
    try:
        for i in range(300):
            s.put(b"r%04d" % i, b"v" * 300)
        s.checkpoint()  # all in a run
        s.delete_range(b"r0100", b"r0200")
        # range tombstone lives in the memtable, masking the run
        assert s.get(b"r0150") is None
        assert s.get(b"r0099") is not None
        assert s.get(b"r0200") is not None
        keys = [k for k, _ in s.scan(b"r0090", b"r0210")]
        assert keys == [b"r%04d" % i for i in
                        list(range(90, 100)) + list(range(200, 210))]
        # a put AFTER the range delete wins
        s.put(b"r0150", b"back")
        assert s.get(b"r0150") == b"back"
        # spill the tombstone itself; masking must survive in the run
        s.checkpoint()
        assert s.get(b"r0151") is None
        assert s.get(b"r0150") == b"back"
    finally:
        s.close()


def test_compaction_folds_runs_and_drops_tombstones(tmp_path):
    s = mk(tmp_path, max_runs=3)
    try:
        for wave in range(8):
            for i in range(200):
                s.put(b"c%04d" % i, b"w%d." % wave + b"f" * 150)
            for i in range(0, 200, 3):
                s.delete(b"c%04d" % i)
            s.checkpoint()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and s.run_count > 3:
            time.sleep(0.1)
        assert s.run_count <= 3, f"compaction never folded: {s.run_count}"
        # post-compaction truth
        assert s.get(b"c0003") is None
        assert s.get(b"c0004") == b"w7." + b"f" * 150
        assert len(s.scan(b"c", b"d")) == sum(
            1 for i in range(200) if i % 3 != 0)
    finally:
        s.close()


def test_reopen_recovers_runs_and_memtable(tmp_path):
    s = mk(tmp_path)
    for i in range(1000):
        s.put(b"p%05d" % i, b"d" * 150)
    s.delete(b"p00500")
    runs_before = s.run_count
    s.close()
    s = mk(tmp_path)
    try:
        assert s.run_count == runs_before
        assert s.get(b"p00499") == b"d" * 150
        assert s.get(b"p00500") is None
        assert len(s.scan(b"p", b"q")) == 999
    finally:
        s.close()


_KILL_WRITER = r"""
import sys
sys.path.insert(0, {repo!r})
from tpuraft.rheakv.native_store import NativeRawKVStore
s = NativeRawKVStore({dir!r}, sync=False, memtable_budget_bytes=32768,
                     max_runs=3)
print("READY", flush=True)
i = 0
while True:
    s.put(b"kill%07d" % i, b"payload" * 30)
    if i % 7 == 0 and i > 0:
        s.delete(b"kill%07d" % (i - 1))
    i += 1
"""


def test_kill9_during_spills_and_compactions(tmp_path):
    """kill -9 while spills and background compactions are in flight:
    reopen must serve a consistent prefix (every surviving key complete,
    no corruption), several times over."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = str(tmp_path / "lsm")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    for round_i in range(2):
        script = _KILL_WRITER.format(repo=repo, dir=d)
        p = subprocess.Popen([sys.executable, "-c", script],
                             stdout=subprocess.PIPE, env=env)
        try:
            assert p.stdout.readline().strip() == b"READY"
            time.sleep(1.2)
        finally:
            p.send_signal(signal.SIGKILL)
            p.wait()
        s = NativeRawKVStore(d, sync=False, memtable_budget_bytes=32768,
                             max_runs=3)
        try:
            rows = s.scan(b"kill", b"kilm")
            assert len(rows) > 50, "writer made no progress"
            for k, v in rows:
                assert v == b"payload" * 30, k
            # deleted keys stay deleted across the crash
            idx = sorted(int(k[4:]) for k, _ in rows)
            present = set(idx)
            for i in idx:
                if i % 7 == 1 and (i + 6) in present and i + 1 <= max(idx):
                    pass  # deletions are racy vs the kill point; spot
                          # integrity is what matters here
        finally:
            s.close()


def test_compaction_io_bounded_by_tier_not_store(tmp_path):
    """VERDICT r2 #7 done-when: with size-tiered pick-K, a compaction
    cycle's input bytes track the small spill tier — they do NOT scale
    with total store size (merge-all did O(dataset) per cycle)."""
    s = mk(tmp_path, budget=32 * 1024, max_runs=4)
    try:
        # phase 1: bulk-load well past the budget -> a big bottom tier
        val = b"B" * 150
        for i in range(12000):
            s.put(b"big%06d" % i, val)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and s.run_count > 4:
            time.sleep(0.05)
        store_bytes = s.data_bytes
        assert store_bytes > 1_000_000, store_bytes

        # phase 2: keep writing; later cycles must merge only the fresh
        # small-spill window, never rewrite the big bottom run
        comp0 = s.compactions
        cum0 = s.compact_input_bytes
        last_inputs = []
        i = 0
        deadline = time.monotonic() + 45
        while len(last_inputs) < 6 and time.monotonic() < deadline:
            s.put(b"new%06d" % i, val)
            i += 1
            if s.compactions > comp0 + len(last_inputs):
                last_inputs.append(s.compact_last_input_bytes)
        assert len(last_inputs) == 6, "compactions never ran in phase 2"
        # merge-all rewrote the WHOLE store every cycle: each cycle's
        # input >= store size and 6 cycles >= 6x store.  Size-tiered
        # pick-K merges small-tier windows (with occasional log-
        # amortized consolidations), so every cycle stays strictly
        # under the store and the cumulative input stays far under
        # merge-all's bill.  (Cycle inputs vary with the tier phase —
        # assert the envelope, not individual samples.)
        store = s.data_bytes
        cum = s.compact_input_bytes - cum0
        assert all(b < store for b in last_inputs), (last_inputs, store)
        assert cum < 3 * store, (cum, store, last_inputs)
        # the big bottom tier was built in phase 1 and must not be part
        # of every phase-2 cycle: at least one cycle merged only
        # small-tier runs (impossible under merge-all)
        assert min(last_inputs) < store / 2, (last_inputs, store)
        # truth unaffected
        assert s.get(b"big000000") == val
        assert s.get(b"big011999") == val
        assert s.get(b"new000000") == val
    finally:
        s.close()


def test_upper_tier_merge_keeps_tombstones_masking_bottom(tmp_path):
    """A NON-bottom merge must retain point/range tombstones: they still
    mask live values in runs below the window (elision is bottom-only)."""
    s = mk(tmp_path, budget=16 * 1024, max_runs=3)
    try:
        val = b"V" * 120
        # bottom tier: 600 keys, folded down by compaction
        for i in range(600):
            s.put(b"t%05d" % i, val)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and s.run_count > 3:
            time.sleep(0.05)
        # upper tiers: deletes of bottom keys + churn to force merges of
        # windows that do NOT include the bottom run
        for i in range(0, 600, 2):
            s.delete(b"t%05d" % i)
        s.delete_range(b"t00500", b"t00550")
        for w in range(6):
            for i in range(300):
                s.put(b"z%05d" % i, b"w%d" % w + val)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and s.run_count > 3:
            time.sleep(0.05)
        # the deletes must keep masking the bottom values through every
        # merge shape (bottom and non-bottom windows)
        assert s.get(b"t00000") is None
        assert s.get(b"t00001") == val
        assert s.get(b"t00501") is None          # range-deleted (odd)
        assert s.get(b"t00551") == val
        live_t = [k for k, _ in s.scan(b"t", b"u")]
        want = [b"t%05d" % i for i in range(600)
                if i % 2 == 1 and not (500 <= i < 550)]
        assert live_t == want
    finally:
        s.close()


def test_lsm_dir_refuses_legacy_open(tmp_path):
    """Opening an LSM-tiered directory without LSM params must fail
    loudly (ADVICE r2): a legacy open would silently ignore the manifest
    and every run — reads miss the dataset and the next checkpoint
    durably excludes it."""
    s = mk(tmp_path)
    try:
        val = b"v" * 200
        for i in range(2048):  # several spills past the 64KB budget
            s.put(b"k%06d" % i, val)
        assert s.run_count >= 1
    finally:
        s.close()
    with pytest.raises(IOError, match="LSM"):
        NativeRawKVStore(str(tmp_path / "lsm"), sync=False,
                         memtable_budget_bytes=0)
    # reopening WITH LSM params still works and sees the data
    s2 = mk(tmp_path)
    try:
        assert s2.get(b"k000000") == val
        assert s2.get(b"k002047") == val
    finally:
        s2.close()


def test_legacy_mode_untouched(tmp_path):
    """memtable_budget=0 keeps the original engine (no manifest, no
    runs, checkpoint file semantics)."""
    s = NativeRawKVStore(str(tmp_path / "legacy"), sync=False)
    try:
        for i in range(100):
            s.put(b"l%03d" % i, b"v")
        s.checkpoint()
        assert s.run_count == 0
        assert os.path.exists(str(tmp_path / "legacy" / "checkpoint"))
        assert not os.path.exists(str(tmp_path / "legacy" / "manifest"))
    finally:
        s.close()
