"""Sharded tick + collective quorum tests over the 8-device virtual mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpuraft.ops.ballot import quorum_match_index  # noqa: E402
from tpuraft.ops.tick import (  # noqa: E402
    ROLE_LEADER,
    GroupState,
    TickOutputs,
    TickParams,
    raft_tick,
)
from tpuraft.parallel.collective import replicated_tick  # noqa: E402
from tpuraft.parallel.mesh import make_mesh, shard_group_state, sharded_tick  # noqa: E402

_NEG = -(2**30)


def _rand_full_state(rng, g, p):
    """Randomized GroupState with EVERY field populated — the same
    distribution as test_ops_tick's numpy-twin differential, so all the
    ISSUE 19 lanes (witness clamp, stepdown cadence, read fences,
    quiescence) are live in the sharded comparison too."""
    s = GroupState.zeros(g, p)
    s.role = jnp.asarray(rng.integers(0, 4, g).astype(np.int32))
    s.commit_rel = jnp.asarray(rng.integers(0, 40, g).astype(np.int32))
    s.pending_rel = jnp.asarray(rng.integers(1, 20, g).astype(np.int32))
    s.match_rel = jnp.asarray(rng.integers(0, 100, (g, p)).astype(np.int32))
    s.granted = jnp.asarray(rng.random((g, p)) < 0.4)
    s.voter_mask = jnp.asarray(rng.random((g, p)) < 0.7)
    s.old_voter_mask = jnp.asarray(np.where(
        (rng.random(g) < 0.2)[:, None], rng.random((g, p)) < 0.5, False))
    s.elect_deadline = jnp.asarray(rng.integers(0, 2500, g).astype(np.int32))
    s.hb_deadline = jnp.asarray(rng.integers(0, 2500, g).astype(np.int32))
    s.last_ack = jnp.asarray(np.where(
        rng.random((g, p)) < 0.8,
        rng.integers(0, 1500, (g, p)), _NEG).astype(np.int32))
    s.snap_deadline = jnp.asarray(rng.integers(0, 3000, g).astype(np.int32))
    s.quiescent = jnp.asarray(rng.random(g) < 0.3)
    s.witness_mask = jnp.asarray(rng.random((g, p)) < 0.2)
    s.stepdown_deadline = jnp.asarray(
        rng.integers(0, 2500, g).astype(np.int32))
    s.fence_start = jnp.asarray(np.where(
        rng.random(g) < 0.4, rng.integers(0, 1500, g), _NEG).astype(np.int32))
    return s


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


def test_sharded_tick_matches_local():
    mesh = make_mesh()
    G, P = 64, 8
    rng = np.random.default_rng(0)
    s = GroupState.zeros(G, P)
    s.role = jnp.full((G,), ROLE_LEADER, jnp.int32)
    s.voter_mask = jnp.asarray(rng.random((G, P)) < 0.7)
    s.match_rel = jnp.asarray(rng.integers(0, 100, (G, P)).astype(np.int32))
    s.pending_rel = jnp.ones((G,), jnp.int32)
    params = TickParams.make(1000, 100, 900)

    from tpuraft.ops.tick import raft_tick

    _, expect = raft_tick(s, jnp.int32(5), params)

    tick = sharded_tick(mesh, donate=False)
    sh = shard_group_state(GroupState.zeros(G, P), mesh)
    sh.role, sh.voter_mask, sh.match_rel, sh.pending_rel = (
        s.role, s.voter_mask, s.match_rel, s.pending_rel)
    sh = shard_group_state(s, mesh)
    ns, out = tick(sh, jnp.int32(5), params)
    np.testing.assert_array_equal(np.asarray(out.commit_rel),
                                  np.asarray(expect.commit_rel))
    np.testing.assert_array_equal(np.asarray(out.elected),
                                  np.asarray(expect.elected))
    # result stays sharded over the mesh
    assert len(out.commit_rel.sharding.device_set) == 8


def test_replicated_tick_psum_quorum():
    """Cross-replica quorum over a (replica=2, groups=4) mesh — collectives
    execute for real across the 8 virtual devices."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("replica", "groups"))
    R, G = 2, 16
    rng = np.random.default_rng(1)
    match = rng.integers(0, 50, (R, G)).astype(np.int32)
    granted = rng.random((R, G)) < 0.5
    run = replicated_tick(mesh, n_replicas=R)
    commit, votes = run(jnp.asarray(match), jnp.asarray(granted))
    # oracle: q-th largest of each column; vote counts per column
    q = R // 2 + 1
    want_commit = np.sort(match, axis=0)[::-1][q - 1]
    want_votes = granted.sum(axis=0)
    np.testing.assert_array_equal(np.asarray(commit), want_commit)
    np.testing.assert_array_equal(np.asarray(votes), want_votes)


def test_replicated_tick_3_replicas():
    from jax.sharding import Mesh

    # replica axis not a divisor trick: use (1,8) mesh, R folds locally
    devs = np.array(jax.devices()).reshape(1, 8)
    mesh = Mesh(devs, ("replica", "groups"))
    R, G = 3, 32
    rng = np.random.default_rng(2)
    match = rng.integers(0, 1000, (R, G)).astype(np.int32)
    granted = rng.random((R, G)) < 0.6
    run = replicated_tick(mesh, n_replicas=R)
    commit, votes = run(jnp.asarray(match), jnp.asarray(granted))
    q = 2
    want_commit = np.sort(match, axis=0)[::-1][q - 1]
    np.testing.assert_array_equal(np.asarray(commit), want_commit)
    np.testing.assert_array_equal(np.asarray(votes), granted.sum(axis=0))


def test_sharded_tick_bitwise_matches_single_device_multiround():
    """ISSUE 19 acceptance: the 8-way group-sharded tick must stay
    BIT-IDENTICAL to the single-device tick across MULTI-ROUND state
    evolution with every [G] lane populated (witness masks, stepdown
    deadlines, read fences, quiescence, joint configs).  Odd rounds
    feed each path's own carried state straight back in (the sharded
    arrays stay resident on the mesh); even-round boundaries apply one
    seeded host perturbation — fresh acks, appended entries, newly
    armed fences — identically to both, so commits keep advancing and
    deadline re-arms keep firing instead of the state going quiescent
    after round one."""
    mesh = make_mesh()
    G, P = 64, 5
    rng = np.random.default_rng(1907)
    local = _rand_full_state(rng, G, P)
    sh = shard_group_state(local, mesh)
    tick_sh = sharded_tick(mesh, donate=False)
    params = TickParams.make(1000, 100, 900, 1500)
    state_fields = list(GroupState.__dataclass_fields__)
    out_fields = list(TickOutputs.__dataclass_fields__)
    now = 100
    for r in range(8):
        now += int(rng.integers(120, 400))
        nl, ol = raft_tick(local, jnp.int32(now), params)
        ns, os_ = tick_sh(sh, jnp.int32(now), params)
        for f in state_fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(ns, f)), np.asarray(getattr(nl, f)),
                err_msg=f"round {r}: new_state.{f} diverged")
        for f in out_fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(os_, f)), np.asarray(getattr(ol, f)),
                err_msg=f"round {r}: outputs.{f} diverged")
        # the carried result never silently gathers back to one device
        assert len(ns.commit_rel.sharding.device_set) == 8
        local, sh = nl, ns
        if r % 2 == 1:
            h = jax.tree_util.tree_map(np.asarray, nl)
            h.match_rel = (h.match_rel
                           + rng.integers(0, 6, (G, P))).astype(np.int32)
            h.last_ack = np.where(rng.random((G, P)) < 0.5, now,
                                  h.last_ack).astype(np.int32)
            h.granted = rng.random((G, P)) < 0.4
            h.fence_start = np.where(
                rng.random(G) < 0.3, now - rng.integers(0, 200, G),
                h.fence_start).astype(np.int32)
            local = jax.tree_util.tree_map(jnp.asarray, h)
            sh = shard_group_state(local, mesh)


def test_sharded_deadline_fold_matches_host_scan():
    """The mesh-mode earliest-deadline reduction (one collective min)
    must agree with the engine's host-side numpy scan
    (MultiRaftEngine._next_deadline) on random role/quiescence/ctrl
    mixes — including the stepdown-deadline row ISSUE 19 added to both
    formulations — and return the DEADLINE_NONE_I32 sentinel when no
    slot schedules anything."""
    from tpuraft.parallel.mesh import DEADLINE_NONE_I32, sharded_deadline_fold

    mesh = make_mesh()
    fold = sharded_deadline_fold(mesh)
    rng = np.random.default_rng(3)
    G = 128
    for trial in range(8):
        role = rng.integers(0, 4, G).astype(np.int32)
        quiescent = rng.random(G) < 0.3
        has_ctrl = rng.random(G) < 0.7
        elect = rng.integers(0, 1 << 20, G).astype(np.int32)
        hb = rng.integers(0, 1 << 20, G).astype(np.int32)
        stepdown = rng.integers(0, 1 << 20, G).astype(np.int32)
        got = int(fold(role, quiescent, has_ctrl, elect, hb, stepdown))
        awake = has_ctrl & ~quiescent
        ec = awake & (role <= 1)
        ld = awake & (role == 2)
        want = int(DEADLINE_NONE_I32)
        if ec.any():
            want = min(want, int(elect[ec].min()))
        if ld.any():
            want = min(want, int(hb[ld].min()))
            want = min(want, int(stepdown[ld].min()))
        assert got == want, f"trial {trial}: fold {got} != host scan {want}"
    # every slot uncontrolled -> the sentinel, not a garbage min
    none = int(fold(np.full(G, 2, np.int32), np.zeros(G, bool),
                    np.zeros(G, bool), elect, hb, stepdown))
    assert none == int(DEADLINE_NONE_I32)
