"""Sharded tick + collective quorum tests over the 8-device virtual mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpuraft.ops.ballot import quorum_match_index  # noqa: E402
from tpuraft.ops.tick import (  # noqa: E402
    ROLE_LEADER,
    GroupState,
    TickParams,
)
from tpuraft.parallel.collective import replicated_tick  # noqa: E402
from tpuraft.parallel.mesh import make_mesh, shard_group_state, sharded_tick  # noqa: E402


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


def test_sharded_tick_matches_local():
    mesh = make_mesh()
    G, P = 64, 8
    rng = np.random.default_rng(0)
    s = GroupState.zeros(G, P)
    s.role = jnp.full((G,), ROLE_LEADER, jnp.int32)
    s.voter_mask = jnp.asarray(rng.random((G, P)) < 0.7)
    s.match_rel = jnp.asarray(rng.integers(0, 100, (G, P)).astype(np.int32))
    s.pending_rel = jnp.ones((G,), jnp.int32)
    params = TickParams.make(1000, 100, 900)

    from tpuraft.ops.tick import raft_tick

    _, expect = raft_tick(s, jnp.int32(5), params)

    tick = sharded_tick(mesh, donate=False)
    sh = shard_group_state(GroupState.zeros(G, P), mesh)
    sh.role, sh.voter_mask, sh.match_rel, sh.pending_rel = (
        s.role, s.voter_mask, s.match_rel, s.pending_rel)
    sh = shard_group_state(s, mesh)
    ns, out = tick(sh, jnp.int32(5), params)
    np.testing.assert_array_equal(np.asarray(out.commit_rel),
                                  np.asarray(expect.commit_rel))
    np.testing.assert_array_equal(np.asarray(out.elected),
                                  np.asarray(expect.elected))
    # result stays sharded over the mesh
    assert len(out.commit_rel.sharding.device_set) == 8


def test_replicated_tick_psum_quorum():
    """Cross-replica quorum over a (replica=2, groups=4) mesh — collectives
    execute for real across the 8 virtual devices."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("replica", "groups"))
    R, G = 2, 16
    rng = np.random.default_rng(1)
    match = rng.integers(0, 50, (R, G)).astype(np.int32)
    granted = rng.random((R, G)) < 0.5
    run = replicated_tick(mesh, n_replicas=R)
    commit, votes = run(jnp.asarray(match), jnp.asarray(granted))
    # oracle: q-th largest of each column; vote counts per column
    q = R // 2 + 1
    want_commit = np.sort(match, axis=0)[::-1][q - 1]
    want_votes = granted.sum(axis=0)
    np.testing.assert_array_equal(np.asarray(commit), want_commit)
    np.testing.assert_array_equal(np.asarray(votes), want_votes)


def test_replicated_tick_3_replicas():
    from jax.sharding import Mesh

    # replica axis not a divisor trick: use (1,8) mesh, R folds locally
    devs = np.array(jax.devices()).reshape(1, 8)
    mesh = Mesh(devs, ("replica", "groups"))
    R, G = 3, 32
    rng = np.random.default_rng(2)
    match = rng.integers(0, 1000, (R, G)).astype(np.int32)
    granted = rng.random((R, G)) < 0.6
    run = replicated_tick(mesh, n_replicas=R)
    commit, votes = run(jnp.asarray(match), jnp.asarray(granted))
    q = 2
    want_commit = np.sort(match, axis=0)[::-1][q - 1]
    np.testing.assert_array_equal(np.asarray(commit), want_commit)
    np.testing.assert_array_equal(np.asarray(votes), granted.sum(axis=0))
