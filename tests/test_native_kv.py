"""Native C++ KV storage engine tests.

Mirrors the reference's RocksRawKVStoreTest tier (SURVEY.md §5 "Storage
unit"): real engine on a temp dir, torn down per test, plus the
crash-recovery drives the reference gets from RocksDB's own WAL tests.
"""

import os
import signal
import struct
import subprocess
import sys
import textwrap
import time

import pytest

from tpuraft.rheakv.native_store import (
    NativeRawKVStore,
    create_raw_kv_store,
    ensure_built,
)
from tpuraft.rheakv.raw_store import MemoryRawKVStore


@pytest.fixture(scope="module", autouse=True)
def built():
    ensure_built()


@pytest.fixture
def store(tmp_path):
    s = NativeRawKVStore(str(tmp_path / "kv"))
    yield s
    s.close()


def test_basic_point_ops(store):
    assert store.get(b"a") is None
    store.put(b"a", b"1")
    store.put(b"b", b"2")
    assert store.get(b"a") == b"1"
    assert store.contains_key(b"b")
    assert store.put_if_absent(b"a", b"x") == b"1"
    assert store.get(b"a") == b"1"
    assert store.get_and_put(b"a", b"3") == b"1"
    assert store.compare_and_put(b"a", b"3", b"4")
    assert not store.compare_and_put(b"a", b"nope", b"5")
    store.merge(b"m", b"x")
    store.merge(b"m", b"y")
    assert store.get(b"m") == b"x,y"
    store.delete(b"a")
    assert store.get(b"a") is None
    assert store.multi_get([b"b", b"zz"]) == {b"b": b"2", b"zz": None}


def test_scan_and_ranges(store):
    store.put_list([(bytes([i]), bytes([i]) * 2) for i in range(10)])
    rows = store.scan(bytes([2]), bytes([5]))
    assert [k for k, _ in rows] == [bytes([2]), bytes([3]), bytes([4])]
    assert rows[0][1] == bytes([2, 2])
    # open-ended + limit + keys-only
    rows = store.scan(b"", b"", limit=3, return_value=False)
    assert [k for k, _ in rows] == [bytes([0]), bytes([1]), bytes([2])]
    assert rows[0][1] is None
    rev = store.reverse_scan(bytes([2]), bytes([5]))
    assert [k for k, _ in rev] == [bytes([4]), bytes([3]), bytes([2])]
    assert store.approximate_keys_in_range(bytes([1]), bytes([4])) == 3
    assert store.jump_over(b"", b"", 4) == bytes([4])
    store.delete_range(bytes([3]), bytes([8]))
    assert [k for k, _ in store.scan(b"", b"")] == [
        bytes([0]), bytes([1]), bytes([2]), bytes([8]), bytes([9])]


def test_binary_safe_keys_values(store):
    k = b"\x00\xff\x00 embedded"
    v = bytes(range(256))
    store.put(k, v)
    assert store.get(k) == v
    assert store.scan(b"\x00", b"\x01")[0] == (k, v)


def test_sequences_and_locks_persist(tmp_path):
    s = NativeRawKVStore(str(tmp_path / "kv"))
    seq = s.get_sequence(b"ids", 10)
    assert (seq.start, seq.end) == (0, 10)
    assert s.get_sequence(b"ids", 5).start == 10
    ok, token, owner = s.try_lock_with(b"L", b"me", 60_000, False)
    assert ok and owner == b"me"
    ok2, token2, owner2 = s.try_lock_with(b"L", b"other", 60_000, False)
    assert not ok2 and owner2 == b"me" and token2 == token
    s.close()

    s = NativeRawKVStore(str(tmp_path / "kv"))  # reopen: WAL replay
    assert s.get_sequence(b"ids", 0).start == 15
    ok3, token3, owner3 = s.try_lock_with(b"L", b"other", 1000, False)
    assert not ok3 and owner3 == b"me"  # lease survives restart
    assert s.release_lock(b"L", b"me")
    ok4, token4, _ = s.try_lock_with(b"L", b"other", 1000, False)
    assert ok4 and token4 > token  # fencing token monotonic across restart
    s.close()


def test_reentrant_lock(store):
    ok, t1, _ = store.try_lock_with(b"L", b"me", 60_000, False)
    ok, t2, _ = store.try_lock_with(b"L", b"me", 60_000, False)
    assert ok and t1 == t2
    assert store.release_lock(b"L", b"me")
    ok, _, owner = store.try_lock_with(b"L", b"other", 1000, False)
    assert not ok and owner == b"me"  # still held: acquired twice
    assert store.release_lock(b"L", b"me")
    ok, _, _ = store.try_lock_with(b"L", b"other", 1000, False)
    assert ok


def test_checkpoint_and_reopen(tmp_path):
    s = NativeRawKVStore(str(tmp_path / "kv"))
    s.put_list([(f"k{i}".encode(), f"v{i}".encode()) for i in range(100)])
    assert s.wal_bytes() > 0
    s.checkpoint()
    assert s.wal_bytes() == 0
    s.put(b"after", b"ckpt")
    s.close()
    s = NativeRawKVStore(str(tmp_path / "kv"))  # checkpoint + WAL replay
    assert s.get(b"k42") == b"v42"
    assert s.get(b"after") == b"ckpt"
    assert len(s.scan(b"", b"")) == 101
    s.close()


def test_auto_checkpoint_threshold(tmp_path):
    s = NativeRawKVStore(str(tmp_path / "kv"), checkpoint_wal_bytes=4096)
    for i in range(200):
        s.put(f"k{i:04}".encode(), b"x" * 64)
    assert s.wal_bytes() < 4096 + 2048  # truncated at least once
    s.close()
    s = NativeRawKVStore(str(tmp_path / "kv"))
    assert len(s.scan(b"", b"")) == 200
    s.close()


def test_torn_wal_tail_dropped(tmp_path):
    path = str(tmp_path / "kv")
    s = NativeRawKVStore(path)
    s.put(b"good", b"1")
    s.put(b"torn", b"2")
    s.close()
    # corrupt the last record's payload byte
    wal = os.path.join(path, "wal.log")
    blob = bytearray(open(wal, "rb").read())
    blob[-1] ^= 0xFF
    open(wal, "wb").write(bytes(blob))
    s = NativeRawKVStore(path)
    assert s.get(b"good") == b"1"
    assert s.get(b"torn") is None  # torn tail dropped cleanly
    s.put(b"new", b"3")  # and appending after recovery works
    s.close()
    s = NativeRawKVStore(path)
    assert s.get(b"new") == b"3"
    s.close()


def test_oversized_wal_length_field_dropped(tmp_path):
    """A corrupted header whose length field reads huge must be treated
    as a torn tail (the header is not self-checksummed) — not trigger a
    multi-GB allocation that aborts the reopening process."""
    path = str(tmp_path / "kv")
    s = NativeRawKVStore(path)
    s.put(b"good", b"1")
    s.close()
    wal = os.path.join(path, "wal.log")
    blob = open(wal, "rb").read()
    # append a frame claiming 0xFFFFFFF0 payload bytes
    open(wal, "ab").write(struct.pack("=II", 0xFFFFFFF0, 0xDEADBEEF))
    s = NativeRawKVStore(path)
    assert s.get(b"good") == b"1"
    s.close()
    assert os.path.getsize(wal) == len(blob)  # bogus frame truncated away
    s = NativeRawKVStore(path)
    s.put(b"new", b"2")
    s.close()
    s = NativeRawKVStore(path)
    assert s.get(b"new") == b"2"
    s.close()


def test_kill9_mid_write_recovers(tmp_path):
    """The reference's durability contract: kill -9 a writer mid-stream,
    reopen, and the surviving prefix is contiguous and uncorrupted."""
    path = str(tmp_path / "kv")
    code = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
        from tpuraft.rheakv.native_store import NativeRawKVStore
        s = NativeRawKVStore({path!r})
        i = 0
        while True:
            s.put(b"k%08d" % i, b"v%08d" % i)
            i += 1
    """)
    proc = subprocess.Popen([sys.executable, "-c", code])
    # wait for REAL bytes on disk, not a fixed sleep: interpreter boot
    # (~2s of sitecustomize jax imports) stretches arbitrarily under
    # full-suite CPU contention
    deadline = time.time() + 60
    while time.time() < deadline:
        total = 0
        if os.path.isdir(path):
            total = sum(
                os.path.getsize(os.path.join(path, f))
                for f in os.listdir(path))
        if total > 200_000:
            break
        time.sleep(0.2)
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    s = NativeRawKVStore(path)
    rows = s.scan(b"", b"")
    assert len(rows) > 0, "writer never wrote"
    for n, (k, v) in enumerate(rows):
        assert k == b"k%08d" % n and v == b"v%08d" % n
    s.close()


def test_snapshot_blob_interchange(tmp_path):
    """serialize_range blobs round-trip between the native and memory
    engines (snapshot install may land on either)."""
    nat = NativeRawKVStore(str(tmp_path / "kv"))
    nat.put_list([(f"k{i}".encode(), f"v{i}".encode()) for i in range(20)])
    nat.get_sequence(b"k5seq", 7)
    nat.try_lock_with(b"k7lock", b"me", 60_000, False)
    blob = nat.serialize_range(b"", b"")

    mem = MemoryRawKVStore()
    mem.load_serialized(blob)
    assert mem.get(b"k9") == b"v9"
    assert mem.get_sequence(b"k5seq", 0).start == 7
    ok, _, owner = mem.try_lock_with(b"k7lock", b"other", 1000, False)
    assert not ok and owner == b"me"

    # and back: memory -> native
    blob2 = mem.serialize_range(b"", b"")
    nat2 = NativeRawKVStore(str(tmp_path / "kv2"))
    nat2.load_serialized(blob2)
    assert nat2.get(b"k9") == b"v9"
    assert nat2.get_sequence(b"k5seq", 0).start == 7
    nat.close()
    nat2.close()


def test_reset_range_clears_all_namespaces(tmp_path):
    """Snapshot load = exact state reset: sequences/locks created after
    the snapshot must not survive a reset_range (replay determinism)."""
    for make in (lambda: NativeRawKVStore(str(tmp_path / "kv")),
                 MemoryRawKVStore):
        s = make()
        s.put(b"ka", b"1")
        s.get_sequence(b"kseq", 10)
        s.try_lock_with(b"klock", b"me", 60_000, False)
        s.put(b"za", b"outside")  # different range: must survive
        s.get_sequence(b"zseq", 5)
        s.reset_range(b"k", b"l")
        assert s.get(b"ka") is None
        assert s.get_sequence(b"kseq", 0).start == 0
        ok, _, _ = s.try_lock_with(b"klock", b"other", 1000, False)
        assert ok  # lock gone
        assert s.get(b"za") == b"outside"
        assert s.get_sequence(b"zseq", 0).start == 5
        if hasattr(s, "close"):
            s.close()


def test_use_after_close_raises(tmp_path):
    s = NativeRawKVStore(str(tmp_path / "kv"))
    s.put(b"a", b"1")
    s.close()
    with pytest.raises(IOError):
        s.get(b"a")
    with pytest.raises(IOError):
        s.put(b"b", b"2")
    s.close()  # idempotent


def test_factory_uri(tmp_path):
    s = create_raw_kv_store(f"native://{tmp_path}/kv")
    assert isinstance(s, NativeRawKVStore)
    s.put(b"a", b"b")
    assert s.get(b"a") == b"b"
    s.close()
    assert isinstance(create_raw_kv_store("memory://"), MemoryRawKVStore)
    with pytest.raises(ValueError):
        create_raw_kv_store("bogus://x")


@pytest.mark.asyncio
async def test_kv_cluster_on_native_engine(tmp_path):
    """Full RheaKV region cluster with the native engine under every
    store: put/get/scan/sequence/lock through raft."""
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.client import RheaKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    c = KVTestCluster(
        3, raw_store_factory=lambda ep: NativeRawKVStore(
            str(tmp_path / ep.replace(":", "_"))))
    await c.start_all()
    try:
        await c.wait_region_leader(1)
        pd = FakePlacementDriverClient(
            [r.copy() for s in [next(iter(c.stores.values()))]
             for r in s.list_regions()])
        client = RheaKVStore(pd, c.client_transport())
        await client.start()
        try:
            assert await client.put(b"alpha", b"1")
            assert await client.put(b"beta", b"2")
            assert await client.get(b"alpha") == b"1"
            rows = await client.scan(b"", b"")
            assert [k for k, _ in rows] == [b"alpha", b"beta"]
            seq = await client.get_sequence(b"s", 100)
            assert seq.end == 100
        finally:
            await client.shutdown()
        # the data actually lives in the native engines
        leader = await c.wait_region_leader(1)
        raw = leader.store_engine.raw_store
        assert isinstance(raw, NativeRawKVStore)
    finally:
        await c.stop_all()
