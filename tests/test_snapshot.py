"""Snapshot subsystem tests (reference: SnapshotExecutorTest,
LocalSnapshotStorageTest, NodeTest snapshot+install cases — SURVEY.md §5).
"""

import asyncio

import pytest

from tests.cluster import MockStateMachine, TestCluster
from tpuraft.core.node import State
from tpuraft.entity import PeerId
from tpuraft.rpc.messages import SnapshotMeta
from tpuraft.storage.snapshot import LocalSnapshotStorage


class TestLocalSnapshotStorage:
    def test_roundtrip(self, tmp_path):
        s = LocalSnapshotStorage(str(tmp_path))
        s.init()
        assert s.open() is None
        w = s.create()
        w.write_file("a", b"alpha")
        w.write_file("b", b"beta" * 100)
        s.commit(w, SnapshotMeta(last_included_index=7, last_included_term=2,
                                 peers=["1.1.1.1:1"]))
        r = s.open()
        assert r is not None
        assert r.load_meta().last_included_index == 7
        assert r.read_file("a") == b"alpha"
        assert r.read_file("b") == b"beta" * 100
        assert r.read_file("missing") is None

    def test_only_newest_kept(self, tmp_path):
        s = LocalSnapshotStorage(str(tmp_path))
        s.init()
        for idx in (5, 9):
            w = s.create()
            w.write_file("d", b"x%d" % idx)
            s.commit(w, SnapshotMeta(last_included_index=idx))
        assert len(s._snapshot_dirs()) == 1
        assert s.open().load_meta().last_included_index == 9

    def test_corrupt_file_detected(self, tmp_path):
        s = LocalSnapshotStorage(str(tmp_path))
        s.init()
        w = s.create()
        w.write_file("d", b"payload")
        path = s.commit(w, SnapshotMeta(last_included_index=3))
        (tmp_path / "snapshot_3" / "d").write_bytes(b"tampered")
        r = s.open()
        with pytest.raises(IOError):
            r.read_file("d")

    def test_chunked_read(self, tmp_path):
        s = LocalSnapshotStorage(str(tmp_path))
        s.init()
        w = s.create()
        w.write_file("big", bytes(range(256)) * 10)
        s.commit(w, SnapshotMeta(last_included_index=1))
        r = s.open()
        out = bytearray()
        off = 0
        while True:
            data, eof = r.read_chunk("big", off, 100)
            out += data
            off += len(data)
            if eof:
                break
        assert bytes(out) == bytes(range(256)) * 10


async def test_snapshot_save_and_restart_recovery(tmp_path):
    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(20):
        await c.apply_ok(leader, b"e%d" % i)
    await c.wait_applied(20)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    assert c.fsms[leader.server_id].snapshots_saved == 1
    # log compacted behind the snapshot
    assert leader.log_manager.first_log_index() > 1
    # more entries after the snapshot
    for i in range(20, 25):
        await c.apply_ok(leader, b"e%d" % i)
    await c.wait_applied(25)
    await c.stop_all()
    # restart: leader-side node must restore from snapshot + log tail
    c2 = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    c2.net = c.net
    await c2.start_all()
    leader2 = await c2.wait_leader()
    await c2.apply_ok(leader2, b"e25")
    await c2.wait_applied(26)
    for p in c2.peers:
        assert c2.fsms[p].logs == [b"e%d" % i for i in range(26)], str(p)
    # at least one node loaded from snapshot rather than replaying all
    assert any(c2.fsms[p].snapshots_loaded > 0 for p in c2.peers)
    await c2.stop_all()


async def test_install_snapshot_to_lagging_follower(tmp_path):
    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(p for p in c.peers if p != leader.server_id)
    await c.apply_ok(leader, b"s0")
    await c.wait_applied(1)
    # crash one follower, write + snapshot + compact so the log is gone
    await c.stop(victim)
    for i in range(1, 15):
        await c.apply_ok(leader, b"s%d" % i)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    assert (leader.log_manager.first_log_index()
            == leader.fsm_caller.last_applied_index + 1)
    # follower comes back: too far behind the compacted log -> InstallSnapshot
    # (drain first: a pre-compaction entry frame still in flight would
    # legally catch the victim up via the log path — the r4 flake)
    await c.drain_sends_to(leader, victim.endpoint)
    await c.start(victim)
    await c.wait_applied(15, timeout_s=10)
    assert c.fsms[victim].logs == [b"s%d" % i for i in range(15)]
    assert c.fsms[victim].snapshots_loaded >= 1
    await c.stop_all()


async def test_snapshot_nothing_new_rejected(tmp_path):
    c = TestCluster(1, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"x")
    await c.wait_applied(1)
    st = await leader.snapshot()
    assert st.is_ok()
    st2 = await leader.snapshot()
    assert not st2.is_ok()  # nothing new
    await c.stop_all()


async def test_periodic_snapshot_timer_compacts(tmp_path):
    """The snapshot timer (reference: snapshotIntervalSecs, default 3600)
    must fire on its own, save a snapshot, and compact the log — no
    explicit Node#snapshot call."""
    c = TestCluster(3, tmp_path=tmp_path, snapshot=True,
                    snapshot_interval_secs=1)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(10):
        await c.apply_ok(leader, b"p%d" % i)
    await c.wait_applied(10)
    deadline = asyncio.get_running_loop().time() + 6
    while asyncio.get_running_loop().time() < deadline:
        if c.fsms[leader.server_id].snapshots_saved >= 1:
            break
        await asyncio.sleep(0.1)
    assert c.fsms[leader.server_id].snapshots_saved >= 1
    # compaction follows the periodic save
    deadline = asyncio.get_running_loop().time() + 3
    while asyncio.get_running_loop().time() < deadline:
        if leader.log_manager.first_log_index() > 1:
            break
        await asyncio.sleep(0.1)
    assert leader.log_manager.first_log_index() > 1
    # the cluster still serves writes afterwards
    st = await c.apply_ok(leader, b"post-snap")
    assert st.is_ok()
    await c.stop_all()


async def test_install_snapshot_filter_before_copy(tmp_path):
    """Files the follower's latest LOCAL snapshot already holds with
    identical name+size+crc are copied locally during InstallSnapshot,
    not re-downloaded (reference: LocalSnapshotCopier#filterBeforeCopy).
    An FSM with a large constant blob + small changing state ships only
    the changed file."""
    from tests.cluster import MockStateMachine
    from tpuraft.errors import Status

    BLOB = bytes(range(256)) * 256          # 64KB, never changes

    class TwoFileFSM(MockStateMachine):
        async def on_snapshot_save(self, writer, done) -> None:
            import struct
            blob = struct.pack("<I", len(self.logs)) + b"".join(
                struct.pack("<I", len(x)) + x for x in self.logs)
            writer.write_file("data", blob)
            writer.write_file("constant-blob", BLOB)
            self.snapshots_saved += 1
            done(Status.OK())

        async def on_snapshot_load(self, reader) -> bool:
            assert reader.read_file("constant-blob") == BLOB
            return await super().on_snapshot_load(reader)

    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    for p in c.peers:
        c.fsms[p] = TwoFileFSM()
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(p for p in c.peers if p != leader.server_id)
    for i in range(3):
        await c.apply_ok(leader, b"f%d" % i)
    await c.wait_applied(3)
    # the victim takes its OWN local snapshot (so it holds the blob)
    st = await c.nodes[victim].snapshot()
    assert st.is_ok(), str(st)
    # victim crashes; leader moves on and compacts past its log
    await c.stop(victim)
    for i in range(3, 16):
        await c.apply_ok(leader, b"f%d" % i)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    # back up: too far behind -> InstallSnapshot; blob must be reused
    await c.start(victim, fsm=TwoFileFSM())
    await c.wait_applied(16, timeout_s=10)
    node = c.nodes[victim]
    reused = node.metrics.snapshot().get("counters", {}).get(
        "install-snapshot-files-reused")
    assert reused == 1, node.metrics.snapshot()
    assert c.fsms[victim].logs == [b"f%d" % i for i in range(16)]
    await c.stop_all()


async def test_filter_before_copy_rejects_rotted_local_file(tmp_path):
    """A local snapshot file whose on-disk bytes rotted after its
    manifest crc was recorded must NOT be reused: the install detects
    the rot on its crc-verified local read and falls back to the
    network copy.  The rot lives in a file the FSM does not touch at
    load time, so startup recovery stays healthy and the install path
    is what meets it."""
    import glob
    import struct

    from tests.cluster import MockStateMachine
    from tpuraft.errors import Status

    BLOB = bytes(range(256)) * 256          # reusable, stays intact
    AUX = b"\x5a" * 4096                    # reusable, gets rotted

    class ThreeFileFSM(MockStateMachine):
        async def on_snapshot_save(self, writer, done) -> None:
            blob = struct.pack("<I", len(self.logs)) + b"".join(
                struct.pack("<I", len(x)) + x for x in self.logs)
            writer.write_file("data", blob)
            writer.write_file("constant-blob", BLOB)
            writer.write_file("aux-blob", AUX)
            self.snapshots_saved += 1
            done(Status.OK())
        # on_snapshot_load: MockStateMachine reads only "data" — the
        # rotted aux-blob is never read at startup

    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    for p in c.peers:
        c.fsms[p] = ThreeFileFSM()
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(p for p in c.peers if p != leader.server_id)
    for i in range(3):
        await c.apply_ok(leader, b"r%d" % i)
    await c.wait_applied(3)
    st = await c.nodes[victim].snapshot()
    assert st.is_ok(), str(st)
    await c.stop(victim)
    # rot the victim's local aux-blob on disk (crc recorded at save time)
    pat = f"{tmp_path}/{victim.ip}_{victim.port}/snapshot/snapshot_*/aux-blob"
    paths = glob.glob(pat)
    assert paths, pat
    with open(paths[0], "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff\xff")
    for i in range(3, 16):
        await c.apply_ok(leader, b"r%d" % i)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    await c.start(victim, fsm=ThreeFileFSM())
    await c.wait_applied(16, timeout_s=10)
    node = c.nodes[victim]
    # only constant-blob reused; the rotted aux-blob fell back to the
    # network, and the installed snapshot's aux bytes are the leader's
    reused = node.metrics.snapshot().get("counters", {}).get(
        "install-snapshot-files-reused")
    assert reused == 1, node.metrics.snapshot()
    from tpuraft.storage.snapshot import SnapshotReader
    snaps = sorted(glob.glob(
        f"{tmp_path}/{victim.ip}_{victim.port}/snapshot/snapshot_*"))
    reader = SnapshotReader(snaps[-1])
    assert reader.read_file("aux-blob") == AUX
    assert reader.read_file("constant-blob") == BLOB
    assert c.fsms[victim].logs == [b"r%d" % i for i in range(16)]
    await c.stop_all()


async def test_install_recovers_from_stale_partial_temp(tmp_path):
    """A crash mid-InstallSnapshot leaves a partial temp dir; on
    restart the temp is ignored by snapshot discovery and the next
    install clears it and succeeds (reference: LocalSnapshotStorage
    temp handling)."""
    import os

    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(p for p in c.peers if p != leader.server_id)
    await c.apply_ok(leader, b"t0")
    await c.wait_applied(1)
    await c.stop(victim)
    # simulate a crash mid-install: partial temp with junk files
    snap_root = f"{tmp_path}/{victim.ip}_{victim.port}/snapshot"
    temp = os.path.join(snap_root, "temp")
    os.makedirs(temp, exist_ok=True)
    with open(os.path.join(temp, "data"), "wb") as f:
        f.write(b"half-written garbage")
    with open(os.path.join(temp, "unrelated-file"), "wb") as f:
        f.write(b"x" * 100)
    # leader moves on and compacts so the victim needs an install
    for i in range(1, 15):
        await c.apply_ok(leader, b"t%d" % i)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    await c.drain_sends_to(leader, victim.endpoint)  # r4 flake guard
    await c.start(victim)
    await c.wait_applied(15, timeout_s=10)
    assert c.fsms[victim].logs == [b"t%d" % i for i in range(15)]
    assert c.fsms[victim].snapshots_loaded >= 1
    # the stale junk did not leak into the installed snapshot
    snaps = [d for d in os.listdir(snap_root) if d.startswith("snapshot_")]
    assert snaps, os.listdir(snap_root)
    newest = os.path.join(snap_root, sorted(
        snaps, key=lambda d: int(d.split("_")[1]))[-1])
    assert "unrelated-file" not in os.listdir(newest)
    await c.stop_all()


async def test_install_under_write_load(tmp_path):
    """InstallSnapshot races the hot replication pipeline: periodic
    snapshots compact the log while a crashed follower misses several
    intervals of writes, then recovers by install DURING sustained
    load — converging to identical logs with every acked entry exactly
    once."""
    import time
    from collections import Counter

    c = TestCluster(3, tmp_path=tmp_path, snapshot=True,
                    snapshot_interval_secs=1, election_timeout_ms=400)
    await c.start_all()
    await c.wait_leader()
    acked = []
    stop = False

    async def writer(wid):
        i = 0
        while not stop:
            data = b"iw%d-%05d" % (wid, i)
            try:
                leader = await c.wait_leader(3.0)
                st = await c.apply_ok(leader, data, timeout_s=3.0)
                if st.is_ok():
                    acked.append(data)
            except Exception:
                pass
            i += 1
            await asyncio.sleep(0.004)

    ws = [asyncio.ensure_future(writer(w)) for w in range(2)]
    try:
        for _round in range(2):
            await asyncio.sleep(1.0)
            leader = await c.wait_leader(5.0)
            victim = next(p for p in c.peers
                          if p != leader.server_id and p in c.nodes)
            await c.stop(victim)
            await asyncio.sleep(2.5)   # 2+ snapshot intervals of writes
            await c.start(victim)
    finally:
        stop = True
        await asyncio.gather(*ws)
    deadline = time.monotonic() + 30
    ok = False
    while time.monotonic() < deadline:
        logs = [c.fsms[p].logs for p in c.peers if p in c.nodes]
        if len(logs) == 3 and logs[0] == logs[1] == logs[2] \
                and set(acked) <= set(logs[0]):
            ok = True
            break
        await asyncio.sleep(0.2)
    assert ok, "no convergence after install-under-load"
    counts = Counter(logs[0])
    assert all(counts[k] == 1 for k in acked)
    assert len(acked) > 100, len(acked)
    # the recovery path under test actually ran: at least one victim
    # came back via a REMOTE InstallSnapshot (the node-side counter —
    # fsm.snapshots_loaded would also count plain boot-time loads of a
    # node's own local snapshot)
    installs = sum(
        n.metrics.snapshot().get("counters", {}).get(
            "install-snapshot-received", 0)
        for n in c.nodes.values())
    assert installs >= 1, "no InstallSnapshot occurred — vacuous run"
    await c.stop_all()


async def test_add_peer_behind_compacted_log_installs_snapshot(tmp_path):
    """Adding a FRESH voter after the leader compacted its log: the
    joint-consensus catch-up phase must bootstrap the joiner via
    InstallSnapshot (its next_index is below the leader's first log
    index), then the change commits and the joiner serves as a voter."""
    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(12):
        await c.apply_ok(leader, b"a%d" % i)
    await c.wait_applied(12)
    st = await leader.snapshot()
    assert st.is_ok(), str(st)
    assert leader.log_manager.first_log_index() > 1  # compacted
    # boot an empty 4th node, then add it as a voter
    new_peer = PeerId.parse("127.0.0.1:5003")
    c.peers.append(new_peer)
    from tpuraft.conf import Configuration
    save_conf = c.conf
    c.conf = Configuration()
    await c.start(new_peer)
    c.conf = save_conf
    st = await asyncio.wait_for(leader.add_peer(new_peer), 15)
    assert st.is_ok(), str(st)
    assert new_peer in leader.list_peers()
    await c.wait_applied(12, nodes=[c.nodes[new_peer]], timeout_s=10)
    # it arrived via a REMOTE install, not log replay
    got = c.nodes[new_peer].metrics.snapshot().get("counters", {}).get(
        "install-snapshot-received", 0)
    assert got >= 1, c.nodes[new_peer].metrics.snapshot()
    # and it votes: kill one ORIGINAL voter, quorum (3 of 4) holds
    victim = next(p for p in c.peers
                  if p not in (leader.server_id, new_peer))
    await c.stop(victim)
    st = await c.apply_ok(await c.wait_leader(), b"post-join")
    assert st.is_ok(), str(st)
    await c.stop_all()


async def test_install_snapshot_on_multilog_scheme(tmp_path):
    """InstallSnapshot + log reset over the SHARED journal engine: a
    follower crashed past the compaction horizon pulls the snapshot and
    its multilog-backed log resets (tlm_reset) to the snapshot index —
    the LogManager#setSnapshot divergent-log path on the shared engine."""
    try:
        from tpuraft.storage.multilog import ensure_built

        ensure_built()
    except Exception:
        pytest.skip("C++ multilog engine not buildable")
    c = TestCluster(3, tmp_path=tmp_path, snapshot=True,
                    log_scheme="multilog")
    await c.start_all()
    try:
        leader = await c.wait_leader()
        victim = next(p for p in c.peers if p != leader.server_id)
        for i in range(10):
            st = await c.apply_ok(leader, b"m%d" % i)
            assert st.is_ok(), st
        await c.wait_applied(10)
        await c.stop(victim)
        leader = await c.wait_leader()
        for i in range(10, 25):
            st = await c.apply_ok(leader, b"m%d" % i)
            assert st.is_ok(), st
        # snapshot + compact: the victim's catch-up point is gone
        st = await leader.snapshot()
        assert st.is_ok(), st
        await c.drain_sends_to(leader, victim.endpoint)  # r4 flake guard
        node = await c.start(victim)
        # generous: re-init + snapshot transfer + FSM load on a loaded host
        await c.wait_applied(25, timeout_s=10)
        assert c.fsms[victim].logs == c.fsms[leader.server_id].logs
        assert c.fsms[victim].snapshots_loaded >= 1  # installed, not replayed
        # and the recovered node's log lives on the shared engine
        assert node.log_manager.first_log_index() > 1
    finally:
        await c.stop_all()
