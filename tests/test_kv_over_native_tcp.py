"""Full-stack cross-wiring: the RheaKV store served over the native C++
epoll transport, with the native C++ KV engine underneath — every byte
on the wire and on disk owned by the native layer, Python orchestrating
(the reference's production shape: Bolt/Netty + RocksDB under a Java
control plane)."""

import asyncio

import pytest

from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.native_store import NativeRawKVStore
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.native_tcp import (
    NativeTcpRpcServer,
    NativeTcpTransport,
    ensure_built,
)


@pytest.fixture(scope="module", autouse=True)
def built():
    ensure_built()


@pytest.mark.asyncio
async def test_kv_cluster_over_native_transport_and_engine(tmp_path):
    # bind ephemeral ports first so the region conf can name real peers
    servers = []
    for _ in range(3):
        srv = NativeTcpRpcServer("127.0.0.1:0")
        await srv.start()
        srv.endpoint = f"127.0.0.1:{srv.bound_port}"
        servers.append(srv)
    endpoints = [s.endpoint for s in servers]
    regions = [Region(id=1, start_key=b"", end_key=b"m",
                      peers=list(endpoints)),
               Region(id=2, start_key=b"m", end_key=b"",
                      peers=list(endpoints))]

    stores: list[StoreEngine] = []
    transports = []
    for srv in servers:
        transport = NativeTcpTransport(endpoint=srv.endpoint)
        transports.append(transport)
        opts = StoreEngineOptions(
            server_id=srv.endpoint,
            initial_regions=[r.copy() for r in regions],
            data_path=str(tmp_path),
            election_timeout_ms=500,
            raw_store_factory=lambda ep=srv.endpoint: NativeRawKVStore(
                str(tmp_path / ("kv_" + ep.replace(":", "_")))),
        )
        store = StoreEngine(opts, srv, transport)
        await store.start()
        stores.append(store)

    client_transport = NativeTcpTransport()
    pd = FakePlacementDriverClient([r.copy() for r in regions])
    kv = RheaKVStore(pd, client_transport)
    await kv.start()
    try:
        # leaders for both regions
        async def wait_leader(rid):
            deadline = asyncio.get_running_loop().time() + 10
            while asyncio.get_running_loop().time() < deadline:
                for s in stores:
                    re = s.get_region_engine(rid)
                    if re is not None and re.is_leader():
                        return re
                await asyncio.sleep(0.05)
            raise TimeoutError(f"no leader for region {rid}")

        await wait_leader(1)
        await wait_leader(2)

        assert await kv.put(b"alpha", b"1")
        assert await kv.put(b"zulu", b"2")
        assert await kv.get(b"alpha") == b"1"
        assert await kv.multi_get([b"alpha", b"zulu", b"nope"]) == {
            b"alpha": b"1", b"zulu": b"2", b"nope": None}
        assert await kv.put_list([(b"a%02d" % i, b"v%d" % i)
                                  for i in range(20)])
        rows = await kv.scan(b"a", b"b")
        assert len(rows) == 21  # a00..a19 + alpha
        seq = await kv.get_sequence(b"ids", 10)
        assert seq.end - seq.start == 10
        lock = kv.get_distributed_lock(b"L", lease_ms=5000)
        assert await lock.try_lock()
        await lock.unlock()

        # kill the region-1 leader's whole server process-analog (server
        # + transport), survivors re-elect, client fails over
        leader1 = await wait_leader(1)
        victim_idx = next(
            i for i, s in enumerate(stores)
            if s is leader1.store_engine)
        await stores[victim_idx].shutdown()
        await servers[victim_idx].stop()
        await transports[victim_idx].close()
        dead = stores.pop(victim_idx)
        servers.pop(victim_idx)
        transports.pop(victim_idx)
        assert dead is not None

        await wait_leader(1)
        assert await kv.get(b"alpha") == b"1"
        assert await kv.put(b"after", b"failover")
        assert await kv.get(b"after") == b"failover"
    finally:
        await kv.shutdown()
        await client_transport.close()
        for s in stores:
            await s.shutdown()
        for srv in servers:
            await srv.stop()
        for t in transports:
            await t.close()
