"""Full-stack cross-wiring: the RheaKV store served over the native C++
epoll transport, with the native C++ KV engine underneath — every byte
on the wire and on disk owned by the native layer, Python orchestrating
(the reference's production shape: Bolt/Netty + RocksDB under a Java
control plane)."""

import asyncio
import contextlib

import pytest

from tpuraft.rheakv.client import RheaKVStore
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.native_store import NativeRawKVStore
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
from tpuraft.rpc.native_tcp import (
    NativeTcpRpcServer,
    NativeTcpTransport,
    ensure_built,
)


@pytest.fixture(scope="module", autouse=True)
def built():
    ensure_built()


class NativeKVCluster:
    """3 StoreEngines over native epoll servers + native KV engines.

    `regions_fn(endpoints)` builds the region layout once the ephemeral
    ports are known.  Owns teardown of every server/store/transport and
    any client made via `client()`.
    """

    def __init__(self, tmp_path, regions_fn=None):
        self._tmp = tmp_path
        self._regions_fn = regions_fn or (
            lambda eps: [Region(id=1, peers=list(eps))])
        self.servers: list = []
        self.stores: list[StoreEngine] = []
        self.transports: list = []
        self.regions: list[Region] = []
        self._clients: list[RheaKVStore] = []

    async def __aenter__(self) -> "NativeKVCluster":
        for _ in range(3):
            srv = NativeTcpRpcServer("127.0.0.1:0")
            await srv.start()
            srv.endpoint = f"127.0.0.1:{srv.bound_port}"
            self.servers.append(srv)
        endpoints = [s.endpoint for s in self.servers]
        self.regions = self._regions_fn(endpoints)
        for srv in self.servers:
            transport = NativeTcpTransport(endpoint=srv.endpoint)
            self.transports.append(transport)
            opts = StoreEngineOptions(
                server_id=srv.endpoint,
                initial_regions=[r.copy() for r in self.regions],
                data_path=str(self._tmp),
                election_timeout_ms=500,
                raw_store_factory=lambda ep=srv.endpoint: NativeRawKVStore(
                    str(self._tmp / ("kv_" + ep.replace(":", "_")))),
            )
            store = StoreEngine(opts, srv, transport)
            await store.start()
            self.stores.append(store)
        return self

    async def __aexit__(self, *exc):
        for kv in self._clients:
            with contextlib.suppress(Exception):
                await kv.shutdown()
        for s in self.stores:
            await s.shutdown()
        for srv in self.servers:
            await srv.stop()
        for t in self.transports:
            await t.close()

    async def client(self, **kw) -> RheaKVStore:
        transport = NativeTcpTransport()
        self.transports.append(transport)
        pd = FakePlacementDriverClient([r.copy() for r in self.regions])
        kv = RheaKVStore(pd, transport, **kw)
        await kv.start()
        self._clients.append(kv)
        return kv

    async def wait_leader(self, rid: int):
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            for s in self.stores:
                re = s.get_region_engine(rid)
                if re is not None and re.is_leader():
                    return re
            await asyncio.sleep(0.05)
        raise TimeoutError(f"no leader for region {rid}")

    async def kill_leader(self, rid: int) -> None:
        """Crash-stop the region leader's whole server process-analog
        (store + server + its outbound transport)."""
        leader = await self.wait_leader(rid)
        i = next(j for j, s in enumerate(self.stores)
                 if s is leader.store_engine)
        await self.stores.pop(i).shutdown()
        await self.servers.pop(i).stop()
        await self.transports.pop(i).close()


@pytest.mark.asyncio
async def test_kv_cluster_over_native_transport_and_engine(tmp_path):
    def two_regions(eps):
        return [Region(id=1, start_key=b"", end_key=b"m", peers=list(eps)),
                Region(id=2, start_key=b"m", end_key=b"", peers=list(eps))]

    async with NativeKVCluster(tmp_path, two_regions) as c:
        kv = await c.client()
        await c.wait_leader(1)
        await c.wait_leader(2)

        assert await kv.put(b"alpha", b"1")
        assert await kv.put(b"zulu", b"2")
        assert await kv.get(b"alpha") == b"1"
        assert await kv.multi_get([b"alpha", b"zulu", b"nope"]) == {
            b"alpha": b"1", b"zulu": b"2", b"nope": None}
        assert await kv.put_list([(b"a%02d" % i, b"v%d" % i)
                                  for i in range(20)])
        rows = await kv.scan(b"a", b"b")
        assert len(rows) == 21  # a00..a19 + alpha
        seq = await kv.get_sequence(b"ids", 10)
        assert seq.end - seq.start == 10
        lock = kv.get_distributed_lock(b"L", lease_ms=5000)
        assert await lock.try_lock()
        await lock.unlock()

        # crash the region-1 leader, survivors re-elect, client fails over
        await c.kill_leader(1)
        await c.wait_leader(1)
        assert await kv.get(b"alpha") == b"1"
        assert await kv.put(b"after", b"failover")
        assert await kv.get(b"after") == b"failover"


@pytest.mark.asyncio
async def test_native_stack_history_is_linearizable(tmp_path):
    """Full native stack under concurrent load + leader kill, with the
    recorded client history proven linearizable: C++ epoll sockets on
    the wire, C++ KV engine on disk, readIndex barriers over both."""
    from tpuraft.util.linearizability import History, check_history

    async with NativeKVCluster(tmp_path) as c:
        kv = await c.client(max_retries=1)
        await c.wait_leader(1)
        h = History()
        stop = asyncio.Event()
        keys = [b"nl-%d" % i for i in range(3)]

        async def worker(cid):
            n = 0
            while not stop.is_set():
                n += 1
                key = keys[n % len(keys)]
                if n % 2 == 0:
                    val = b"c%d-%d" % (cid, n)
                    tok = h.invoke(cid, "w", (key, val))
                    try:
                        await asyncio.wait_for(kv.put(key, val), 4.0)
                        h.complete(tok, True)
                    except Exception:
                        pass
                else:
                    tok = h.invoke(cid, "r", (key,))
                    try:
                        v = await asyncio.wait_for(kv.get(key), 4.0)
                        h.complete(tok, v)
                    except Exception:
                        pass
                await asyncio.sleep(0.003)

        workers = [asyncio.ensure_future(worker(i)) for i in range(4)]
        await asyncio.sleep(1.2)
        await c.kill_leader(1)       # crash mid-load
        await c.wait_leader(1)
        await asyncio.sleep(1.2)
        stop.set()
        await asyncio.gather(*workers)

        ops = h.ops()
        done = sum(1 for o in ops if o.ret is not None)
        assert done > 100, f"only {done}/{len(ops)} completed"
        rep = check_history(h)
        assert rep.ok, str(rep)


# ---------------------------------------------------------------------------
# process-fabric lifecycle: real OS-process stores (tests/proc_cluster.py
# over examples.proc_supervisor — the promoted NativeKVCluster)
# ---------------------------------------------------------------------------

from proc_cluster import ProcCluster  # noqa: E402 — tests/ is on sys.path


@pytest.mark.asyncio
async def test_proc_readiness_probe_gates_client_traffic(tmp_path):
    """A store that boots slow must not receive traffic early: the
    cluster enter blocks on every child's READY probe, and the moment
    it returns, ops succeed."""
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    async with ProcCluster(tmp_path, stores=3, regions=2,
                           boot_delay_s={0: 1.5}) as c:
        # enter awaited the delayed store's READY line
        assert loop.time() - t0 >= 1.5
        assert all(p.ready.is_set() for p in c.procs)
        assert all(p.info.get("endpoint") == p.endpoint for p in c.procs)
        kv = await c.client(max_retries=12)
        assert await kv.put(b"gated", b"1")
        assert await kv.get(b"gated") == b"1"


@pytest.mark.asyncio
async def test_proc_sigterm_drains_inflight_writes(tmp_path):
    """SIGTERM = drain: everything admitted acks, NEW work is bounced
    retryably to the surviving quorum, and the child exits 0 with a
    clean DRAINED verdict."""
    async with ProcCluster(tmp_path, stores=3, regions=2) as c:
        kv = await c.client(max_retries=12)
        assert await kv.put(b"pre", b"1")
        # a burst in flight while store 0 is told to drain: each put
        # either acks on the draining store before it exits or retries
        # onto the re-elected quorum — no ack may be lost either way
        puts = [asyncio.ensure_future(kv.put(b"k%02d" % i, b"v%d" % i))
                for i in range(40)]
        rc = await c.sigterm(0)
        assert rc == 0
        assert c.procs[0].drained is not None
        assert c.procs[0].drained.get("clean") is True
        assert all(await asyncio.gather(*puts))
        for i in range(40):
            assert await kv.get(b"k%02d" % i) == b"v%d" % i


@pytest.mark.asyncio
async def test_proc_sigkill_supervised_restart_recovers_durably(tmp_path):
    """SIGKILL (no drain) then restart: every store replays its raft
    log and the full committed state is served again — the supervised
    crash-restart path the soak leans on."""
    async with ProcCluster(tmp_path, stores=3, regions=2) as c:
        kv = await c.client(max_retries=12)
        for i in range(24):
            assert await kv.put(b"dur%02d" % i, b"v%d" % i)
        # crash-stop the WHOLE fleet: nothing survives but the logs
        for i in range(3):
            rc = await c.sigkill(i)
            assert rc != 0          # SIGKILL is not a clean exit
        for i in range(3):
            await c.restart(i)
        kv2 = await c.client(max_retries=12)
        for i in range(24):
            assert await kv2.get(b"dur%02d" % i) == b"v%d" % i
