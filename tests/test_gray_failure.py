"""Gray-failure survival (ISSUE 11): fail-slow injection, store health
scoring, leadership evacuation, serving-plane shedding.

Seeded and deterministic throughout: the HealthTracker's hysteresis
counts evaluation rounds (never wall-clock), the injection layers draw
from seeded rngs, and the evacuation tests drive the scoring rounds by
hand — byte-identical transitions on every run.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time

import pytest

from tpuraft.util.health import (
    DEGRADED,
    HEALTHY,
    SICK,
    DiskLatencyProbe,
    HealthOptions,
    HealthTracker,
)


# ---------------------------------------------------------------------------
# HealthTracker scoring: thresholds + hysteresis (seeded, deterministic)
# ---------------------------------------------------------------------------


def test_hysteresis_worsens_fast_recovers_slow():
    """Score transitions are evaluation-counted: worsen after 2
    consecutive bad rounds, recover only after 5 consecutive good ones
    — one writeback spike never flips the level, and a recovering
    store must PROVE health before the mitigation brake releases."""
    t = HealthTracker(HealthOptions(worsen_after=2, recover_after=5))
    assert t.score() == HEALTHY
    # one sick sample + one evaluation: still HEALTHY (hysteresis)
    t.disk.note(0.5)
    assert t.evaluate() == HEALTHY
    # second consecutive sick round crosses worsen_after
    assert t.evaluate() == SICK
    assert t.cause == "disk"
    # now recover the EMA below every threshold
    for _ in range(60):
        t.disk.note(0.0005)
    # four good rounds: still SICK (recover_after=5)
    for _ in range(4):
        assert t.evaluate() == SICK
    # the fifth releases
    assert t.evaluate() == HEALTHY


def test_degraded_level_does_not_reach_sick():
    t = HealthTracker(HealthOptions(worsen_after=1))
    for _ in range(20):
        t.disk.note(0.05)   # 50ms: over degraded (25), under sick (120)
    assert t.evaluate() == DEGRADED
    for _ in range(10):
        assert t.evaluate() == DEGRADED
    assert t.score() == DEGRADED


def test_disk_stall_detected_via_inflight_age():
    """A fully hung fsync never completes a sample, so the EMA alone
    would stay clean forever — the probe's in-flight age catches it."""
    clock = [0.0]
    t = HealthTracker(HealthOptions(worsen_after=2, disk_stall_ms=500.0),
                      clock=lambda: clock[0])
    # healthy history
    for _ in range(10):
        tok = t.disk.begin()
        clock[0] += 0.001
        t.disk.end(tok)
    assert t.evaluate() == HEALTHY
    # a flush begins... and never ends
    t.disk.begin()
    clock[0] += 0.3
    assert t.evaluate() == HEALTHY      # under the stall bound
    clock[0] += 0.3                     # 600ms in flight now
    assert t.evaluate() == HEALTHY      # hysteresis round 1
    assert t.evaluate() == SICK
    assert t.cause == "stall"


def test_apply_backlog_scores():
    t = HealthTracker(HealthOptions(worsen_after=1, apply_degraded=100,
                                    apply_sick=1000))
    for _ in range(30):
        t.note_apply_depth(400)
    assert t.evaluate() == DEGRADED
    for _ in range(30):
        t.note_apply_depth(5000)
    assert t.evaluate() == SICK
    assert t.cause == "apply"


def test_peer_scores_from_rtts():
    t = HealthTracker(HealthOptions(worsen_after=2, peer_degraded_ms=50,
                                    peer_sick_ms=250))
    for _ in range(10):
        t.note_peer_rtt("a:1", 0.005)   # 5ms: healthy
        t.note_peer_rtt("b:1", 0.100)   # 100ms: degraded
        t.note_peer_rtt("c:1", 0.400)   # 400ms: sick
    for _ in range(3):
        t.evaluate()
    assert t.peer_score("a:1") == HEALTHY
    assert t.peer_score("b:1") == DEGRADED
    assert t.peer_score("c:1") == SICK
    assert t.slow_peers() == ["b:1", "c:1"]
    # an endpoint never heard from defaults healthy
    assert t.peer_score("zz:9") == HEALTHY


def test_disk_ema_fed_in_thread_only_not_by_round_waits():
    """Regression (gray A/B bench): end-to-end round time includes
    executor-queue wait, so one co-hosted store's slow disk saturating
    the shared executor scored EVERY store sick and triggered a
    mutual-evacuation leadership storm.  begin/end feed only the
    stall-age signal; the EMA comes exclusively from note()'s in-thread
    measurements."""
    clock = [0.0]
    p = DiskLatencyProbe(clock=lambda: clock[0])
    tok = p.begin()
    clock[0] += 5.0          # five seconds queued behind a neighbor
    p.end(tok)
    ema, age, n = p.snapshot()
    assert n == 0 and ema == 0.0, \
        "round wait must not contaminate the disk EMA"
    p.note(0.002)
    ema, _age, n = p.snapshot()
    assert n == 1 and abs(ema - 2.0) < 1e-9


async def test_sick_store_refuses_timeout_now():
    """Regression (gray A/B bench): a SICK store must not ACCEPT
    leadership — two slow stores evacuating at each other ping-ponged
    every lease.  Refusing TimeoutNow is always safe: the transfer
    times out and the old leader's watchdog resumes."""
    from tpuraft.core.node import Node, State
    from tpuraft.entity import PeerId
    from tpuraft.options import NodeOptions
    from tpuraft.rpc.messages import TimeoutNowRequest

    t = HealthTracker(HealthOptions(worsen_after=1))
    node = Node.__new__(Node)
    node._lock = asyncio.Lock()
    node.current_term = 3
    node.state = State.FOLLOWER
    node.options = NodeOptions(health=t)
    node.group_id = "g"
    node.server_id = PeerId.parse("127.0.0.1:9001")
    req = TimeoutNowRequest(group_id="g", server_id="127.0.0.1:9002",
                            peer_id="127.0.0.1:9001", term=3)
    for _ in range(5):
        t.disk.note(0.5)
    t.evaluate()
    assert t.score() == SICK
    resp = await node.handle_timeout_now(req)
    assert resp.success is False, "SICK store accepted leadership"


def test_probe_is_thread_safe_under_concurrent_feeders():
    """The disk probe is the one tracker piece fed from executor
    threads (multilog fsync timing) — hammer it from 4 threads while
    snapshotting."""
    p = DiskLatencyProbe()
    stop = threading.Event()

    def feed():
        while not stop.is_set():
            tok = p.begin()
            p.end(tok)
            p.note(0.001)

    threads = [threading.Thread(target=feed) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(200):
            ema, age, n = p.snapshot()
            assert ema >= 0.0 and age >= 0.0
    finally:
        stop.set()
        for th in threads:
            th.join()
    ema, age, n = p.snapshot()
    assert n > 0


# ---------------------------------------------------------------------------
# fail-slow injection: ChaosDir latency faults
# ---------------------------------------------------------------------------


def test_chaosdir_set_slow_delays_fsync_and_write(tmp_path):
    import os

    from tpuraft.storage.fault import ChaosDir

    root = tmp_path / "slow"
    with ChaosDir(str(root)) as cd:
        cd.set_slow(fsync_ms=40, write_ms=10, seed=7)
        path = root / "f.bin"
        t0 = time.perf_counter()
        with open(str(path), "wb") as f:
            f.write(b"x" * 64)
            f.flush()
            os.fsync(f.fileno())
        dur = time.perf_counter() - t0
        assert dur >= 0.045, f"latency injection missing ({dur * 1e3:.1f}ms)"
        assert cd.slow_counts.get("fsync_slowed", 0) >= 1
        assert cd.slow_counts.get("write_slowed", 0) >= 1
        cd.heal_slow()
        t0 = time.perf_counter()
        with open(str(path), "wb") as f:
            f.write(b"y" * 64)
            f.flush()
            os.fsync(f.fileno())
        assert time.perf_counter() - t0 < 0.03, "heal_slow did not clear"


def test_chaosdir_stall_fsync_blocks_until_heal(tmp_path):
    import os

    from tpuraft.storage.fault import ChaosDir

    root = tmp_path / "stall"
    with ChaosDir(str(root)) as cd:
        path = root / "f.bin"
        f = open(str(path), "wb")  # noqa: SIM115 — fsynced across threads
        f.write(b"x")
        f.flush()
        cd.stall_fsync()
        done = threading.Event()

        def sync():
            os.fsync(f.fileno())
            done.set()

        th = threading.Thread(target=sync)
        th.start()
        try:
            assert not done.wait(0.15), "stalled fsync completed"
            cd.heal_slow()
            assert done.wait(2.0), "healed fsync still stuck"
        finally:
            cd.heal_slow()
            th.join()
            f.close()
        assert cd.slow_counts.get("fsync_stalled", 0) == 1


def test_chaosdir_uninstall_releases_stalled_fsync(tmp_path):
    """A leaked stall must not wedge executor threads past the chaos
    drive: uninstall() heals."""
    import os

    from tpuraft.storage.fault import ChaosDir

    root = tmp_path / "leak"
    cd = ChaosDir(str(root)).install()
    path = root / "f.bin"
    f = open(str(path), "wb")  # noqa: SIM115
    f.write(b"x")
    f.flush()
    cd.stall_fsync()
    done = threading.Event()
    th = threading.Thread(target=lambda: (os.fsync(f.fileno()), done.set()))
    th.start()
    try:
        assert not done.wait(0.1)
        cd.uninstall()
        assert done.wait(2.0), "uninstall did not release the stall"
    finally:
        th.join()
        f.close()


# ---------------------------------------------------------------------------
# fail-slow injection: per-endpoint topology events
# ---------------------------------------------------------------------------


def test_topology_endpoint_degrade_both_directions_and_heal():
    from tpuraft.rpc.topology import NetworkTopology

    topo = NetworkTopology(seed=3)
    topo.degrade_endpoint("a:1", latency_ms=50, jitter_ms=0)
    # frames TOUCHING a:1 pay the limp, both directions
    d1, drop1 = topo.plan("a:1", "b:1")
    d2, drop2 = topo.plan("b:1", "a:1")
    assert not drop1 and not drop2
    assert d1 >= 0.05 and d2 >= 0.05
    # frames between healthy endpoints are untouched
    d3, _ = topo.plan("b:1", "c:1")
    assert d3 == 0.0
    assert topo.counters["ep_shaped"] == 2
    topo.heal_events()
    d4, _ = topo.plan("a:1", "b:1")
    assert d4 == 0.0
    assert not topo.endpoint_degraded("a:1")


def test_topology_endpoint_limp_composes_with_zone_link():
    """The endpoint limp is ADDITIVE on the zone link — one store can
    crawl while its zone's base shape stays intact for its siblings."""
    from tpuraft.rpc.topology import LinkProfile, NetworkTopology

    topo = NetworkTopology(seed=5)
    for ep, z in (("a:1", "z0"), ("b:1", "z0"), ("c:1", "z1")):
        topo.set_zone(ep, z)
    topo.set_link("z0", "z1", LinkProfile(latency_ms=10), symmetric=True)
    topo.degrade_endpoint("a:1", latency_ms=100, jitter_ms=0)
    d_limped, _ = topo.plan("a:1", "c:1")
    d_healthy, _ = topo.plan("b:1", "c:1")
    assert abs(d_healthy - 0.010) < 1e-9
    assert abs(d_limped - 0.110) < 1e-9


def test_topology_stall_endpoint_delivers_late_not_never():
    from tpuraft.rpc.topology import NetworkTopology

    topo = NetworkTopology(seed=1)
    topo.stall_endpoint("a:1", stall_ms=800)
    delay, dropped = topo.plan("b:1", "a:1")
    assert not dropped, "stall must deliver (late), not drop"
    assert delay >= 0.8


def test_topology_endpoint_loss_seeded_replay():
    from tpuraft.rpc.topology import NetworkTopology

    def run(seed):
        topo = NetworkTopology(seed=seed)
        topo.degrade_endpoint("a:1", latency_ms=5, jitter_ms=5, loss=0.3)
        return [topo.plan("a:1", "b:1") for _ in range(64)]

    assert run(9) == run(9), "same seed must replay byte-identically"
    assert run(9) != run(10)


# ---------------------------------------------------------------------------
# leadership evacuation: rate-bounded, hysteretic, health-target-aware
# ---------------------------------------------------------------------------


@contextlib.asynccontextmanager
async def _kv_cluster(tmp_path, n_regions=4, **opt_overrides):
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.metadata import Region

    regions = [Region(id=k + 1,
                      start_key=b"k%02d" % k if k else b"",
                      end_key=b"k%02d" % (k + 1) if k + 1 < n_regions
                      else b"")
               for k in range(n_regions)]
    c = KVTestCluster(n_stores=3, tmp_path=tmp_path, regions=regions)
    # the gray knobs ride StoreEngineOptions; KVTestCluster builds them
    # internally, so patch post-construction before start
    orig = c.start_store

    async def start_store(ep):
        store = await orig(ep)
        for k, v in opt_overrides.items():
            setattr(store.opts, k, v)
        return store

    c.start_store = start_store
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


async def _concentrate_leadership(c, ep, n_regions):
    """Transfer every region's leadership onto store ``ep``."""
    from tpuraft.entity import PeerId

    target = PeerId.parse(ep)
    for rid in range(1, n_regions + 1):
        engine = await c.wait_region_leader(rid)
        if engine.store_engine.server_id.endpoint == ep:
            continue
        st = await engine.node.transfer_leadership_to(target)
        assert st.is_ok(), f"transfer of region {rid}: {st}"
    # wait until the target actually leads everything
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if sorted(c.stores[ep].leader_region_ids()) == \
                list(range(1, n_regions + 1)):
            return
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"leadership never concentrated on {ep}: "
        f"{c.stores[ep].leader_region_ids()}")


def _force_level(store, level: str) -> None:
    """Deterministically drive the store's tracker to a level."""
    ms = {HEALTHY: 0.0002, DEGRADED: 0.05, SICK: 0.4}[level]
    for _ in range(40):
        store.health.disk.note(ms)
    for _ in range(max(store.health.opts.worsen_after,
                       store.health.opts.recover_after) + 1):
        store.health.evaluate()
    assert store.health.score() == level


async def test_evacuation_rate_bounded_and_cooldown(tmp_path):
    """Acceptance criterion: a SICK store moves at most
    ``evacuation_rate`` leaders per evaluation round, and a region it
    just moved (or tried to) is cooled down for
    ``evacuation_cooldown_rounds`` rounds."""
    # park the background health loop (huge eval interval): this test
    # drives _evacuate_leaders() by hand, and a concurrent REAL round
    # against the forced-SICK tracker would break the count arithmetic
    async with _kv_cluster(tmp_path, n_regions=4, evacuation_rate=2,
                           evacuation_cooldown_rounds=100,
                           health_eval_interval_ms=3_600_000) as c:
        ep0 = c.endpoints[0]
        await _concentrate_leadership(c, ep0, 4)
        store = c.stores[ep0]
        _force_level(store, SICK)
        # round 1: exactly evacuation_rate transfers
        moved = await store._evacuate_leaders()
        assert moved == 2
        assert store.evacuations == 2
        # round 2 (same _evac_round: cooldown horizon far ahead): the 2
        # still-led regions move, the 2 cooled ones are skipped
        moved = await store._evacuate_leaders()
        assert moved == 2
        assert store.evacuations == 4
        # round 3: everything either moved or cooled — nothing happens
        moved = await store._evacuate_leaders()
        assert moved == 0
        assert store.evacuations == 4


async def test_degraded_recovering_store_keeps_its_leaders(tmp_path):
    """Acceptance criterion (no flapping): a store that went SICK,
    evacuated, and is now RECOVERING through DEGRADED keeps the leaders
    it still holds — the health loop only evacuates at SICK, and the
    recover_after hysteresis keeps a noisy store from oscillating."""
    async with _kv_cluster(tmp_path, n_regions=4, evacuation_rate=1,
                           health_eval_interval_ms=40) as c:
        ep0 = c.endpoints[0]
        await _concentrate_leadership(c, ep0, 4)
        store = c.stores[ep0]
        _force_level(store, SICK)
        # let the REAL health loop evacuate at its bounded rate
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and store.evacuations == 0:
            store.health.disk.note(0.4)   # fault still active
            await asyncio.sleep(0.05)
        assert store.evacuations > 0, "SICK store never evacuated"
        # the disk recovers: good samples drive the score to DEGRADED
        # territory and beyond — while DEGRADED, NO further evacuation
        for _ in range(40):
            store.health.disk.note(0.05)   # degraded-level latency
        for _ in range(store.health.opts.recover_after + 2):
            store.health.evaluate()
        assert store.health.score() == DEGRADED
        # evacuations ordered during the SICK phase land asynchronously
        # (the leadership transfer completes off the health loop — on a
        # loaded host well after the score recovered): let leadership
        # settle before snapshotting what the store still holds
        led_before = store.leader_region_ids()
        settle_deadline = time.monotonic() + 8
        stable_since = time.monotonic()
        while time.monotonic() < settle_deadline:
            store.health.disk.note(0.05)   # keep the score DEGRADED
            await asyncio.sleep(0.05)
            cur = store.leader_region_ids()
            if cur != led_before:
                led_before = cur
                stable_since = time.monotonic()
            elif time.monotonic() - stable_since > 0.6:
                break
        evac_before = store.evacuations
        feed_until = time.monotonic() + 1.5
        while time.monotonic() < feed_until:
            store.health.disk.note(0.05)   # still degraded, recovering
            await asyncio.sleep(0.03)
        assert store.evacuations == evac_before, \
            "DEGRADED-but-recovering store evacuated (flapping)"
        assert store.leader_region_ids() == led_before, \
            "DEGRADED store lost leaders it should have kept"


async def test_evacuation_targets_healthiest_peer(tmp_path):
    """The transfer target skips peers the tracker scores SICK and
    prefers HEALTHY over DEGRADED."""
    async with _kv_cluster(tmp_path, n_regions=1) as c:
        ep0, ep1, ep2 = c.endpoints
        await _concentrate_leadership(c, ep0, 1)
        store = c.stores[ep0]
        engine = store.get_region_engine(1)

        def feed_until(pred, feeds):
            # the LIVE hub keeps folding real (fast) beat RTTs into the
            # same EMAs, so keep feeding until the score holds
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline and not pred():
                for ep, rtt in feeds:
                    for _ in range(8):
                        store.health.note_peer_rtt(ep, rtt)
                store.health.evaluate()
            assert pred(), {e: store.health.peer_score(e)
                            for e in (ep1, ep2)}

        # score ep1 SICK while ep2 stays no worse than DEGRADED — and
        # tolerate a loaded host where the hub's REAL beat RTTs shove
        # ep2 over the sick bound transiently: keep feeding until the
        # intended state holds at the instant of the pick
        deadline = time.monotonic() + 10
        target = None
        while time.monotonic() < deadline and target is None:
            for _ in range(8):
                store.health.note_peer_rtt(ep1, 0.400)
                store.health.note_peer_rtt(ep2, 0.100)
            store.health.evaluate()
            if store.health.peer_score(ep1) == SICK \
                    and store.health.peer_score(ep2) != SICK:
                target = store._pick_evacuation_target(engine)
            await asyncio.sleep(0)
        assert target is not None, \
            {e: store.health.peer_score(e) for e in (ep1, ep2)}
        assert target.endpoint == ep2, \
            "must pick the non-sick peer over the sick one"
        # with BOTH peers sick there is no target at all
        feed_until(lambda: store.health.peer_score(ep1) == SICK
                   and store.health.peer_score(ep2) == SICK,
                   [(ep1, 0.400), (ep2, 0.400)])
        assert store._pick_evacuation_target(engine) is None


# ---------------------------------------------------------------------------
# serving-plane degradation: shed instead of queue
# ---------------------------------------------------------------------------


async def test_sick_store_sheds_batches_with_retry_after(tmp_path):
    from tpuraft.rheakv.kv_operation import KVOp, KVOperation
    from tpuraft.rheakv.kv_service import (
        ERR_STORE_BUSY,
        KVCommandBatchRequest,
        decode_batch_reply,
        encode_batch_item,
    )

    async with _kv_cluster(tmp_path, n_regions=1,
                           shed_backlog_items=8) as c:
        engine = await c.wait_region_leader(1)
        store = engine.store_engine
        region = engine.region
        item = encode_batch_item(
            1, region.epoch.conf_ver, region.epoch.version,
            KVOperation(KVOp.PUT, b"k", b"v").encode())
        # healthy: no shed, whatever the backlog
        store.kv_processor.inflight_items = 10_000
        resp = await store.kv_processor.handle_batch(
            KVCommandBatchRequest(items=[item]))
        code, _m, _r, _g = decode_batch_reply(resp.items[0])
        assert code == 0
        # SICK + backlog over the bound: per-item EBUSY + retry-after,
        # nothing admitted to the propose pipe
        store.kv_processor.inflight_items = 10_000
        _force_level(store, SICK)
        resp = await store.kv_processor.handle_batch(
            KVCommandBatchRequest(items=[item, item]))
        for blob in resp.items:
            code, msg, _r, _g = decode_batch_reply(blob)
            assert code == ERR_STORE_BUSY
            assert "retry-after-ms=" in msg
        assert store.kv_processor.shed_items == 2
        assert store.kv_processor.inflight_items == 10_000  # untouched
        # SICK but the pipe is empty: still serves (deadline-aware —
        # shed only once queueing would add the fatal wait)
        store.kv_processor.inflight_items = 0
        resp = await store.kv_processor.handle_batch(
            KVCommandBatchRequest(items=[item]))
        code, _m, _r, _g = decode_batch_reply(resp.items[0])
        assert code == 0


def test_client_treats_shed_bounce_as_retryable():
    from tpuraft.rheakv.client import RheaKVStore, _Retry
    from tpuraft.rheakv.kv_service import ERR_STORE_BUSY, encode_batch_reply
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    region = Region(id=1, peers=["127.0.0.1:9001", "127.0.0.1:9002"])
    kv = RheaKVStore(FakePlacementDriverClient([region]), transport=None)
    kv._leaders[1] = "127.0.0.1:9001"
    out = kv._decode_outcome(
        region, "127.0.0.1:9001",
        encode_batch_reply(ERR_STORE_BUSY,
                           "store sick: shedding (retry-after-ms=250)"))
    assert isinstance(out, _Retry)
    assert 1 not in kv._leaders, \
        "a shedding store's leader hint must drop (evacuation moves it)"


# ---------------------------------------------------------------------------
# client: jittered backoff + slow-replica read routing
# ---------------------------------------------------------------------------


def test_backoff_jitter_is_seeded_and_bounded():
    from tpuraft.rheakv.client import RheaKVStore
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    def series(seed):
        kv = RheaKVStore(FakePlacementDriverClient([]), transport=None,
                         retry_interval_ms=100, jitter_seed=seed)
        return [kv._backoff_s(a) for a in range(8)]

    s1, s2, s3 = series(7), series(7), series(8)
    assert s1 == s2, "same seed must give the same backoff series"
    assert s1 != s3
    for attempt, val in enumerate(s1):
        base = 0.1 * (attempt + 1)
        assert 0.5 * base <= val < 1.5 * base


def test_read_candidates_route_off_slow_replicas():
    from tpuraft.rheakv.client import RheaKVStore
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    peers = ["127.0.0.1:9001", "127.0.0.1:9002", "127.0.0.1:9003"]
    region = Region(id=1, peers=list(peers))
    kv = RheaKVStore(FakePlacementDriverClient([region]), transport=None,
                     read_from="follower")
    kv._leaders[1] = peers[0]
    # endpoint 9002 observed slow (gray), the others fast
    kv._ep_lat_ms = {"127.0.0.1:9001": 2.0, "127.0.0.1:9002": 300.0,
                     "127.0.0.1:9003": 3.0}
    for attempt in range(6):
        cands = kv._read_candidates(region, attempt)
        followers = [c for c in cands if c != peers[0]]
        assert followers[-1] == peers[1] or peers[1] not in followers[:1], \
            f"slow follower probed first: {cands}"
        assert cands.index(peers[2]) < cands.index(peers[1]), \
            f"slow replica not deprioritized: {cands}"
    # with no latency data the rotation is untouched
    kv._ep_lat_ms = {}
    seen_first = {kv._read_candidates(region, 0)[0] for _ in range(6)}
    assert len(seen_first) > 1, "rotation must still spread"


def test_any_mode_reads_also_route_off_slow_replicas():
    from tpuraft.rheakv.client import RheaKVStore
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    peers = ["127.0.0.1:9001", "127.0.0.1:9002", "127.0.0.1:9003"]
    region = Region(id=1, peers=list(peers))
    kv = RheaKVStore(FakePlacementDriverClient([region]), transport=None,
                     read_from="any")
    kv._ep_lat_ms = {"127.0.0.1:9001": 2.0, "127.0.0.1:9002": 250.0,
                     "127.0.0.1:9003": 3.0}
    for _ in range(6):
        eps = kv._read_endpoints_for(region)
        assert eps[-1] == peers[1], \
            f"'any' fan-out did not push the gray replica last: {eps}"


def test_ep_latency_ema_not_fed_by_shed_bounces():
    """Review finding: a SICK store's instant ERR_STORE_BUSY bounces
    must not drag its latency EMA back under the slow floor — only
    SERVED replies feed the EMA."""
    from tpuraft.rheakv.client import RheaKVStore, _StoreSender
    from tpuraft.rheakv.kv_operation import KVOp, KVOperation
    from tpuraft.rheakv.kv_service import (
        ERR_STORE_BUSY,
        KVCommandBatchResponse,
        encode_batch_reply,
    )
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient

    class ShedTransport:
        async def call(self, dst, method, request, timeout_ms=None):
            bounce = encode_batch_reply(ERR_STORE_BUSY, "shedding")
            return KVCommandBatchResponse(
                items=[bounce] * len(request.items))

    region = Region(id=1, peers=["127.0.0.1:9001"])
    kv = RheaKVStore(FakePlacementDriverClient([region]),
                     transport=ShedTransport())
    kv._ep_lat_ms["127.0.0.1:9001"] = 300.0   # learned while limping

    async def run():
        sender = _StoreSender(kv, "127.0.0.1:9001")
        fut = sender.submit(region, "127.0.0.1:9001",
                            KVOperation(KVOp.PUT, b"k", b"v"))
        await asyncio.wait_for(fut, 2.0)

    asyncio.run(run())
    assert kv._ep_lat_ms["127.0.0.1:9001"] == 300.0, \
        "shed bounce fed the EMA and erased the gray signal"


# ---------------------------------------------------------------------------
# PD: SICK-aware placement + drain
# ---------------------------------------------------------------------------


def _stats(cooldown=0.0):
    from tpuraft.rheakv.pd_server import ClusterStatsManager

    s = ClusterStatsManager(split_threshold_keys=0)
    s._grace_until = 0.0
    return s


def test_pd_never_targets_a_sick_store():
    from tpuraft.rheakv.metadata import Region

    s = _stats()
    region = Region(id=1, peers=["a:1", "b:1", "c:1"])
    leaders = {1: "a:1", 2: "a:1", 3: "a:1", 4: "a:1"}
    # without health, b or c gets the move (a leads 4, they lead 0)
    t = s.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0)
    assert t in ("b:1", "c:1")
    # with b SICK, the move lands on c (fresh manager: no cooldown)
    s2 = _stats()
    t = s2.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0,
                                health={"b:1": "sick"})
    assert t == "c:1"
    # everyone else sick: nowhere to go
    s3 = _stats()
    t = s3.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0,
                                health={"b:1": "sick", "c:1": "sick"})
    assert t is None


def test_pd_drains_sick_leader_without_imbalance():
    """Balanced leader counts normally suppress transfers (< 2 diff);
    a SICK leader store is drained anyway — onto a healthy peer."""
    from tpuraft.rheakv.metadata import Region

    s = _stats()
    region = Region(id=1, peers=["a:1", "b:1", "c:1"])
    leaders = {1: "a:1", 2: "b:1", 3: "c:1"}   # perfectly balanced
    assert s.pick_transfer_target(region, "a:1", leaders,
                                  cooldown_s=10.0) is None
    s2 = _stats()
    t = s2.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0,
                                health={"a:1": "sick"})
    assert t in ("b:1", "c:1"), "sick leader must drain"
    # degraded peers lose the tie to healthy ones during a drain
    s3 = _stats()
    t = s3.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0,
                                health={"a:1": "sick", "b:1": "degraded"})
    assert t == "c:1"
    # and the cooldown still paces repeated drains of one region
    t = s3.pick_transfer_target(region, "a:1", leaders, cooldown_s=10.0,
                                health={"a:1": "sick"})
    assert t is None, "drain must respect the per-region cooldown"


async def test_pre_health_pd_client_override_still_heartbeats(tmp_path):
    """API compat: a PD-client subclass whose store_heartbeat_batch
    predates the health kwarg must keep receiving heartbeats (probed by
    signature at construction) — the naive call would raise TypeError
    into the retry loop and silently starve the PD forever."""
    from tpuraft.rheakv.metadata import Region
    from tpuraft.rheakv.pd_client import FakePlacementDriverClient
    from tpuraft.rheakv.store_engine import StoreEngine, StoreEngineOptions
    from tpuraft.rpc.transport import (
        InProcNetwork,
        InProcTransport,
        RpcServer,
    )

    class LegacyPD(FakePlacementDriverClient):
        batches = 0

        async def store_heartbeat_batch(self, meta, deltas, full=False):
            LegacyPD.batches += 1
            return [], False

    ep = "127.0.0.1:6777"
    net = InProcNetwork()
    server = RpcServer(ep)
    net.bind(server)
    net.start_endpoint(ep)
    store = StoreEngine(
        StoreEngineOptions(server_id=ep,
                           initial_regions=[Region(id=1, peers=[ep])],
                           heartbeat_interval_ms=30),
        server, InProcTransport(net, ep),
        pd_client=LegacyPD([]))
    assert store._pd_health_kwarg is False
    await store.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and LegacyPD.batches == 0:
            await asyncio.sleep(0.05)
        assert LegacyPD.batches > 0, \
            "legacy PD client never received a heartbeat"
    finally:
        await store.shutdown()


def test_pd_server_tracks_and_clears_store_health():
    from tpuraft.rheakv.pd_server import PlacementDriverServer

    srv = PlacementDriverServer.__new__(PlacementDriverServer)
    srv._store_health = {}
    srv._note_store_health("a:1", "sick")
    assert srv._store_health == {"a:1": "sick"}
    srv._note_store_health("a:1", "healthy")
    assert srv._store_health == {"a:1": "healthy"}
    # "" = store stopped reporting scores: never leave a stale verdict
    srv._note_store_health("a:1", "")
    assert srv._store_health == {}


# ---------------------------------------------------------------------------
# node: SICK election gate (bounded deferral, then liveness wins)
# ---------------------------------------------------------------------------


def test_sick_store_defers_elections_boundedly():
    from tpuraft.core.node import Node
    from tpuraft.entity import ElectionPriority, PeerId
    from tpuraft.options import NodeOptions

    t = HealthTracker(HealthOptions(worsen_after=1))
    node = Node.__new__(Node)
    node.options = NodeOptions(health=t, sick_election_rounds=2)
    node.server_id = PeerId.parse("127.0.0.1:9001")
    node._sick_election_skips = 0
    node._election_round = 0
    node.target_priority = ElectionPriority.DISABLED
    # healthy: elections run
    assert node._allow_launch_election() is True
    # sick: defer exactly sick_election_rounds rounds...
    for _ in range(5):
        t.disk.note(0.5)
    t.evaluate()
    assert t.score() == SICK
    assert node._allow_launch_election() is False
    assert node._allow_launch_election() is False
    # ...then liveness wins (every peer may be worse off)
    assert node._allow_launch_election() is True
    # recovery resets the skip budget
    for _ in range(60):
        t.disk.note(0.0002)
    for _ in range(t.opts.recover_after + 1):
        t.evaluate()
    assert t.score() == HEALTHY
    assert node._allow_launch_election() is True
    assert node._sick_election_skips == 0


# ---------------------------------------------------------------------------
# end-to-end: a slow disk on a leader is detected through REAL signals
# ---------------------------------------------------------------------------


async def test_slow_disk_scores_sick_through_real_flush_path(tmp_path):
    """No synthetic samples: ChaosDir latency on the leader store's
    data dir, real KV writes, and the tracker must reach SICK from the
    LogManager's own flush timing."""
    import os

    from tpuraft.storage.fault import ChaosDir

    # interposition must be live BEFORE the stores open their files
    # (files opened earlier are not tracked), so install for every
    # store dir up front and arm only the leader's
    chaos = {}
    for i in range(3):
        ep = f"127.0.0.1:{6000 + i}"
        ip, port = ep.rsplit(":", 1)
        chaos[ep] = ChaosDir(
            os.path.join(str(tmp_path), f"{ip}_{port}")).install()
    try:
        async with _kv_cluster(tmp_path, n_regions=1,
                               health_eval_interval_ms=60) as c:
            engine = await c.wait_region_leader(1)
            store = engine.store_engine
            cd = chaos[store.server_id.endpoint]
            cd.set_slow(fsync_ms=200, write_ms=10, seed=3)
            deadline = time.monotonic() + 12
            while time.monotonic() < deadline \
                    and store.health.score() != SICK:
                try:
                    await asyncio.wait_for(
                        engine.raft_store.put(b"k", b"v"), 2.0)
                except Exception:
                    pass   # slow is the point
                await asyncio.sleep(0.02)
            cd.heal_slow()   # let shutdown proceed at disk speed
            assert store.health.score() == SICK, store.health.describe()
            assert store.health.cause in ("disk", "stall")
    finally:
        for cd in chaos.values():
            cd.uninstall()
