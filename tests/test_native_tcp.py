"""Native C++ epoll transport tests.

Same coverage tiers as test_tcp.py (framing/pipelining/reconnect, full
raft cluster over real sockets), plus wire-level interop: the native
engine and the pure-Python asyncio transport speak the same frame
format, so each must serve the other (the reference's Netty *native*
epoll transport is a drop-in under the same Bolt protocol —
SURVEY.md §3.4).
"""

import asyncio

import pytest

from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import GetFileResponse, ReadIndexResponse
from tpuraft.rpc.native_tcp import (
    NativeTcpRpcServer,
    NativeTcpTransport,
    ensure_built,
)
from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport
from tpuraft.rpc.transport import RpcError

from tests.test_tcp import TcpCluster, _start_server


@pytest.fixture(scope="module", autouse=True)
def built():
    ensure_built()


def _rir(i: int) -> ReadIndexResponse:
    return ReadIndexResponse(index=i, success=True)


class TestNativeRpc:
    @pytest.mark.asyncio
    async def test_roundtrip_and_error(self):
        srv = await _start_server(NativeTcpRpcServer)

        async def echo(req):
            return ReadIndexResponse(index=req.index, success=True)

        async def boom(req):
            raise RpcError(Status.error(RaftError.EPERM, "not leader"))

        srv.register("echo", echo)
        srv.register("boom", boom)
        t = NativeTcpTransport()
        resp = await t.call(srv.endpoint, "echo", _rir(42))
        assert resp.index == 42 and resp.success
        with pytest.raises(RpcError) as ei:
            await t.call(srv.endpoint, "boom", _rir(0))
        assert ei.value.status.code == int(RaftError.EPERM)
        with pytest.raises(RpcError):
            await t.call(srv.endpoint, "nope", _rir(0))
        resp = await t.call(srv.endpoint, "echo", _rir(7))
        assert resp.index == 7
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_pipelining_out_of_order_completion(self):
        srv = await _start_server(NativeTcpRpcServer)

        async def slow(req):
            await asyncio.sleep(0.2)
            return ReadIndexResponse(index=req.index, success=True)

        async def fast(req):
            return ReadIndexResponse(index=req.index, success=True)

        srv.register("slow", slow)
        srv.register("fast", fast)
        t = NativeTcpTransport()
        t_slow = asyncio.ensure_future(
            t.call(srv.endpoint, "slow", _rir(1), timeout_ms=2000))
        t_fast = asyncio.ensure_future(t.call(srv.endpoint, "fast", _rir(2)))
        fast_resp = await asyncio.wait_for(t_fast, 0.15)
        assert fast_resp.index == 2
        assert (await t_slow).index == 1
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_timeout_and_reconnect_after_restart(self):
        srv = await _start_server(NativeTcpRpcServer)
        endpoint = srv.endpoint

        async def hang(req):
            await asyncio.sleep(10)

        async def ok(req):
            return ReadIndexResponse(index=5, success=True)

        srv.register("hang", hang)
        srv.register("ok", ok)
        t = NativeTcpTransport()
        with pytest.raises(RpcError) as ei:
            await t.call(endpoint, "hang", _rir(0), timeout_ms=100)
        assert ei.value.status.code == int(RaftError.ETIMEDOUT)
        await srv.stop()
        with pytest.raises(RpcError):
            await t.call(endpoint, "ok", _rir(0), timeout_ms=300)
        srv2 = NativeTcpRpcServer(endpoint)
        await srv2.start()
        srv2.register("ok", ok)
        # the pool may need one failed call to evict the dead connection
        resp = None
        for _ in range(4):
            try:
                resp = await t.call(endpoint, "ok", _rir(0), timeout_ms=1000)
                break
            except RpcError:
                await asyncio.sleep(0.05)
        assert resp is not None and resp.index == 5
        await t.close()
        await srv2.stop()

    @pytest.mark.asyncio
    async def test_large_payload(self):
        srv = await _start_server(NativeTcpRpcServer)

        async def echo(req):
            return ReadIndexResponse(index=len(req.data), success=True)

        srv.register("echo", echo)
        t = NativeTcpTransport()
        blob = bytes(range(256)) * (4 * 1024 * 16)  # 4 MB
        resp = await t.call(srv.endpoint, "echo",
                            GetFileResponse(eof=False, data=blob),
                            timeout_ms=5000)
        assert resp.index == len(blob)
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_many_concurrent_calls(self):
        """Stress the event queue + pipelining: 200 interleaved calls."""
        srv = await _start_server(NativeTcpRpcServer)

        async def echo(req):
            return ReadIndexResponse(index=req.index, success=True)

        srv.register("echo", echo)
        t = NativeTcpTransport()
        results = await asyncio.gather(*[
            t.call(srv.endpoint, "echo", _rir(i), timeout_ms=5000)
            for i in range(200)])
        assert [r.index for r in results] == list(range(200))
        await t.close()
        await srv.stop()


class TestInterop:
    """Wire compatibility both directions."""

    @pytest.mark.asyncio
    async def test_python_client_native_server(self):
        srv = await _start_server(NativeTcpRpcServer)

        async def echo(req):
            return ReadIndexResponse(index=req.index, success=True)

        srv.register("echo", echo)
        t = TcpTransport()
        resp = await t.call(srv.endpoint, "echo", _rir(99))
        assert resp.index == 99
        await t.close()
        await srv.stop()

    @pytest.mark.asyncio
    async def test_native_client_python_server(self):
        srv = await _start_server(TcpRpcServer)

        async def echo(req):
            return ReadIndexResponse(index=req.index, success=True)

        srv.register("echo", echo)
        t = NativeTcpTransport()
        resp = await t.call(srv.endpoint, "echo", _rir(123))
        assert resp.index == 123
        await t.close()
        await srv.stop()


class NativeCluster(TcpCluster):
    server_cls = NativeTcpRpcServer
    transport_cls = NativeTcpTransport


class TestRaftOverNativeTransport:
    @pytest.mark.asyncio
    async def test_elect_replicate_failover(self, tmp_path):
        c = NativeCluster(tmp_path)
        await c.start(3)
        try:
            leader = await c.wait_leader()
            for i in range(5):
                st = await c.apply_ok(leader, b"cmd%d" % i)
                assert st.is_ok(), st
            await c.wait_applied(5)
            dead = leader.server_id
            await c.crash(dead)
            leader2 = await c.wait_leader()
            assert leader2.server_id != dead
            st = await c.apply_ok(leader2, b"after-failover")
            assert st.is_ok(), st
            await c.restart(dead)
            await c.wait_applied(6)
            assert c.fsms[dead].logs[-1] == b"after-failover"
        finally:
            await c.stop_all()


class TestSnapshotInstallOverNativeTransport:
    @pytest.mark.asyncio
    async def test_install_snapshot_remote_copy(self, tmp_path):
        """InstallSnapshot's chunked remote file copy (GetFileRequest /
        FileService) over the native epoll transport: a follower that
        crashed past the compaction horizon pulls the snapshot over real
        sockets through the C++ engine."""
        c = NativeCluster(tmp_path, snapshot=True)
        await c.start(3)
        try:
            leader = await c.wait_leader()
            victim = next(p for p in c.peers if p != leader.server_id)
            st = await c.apply_ok(leader, b"s0")
            assert st.is_ok()
            await c.wait_applied(1)
            await c.crash(victim)
            for i in range(1, 15):
                st = await c.apply_ok(leader, b"s%d" % i)
                assert st.is_ok(), st
            st = await leader.snapshot()
            assert st.is_ok(), str(st)
            assert leader.log_manager.first_log_index() > 1
            # Drain in-flight sends to the victim BEFORE restarting it
            # (the r4 "snapshots_loaded 0" flake, root-caused by
            # submit/restart trace: an entry-bearing frame built from
            # the not-yet-compacted log during the snapshot was
            # delivered to the RESTARTED server 9ms later, catching the
            # victim up via the log path — see drain_sends_to)
            from tests.cluster import TestCluster
            await TestCluster.drain_sends_to(leader, victim.endpoint)
            await c.restart(victim)
            await c.wait_applied(15, timeout_s=15)
            assert c.fsms[victim].logs == [b"s%d" % i for i in range(15)]
            assert c.fsms[victim].snapshots_loaded >= 1
        finally:
            await c.stop_all()


class FaultyNativeCluster(NativeCluster):
    """Native-transport cluster with per-node fault injection wrappers."""

    def transport_cls(self, endpoint):  # type: ignore[override]
        from tpuraft.rpc.fault import FaultInjectingTransport

        t = FaultInjectingTransport(NativeTcpTransport(endpoint=endpoint),
                                    seed=len(self.faults) + 1)
        self.faults.append(t)
        return t

    def __init__(self, tmp_path=None, snapshot=False):
        super().__init__(tmp_path, snapshot)
        self.faults = []


class TestAdversarialOverNativeTransport:
    @pytest.mark.asyncio
    async def test_drops_and_delays_over_real_sockets(self, tmp_path):
        """The adversarial tier on production wire paths: 5% injected
        drops + 2ms delays on every node's outbound calls over the C++
        epoll transport; writes keep committing and replicas converge
        exactly-once."""
        import time as _time

        c = FaultyNativeCluster(tmp_path)
        await c.start(3)
        try:
            leader = await c.wait_leader()
            for f in c.faults:
                f.set_drop_rate(0.05)
                f.set_delay_ms(2)
            acked = []
            for i in range(40):
                try:
                    st = await c.apply_ok(leader, b"f%03d" % i)
                    if st.is_ok():
                        acked.append(b"f%03d" % i)
                except asyncio.TimeoutError:
                    pass  # counts against the >=30 threshold below
                leader = await c.wait_leader()
            assert len(acked) >= 30, len(acked)
            for f in c.faults:
                f.set_drop_rate(0)
                f.set_delay_ms(0)
            acked_set = set(acked)
            deadline = _time.monotonic() + 15
            while _time.monotonic() < deadline:
                logs = [c.fsms[p].logs for p in c.peers]
                if (logs[0] == logs[1] == logs[2]
                        and acked_set <= set(logs[0])):
                    break
                await asyncio.sleep(0.1)
            logs = [c.fsms[p].logs for p in c.peers]
            assert logs[0] == logs[1] == logs[2]
            from collections import Counter
            occ = Counter(logs[0])
            for e in acked_set:
                assert occ[e] == 1, (e, occ[e])
        finally:
            await c.stop_all()
