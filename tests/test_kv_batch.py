"""Store-grouped KV serving plane: kv_command_batch wire compat, the
cross-region propose fan-out, MULTI log entries, and coalesced FSM apply.

Covers ISSUE 6's tentpole + test satellites: old-client/new-server and
new-client/old-server interop (the ENOMETHOD fallback sticks and the
counters say so), per-item epoch/range errors (a batch racing a split
re-shards only the escaping items), apply_multi's one-entry
amortization with per-op results, and the apply coalescer's semantics.
"""

import asyncio
import contextlib

from tests.kv_cluster import KVTestCluster
from tpuraft.entity import LogEntry, LogId
from tpuraft.errors import RaftError, Status
from tpuraft.rheakv.client import BatchingOptions, RheaKVStore
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.kv_service import (
    ERR_INVALID_EPOCH,
    ERR_KEY_OUT_OF_RANGE,
    KVCommandBatchRequest,
    decode_batch_reply,
    decode_result,
    encode_batch_item,
)
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_client import FakePlacementDriverClient
from tpuraft.rheakv.raw_store import MemoryRawKVStore
from tpuraft.rheakv.state_machine import KVClosure, KVStoreStateMachine
from tpuraft.core.state_machine import Iterator


@contextlib.asynccontextmanager
async def batch_cluster(regions=None, batching=True, **kw):
    c = KVTestCluster(3, regions=regions, **kw)
    await c.start_all()
    pd = FakePlacementDriverClient(c.region_template)
    pd._regions = {r.id: r.copy() for s in [next(iter(c.stores.values()))]
                   for r in s.list_regions()}
    transport = c.client_transport()
    calls = []
    orig_call = transport.call

    async def counting_call(dst, method, req, timeout_ms=None):
        calls.append(method)
        return await orig_call(dst, method, req, timeout_ms)

    transport.call = counting_call
    kv = RheaKVStore(pd, transport,
                     batching=BatchingOptions(enabled=True)
                     if batching else None)
    await kv.start()
    try:
        yield c, kv, calls
    finally:
        await kv.shutdown()
        await c.stop_all()


REGIONS2 = lambda: [Region(id=1, start_key=b"", end_key=b"m"),  # noqa: E731
                    Region(id=2, start_key=b"m", end_key=b"")]


async def test_batched_puts_ride_store_grouped_rpcs():
    """Concurrent puts spanning regions coalesce into kv_command_batch
    RPCs — one per leader STORE, not one per region or per op."""
    async with batch_cluster(regions=REGIONS2()) as (c, kv, calls):
        for rid in (1, 2):
            await c.wait_region_leader(rid)
        # prime leader hints so the measured burst groups by known stores
        assert await kv.put(b"a-prime", b"p")
        assert await kv.put(b"z-prime", b"p")
        n0 = len(calls)
        b0 = kv.batch_rpcs
        oks = await asyncio.gather(
            *[kv.put(b"a%03d" % i, b"v%d" % i) for i in range(20)],
            *[kv.put(b"z%03d" % i, b"w%d" % i) for i in range(20)])
        assert all(oks)
        burst = [m for m in calls[n0:] if m.startswith("kv_command")]
        # 40 puts over 2 regions whose leaders sit on <= 2 stores: a
        # handful of store-grouped RPCs, NOT one per region/op
        assert len(burst) <= 4, burst
        assert kv.batch_rpcs > b0
        assert kv.batch_items >= kv.batch_rpcs  # many items per RPC
        # server counted them too
        assert sum(s.kv_processor.batch_rpcs
                   for s in c.stores.values()) >= kv.batch_rpcs - b0
        # data landed
        got = await kv.multi_get([b"a%03d" % i for i in range(20)])
        assert got == {b"a%03d" % i: b"v%d" % i for i in range(20)}


async def test_batched_gets_and_mixed_rounds():
    async with batch_cluster(regions=REGIONS2()) as (c, kv, calls):
        for rid in (1, 2):
            await c.wait_region_leader(rid)
        oks = await asyncio.gather(
            *[kv.put(b"g%03d" % i, b"v%d" % i) for i in range(16)])
        assert all(oks)
        n0 = len(calls)
        got = await asyncio.gather(
            *[kv.get(b"g%03d" % i) for i in range(16)],
            kv.get(b"zz-missing"))
        assert got[:16] == [b"v%d" % i for i in range(16)]
        assert got[16] is None
        reads = [m for m in calls[n0:] if m.startswith("kv_command")]
        assert len(reads) <= 4, reads


async def test_new_client_old_server_enomethod_fallback_sticks():
    """A fleet without kv_command_batch: the first batch RPC comes back
    ENOMETHOD, the client downgrades PERMANENTLY to per-op kv_command,
    serves the round through it, and never probes again."""
    async with batch_cluster(regions=REGIONS2()) as (c, kv, calls):
        for rid in (1, 2):
            await c.wait_region_leader(rid)
        assert await kv.put(b"a-prime", b"p")
        assert await kv.put(b"z-prime", b"p")
        # simulate an old fleet: drop the handler from every store
        for s in c.stores.values():
            s.rpc_server._handlers.pop("kv_command_batch", None)
        oks = await asyncio.gather(
            *[kv.put(b"a%03d" % i, b"v%d" % i) for i in range(8)],
            *[kv.put(b"z%03d" % i, b"w%d" % i) for i in range(8)])
        assert all(oks)
        assert kv._batch_ok is False
        assert kv.batch_fallbacks >= 1
        fallbacks_after_first = kv.batch_fallbacks
        n0 = len(calls)
        assert await asyncio.gather(
            *[kv.put(b"a-again%d" % i, b"x") for i in range(6)])
        # no further kv_command_batch attempts — the downgrade stuck
        assert "kv_command_batch" not in calls[n0:]
        assert kv.batch_fallbacks == fallbacks_after_first
        assert await kv.get(b"a003") == b"v3"


async def test_old_client_new_server_per_op_path_serves():
    """Old clients keep speaking per-op kv_command against a batch-aware
    store (the handler stays registered and counted)."""
    async with batch_cluster(regions=REGIONS2(), batching=False) \
            as (c, kv, calls):
        await c.wait_region_leader(1)
        assert await kv.put(b"legacy", b"v")
        assert await kv.get(b"legacy") == b"v"
        assert "kv_command" in calls
        assert "kv_command_batch" not in calls
        assert sum(s.kv_processor.single_rpcs
                   for s in c.stores.values()) >= 2
        assert sum(s.kv_processor.batch_rpcs
                   for s in c.stores.values()) == 0


async def test_batch_per_item_errors_epoch_and_range():
    """One RPC, three items: a valid op, a stale-epoch item and an
    out-of-range item — each answers its OWN code (+ fresh region meta),
    the valid neighbour commits."""
    async with batch_cluster(regions=REGIONS2()) as (c, kv, calls):
        leader2 = await c.wait_region_leader(2)
        se = leader2.store_engine
        region2 = leader2.region
        ep = se.server_id.endpoint
        items = [
            # valid: a region-2 key at the current epoch
            encode_batch_item(2, region2.epoch.conf_ver,
                              region2.epoch.version,
                              KVOperation(KVOp.PUT, b"zz-ok", b"1").encode()),
            # stale epoch
            encode_batch_item(2, region2.epoch.conf_ver,
                              region2.epoch.version + 7,
                              KVOperation(KVOp.PUT, b"zz-x", b"1").encode()),
            # right epoch, key belongs to region 1
            encode_batch_item(2, region2.epoch.conf_ver,
                              region2.epoch.version,
                              KVOperation(KVOp.PUT, b"aa-x", b"1").encode()),
        ]
        resp = await c.client_transport("probe:0").call(
            ep, "kv_command_batch", KVCommandBatchRequest(items=items), 2000)
        codes = [decode_batch_reply(b)[0] for b in resp.items]
        assert codes == [0, ERR_INVALID_EPOCH, ERR_KEY_OUT_OF_RANGE], codes
        # rejected items carry the current region meta for re-sharding
        for blob in resp.items[1:]:
            meta = decode_batch_reply(blob)[3]
            assert Region.decode(meta).id == 2
        assert decode_result(decode_batch_reply(resp.items[0])[2]) is True
        assert await kv.get(b"zz-ok") == b"1"
        assert await kv.get(b"aa-x") is None


async def test_batch_races_split_reshards_only_escaping_items():
    """A split lands under a batching client's stale route view: the
    stale region's items bounce per item, get re-sharded against the
    refreshed routes and commit; items for other regions in the same
    store batch are untouched."""
    async with batch_cluster() as (c, kv, calls):
        leader = await c.wait_region_leader(1)
        for i in range(32):
            assert await kv.put(b"key%02d" % i, b"v%d" % i)
        # split behind the client's back
        st = await leader.store_engine.apply_split(1, 2)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(2)
        await c.wait_region_leader(2)
        # burst across the WHOLE old range: every item initially groups
        # into stale region 1
        oks = await asyncio.gather(
            *[kv.put(b"key%02d" % i, b"u%d" % i) for i in range(32)])
        assert all(oks)
        assert len(kv.route_table.list_regions()) == 2
        for i in range(32):
            assert await kv.get(b"key%02d" % i) == b"u%d" % i


async def test_apply_multi_one_entry_per_region_with_per_op_results():
    """apply_multi rides ONE log entry (one quorum round) and returns
    per-op (status, result); a failing sub-op fails only its slot."""
    async with batch_cluster() as (c, kv, calls):
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        node = leader.node
        entries = []
        orig_ab = node.apply_batch

        async def counting_ab(tasks):
            entries.append(len(tasks))
            return await orig_ab(tasks)

        node.apply_batch = counting_ab
        try:
            await rs.apply(KVOperation(KVOp.PUT, b"m0", b"base"))
            entries.clear()
            outs = await rs.apply_multi([
                KVOperation(KVOp.PUT, b"m1", b"v1"),
                KVOperation.cas(b"m0", b"WRONG", b"nope"),   # CAS miss
                KVOperation(KVOp.PUT_IF_ABSENT, b"m0", b"x"),
                KVOperation(KVOp.DELETE, b"m1"),
                KVOperation(KVOp.GET_SEQUENCE, b"mseq",
                            aux=__import__("struct").pack("<q", 5)),
            ])
        finally:
            node.apply_batch = orig_ab
        # the whole sub-batch rode one Task in one apply_batch call
        assert sum(entries) == 1, entries
        sts = [st for st, _ in outs]
        assert all(st.is_ok() for st in sts), sts
        results = [r for _, r in outs]
        assert results[0] is True
        assert results[1] is False          # CAS miss is a result, not error
        assert results[2] == b"base"        # put_if_absent saw existing
        assert results[3] is True
        assert results[4] == (0, 5)
        assert await kv.get(b"m1") is None
        assert await kv.get(b"m0") == b"base"


async def test_raft_store_public_apply_api():
    """kv_service drives proposals through the public apply() now; the
    legacy _apply name stays as an alias for straggler callers."""
    async with batch_cluster() as (c, kv, calls):
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        assert await rs.apply(KVOperation(KVOp.PUT, b"pub", b"1")) is True
        assert await rs._apply(KVOperation(KVOp.PUT, b"pri", b"2")) is True
        # blind writes ack at COMMIT (ISSUE 15 pipelined apply): the
        # fenced read path — not a raw store peek — observes the value
        assert await rs.get(b"pub") == b"1"
        assert await rs.get(b"pri") == b"2"


# ---- FSM apply coalescing (unit tier) --------------------------------------


def _entry(op: KVOperation, index: int) -> LogEntry:
    return LogEntry(id=LogId(index=index, term=1), data=op.encode())


class _BatchSpyStore(MemoryRawKVStore):
    def __init__(self):
        super().__init__()
        self.batch_calls: list[int] = []
        self.single_calls = 0

    def apply_write_batch(self, ops):
        self.batch_calls.append(len(ops))
        super().apply_write_batch(ops)

    def put(self, key, value):
        self.single_calls += 1
        super().put(key, value)


async def test_fsm_coalesces_consecutive_put_delete_runs():
    store = _BatchSpyStore()
    region = Region(id=1, start_key=b"", end_key=b"")
    fsm = KVStoreStateMachine(region, store)
    futs = [asyncio.get_running_loop().create_future() for _ in range(6)]
    ops = [
        KVOperation(KVOp.PUT, b"a", b"1"),
        KVOperation(KVOp.PUT, b"b", b"2"),
        KVOperation.put_list([(b"c", b"3"), (b"d", b"4")]),
        KVOperation(KVOp.DELETE, b"a"),
        KVOperation(KVOp.MERGE, b"e", b"x"),   # breaks the run
        KVOperation(KVOp.PUT, b"f", b"6"),
    ]
    it = Iterator([_entry(op, i + 1) for i, op in enumerate(ops)],
                  [KVClosure(f) for f in futs])
    await fsm.on_apply(it)
    # one coalesced flush for ops 0-3 (5 rows), merge dispatched singly,
    # trailing put flushed as its own run
    assert store.batch_calls[0] == 5, store.batch_calls
    assert fsm.coalesced_flushes == 1
    assert fsm.coalesced_ops == 5
    assert store.get(b"a") is None
    assert store.get(b"b") == b"2"
    assert store.get(b"c") == b"3"
    assert store.get(b"e") == b"x"
    assert store.get(b"f") == b"6"
    for f in futs:
        st, result = f.result()
        assert st.is_ok()
        assert result is True or result is None  # merge returns True too
    # every closure that rode the run reports True
    assert futs[0].result()[1] is True
    assert futs[3].result()[1] is True


async def test_fsm_coalescing_off_preserves_per_op_calls():
    store = _BatchSpyStore()
    region = Region(id=1, start_key=b"", end_key=b"")
    fsm = KVStoreStateMachine(region, store, coalesce_applies=False)
    ops = [KVOperation(KVOp.PUT, b"x%d" % i, b"v") for i in range(4)]
    it = Iterator([_entry(op, i + 1) for i, op in enumerate(ops)],
                  [None] * 4)
    await fsm.on_apply(it)
    assert store.batch_calls == []
    assert store.single_calls == 4
    assert fsm.coalesced_flushes == 0


async def test_fsm_multi_entry_per_op_outcomes_and_inner_coalescing():
    store = _BatchSpyStore()
    region = Region(id=1, start_key=b"", end_key=b"")
    fsm = KVStoreStateMachine(region, store)
    store.put(b"seed", b"s")
    store.batch_calls.clear()
    multi = KVOperation.multi([
        KVOperation(KVOp.PUT, b"p1", b"1"),
        KVOperation(KVOp.DELETE, b"seed"),
        KVOperation(KVOp.PUT, b"p2", b"2"),
        KVOperation.cas(b"p9", b"nope", b"x"),     # CAS miss mid-batch
        KVOperation(KVOp.PUT, b"p3", b"3"),
    ])
    fut = asyncio.get_running_loop().create_future()
    it = Iterator([_entry(multi, 1)], [KVClosure(fut)])
    await fsm.on_apply(it)
    st, outs = fut.result()
    assert st.is_ok()
    codes = [c for c, _m, _r in outs]
    results = [r for _c, _m, r in outs]
    assert codes == [0, 0, 0, 0, 0]
    assert results == [True, True, True, False, True]
    # the three leading write ops coalesced into one batch write
    assert store.batch_calls[0] == 3, store.batch_calls
    assert store.get(b"p1") == b"1" and store.get(b"seed") is None


async def test_batching_client_history_stays_linearizable():
    """Writers + readers through the batching client: the recorded
    history checks out linearizable — batched ops ack and apply
    atomically per item."""
    from tpuraft.util.linearizability import History, check_history

    async with batch_cluster(regions=REGIONS2()) as (c, kv, calls):
        for rid in (1, 2):
            await c.wait_region_leader(rid)
        h = History()
        stop = asyncio.Event()
        keys = [b"ba-%d" % i for i in range(2)] + [b"zb-%d" % i
                                                  for i in range(2)]
        # one guaranteed-concurrent burst across both regions so the
        # store-grouped path is exercised even if the mixed load below
        # happens to drain one op per round
        seeds = [h.invoke(9, "w", (k, b"seed")) for k in keys]
        assert all(await asyncio.gather(*(kv.put(k, b"seed")
                                          for k in keys)))
        for tok in seeds:
            h.complete(tok, True)
        assert kv.batch_rpcs > 0
        n_ok = [0]

        async def writer(cid):
            n = 0
            while not stop.is_set():
                n += 1
                key = keys[n % len(keys)]
                val = b"c%d-%d" % (cid, n)
                tok = h.invoke(cid, "w", (key, val))
                try:
                    await asyncio.wait_for(kv.put(key, val), 4.0)
                    h.complete(tok, True)
                    n_ok[0] += 1
                except Exception:
                    pass
                await asyncio.sleep(0.003)

        async def reader(cid):
            n = 0
            while not stop.is_set():
                n += 1
                key = keys[n % len(keys)]
                tok = h.invoke(cid, "r", (key,))
                try:
                    v = await asyncio.wait_for(kv.get(key), 4.0)
                    h.complete(tok, v)
                    n_ok[0] += 1
                except Exception:
                    pass
                await asyncio.sleep(0.002)

        tasks = [asyncio.ensure_future(writer(0)),
                 asyncio.ensure_future(writer(1)),
                 asyncio.ensure_future(reader(2)),
                 asyncio.ensure_future(reader(3))]
        await asyncio.sleep(2.0)
        stop.set()
        await asyncio.gather(*tasks)
        assert n_ok[0] > 100, f"only {n_ok[0]} ops completed"
        assert kv.batch_rpcs > 0   # the load actually rode the batch path
        rep = check_history(h)
        assert rep.ok, str(rep)
