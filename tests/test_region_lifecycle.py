"""Region lifecycle tests (ISSUE 20): the keyspace-coverage oracle,
the PD-side placement policy (cold merge / cross-store move picks and
their mutual-exclusion busy sets), and the store-side choreography
under churn — merge after split on the live tiling, merge deferring
(not wedging) on an in-flight conf change, a replica move racing a
leader kill, and a lifecycle-enabled PD merging cold regions end to
end with the client re-resolving routes out of the merged-away region.
"""

import asyncio
import contextlib
import time

import pytest

from tests.kv_cluster import KVTestCluster, PDTestCluster
from tests.oracle import coverage_errors
from tpuraft.errors import RaftError
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_server import RegionStats
from tpuraft.rheakv.placement import LifecycleOptions, PlacementEngine


# ---- unit: keyspace-coverage oracle ----------------------------------------


def _r(rid, start, end):
    return Region(id=rid, start_key=start, end_key=end)


def test_coverage_oracle_accepts_tiling():
    assert coverage_errors([_r(1, b"", b"")]) == []
    assert coverage_errors([_r(1, b"", b"m"), _r(2, b"m", b"")]) == []
    assert coverage_errors(
        [_r(3, b"g", b"t"), _r(1, b"", b"g"), _r(2, b"t", b"")]) == []


def test_coverage_oracle_flags_violations():
    assert coverage_errors([]) != []
    # hole at the left edge, in the middle, and at the right edge
    assert any("hole" in e for e in coverage_errors([_r(1, b"a", b"")]))
    assert any("hole" in e for e in coverage_errors(
        [_r(1, b"", b"g"), _r(2, b"h", b"")]))
    assert any("hole" in e for e in coverage_errors([_r(1, b"", b"z")]))
    # overlap (the merge-bug signature: source resurrected next to the
    # extended target) and duplicate ids
    assert any("overlap" in e for e in coverage_errors(
        [_r(1, b"", b"m"), _r(2, b"g", b"")]))
    assert any("unbounded" in e for e in coverage_errors(
        [_r(1, b"", b""), _r(2, b"m", b"")]))
    assert any("twice" in e for e in coverage_errors(
        [_r(1, b"", b"m"), _r(1, b"m", b"")]))


# ---- unit: placement policy ------------------------------------------------


EP = ["127.0.0.1:6%03d" % i for i in range(4)]


class _StatsStub:
    """Duck-typed ClusterStatsManager slice the policy reads."""

    def __init__(self, stats=None, hot=()):
        self._stats = dict(stats or {})
        self._hot = set(hot)

    def hot_regions(self):
        return set(self._hot)

    def region_stats(self, rid):
        return self._stats.get(rid) or RegionStats()

    def last_keys(self, rid):
        return self.region_stats(rid).keys


def _three_regions():
    peers = list(EP[:3])
    return {
        1: Region(id=1, start_key=b"", end_key=b"g", peers=list(peers)),
        2: Region(id=2, start_key=b"g", end_key=b"t", peers=list(peers)),
        3: Region(id=3, start_key=b"t", end_key=b"", peers=list(peers)),
    }


def test_pick_merge_cold_adjacent_pair_and_pacing():
    eng = PlacementEngine(LifecycleOptions(min_regions=2))
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({rid: RegionStats(keys=10) for rid in regions})
    pick = eng.pick_merge(regions, leaders, EP[0], stats, {}, {})
    assert pick == (1, 2)   # coldest source absorbs into its RIGHT neighbor
    # both sides now cool: an immediate re-pick must not double-order
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) is None


def test_pick_merge_busy_and_floor_exclusions():
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({rid: RegionStats(keys=10) for rid in regions})

    def fresh():
        return PlacementEngine(LifecycleOptions(min_regions=2))

    # a pending SPLIT on either side takes the pair off the table
    # (merge-races-split exclusion — replicated busy sets)
    assert fresh().pick_merge(regions, leaders, EP[0], stats,
                              {}, {1: 99}) == (2, 3)
    assert fresh().pick_merge(regions, leaders, EP[0], stats,
                              {}, {1: 99, 2: 98}) is None
    # a HOT region is never merged (either side)
    hot = _StatsStub({rid: RegionStats(keys=10) for rid in regions},
                     hot={1, 2})
    assert fresh().pick_merge(regions, leaders, EP[0], hot, {}, {}) is None
    # inflight cap
    eng = PlacementEngine(LifecycleOptions(min_regions=2,
                                           max_inflight_merges=1))
    assert eng.pick_merge(regions, leaders, EP[0], stats,
                          {7: 8}, {}) is None
    # min_regions floor: never merge the fleet below it
    eng = PlacementEngine(LifecycleOptions(min_regions=3))
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) is None
    # only regions led from the heartbeating store can act
    assert fresh().pick_merge(regions, leaders, EP[1], stats, {}, {}) is None


def test_pick_merge_oversized_source_excluded():
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({1: RegionStats(keys=100000),
                        2: RegionStats(keys=10),
                        3: RegionStats(keys=10)})
    eng = PlacementEngine(LifecycleOptions(min_regions=2,
                                           merge_max_keys=4096))
    # region 1 holds too many keys to churn through the target's log
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) == (2, 3)


def test_pick_move_imbalance_zone_and_health():
    peers = list(EP[:3])
    regions = {i: Region(id=i, start_key=b"%d" % i, end_key=b"%d" % (i + 1),
                         peers=list(peers)) for i in range(1, 4)}
    leaders = {rid: EP[0] for rid in regions}
    eng = PlacementEngine(LifecycleOptions(move_imbalance=2))
    mv = eng.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {})
    assert mv is not None
    rid, src_p, dst_ep = mv
    assert dst_ep == EP[3]           # the only store hosting nothing
    assert src_p != leaders[rid]     # non-leader sources preferred
    # inflight cap: with max_inflight_moves=1 the next pick waits
    eng2 = PlacementEngine(LifecycleOptions(move_imbalance=2,
                                            max_inflight_moves=1))
    assert eng2.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {})
    assert eng2.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {}) \
        is None
    # a SICK destination is never targeted — here it is the only one
    eng3 = PlacementEngine(LifecycleOptions(move_imbalance=2))
    assert eng3.pick_move(regions, leaders, EP[0], EP, {},
                          {EP[3]: "sick"}, {}, {}) is None
    # zone diversity breaks ties between equally-roomy destinations
    two = {i: Region(id=i, start_key=b"%d" % i, end_key=b"%d" % (i + 1),
                     peers=[EP[0], EP[1]]) for i in range(1, 4)}
    zones = {EP[0]: "z1", EP[1]: "z1", EP[2]: "z1", EP[3]: "z2"}
    eng4 = PlacementEngine(LifecycleOptions(move_imbalance=2))
    mv = eng4.pick_move(two, {rid: EP[0] for rid in two}, EP[0], EP,
                        zones, {}, {}, {})
    assert mv is not None and mv[2] == EP[3]   # the new-zone store wins


def test_pick_move_balanced_fleet_is_left_alone():
    regions = {1: Region(id=1, start_key=b"", end_key=b"",
                         peers=list(EP[:3]))}
    eng = PlacementEngine(LifecycleOptions(move_imbalance=2))
    assert eng.pick_move(regions, {1: EP[0]}, EP[0], EP[:3],
                         {}, {}, {}, {}) is None


# ---- integration: store-side merge choreography ----------------------------


@contextlib.asynccontextmanager
async def kv_cluster(n=3, regions=None, **kw):
    c = KVTestCluster(n, regions=regions, **kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


def _two_region_template():
    return [Region(id=1, start_key=b"", end_key=b"m"),
            Region(id=2, start_key=b"m", end_key=b"")]


async def _wait(cond, timeout_s=8.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {what}")


async def test_merge_absorbs_keyspace_and_retires_source():
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        for i in range(8):
            assert await l1.raft_store.put(b"a%02d" % i, b"L%d" % i)
            assert await l2.raft_store.put(b"z%02d" % i, b"R%d" % i)
        st = await l1.store_engine.apply_merge(
            1, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        # every store retires its source replica and extends its target
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="source retirement on all stores")
        for s in c.stores.values():
            r2 = s.get_region_engine(2).region
            assert (r2.start_key, r2.end_key) == (b"", b"")
            assert coverage_errors([r2]) == []
            assert s.regions_retired == 1 or s.regions_absorbed >= 0
        # the absorbed keyspace serves through the surviving group
        l2 = await c.wait_region_leader(2)
        assert await l2.raft_store.get(b"a03") == b"L3"
        assert await l2.raft_store.get(b"z03") == b"R3"
        assert await l2.raft_store.put(b"a99", b"post-merge")
        assert await l2.raft_store.get(b"a99") == b"post-merge"
        assert l1.store_engine.merges_led == 1


async def test_merge_defers_on_inflight_conf_change():
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        tp = str(l2.node.server_id)
        # pin a conf change in flight: the merge must DEFER (EBUSY, no
        # seal proposed, nothing wedged), exactly what the PD's paced
        # re-issue loop expects
        l1.node._conf_ctx = object()
        try:
            st = await l1.store_engine.apply_merge(1, 2, tp)
            assert st.code == RaftError.EBUSY, str(st)
            assert getattr(l1.fsm, "sealed_into", -1) == -1
        finally:
            l1.node._conf_ctx = None
        # conf change done: the re-issued instruction goes through
        st = await l1.store_engine.apply_merge(1, 2, tp)
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="deferred merge completion")


async def test_merge_rides_the_live_tiling_after_split():
    """Merge-races-split, sequenced the way the PD's replicated busy
    sets allow: the split lands first, then merges run on the POST-
    split tiling (absorb right-to-left chain) — coverage holds at
    every step and every key stays readable."""
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        for i in range(32):
            assert await l1.raft_store.put(b"k%02d" % i, b"v%d" % i)
        st = await l1.store_engine.apply_split(1, 3)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(3)
        l3 = await c.wait_region_leader(3)
        l2 = await c.wait_region_leader(2)
        store = next(iter(c.stores.values()))
        regs = [store.get_region_engine(i).region for i in (1, 2, 3)]
        assert coverage_errors(regs) == []
        # merge the split child into its right neighbor (extend LEFT)
        st = await l3.store_engine.apply_merge(3, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(3) is None
                                for s in c.stores.values()),
                    what="child retirement")
        # then the shrunken parent into the extended survivor
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        st = await l1.store_engine.apply_merge(1, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="parent retirement")
        for s in c.stores.values():
            r2 = s.get_region_engine(2).region
            assert coverage_errors([r2]) == []
        l2 = await c.wait_region_leader(2)
        for i in range(32):
            assert await l2.raft_store.get(b"k%02d" % i) == b"v%d" % i


# ---- integration: cross-store move -----------------------------------------


EP4 = [f"127.0.0.1:{6000 + i}" for i in range(4)]


async def test_move_replica_to_fresh_store():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])]) as c:
        leader = await c.wait_region_leader(1)
        assert await leader.raft_store.put(b"k", b"v")
        src = next(p for p in leader.region.peers
                   if p != str(leader.node.server_id))
        st = await leader.store_engine.apply_move(1, EP4[3], src)
        assert st.is_ok(), str(st)
        ce = leader.node.conf_entry
        peers = {str(p) for p in ce.conf.peers}
        assert EP4[3] in peers and src not in peers
        assert ce.is_stable()   # joint change fully committed
        assert leader.store_engine.moves_applied == 1
        # a retried instruction (PD re-issue after a lost ack) is a no-op
        st = await leader.store_engine.apply_move(1, EP4[3], src)
        assert st.is_ok(), str(st)
        assert await leader.raft_store.get(b"k") == b"v"


async def test_move_self_leader_source_hands_off_first():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])]) as c:
        leader = await c.wait_region_leader(1)
        me = str(leader.node.server_id)
        st = await leader.store_engine.apply_move(1, EP4[3], me)
        assert st.code == RaftError.EBUSY, str(st)

        # leadership moves off the source so the re-issued move can run
        async def _moved():
            nl = await c.wait_region_leader(1)
            return str(nl.node.server_id) != me

        deadline = time.monotonic() + 8.0
        while not await _moved():
            assert time.monotonic() < deadline, \
                "leadership never left the move source"
            await asyncio.sleep(0.05)


async def test_move_races_leader_kill():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])],
                          tmp_path=None) as c:
        leader = await c.wait_region_leader(1)
        leader_ep = leader.node.server_id.endpoint
        src = next(p for p in leader.region.peers
                   if p != str(leader.node.server_id))
        move = asyncio.ensure_future(
            leader.store_engine.apply_move(1, EP4[3], src))
        await asyncio.sleep(0.05)   # land mid-catchup / mid-joint
        await c.stop_store(leader_ep)
        with contextlib.suppress(Exception):
            await move
        # a new leader emerges among the surviving conf members and the
        # re-issued move converges (retry-safe whatever the kill hit)
        new_leader = await c.wait_region_leader(1, timeout_s=10.0)
        deadline = time.monotonic() + 10.0
        while True:
            st = await new_leader.store_engine.apply_move(1, EP4[3], src)
            ce = new_leader.node.conf_entry
            peers = {str(p) for p in ce.conf.peers}
            if st.is_ok() and EP4[3] in peers and src not in peers \
                    and ce.is_stable():
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"move did not converge: {st} peers={peers}")
            await asyncio.sleep(0.2)
            new_leader = await c.wait_region_leader(1, timeout_s=10.0)
        assert await new_leader.raft_store.put(b"post", b"kill")


# ---- integration: lifecycle-enabled PD end to end --------------------------


async def test_pd_lifecycle_merges_cold_regions_end_to_end():
    """A lifecycle PD observes an all-cold 4-region fleet, orders cold
    merges down to the floor, replicates completion, and the CLIENT
    re-resolves routes out of the merged-away regions (satellite 1:
    stale-route eviction on ERR_NO_REGION + PD adjudication)."""
    from tpuraft.rheakv.client import RheaKVStore

    template = [
        Region(id=1, start_key=b"", end_key=b"g"),
        Region(id=2, start_key=b"g", end_key=b"n"),
        Region(id=3, start_key=b"n", end_key=b"t"),
        Region(id=4, start_key=b"t", end_key=b""),
    ]
    c = PDTestCluster(
        n_stores=3, n_pd=1, regions=template,
        heartbeat_interval_ms=100,
        pd_opts={
            "lifecycle": True,
            "lifecycle_min_regions": 2,
            "lifecycle_merge_cooldown_s": 0.5,
            "lifecycle_move_cooldown_s": 0.5,
            "lifecycle_max_inflight_merges": 1,
            # suppress moves: this test isolates the merge actuator
            "lifecycle_move_imbalance": 99,
        })
    await c.start_all()
    try:
        pd = await c.wait_pd_leader()
        kv = RheaKVStore(c.pd_client(), c.client_transport(),
                         timeout_ms=3000, max_retries=16)
        await kv.start()
        # seed the client's route table AND data in every region
        for k in (b"a", b"h", b"p", b"x"):
            assert await kv.put(k, b"v-" + k)
        # snapshot the pre-merge routes: an epoch bounce during the
        # merge window can refresh the table early, so pin the stale
        # view back afterwards to make the eviction path deterministic
        stale_routes = [r.copy() for r in kv.route_table.list_regions()]
        # the policy merges the cold fleet down to the floor
        await _wait(lambda: len(pd.fsm.regions) <= 2
                    and not pd.fsm.pending_merges,
                    timeout_s=30.0, what="cold merges down to the floor")
        assert pd.merges_completed >= 2
        assert coverage_errors(pd.fsm.regions.values()) == []
        kv.route_table.reset([r.copy() for r in stale_routes])
        # every key survives, including ones whose region merged away —
        # the client bounces off the retired group, evicts the stale
        # route and lands in the absorbing region
        for k in (b"a", b"h", b"p", b"x"):
            assert await kv.get(k) == b"v-" + k
        assert await kv.put(b"hh", b"post-merge")
        assert await kv.get(b"hh") == b"post-merge"
        assert kv.merged_evictions >= 1
        # the admin surface reports the lifecycle plane
        view = await kv.pd.cluster_describe()
        assert view and view.get("lifecycle"), view
        assert view["lifecycle"]["merges_completed"] >= 2
        await kv.shutdown()
    finally:
        await c.stop_all()


def test_admin_regions_view_renders(capsys):
    """The admin `regions` renderer handles a lifecycle view, a region
    with no heat row, pending merges, and the lifecycle-off PD."""
    from examples.admin import _print_regions_view

    regions = [Region(id=1, start_key=b"", end_key=b"m",
                      peers=[EP[0], EP[1]]),
               Region(id=2, start_key=b"m", end_key=b"",
                      peers=[EP[0], EP[1]])]
    view = {
        "hot": [{"region": 1, "leader": EP[0], "score": 3.1,
                 "writes_s": 9.0, "reads_s": 2.0, "keys": 64}],
        "cold": [],
        "hot_flagged": [1],
        "lifecycle": {
            "pending_merges": {"2": 1},
            "retired_regions": 3,
            "recent": [{"kind": "heat_split", "term": 1, "region": 1,
                        "child": 1024},
                       {"kind": "move", "term": 1, "region": 2,
                        "src": EP[0], "dst": EP[1]}],
            "heat_splits_ordered": 4, "merges_ordered": 2,
            "merges_completed": 2, "moves_ordered": 1,
        },
    }
    _print_regions_view(regions, view)
    out = capsys.readouterr().out
    assert "lifecycle ON" in out and "1 pending merge" in out
    assert "HOT" in out and "MERGING->1" in out
    assert "heat_split" in out and "child=1024" in out
    # pre-lifecycle PD (or lifecycle off): renders without decisions
    _print_regions_view(regions, {"hot": [], "cold": []})
    out = capsys.readouterr().out
    assert "lifecycle off" in out and "no placement decisions" in out


def test_replayed_split_report_cannot_resurrect_merged_region():
    """Regression: a mint-era split report replayed AFTER the child has
    merged away must not resurrect it in the PD metadata.

    ``do_split`` runs on every replica and every replica's async boot
    re-reports the split; a learner moved onto the group later replays
    the parent log and re-reports splits that are ancient history.  If
    the child has since gone cold and been absorbed by its neighbor,
    its record was popped (tombstoned) — ``cur is None`` — so the epoch
    guard alone lets the stale mint-era record land and double-cover
    the keyspace the absorber already extended over."""
    import struct

    from tpuraft.rheakv.pd_server import (
        _CMD_MERGE, _CMD_REGION_UPSERT, _CMD_SPLIT, PDMetadataFSM, _cmd)

    fsm = PDMetadataFSM()

    def upsert(region, leader=EP[0]):
        lb = leader.encode()
        fsm._dispatch(_cmd(
            _CMD_REGION_UPSERT,
            struct.pack("<H", len(lb)) + lb + region.encode()))

    # initial tiling: region 1 [-inf, m), region 2 [m, +inf)
    upsert(_r(1, b"", b"m"))
    upsert(_r(2, b"m", b""))

    # region 1 splits at g -> child 1024; both halves bump to version 2
    parent = _r(1, b"", b"g")
    parent.epoch.version = 2
    child = _r(1024, b"g", b"m")
    child.epoch.version = 2
    pb = parent.encode()
    split_report = _cmd(
        _CMD_SPLIT, struct.pack("<I", len(pb)) + pb + child.encode())
    assert fsm._dispatch(split_report) is True
    assert coverage_errors(fsm.regions.values()) == []

    # the child goes cold and merges into its right neighbor: region 2
    # extends left over [g, m) and 1024 is tombstoned
    assert fsm._dispatch(
        _cmd(_CMD_MERGE, struct.pack("<qq", 1024, 2))) is True
    assert 1024 not in fsm.regions
    assert fsm.retired_regions[1024] == 2
    assert fsm.regions[2].start_key == b"g"

    # a moved-in learner replays the parent log and re-reports the
    # mint-era split: the tombstone must win over ``cur is None``
    assert fsm._dispatch(split_report) is True
    assert 1024 not in fsm.regions, "merged-away child resurrected"
    assert fsm.regions[2].start_key == b"g"
    assert fsm.regions[2].end_key == b""
    assert coverage_errors(fsm.regions.values()) == []
    # finalizing the same merge again is not "fresh" (no double count)
    assert fsm._dispatch(
        _cmd(_CMD_MERGE, struct.pack("<qq", 1024, 2))) is False
