"""Region lifecycle tests (ISSUE 20): the keyspace-coverage oracle,
the PD-side placement policy (cold merge / cross-store move picks and
their mutual-exclusion busy sets), and the store-side choreography
under churn — merge after split on the live tiling, merge deferring
(not wedging) on an in-flight conf change, a replica move racing a
leader kill, and a lifecycle-enabled PD merging cold regions end to
end with the client re-resolving routes out of the merged-away region.
"""

import asyncio
import contextlib
import time

import pytest

from tests.kv_cluster import KVTestCluster, PDTestCluster
from tests.oracle import coverage_errors
from tpuraft.errors import RaftError
from tpuraft.rheakv.metadata import Region
from tpuraft.rheakv.pd_server import RegionStats
from tpuraft.rheakv.placement import LifecycleOptions, PlacementEngine


# ---- unit: keyspace-coverage oracle ----------------------------------------


def _r(rid, start, end):
    return Region(id=rid, start_key=start, end_key=end)


def test_coverage_oracle_accepts_tiling():
    assert coverage_errors([_r(1, b"", b"")]) == []
    assert coverage_errors([_r(1, b"", b"m"), _r(2, b"m", b"")]) == []
    assert coverage_errors(
        [_r(3, b"g", b"t"), _r(1, b"", b"g"), _r(2, b"t", b"")]) == []


def test_coverage_oracle_flags_violations():
    assert coverage_errors([]) != []
    # hole at the left edge, in the middle, and at the right edge
    assert any("hole" in e for e in coverage_errors([_r(1, b"a", b"")]))
    assert any("hole" in e for e in coverage_errors(
        [_r(1, b"", b"g"), _r(2, b"h", b"")]))
    assert any("hole" in e for e in coverage_errors([_r(1, b"", b"z")]))
    # overlap (the merge-bug signature: source resurrected next to the
    # extended target) and duplicate ids
    assert any("overlap" in e for e in coverage_errors(
        [_r(1, b"", b"m"), _r(2, b"g", b"")]))
    assert any("unbounded" in e for e in coverage_errors(
        [_r(1, b"", b""), _r(2, b"m", b"")]))
    assert any("twice" in e for e in coverage_errors(
        [_r(1, b"", b"m"), _r(1, b"m", b"")]))


# ---- unit: placement policy ------------------------------------------------


EP = ["127.0.0.1:6%03d" % i for i in range(4)]


class _StatsStub:
    """Duck-typed ClusterStatsManager slice the policy reads."""

    def __init__(self, stats=None, hot=()):
        self._stats = dict(stats or {})
        self._hot = set(hot)

    def hot_regions(self):
        return set(self._hot)

    def region_stats(self, rid):
        return self._stats.get(rid) or RegionStats()

    def last_keys(self, rid):
        return self.region_stats(rid).keys


def _three_regions():
    peers = list(EP[:3])
    return {
        1: Region(id=1, start_key=b"", end_key=b"g", peers=list(peers)),
        2: Region(id=2, start_key=b"g", end_key=b"t", peers=list(peers)),
        3: Region(id=3, start_key=b"t", end_key=b"", peers=list(peers)),
    }


def test_pick_merge_cold_adjacent_pair_and_pacing():
    eng = PlacementEngine(LifecycleOptions(min_regions=2))
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({rid: RegionStats(keys=10) for rid in regions})
    pick = eng.pick_merge(regions, leaders, EP[0], stats, {}, {})
    assert pick == (1, 2)   # coldest source absorbs into its RIGHT neighbor
    # both sides now cool: an immediate re-pick must not double-order
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) is None


def test_pick_merge_busy_and_floor_exclusions():
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({rid: RegionStats(keys=10) for rid in regions})

    def fresh():
        return PlacementEngine(LifecycleOptions(min_regions=2))

    # a pending SPLIT on either side takes the pair off the table
    # (merge-races-split exclusion — replicated busy sets)
    assert fresh().pick_merge(regions, leaders, EP[0], stats,
                              {}, {1: 99}) == (2, 3)
    assert fresh().pick_merge(regions, leaders, EP[0], stats,
                              {}, {1: 99, 2: 98}) is None
    # a HOT region is never merged (either side)
    hot = _StatsStub({rid: RegionStats(keys=10) for rid in regions},
                     hot={1, 2})
    assert fresh().pick_merge(regions, leaders, EP[0], hot, {}, {}) is None
    # inflight cap
    eng = PlacementEngine(LifecycleOptions(min_regions=2,
                                           max_inflight_merges=1))
    assert eng.pick_merge(regions, leaders, EP[0], stats,
                          {7: 8}, {}) is None
    # min_regions floor: never merge the fleet below it
    eng = PlacementEngine(LifecycleOptions(min_regions=3))
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) is None
    # only regions led from the heartbeating store can act
    assert fresh().pick_merge(regions, leaders, EP[1], stats, {}, {}) is None


def test_pick_merge_oversized_source_excluded():
    regions = _three_regions()
    leaders = {rid: EP[0] for rid in regions}
    stats = _StatsStub({1: RegionStats(keys=100000),
                        2: RegionStats(keys=10),
                        3: RegionStats(keys=10)})
    eng = PlacementEngine(LifecycleOptions(min_regions=2,
                                           merge_max_keys=4096))
    # region 1 holds too many keys to churn through the target's log
    assert eng.pick_merge(regions, leaders, EP[0], stats, {}, {}) == (2, 3)


def test_pick_move_imbalance_zone_and_health():
    peers = list(EP[:3])
    regions = {i: Region(id=i, start_key=b"%d" % i, end_key=b"%d" % (i + 1),
                         peers=list(peers)) for i in range(1, 4)}
    leaders = {rid: EP[0] for rid in regions}
    eng = PlacementEngine(LifecycleOptions(move_imbalance=2))
    mv = eng.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {})
    assert mv is not None
    rid, src_p, dst_ep = mv
    assert dst_ep == EP[3]           # the only store hosting nothing
    assert src_p != leaders[rid]     # non-leader sources preferred
    # inflight cap: with max_inflight_moves=1 the next pick waits
    eng2 = PlacementEngine(LifecycleOptions(move_imbalance=2,
                                            max_inflight_moves=1))
    assert eng2.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {})
    assert eng2.pick_move(regions, leaders, EP[0], EP, {}, {}, {}, {}) \
        is None
    # a SICK destination is never targeted — here it is the only one
    eng3 = PlacementEngine(LifecycleOptions(move_imbalance=2))
    assert eng3.pick_move(regions, leaders, EP[0], EP, {},
                          {EP[3]: "sick"}, {}, {}) is None
    # zone diversity breaks ties between equally-roomy destinations
    two = {i: Region(id=i, start_key=b"%d" % i, end_key=b"%d" % (i + 1),
                     peers=[EP[0], EP[1]]) for i in range(1, 4)}
    zones = {EP[0]: "z1", EP[1]: "z1", EP[2]: "z1", EP[3]: "z2"}
    eng4 = PlacementEngine(LifecycleOptions(move_imbalance=2))
    mv = eng4.pick_move(two, {rid: EP[0] for rid in two}, EP[0], EP,
                        zones, {}, {}, {})
    assert mv is not None and mv[2] == EP[3]   # the new-zone store wins


def test_pick_move_balanced_fleet_is_left_alone():
    regions = {1: Region(id=1, start_key=b"", end_key=b"",
                         peers=list(EP[:3]))}
    eng = PlacementEngine(LifecycleOptions(move_imbalance=2))
    assert eng.pick_move(regions, {1: EP[0]}, EP[0], EP[:3],
                         {}, {}, {}, {}) is None


# ---- integration: store-side merge choreography ----------------------------


@contextlib.asynccontextmanager
async def kv_cluster(n=3, regions=None, **kw):
    c = KVTestCluster(n, regions=regions, **kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


def _two_region_template():
    return [Region(id=1, start_key=b"", end_key=b"m"),
            Region(id=2, start_key=b"m", end_key=b"")]


async def _wait(cond, timeout_s=8.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {what}")


async def test_merge_absorbs_keyspace_and_retires_source():
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        for i in range(8):
            assert await l1.raft_store.put(b"a%02d" % i, b"L%d" % i)
            assert await l2.raft_store.put(b"z%02d" % i, b"R%d" % i)
        st = await l1.store_engine.apply_merge(
            1, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        # every store retires its source replica and extends its target
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="source retirement on all stores")
        for s in c.stores.values():
            r2 = s.get_region_engine(2).region
            assert (r2.start_key, r2.end_key) == (b"", b"")
            assert coverage_errors([r2]) == []
            assert s.regions_retired == 1 or s.regions_absorbed >= 0
        # the absorbed keyspace serves through the surviving group
        l2 = await c.wait_region_leader(2)
        assert await l2.raft_store.get(b"a03") == b"L3"
        assert await l2.raft_store.get(b"z03") == b"R3"
        assert await l2.raft_store.put(b"a99", b"post-merge")
        assert await l2.raft_store.get(b"a99") == b"post-merge"
        assert l1.store_engine.merges_led == 1


async def test_merge_defers_on_inflight_conf_change():
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        tp = str(l2.node.server_id)
        # pin a conf change in flight: the merge must DEFER (EBUSY, no
        # seal proposed, nothing wedged), exactly what the PD's paced
        # re-issue loop expects
        l1.node._conf_ctx = object()
        try:
            st = await l1.store_engine.apply_merge(1, 2, tp)
            assert st.code == RaftError.EBUSY, str(st)
            assert getattr(l1.fsm, "sealed_into", -1) == -1
        finally:
            l1.node._conf_ctx = None
        # conf change done: the re-issued instruction goes through
        st = await l1.store_engine.apply_merge(1, 2, tp)
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="deferred merge completion")


async def test_merge_rides_the_live_tiling_after_split():
    """Merge-races-split, sequenced the way the PD's replicated busy
    sets allow: the split lands first, then merges run on the POST-
    split tiling (absorb right-to-left chain) — coverage holds at
    every step and every key stays readable."""
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        for i in range(32):
            assert await l1.raft_store.put(b"k%02d" % i, b"v%d" % i)
        st = await l1.store_engine.apply_split(1, 3)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(3)
        l3 = await c.wait_region_leader(3)
        l2 = await c.wait_region_leader(2)
        store = next(iter(c.stores.values()))
        regs = [store.get_region_engine(i).region for i in (1, 2, 3)]
        assert coverage_errors(regs) == []
        # merge the split child into its right neighbor (extend LEFT)
        st = await l3.store_engine.apply_merge(3, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(3) is None
                                for s in c.stores.values()),
                    what="child retirement")
        # then the shrunken parent into the extended survivor
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        st = await l1.store_engine.apply_merge(1, 2, str(l2.node.server_id))
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="parent retirement")
        for s in c.stores.values():
            r2 = s.get_region_engine(2).region
            assert coverage_errors([r2]) == []
        l2 = await c.wait_region_leader(2)
        for i in range(32):
            assert await l2.raft_store.get(b"k%02d" % i) == b"v%d" % i


# ---- integration: cross-store move -----------------------------------------


EP4 = [f"127.0.0.1:{6000 + i}" for i in range(4)]


async def test_move_replica_to_fresh_store():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])]) as c:
        leader = await c.wait_region_leader(1)
        assert await leader.raft_store.put(b"k", b"v")
        src = next(p for p in leader.region.peers
                   if p != str(leader.node.server_id))
        st = await leader.store_engine.apply_move(1, EP4[3], src)
        assert st.is_ok(), str(st)
        ce = leader.node.conf_entry
        peers = {str(p) for p in ce.conf.peers}
        assert EP4[3] in peers and src not in peers
        assert ce.is_stable()   # joint change fully committed
        assert leader.store_engine.moves_applied == 1
        # a retried instruction (PD re-issue after a lost ack) is a no-op
        st = await leader.store_engine.apply_move(1, EP4[3], src)
        assert st.is_ok(), str(st)
        assert await leader.raft_store.get(b"k") == b"v"


async def test_move_self_leader_source_hands_off_first():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])]) as c:
        leader = await c.wait_region_leader(1)
        me = str(leader.node.server_id)
        st = await leader.store_engine.apply_move(1, EP4[3], me)
        assert st.code == RaftError.EBUSY, str(st)

        # leadership moves off the source so the re-issued move can run
        async def _moved():
            nl = await c.wait_region_leader(1)
            return str(nl.node.server_id) != me

        deadline = time.monotonic() + 8.0
        while not await _moved():
            assert time.monotonic() < deadline, \
                "leadership never left the move source"
            await asyncio.sleep(0.05)


async def test_move_races_leader_kill():
    async with kv_cluster(4, regions=[Region(id=1, peers=EP4[:3])],
                          tmp_path=None) as c:
        leader = await c.wait_region_leader(1)
        leader_ep = leader.node.server_id.endpoint
        src = next(p for p in leader.region.peers
                   if p != str(leader.node.server_id))
        move = asyncio.ensure_future(
            leader.store_engine.apply_move(1, EP4[3], src))
        await asyncio.sleep(0.05)   # land mid-catchup / mid-joint
        await c.stop_store(leader_ep)
        with contextlib.suppress(Exception):
            await move
        # a new leader emerges among the surviving conf members and the
        # re-issued move converges (retry-safe whatever the kill hit)
        new_leader = await c.wait_region_leader(1, timeout_s=10.0)
        deadline = time.monotonic() + 10.0
        while True:
            st = await new_leader.store_engine.apply_move(1, EP4[3], src)
            ce = new_leader.node.conf_entry
            peers = {str(p) for p in ce.conf.peers}
            if st.is_ok() and EP4[3] in peers and src not in peers \
                    and ce.is_stable():
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"move did not converge: {st} peers={peers}")
            await asyncio.sleep(0.2)
            new_leader = await c.wait_region_leader(1, timeout_s=10.0)
        assert await new_leader.raft_store.put(b"post", b"kill")


# ---- integration: lifecycle-enabled PD end to end --------------------------


async def test_pd_lifecycle_merges_cold_regions_end_to_end():
    """A lifecycle PD observes an all-cold 4-region fleet, orders cold
    merges down to the floor, replicates completion, and the CLIENT
    re-resolves routes out of the merged-away regions (satellite 1:
    stale-route eviction on ERR_NO_REGION + PD adjudication)."""
    from tpuraft.rheakv.client import RheaKVStore

    template = [
        Region(id=1, start_key=b"", end_key=b"g"),
        Region(id=2, start_key=b"g", end_key=b"n"),
        Region(id=3, start_key=b"n", end_key=b"t"),
        Region(id=4, start_key=b"t", end_key=b""),
    ]
    c = PDTestCluster(
        n_stores=3, n_pd=1, regions=template,
        heartbeat_interval_ms=100,
        pd_opts={
            "lifecycle": True,
            "lifecycle_min_regions": 2,
            "lifecycle_merge_cooldown_s": 0.5,
            "lifecycle_move_cooldown_s": 0.5,
            "lifecycle_max_inflight_merges": 1,
            # suppress moves: this test isolates the merge actuator
            "lifecycle_move_imbalance": 99,
        })
    await c.start_all()
    try:
        pd = await c.wait_pd_leader()
        kv = RheaKVStore(c.pd_client(), c.client_transport(),
                         timeout_ms=3000, max_retries=16)
        await kv.start()
        # seed the client's route table AND data in every region
        for k in (b"a", b"h", b"p", b"x"):
            assert await kv.put(k, b"v-" + k)
        # snapshot the pre-merge routes: an epoch bounce during the
        # merge window can refresh the table early, so pin the stale
        # view back afterwards to make the eviction path deterministic
        stale_routes = [r.copy() for r in kv.route_table.list_regions()]
        # the policy merges the cold fleet down to the floor
        await _wait(lambda: len(pd.fsm.regions) <= 2
                    and not pd.fsm.pending_merges,
                    timeout_s=30.0, what="cold merges down to the floor")
        assert pd.merges_completed >= 2
        assert coverage_errors(pd.fsm.regions.values()) == []
        kv.route_table.reset([r.copy() for r in stale_routes])
        # every key survives, including ones whose region merged away —
        # the client bounces off the retired group, evicts the stale
        # route and lands in the absorbing region
        for k in (b"a", b"h", b"p", b"x"):
            assert await kv.get(k) == b"v-" + k
        assert await kv.put(b"hh", b"post-merge")
        assert await kv.get(b"hh") == b"post-merge"
        assert kv.merged_evictions >= 1
        # the admin surface reports the lifecycle plane
        view = await kv.pd.cluster_describe()
        assert view and view.get("lifecycle"), view
        assert view["lifecycle"]["merges_completed"] >= 2
        await kv.shutdown()
    finally:
        await c.stop_all()


def test_admin_regions_view_renders(capsys):
    """The admin `regions` renderer handles a lifecycle view, a region
    with no heat row, pending merges, and the lifecycle-off PD."""
    from examples.admin import _print_regions_view

    regions = [Region(id=1, start_key=b"", end_key=b"m",
                      peers=[EP[0], EP[1]]),
               Region(id=2, start_key=b"m", end_key=b"",
                      peers=[EP[0], EP[1]])]
    view = {
        "hot": [{"region": 1, "leader": EP[0], "score": 3.1,
                 "writes_s": 9.0, "reads_s": 2.0, "keys": 64}],
        "cold": [],
        "hot_flagged": [1],
        "lifecycle": {
            "pending_merges": {"2": 1},
            "retired_regions": 3,
            "recent": [{"kind": "heat_split", "term": 1, "region": 1,
                        "child": 1024},
                       {"kind": "move", "term": 1, "region": 2,
                        "src": EP[0], "dst": EP[1]}],
            "heat_splits_ordered": 4, "merges_ordered": 2,
            "merges_completed": 2, "moves_ordered": 1,
        },
    }
    _print_regions_view(regions, view)
    out = capsys.readouterr().out
    assert "lifecycle ON" in out and "1 pending merge" in out
    assert "HOT" in out and "MERGING->1" in out
    assert "heat_split" in out and "child=1024" in out
    # pre-lifecycle PD (or lifecycle off): renders without decisions
    _print_regions_view(regions, {"hot": [], "cold": []})
    out = capsys.readouterr().out
    assert "lifecycle off" in out and "no placement decisions" in out


def test_replayed_split_report_cannot_resurrect_merged_region():
    """Regression: a mint-era split report replayed AFTER the child has
    merged away must not resurrect it in the PD metadata.

    ``do_split`` runs on every replica and every replica's async boot
    re-reports the split; a learner moved onto the group later replays
    the parent log and re-reports splits that are ancient history.  If
    the child has since gone cold and been absorbed by its neighbor,
    its record was popped (tombstoned) — ``cur is None`` — so the epoch
    guard alone lets the stale mint-era record land and double-cover
    the keyspace the absorber already extended over."""
    import struct

    from tpuraft.rheakv.pd_server import (
        _CMD_MERGE, _CMD_REGION_UPSERT, _CMD_SPLIT, PDMetadataFSM, _cmd)

    fsm = PDMetadataFSM()

    def upsert(region, leader=EP[0]):
        lb = leader.encode()
        fsm._dispatch(_cmd(
            _CMD_REGION_UPSERT,
            struct.pack("<H", len(lb)) + lb + region.encode()))

    # initial tiling: region 1 [-inf, m), region 2 [m, +inf)
    upsert(_r(1, b"", b"m"))
    upsert(_r(2, b"m", b""))

    # region 1 splits at g -> child 1024; both halves bump to version 2
    parent = _r(1, b"", b"g")
    parent.epoch.version = 2
    child = _r(1024, b"g", b"m")
    child.epoch.version = 2
    pb = parent.encode()
    split_report = _cmd(
        _CMD_SPLIT, struct.pack("<I", len(pb)) + pb + child.encode())
    assert fsm._dispatch(split_report) is True
    assert coverage_errors(fsm.regions.values()) == []

    # the child goes cold and merges into its right neighbor: region 2
    # extends left over [g, m) and 1024 is tombstoned
    assert fsm._dispatch(
        _cmd(_CMD_MERGE, struct.pack("<qq", 1024, 2))) is True
    assert 1024 not in fsm.regions
    assert fsm.retired_regions[1024] == 2
    assert fsm.regions[2].start_key == b"g"

    # a moved-in learner replays the parent log and re-reports the
    # mint-era split: the tombstone must win over ``cur is None``
    assert fsm._dispatch(split_report) is True
    assert 1024 not in fsm.regions, "merged-away child resurrected"
    assert fsm.regions[2].start_key == b"g"
    assert fsm.regions[2].end_key == b""
    assert coverage_errors(fsm.regions.values()) == []
    # finalizing the same merge again is not "fresh" (no double count)
    assert fsm._dispatch(
        _cmd(_CMD_MERGE, struct.pack("<qq", 1024, 2))) is False


# ---- regression: merge finalization safety (review findings) ---------------


async def test_target_coverage_alone_never_finalizes_pending_merge(tmp_path):
    """Regression: the TARGET's extended range proves the absorb
    committed — NOT that the source's MERGE_COMMIT is durable.  If the
    PD tombstoned the pending pair on coverage alone, a source leader
    crash between the absorb and the commit would stop the KIND_MERGE
    re-issue (the only path that proposes MERGE_COMMIT) and leave the
    sealed source group alive forever, serving stale sealed GETs for
    keyspace the target now owns.  The pending pair must survive the
    coverage report, keep re-issuing, and finalize only on an explicit
    pd_report_merge from the source group."""
    import struct

    from tpuraft.rheakv.pd_messages import (
        Instruction, ReportMergeRequest, StoreHeartbeatBatchRequest,
        encode_region_delta)
    from tpuraft.rheakv.pd_server import _CMD_MERGE_ISSUED, _cmd

    c = PDTestCluster(
        n_stores=0, n_pd=1, tmp_path=tmp_path,
        pd_opts={"lifecycle": True,
                 # the policy must not order merges of its own: this
                 # test injects the pending pair by hand
                 "lifecycle_min_regions": 99,
                 "lifecycle_merge_cooldown_s": 0.01})
    for ep in c.pd_endpoints:
        await c.start_pd(ep)
    try:
        pd = await c.wait_pd_leader()
        pd_client = c.pd_client()
        store_ep = "127.0.0.1:9001"

        def hb(regions):
            return pd_client._call(
                "pd_store_heartbeat_batch",
                StoreHeartbeatBatchRequest(
                    store_id=1, endpoint=store_ep,
                    deltas=[encode_region_delta(r.encode(), store_ep, 5)
                            for r in regions],
                    full=True))

        src = Region(id=1, start_key=b"", end_key=b"m", peers=[store_ep])
        tgt = Region(id=2, start_key=b"m", end_key=b"", peers=[store_ep])
        resp = await hb([src, tgt])
        assert resp.success
        # replicate the pending (1 -> 2) pair, as _lifecycle_pass would
        assert await pd._apply(
            _cmd(_CMD_MERGE_ISSUED, struct.pack("<qq", 1, 2))) == 2
        # the absorb commits at the target: it reports its EXTENDED
        # range (covering the source) under a bumped epoch — the exact
        # window where the source's MERGE_COMMIT may not be durable yet
        grown = Region(id=2, start_key=b"", end_key=b"",
                       peers=[store_ep])
        grown.epoch.version = 2
        await asyncio.sleep(0.05)   # clear the merge_reissue pacing
        resp = await hb([src, grown])
        assert resp.success
        # coverage must NOT finalize: pending survives, no tombstone
        assert pd.fsm.pending_merges == {1: 2}
        assert 1 in pd.fsm.regions
        assert 1 not in pd.fsm.retired_regions
        assert pd.merges_completed == 0
        # ...and the KIND_MERGE keeps re-issuing toward the source
        ins = [Instruction.decode(b) for b in resp.instructions]
        merges = [i for i in ins if i.kind == Instruction.KIND_MERGE]
        assert merges, "pending merge stopped re-issuing"
        assert merges[0].region_id == 1
        assert merges[0].new_region_id == 2
        # only the source group's explicit completion report finalizes
        await pd_client._call("pd_report_merge", ReportMergeRequest(
            source_region_id=1, target_region_id=2))
        assert pd.fsm.pending_merges == {}
        assert 1 not in pd.fsm.regions
        assert pd.fsm.retired_regions[1] == 2
        assert pd.merges_completed == 1
        assert coverage_errors(pd.fsm.regions.values()) == []
    finally:
        await c.stop_all()


def test_duplicate_absorb_does_not_roll_back_target_writes():
    """Regression: a re-issued MERGE_ABSORB (the PD retrying after a
    lost ack) carries the sealed source's ORIGINAL blob; reloading it
    after the first absorb landed would resurrect stale source values
    over writes the target accepted in its extended range since (lost
    updates).  Containment-first makes the duplicate a pure no-op —
    no data load, no epoch bump."""
    from tpuraft.rheakv.kv_operation import KVOperation
    from tpuraft.rheakv.raw_store import MemoryRawKVStore
    from tpuraft.rheakv.state_machine import KVStoreStateMachine

    src_store = MemoryRawKVStore()
    src_store.put(b"a", b"stale")
    blob = src_store.serialize_range(b"", b"m")

    tgt_store = MemoryRawKVStore()
    region = Region(id=2, start_key=b"m", end_key=b"")
    fsm = KVStoreStateMachine(region, tgt_store)
    absorb = KVOperation.merge_absorb(1, b"", b"m", blob)
    assert fsm._dispatch(absorb) is True
    assert (region.start_key, region.end_key) == (b"", b"")
    assert tgt_store.get(b"a") == b"stale"
    ver = region.epoch.version
    # the target accepts a write in its extended range...
    tgt_store.put(b"a", b"fresh")
    # ...then the duplicate absorb arrives: no rollback, no epoch bump
    assert fsm._dispatch(absorb) is True
    assert tgt_store.get(b"a") == b"fresh"
    assert region.epoch.version == ver


def test_pd_merge_finalize_non_adjacent_degrades_gracefully():
    """Regression: _CMD_MERGE runs inside the replicated PD FSM apply;
    a non-adjacent pair (policy bug / metadata skew) must degrade to a
    logged violation, never throw out of on_apply on every replica."""
    import struct

    from tpuraft.rheakv.pd_server import (
        _CMD_MERGE, _CMD_REGION_UPSERT, PDMetadataFSM, _cmd)

    fsm = PDMetadataFSM()
    lb = EP[0].encode()
    for region in (_r(1, b"", b"g"), _r(2, b"t", b"")):
        fsm._dispatch(_cmd(
            _CMD_REGION_UPSERT,
            struct.pack("<H", len(lb)) + lb + region.encode()))
    # regions 1 and 2 are NOT adjacent: the apply must not raise
    assert fsm._dispatch(
        _cmd(_CMD_MERGE, struct.pack("<qq", 1, 2))) is True
    assert fsm.retired_regions[1] == 2
    # the target's range is left for heartbeat repair, not torn
    assert fsm.regions[2].start_key == b"t"
    assert fsm.regions[2].end_key == b""


async def test_failed_seal_propose_clears_leader_local_sealing():
    """Regression: engine.sealing is set at propose time; if the seal
    never applies (propose failed / leadership lost mid-attempt) the
    flag must clear, or a regained leadership would bounce every write
    ERR_STORE_BUSY on a region that was never sealed."""
    async with kv_cluster(regions=_two_region_template()) as c:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        tp = str(l2.node.server_id)

        async def boom(_target_id):
            raise RuntimeError("propose lost with leadership")

        l1.raft_store.merge_seal = boom
        st = await l1.store_engine.apply_merge(1, 2, tp)
        assert st.code == RaftError.EINTERNAL, str(st)
        assert getattr(l1.fsm, "sealed_into", -1) == -1
        assert l1.sealing is False, \
            "leader-local seal flag leaked after a failed attempt"
        # the region still serves writes and a retried merge completes
        assert await l1.raft_store.put(b"pre", b"merge")
        del l1.raft_store.merge_seal    # restore the real propose path
        st = await l1.store_engine.apply_merge(1, 2, tp)
        assert st.is_ok(), str(st)
        await _wait(lambda: all(s.get_region_engine(1) is None
                                for s in c.stores.values()),
                    what="retried merge completion")
        # every store remembers the retirement, so a re-issued
        # KIND_MERGE after a lost report is answered with a fresh one
        for s in c.stores.values():
            assert s._retired_into.get(1) == 2
        l2 = await c.wait_region_leader(2)
        assert await l2.raft_store.get(b"pre") == b"merge"
