"""Group quiescence ("hibernate raft"): idle groups suppress their
beat plane and delegate liveness to the store-level lease
(RaftOptions.quiesce_after_rounds; ISSUE 4 tentpole).

Covers the wake races the design note calls out: a write arriving
during hibernation, a store-lease expiry waking exactly the dependent
groups, a conf change waking the group, and a leader-store kill while
every group is quiescent (fail-over inside the normal fault-detection
envelope).
"""

import asyncio

import pytest  # noqa: F401

from tests.test_engine import MultiRaftCluster
from tpuraft.core.node import State
from tpuraft.entity import Task


class QuiesceCluster(MultiRaftCluster):
    coalesce_heartbeats = None   # AUTO: the handshake rides the fast path
    quiesce_after_rounds = 3


async def _commit(leader, data: bytes):
    fut = asyncio.get_running_loop().create_future()
    await leader.apply(Task(data=data, done=fut.set_result))
    st = await asyncio.wait_for(fut, 10)
    assert st.is_ok(), str(st)


async def _wait(pred, timeout_s: float, what: str):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    while loop.time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _group_slots(c, gid):
    return [(c.engines[ep.endpoint], c.nodes[(gid, ep)]._ctrl.slot)
            for ep in c.endpoints]


def _all_quiescent(c, gid) -> bool:
    return all(bool(e.quiescent[s]) for e, s in _group_slots(c, gid))


async def test_idle_group_quiesces_and_beats_stop():
    """The headline: after N fully-acked idle rounds every replica of
    the group hibernates, the hub's beat counters stop advancing, and
    the store-level lease keeps flowing instead."""
    c = QuiesceCluster(3, 4, election_timeout_ms=400)
    await c.start_all()
    try:
        for gid in c.groups:
            leader = await c.wait_leader(gid)
            await _commit(leader, b"seed-" + gid.encode())
        await _wait(lambda: all(_all_quiescent(c, g) for g in c.groups),
                    8.0, "all groups quiescent")
        hubs = [c.nodes[(c.groups[0], ep)].node_manager.heartbeat_hub
                for ep in c.endpoints]
        beats0 = sum(h.beats_sent + h.fast_beats_sent for h in hubs)
        lease0 = sum(h.lease_rpcs_sent for h in hubs)
        await asyncio.sleep(0.8)   # several beat intervals of quiet
        beats1 = sum(h.beats_sent + h.fast_beats_sent for h in hubs)
        lease1 = sum(h.lease_rpcs_sent for h in hubs)
        assert beats1 == beats0, "quiescent groups still beating"
        assert lease1 > lease0, "store lease not flowing"
        # nobody lost leadership while hibernating
        for gid in c.groups:
            assert sum(1 for ep in c.endpoints
                       if c.nodes[(gid, ep)].state == State.LEADER) == 1
        assert sum(h.groups_quiesced for h in hubs) >= 3 * len(c.groups)
    finally:
        await c.stop_all()


async def test_write_arriving_during_quiesce_wakes_and_commits():
    """The classic race: a client write lands on a hibernating leader.
    note_activity must wake the group and the write must commit on
    every replica (the woken leader's beats re-absorb its followers)."""
    c = QuiesceCluster(3, 2, election_timeout_ms=400)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _commit(leader, b"w1")
        await _wait(lambda: _all_quiescent(c, gid), 8.0, "group quiescent")
        await _commit(leader, b"w2")
        eng = c.engines[leader.server_id.endpoint]
        assert not eng.quiescent[leader._ctrl.slot]
        await _wait(lambda: all(
            c.fsms[(gid, ep)].logs == [b"w1", b"w2"] for ep in c.endpoints),
            8.0, "w2 applied everywhere")
        # and the group hibernates AGAIN once idle — quiescence is a
        # steady state, not a one-shot
        await _wait(lambda: _all_quiescent(c, gid), 8.0, "re-quiesced")
        await _commit(leader, b"w3")   # still writable after the 2nd nap
    finally:
        await c.stop_all()


async def test_conf_change_wakes_quiescent_group():
    c = QuiesceCluster(3, 1, election_timeout_ms=400)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _commit(leader, b"x")
        await _wait(lambda: _all_quiescent(c, gid), 8.0, "group quiescent")
        victim = next(ep for ep in c.endpoints if ep != leader.server_id)
        st = await asyncio.wait_for(leader.remove_peer(victim), 15)
        assert st.is_ok(), str(st)
        eng = c.engines[leader.server_id.endpoint]
        assert eng.voter_mask[leader._ctrl.slot].sum() == 2
        await _commit(leader, b"y")
    finally:
        await c.stop_all()


async def test_leader_store_kill_wakes_exactly_dependent_groups():
    """Store-lease expiry: killing the endpoint that leads SOME groups
    must wake (and re-elect) exactly those groups' followers; groups
    led by surviving stores stay hibernated."""
    c = QuiesceCluster(3, 6, election_timeout_ms=400)
    await c.start_all()
    try:
        for gid in c.groups:
            leader = await c.wait_leader(gid)
            await _commit(leader, b"seed")
        await _wait(lambda: all(_all_quiescent(c, g) for g in c.groups),
                    10.0, "all groups quiescent")
        by_leader: dict[str, list[str]] = {}
        for gid in c.groups:
            ld = next(n for (g, ep), n in c.nodes.items()
                      if g == gid and n.is_leader())
            by_leader.setdefault(ld.server_id.endpoint, []).append(gid)
        # kill the endpoint leading the most groups
        dead_ep_s = max(by_leader, key=lambda k: len(by_leader[k]))
        dead_groups = by_leader[dead_ep_s]
        live_groups = [g for g in c.groups if g not in dead_groups]
        dead_ep = next(ep for ep in c.endpoints
                       if ep.endpoint == dead_ep_s)
        c.net.stop_endpoint(dead_ep_s)
        for g in c.groups:
            n = c.nodes.pop((g, dead_ep))
            await n.shutdown()
        await c.engines.pop(dead_ep_s).shutdown()
        c.net.unbind(dead_ep_s)

        # the dead store's dependent groups elect within the normal
        # fault-detection envelope: lease expiry (~eto) + randomized
        # election spread (up to ~2x eto) + the election itself
        for gid in dead_groups:
            leader2 = await c.wait_leader(gid, timeout_s=12.0)
            assert leader2.server_id.endpoint != dead_ep_s
            await _commit(leader2, b"post-failover")
        # groups led by SURVIVING stores never woke: their store's
        # lease kept flowing the whole time (lease beats between the
        # two live endpoints), so hibernation held
        for gid in live_groups:
            for ep in c.endpoints:
                if ep == dead_ep:
                    continue
                n = c.nodes[(gid, ep)]
                if n.is_leader():
                    continue   # the leader row wakes only on activity
                eng = c.engines[ep.endpoint]
                assert eng.quiescent[n._ctrl.slot], \
                    f"{gid}@{ep.endpoint} woke without cause"
    finally:
        await c.stop_all()


async def test_quiescent_group_survives_on_lease_and_wakes_on_vote():
    """A quiescent follower must refuse to elect while its leader's
    store lease is fresh (suppressed election timeout), and the whole
    group must resume cleanly when a vote request arrives anyway."""
    c = QuiesceCluster(3, 1, election_timeout_ms=400)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        term0 = leader.current_term
        await _commit(leader, b"a")
        await _wait(lambda: _all_quiescent(c, gid), 8.0, "group quiescent")
        # several election timeouts of TOTAL beat silence: without the
        # store lease this is guaranteed re-election territory
        await asyncio.sleep(1.5)
        assert leader.state == State.LEADER
        assert leader.current_term == term0, \
            "a quiescent group re-elected under a fresh store lease"
        await _commit(leader, b"b")
    finally:
        await c.stop_all()


async def test_prevote_against_quiescent_group_refused_while_lease_fresh():
    """The wake-vs-guard race: a vote solicitation wakes a quiescent
    follower (note_activity) BEFORE the pre-vote guard runs, which
    clears quiescent_leader_alive() — the wake must carry the store
    lease's liveness proof into _last_leader_timestamp, or one
    restarted store pre-voting at thousands of hibernating groups
    deposes every healthy leader at once."""
    from tpuraft.rpc.messages import RequestVoteRequest

    c = QuiesceCluster(3, 1, election_timeout_ms=400)
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        term0 = leader.current_term
        await _commit(leader, b"a")
        await _wait(lambda: _all_quiescent(c, gid), 8.0, "group quiescent")
        # long enough that the per-group leader-contact timestamp is
        # stale by every non-delegated measure
        await asyncio.sleep(1.2)
        cand_ep, tgt_ep = [ep for ep in c.endpoints
                           if ep != leader.server_id]
        target = c.nodes[(gid, tgt_ep)]
        last = target.log_manager.last_log_id()
        resp = await target.handle_request_vote(RequestVoteRequest(
            group_id=gid, server_id=str(cand_ep),
            peer_id=str(tgt_ep), term=term0 + 1,
            last_log_index=last.index, last_log_term=last.term,
            pre_vote=True))
        assert not resp.granted, \
            "pre-vote granted against a lease-fresh hibernating leader"
        # the solicitation woke the follower (by design) ...
        assert not c.engines[tgt_ep.endpoint].quiescent[target._ctrl.slot]
        # ... but the leader keeps its seat through the follower's next
        # election window: the woken guard still counts the leader alive
        await asyncio.sleep(1.0)
        assert leader.state == State.LEADER and leader.current_term == term0
        await _commit(leader, b"b")
    finally:
        await c.stop_all()


async def test_store_lease_pair_dedupe_suppresses_one_direction():
    """The lease beat is a bidirectional liveness proof (the beat
    proves its sender alive, the ack proves the receiver alive): with
    leaders hibernating on BOTH endpoints of a pair, the higher
    endpoint must ride the lower's beats (lease_suppressed advances)
    instead of sending its own — and neither side's hibernation or
    leadership may suffer for it."""
    c = QuiesceCluster(3, 2, election_timeout_ms=400)
    await c.start_all()
    try:
        eps = sorted(c.endpoints, key=lambda e: e.endpoint)
        lo, hi = eps[0], eps[1]
        # pin one leader to the LOW endpoint and one to the HIGH so the
        # (lo, hi) pair has lease senders both ways
        for gid, target in zip(c.groups, (lo, hi)):
            leader = await c.wait_leader(gid)
            if leader.server_id != target:
                st = await asyncio.wait_for(
                    leader.transfer_leadership_to(target), 15)
                assert st.is_ok(), str(st)
                await _wait(lambda: c.nodes[(gid, target)].is_leader(),
                            10.0, f"{gid} led by {target.endpoint}")
        for gid in c.groups:
            await _commit(await c.wait_leader(gid), b"seed")
        await _wait(lambda: all(_all_quiescent(c, g) for g in c.groups),
                    10.0, "all groups quiescent")
        hub_hi = c.nodes[(c.groups[0], hi)].node_manager.heartbeat_hub
        sup0 = hub_hi.lease_suppressed
        # several lease intervals (eto/4 = 100ms) of steady state
        await asyncio.sleep(1.0)
        assert hub_hi.lease_suppressed > sup0, \
            "higher endpoint kept sending its half of the pair"
        # suppression cost nothing: both leaders still lead, every
        # group is still hibernated, and both groups still take writes
        for gid, target in zip(c.groups, (lo, hi)):
            assert c.nodes[(gid, target)].is_leader()
            assert _all_quiescent(c, gid)
            await _commit(c.nodes[(gid, target)], b"post-dedupe")
    finally:
        await c.stop_all()


async def test_single_voter_group_quiesces_without_lease():
    """A single-voter group has nobody to handshake with and needs no
    store lease: it hibernates on its own and wakes on writes."""
    from tests.test_engine import MultiRaftCluster

    class OneVoter(MultiRaftCluster):
        coalesce_heartbeats = None
        quiesce_after_rounds = 3

        def __init__(self):
            super().__init__(1, 2, election_timeout_ms=400)

    c = OneVoter()
    await c.start_all()
    try:
        gid = c.groups[0]
        leader = await c.wait_leader(gid)
        await _commit(leader, b"solo")
        eng = c.engines[leader.server_id.endpoint]
        await _wait(lambda: bool(eng.quiescent[leader._ctrl.slot]),
                    8.0, "single-voter quiesced")
        hub = leader.node_manager.heartbeat_hub
        assert not hub._lease_targets   # no peers -> no lease traffic
        await _commit(leader, b"solo2")
        await _wait(lambda: bool(eng.quiescent[leader._ctrl.slot]),
                    8.0, "re-quiesced")
    finally:
        await c.stop_all()
