"""Smoke tests for the L7 examples (reference: jraft-example — SURVEY.md
§3.3): each demo must run end-to-end in-process, including its failure
injection (leader kill)."""

import asyncio

from examples.counter import demo as counter_demo
from examples.election import demo as election_demo
from examples.rheakv_bench import run_bench


async def test_counter_demo(tmp_path):
    v = await asyncio.wait_for(
        counter_demo(increments=4, data_root=str(tmp_path), verbose=False),
        60)
    assert v == 9  # 4 increments + 5 after failover


async def test_election_demo():
    first, second = await asyncio.wait_for(election_demo(verbose=False), 60)
    assert first != second


async def test_rheakv_bench_small():
    r = await asyncio.wait_for(
        run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                  concurrency=16, verbose=False), 120)
    assert r["ops_per_s"] > 0 and r["p99_ms"] > 0


async def test_rheakv_bench_lease_reads():
    r = await asyncio.wait_for(
        run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                  concurrency=16, lease_reads=True, verbose=False), 120)
    assert r["ops_per_s"] > 0


async def test_admin_cli_against_live_cluster(tmp_path):
    """The admin CLI (examples/admin.py) drives a live TCP cluster as a
    separate OS process: leader lookup, peer listing, leadership
    transfer (reference: the CliService operator surface)."""
    import os
    import subprocess
    import sys

    from tests.test_tcp import TcpCluster

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    c = TcpCluster(tmp_path)
    await c.start(3)
    try:
        leader = await c.wait_leader()
        peers_arg = ",".join(str(p) for p in c.peers)

        def admin(*cmd):
            return subprocess.run(
                [sys.executable, "-m", "examples.admin",
                 "--group", "tcp_group", "--peers", peers_arg, *cmd],
                cwd=repo, env=dict(os.environ, PYTHONPATH=repo),
                capture_output=True, text=True, timeout=60)

        loop = asyncio.get_running_loop()
        r = await loop.run_in_executor(None, admin, "leader")
        assert r.returncode == 0, r.stderr
        assert r.stdout.strip() == str(leader.server_id)

        r = await loop.run_in_executor(None, admin, "peers")
        assert r.returncode == 0, r.stderr
        assert set(r.stdout.split("voters: ")[1].strip().split(",")) == \
            {str(p) for p in c.peers}

        target = next(p for p in c.peers if p != leader.server_id)
        r = await loop.run_in_executor(
            None, admin, "transfer", str(target))
        assert r.returncode == 0, r.stderr + r.stdout
        deadline = loop.time() + 8
        while loop.time() < deadline:
            if c.nodes[target].state.value == "leader":
                break
            await asyncio.sleep(0.05)
        assert c.nodes[target].state.value == "leader"

        # learner lifecycle through the CLI: boot a 4th node outside
        # the conf, add it as learner, then clear the set atomically
        from tests.test_tcp import _start_server
        from tpuraft.entity import PeerId

        srv = await _start_server(c.server_cls)
        lp = PeerId.parse(srv.endpoint)
        await c._boot(lp, srv)
        r = await loop.run_in_executor(
            None, admin, "add-learners", str(lp))
        assert r.returncode == 0, r.stderr + r.stdout
        r = await loop.run_in_executor(None, admin, "peers")
        assert r.returncode == 0, r.stderr
        assert f"learners: {lp}" in r.stdout, r.stdout
        r = await loop.run_in_executor(None, admin, "reset-learners", "none")
        assert r.returncode == 0, r.stderr + r.stdout
        r = await loop.run_in_executor(None, admin, "peers")
        assert r.returncode == 0, r.stderr
        assert "learners:" not in r.stdout, r.stdout
    finally:
        await c.stop_all()


async def test_rheakv_bench_native_stack(tmp_path):
    """The benchmark's full-native mode: C++ epoll transport + C++ KV
    engine, small sizes."""
    r = await run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                        concurrency=16, transport="native", store="native",
                        data_path=str(tmp_path), verbose=False)
    assert r["ops_per_s"] > 0
    assert r["transport"] == "native" and r["store"] == "native"


async def test_rheakv_bench_zipfian():
    r = await asyncio.wait_for(
        run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                  concurrency=16, zipf_theta=0.99, verbose=False), 120)
    assert r["ops_per_s"] > 0 and r["zipf_theta"] == 0.99


async def test_soak_runner_short():
    """The chaos soak runner (examples/soak.py): 8s of nemesis faults
    under load, history proven linearizable, faults actually fired."""
    import tempfile

    from examples.soak import run_soak

    with tempfile.TemporaryDirectory() as d:
        r = await asyncio.wait_for(
            run_soak(duration_s=8, n_stores=3, n_keys=4, seed=3,
                     data_path=d, verbose=False), 110)
    assert r["linearizable"], r
    assert r["ops"] > 50, r
    assert sum(r["faults"].values()) >= 2, r
