"""Smoke tests for the L7 examples (reference: jraft-example — SURVEY.md
§3.3): each demo must run end-to-end in-process, including its failure
injection (leader kill)."""

import asyncio

from examples.counter import demo as counter_demo
from examples.election import demo as election_demo
from examples.rheakv_bench import run_bench


async def test_counter_demo(tmp_path):
    v = await asyncio.wait_for(
        counter_demo(increments=4, data_root=str(tmp_path), verbose=False),
        60)
    assert v == 9  # 4 increments + 5 after failover


async def test_election_demo():
    first, second = await asyncio.wait_for(election_demo(verbose=False), 60)
    assert first != second


async def test_rheakv_bench_small():
    r = await asyncio.wait_for(
        run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                  concurrency=16, verbose=False), 120)
    assert r["ops_per_s"] > 0 and r["p99_ms"] > 0


async def test_rheakv_bench_lease_reads():
    r = await asyncio.wait_for(
        run_bench(n_stores=3, n_regions=2, n_keys=60, n_ops=120,
                  concurrency=16, lease_reads=True, verbose=False), 120)
    assert r["ops_per_s"] > 0
