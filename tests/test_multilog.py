"""Shared multi-group log engine (native/multilog.cc + storage.multilog):
one engine instance per process, group-keyed records in shared journals,
ONE fsync per flush round across all groups (VERDICT r1 #3; reference:
RocksDB WriteBatch under RocksDBLogStorage, SURVEY §3.1/§8.3)."""

import asyncio
import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from tests.test_storage import _BaseLogStorageSuite, mk_entries
from tpuraft.entity import LogId


def _available():
    try:
        from tpuraft.storage.multilog import ensure_built

        ensure_built()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _available(),
                                reason="C++ multilog engine not buildable")


def mk_storage(tmp_path, group="g1", seg_max=0):
    from tpuraft.storage.multilog import MultiLogStorage

    s = MultiLogStorage(str(tmp_path / "mlog"), group)
    if seg_max:
        # engine seg_max is fixed at first open per process+dir
        from tpuraft.storage import multilog

        key = os.path.realpath(str(tmp_path / "mlog"))
        if key not in multilog._engines:
            multilog._engines[key] = multilog.MultiLogEngine(
                str(tmp_path / "mlog"), seg_max)
    return s


class TestMultiLogStorage(_BaseLogStorageSuite):
    """The generic LogStorage battery over one group of the shared
    engine (same contract as file/native single-group engines)."""

    def mk(self, tmp_path):
        return mk_storage(tmp_path)


def test_groups_are_independent(tmp_path):
    a = mk_storage(tmp_path, "ga")
    b = mk_storage(tmp_path, "gb")
    a.init()
    b.init()
    try:
        # interleaved appends share journals but not index spaces
        a.append_entries(mk_entries(1, 5, term=1))
        b.append_entries(mk_entries(1, 3, term=7))
        a.append_entries(mk_entries(6, 5, term=2))
        assert a.last_log_index() == 10
        assert b.last_log_index() == 3
        assert a.get_term(7) == 2 and b.get_term(2) == 7
        # truncation in one group leaves the other intact
        a.truncate_suffix(4)
        b.truncate_prefix(2)
        assert a.last_log_index() == 4
        assert b.first_log_index() == 2 and b.last_log_index() == 3
        assert a.engine is b.engine  # ONE engine instance
    finally:
        a.shutdown()
        b.shutdown()


def test_multi_group_restart_recovery(tmp_path):
    groups = [f"g{i}" for i in range(16)]
    stores = [mk_storage(tmp_path, g) for g in groups]
    for i, s in enumerate(stores):
        s.init()
        s.append_entries(mk_entries(1, 4 + i, term=i + 1))
    stores[3].truncate_suffix(2)
    stores[5].truncate_prefix(3)
    stores[7].reset(50)
    stores[7].append_entries(mk_entries(50, 2, term=9))
    for s in stores:
        s.shutdown()

    stores = [mk_storage(tmp_path, g) for g in groups]
    for s in stores:
        s.init()
    try:
        for i, s in enumerate(stores):
            if i == 3:
                assert s.last_log_index() == 2
            elif i == 5:
                assert (s.first_log_index(), s.last_log_index()) == (3, 9)
            elif i == 7:
                assert (s.first_log_index(), s.last_log_index()) == (50, 51)
                assert s.get_term(51) == 9
            else:
                assert s.last_log_index() == 4 + i, groups[i]
                assert s.get_entry(2).id == LogId(2, i + 1)
    finally:
        for s in stores:
            s.shutdown()


def test_thousand_groups_one_engine(tmp_path):
    """1K groups on ONE engine instance: fd count stays O(journal
    files), not O(groups) (round 1: thousands of open segment files)."""
    G = 1000
    stores = [mk_storage(tmp_path, f"r{k}") for k in range(G)]
    for s in stores:
        s.init()
    try:
        for k, s in enumerate(stores):
            s.append_entries(mk_entries(1, 2, term=k % 7 + 1), sync=False)
        eng = stores[0].engine
        eng.sync()
        assert eng.file_count <= 4, "journal files should be shared"
        # spot-check reads across the space
        for k in (0, 1, 499, 998, 999):
            assert stores[k].last_log_index() == 2
            assert stores[k].get_term(2) == k % 7 + 1
    finally:
        for s in stores:
            s.shutdown()
    # reopen: all 1000 groups recover
    stores = [mk_storage(tmp_path, f"r{k}") for k in range(G)]
    for s in stores:
        s.init()
    try:
        assert all(s.last_log_index() == 2 for s in stores)
    finally:
        for s in stores:
            s.shutdown()


async def test_group_fsync_coalescing(tmp_path):
    """The headline property: N groups flushing concurrently cost ~1
    fsync round, not N (RocksDB group commit)."""
    G = 64
    stores = [mk_storage(tmp_path, f"c{k}") for k in range(G)]
    for s in stores:
        s.init()
    try:
        eng = stores[0].engine
        sync0 = eng.sync_count

        async def flush_one(k):
            await stores[k].append_entries_async(
                mk_entries(1, 3, term=1), sync=True)

        await asyncio.gather(*(flush_one(k) for k in range(G)))
        rounds = eng.sync_count - sync0
        # every group's flush is durable, but the 64 concurrent flushes
        # coalesced into a handful of fsync rounds
        assert rounds <= G // 4, f"{rounds} fsync rounds for {G} groups"
        assert all(s.last_log_index() == 3 for s in stores)
        print(f"{G} group flushes -> {rounds} fsync rounds")
    finally:
        for s in stores:
            s.shutdown()


def test_group_commit_across_event_loops(tmp_path):
    """The engine is shared process-wide by directory, so stores on
    DIFFERENT event loops (threads) may join the same group-commit; each
    waiter must resolve on its own loop (ADVICE r2: futures were set
    from whichever loop ran the round — not thread-safe)."""
    import threading

    from tests.test_storage import mk_entries

    T, ROUNDS = 4, 25
    errors: list[BaseException] = []
    barrier = threading.Barrier(T)

    def worker(k: int) -> None:
        async def run():
            s = mk_storage(tmp_path, f"loop{k}")
            s.init()
            try:
                for i in range(ROUNDS):
                    await s.append_entries_async(
                        mk_entries(3 * i + 1, 3, term=1), sync=True)
                    # stagger so rounds interleave across loops
                    await asyncio.sleep(0.001 * (k % 3))
                assert s.last_log_index() == 3 * ROUNDS
            finally:
                s.shutdown()

        barrier.wait(timeout=30)
        try:
            asyncio.run(run())
        except BaseException as e:  # noqa: BLE001 — surfaced to the test
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # a stranded waiter hangs its worker inside asyncio.run — join()
    # returning on timeout must fail the test, not pass it silently
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors


def test_journal_gc_after_prefix_truncation(tmp_path):
    s = mk_storage(tmp_path, "g1", seg_max=4096)
    s.init()
    try:
        s.append_entries(mk_entries(1, 200, term=1, size=64))
        eng = s.engine
        files_before = eng.file_count
        assert files_before > 2  # rotated
        s.truncate_prefix(190)  # storage gc()s opportunistically
        assert eng.file_count < files_before
        # data still intact post-GC
        assert s.first_log_index() == 190
        assert s.last_log_index() == 200
        assert s.get_entry(195) is not None
    finally:
        s.shutdown()
    # and recovery after GC (markers re-asserted state)
    s = mk_storage(tmp_path, "g1")
    s.init()
    try:
        assert (s.first_log_index(), s.last_log_index()) == (190, 200)
    finally:
        s.shutdown()


def test_torn_tail_recovery(tmp_path):
    s = mk_storage(tmp_path, "g1")
    s.init()
    s.append_entries(mk_entries(1, 3, size=40))
    s.shutdown()
    j = sorted((tmp_path / "mlog").glob("journal_*.log"))[0]
    j.write_bytes(j.read_bytes()[:-10])
    s = mk_storage(tmp_path, "g1")
    s.init()
    try:
        assert s.last_log_index() == 2
        assert s.get_entry(2) is not None
    finally:
        s.shutdown()


def test_torn_registry_tail_recovery(tmp_path):
    """The group registry is append-only (r5: per-registration rewrites
    made 16K boot O(G^2)); a torn registration append must be dropped
    at reopen while every completed registration survives, and new
    registrations must extend the cleaned stream."""
    stores = [mk_storage(tmp_path, f"g{i}") for i in range(8)]
    for s in stores:
        s.init()
        s.append_entries(mk_entries(1, 2, size=40))
    for s in stores:
        s.shutdown()
    reg = tmp_path / "mlog" / "groups"
    reg.write_bytes(reg.read_bytes() + b"\x05\x00\x00\x00")  # torn append
    back = [mk_storage(tmp_path, f"g{i}") for i in range(8)]
    for s in back:
        s.init()
    try:
        for s in back:
            assert s.last_log_index() == 2
            assert s.get_entry(1) is not None
        extra = mk_storage(tmp_path, "g-new")
        extra.init()
        extra.append_entries(mk_entries(1, 1, size=40))
        assert extra.last_log_index() == 1
        extra.shutdown()
    finally:
        for s in back:
            s.shutdown()
    # and the new registration is durable across another reopen
    again = mk_storage(tmp_path, "g-new")
    again.init()
    assert again.last_log_index() == 1
    again.shutdown()


def test_corrupt_record_drops_tail(tmp_path):
    """A flipped byte mid-journal: recovery keeps the clean prefix, the
    engine reopens (no exception, no half-read groups)."""
    s = mk_storage(tmp_path, "g1")
    s.init()
    s.append_entries(mk_entries(1, 10, size=40))
    s.shutdown()
    j = sorted((tmp_path / "mlog").glob("journal_*.log"))[0]
    data = bytearray(j.read_bytes())
    data[len(data) // 2] ^= 0xFF
    j.write_bytes(bytes(data))
    s = mk_storage(tmp_path, "g1")
    s.init()
    try:
        last = s.last_log_index()
        assert 0 < last < 10
        for i in range(s.first_log_index(), last + 1):
            assert s.get_entry(i) is not None
    finally:
        s.shutdown()


_KILL_WRITER = r"""
import sys, time
sys.path.insert(0, {repo!r})
from tests.test_storage import mk_entries
from tpuraft.storage.multilog import MultiLogStorage

d = {dir!r}
stores = [MultiLogStorage(d, "k%d" % k) for k in range(8)]
for s in stores:
    s.init()
print("READY", flush=True)
i = 1
while True:
    for k, s in enumerate(stores):
        s.append_entries(mk_entries(i, 1, term=1, size=32), sync=(k == 7))
    i += 1
"""


def test_kill9_recovery_per_group(tmp_path):
    """kill -9 a process writing 8 groups through one engine; reopen:
    every group's log is contiguous with no exception."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _KILL_WRITER.format(repo=repo, dir=str(tmp_path / "mlog"))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    p = subprocess.Popen([sys.executable, "-c", script],
                         stdout=subprocess.PIPE, env=env)
    try:
        assert p.stdout.readline().strip() == b"READY"
        time.sleep(1.0)  # let it write under fire
    finally:
        p.send_signal(signal.SIGKILL)
        p.wait()

    stores = [mk_storage(tmp_path, f"k{k}") for k in range(8)]
    for s in stores:
        s.init()
    try:
        lasts = [s.last_log_index() for s in stores]
        assert min(lasts) > 5, lasts  # it was really writing
        for s, last in zip(stores, lasts):
            # contiguity: every index up to last reads back
            for i in range(1, last + 1):
                e = s.get_entry(i)
                assert e is not None and e.id.index == i
        # all groups within one sync round of each other
        assert max(lasts) - min(lasts) <= 2, lasts
    finally:
        for s in stores:
            s.shutdown()


async def test_kv_store_regions_share_one_log_engine(tmp_path):
    """RheaKV production integration: StoreEngineOptions(log_scheme=
    "multilog") puts every region of a store on ONE shared journal
    engine — writes across regions coalesce into shared fsync rounds
    and survive a store restart."""
    from tests.kv_cluster import KVTestCluster
    from tpuraft.rheakv.metadata import Region
    from tpuraft.storage import multilog

    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    c = KVTestCluster(3, tmp_path=tmp_path, regions=regions,
                      log_scheme="multilog")
    await c.start_all()
    try:
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        for i in range(10):
            assert await l1.raft_store.put(b"a%03d" % i, b"v%d" % i)
            assert await l2.raft_store.put(b"z%03d" % i, b"v%d" % i)
        # both regions' logs live in each store's ONE engine
        engines = list(multilog._engines.values())
        assert engines, "no shared engines registered"
        assert len(engines) == 3  # one per store, not one per region
        # restart a store: both its region logs recover from the engine
        victim = c.endpoints[0]
        await c.stop_store(victim)
        await c.start_store(victim)
        l1 = await c.wait_region_leader(1)
        assert await l1.raft_store.get(b"a005") == b"v5"
        l2 = await c.wait_region_leader(2)
        assert await l2.raft_store.get(b"z007") == b"v7"
    finally:
        await c.stop_all()


async def test_cluster_on_shared_log_engine(tmp_path):
    """End-to-end: 3 endpoints x 8 groups, every endpoint's groups on
    ONE shared log engine, electing and committing through the device
    plane with group-commit fsync."""
    from tests.test_engine import MultiRaftCluster
    from tpuraft.entity import Task

    class MLCluster(MultiRaftCluster):
        def __init__(self, *a, **kw):
            self.tmp = kw.pop("tmp")
            super().__init__(*a, **kw)

    c = MLCluster(3, 8, election_timeout_ms=500, tmp=tmp_path)
    # monkey-wire log uris: one shared dir per endpoint
    orig_start = c.start_all

    async def start_all():
        from tests.cluster import MockStateMachine
        from tpuraft.core.node import Node
        from tpuraft.core.node_manager import NodeManager
        from tpuraft.core.engine import MultiRaftEngine
        from tpuraft.options import NodeOptions, TickOptions
        from tpuraft.rpc.transport import InProcTransport, RpcServer

        for ep in c.endpoints:
            server = RpcServer(ep.endpoint)
            manager = NodeManager(server)
            c.net.bind(server)
            transport = InProcTransport(c.net, ep.endpoint)
            engine = MultiRaftEngine(TickOptions(
                max_groups=len(c.groups) + 4, max_peers=8,
                tick_interval_ms=c.tick_ms))
            await engine.start()
            c.engines[ep.endpoint] = engine
            factory = engine.ballot_box_factory()
            mdir = f"{c.tmp}/{ep.port}/mlog"
            for gid in c.groups:
                fsm = MockStateMachine()
                c.fsms[(gid, ep)] = fsm
                opts = NodeOptions(
                    election_timeout_ms=c.election_timeout_ms,
                    initial_conf=c.conf.copy(), fsm=fsm,
                    log_uri=f"multilog://{mdir}#{gid}",
                    raft_meta_uri=f"file://{c.tmp}/{ep.port}/meta_{gid}")
                node = Node(gid, ep, opts, transport,
                            ballot_box_factory=factory)
                node.node_manager = manager
                manager.add(node)
                assert await node.init()
                c.nodes[(gid, ep)] = node

    c.start_all = start_all
    await c.start_all()
    try:
        async def put(gid, i):
            leader = await c.wait_leader(gid)
            fut = asyncio.get_running_loop().create_future()
            await leader.apply(Task(data=b"%s-%d" % (gid.encode(), i),
                                    done=fut.set_result))
            st = await asyncio.wait_for(fut, 15)
            assert st.is_ok(), f"{gid}: {st}"

        await asyncio.gather(*(put(g, i) for g in c.groups for i in range(3)))
        # one engine dir per endpoint; fsyncs coalesced across groups
        from tpuraft.storage import multilog

        engines = [e for e in multilog._engines.values()]
        assert engines, "shared engines should be registered"
        for eng in engines:
            assert eng.sync_count <= eng.append_count
    finally:
        await c.stop_all()
