"""Property tests: vectorized ballot kernels vs the scalar per-index
Ballot oracle (reference semantics), per SURVEY.md §8 build order step 2.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpuraft.ops.ballot import (  # noqa: E402
    NEG_INF_I32,
    joint_quorum_match_index,
    joint_vote_quorum,
    quorum_match_index,
    vote_quorum,
)
from tests.oracle import OracleBallot, oracle_commit_index  # noqa: E402


def _oracle_quorum_match(match_row, voters):
    """Largest i such that |{p in voters: match[p] >= i}| >= quorum; the
    oracle form: q-th largest voter matchIndex."""
    vals = sorted((match_row[p] for p in voters), reverse=True)
    if not vals:
        return None
    q = len(voters) // 2 + 1
    return vals[q - 1]


class TestQuorumMatchIndex:
    def test_simple_3_voters(self):
        match = jnp.array([[5, 3, 7, 0]], jnp.int32)
        mask = jnp.array([[True, True, True, False]])
        assert int(quorum_match_index(match, mask)[0]) == 5

    def test_even_voters(self):
        # 4 voters -> quorum 3 -> 3rd largest
        match = jnp.array([[10, 8, 6, 4]], jnp.int32)
        mask = jnp.ones((1, 4), bool)
        assert int(quorum_match_index(match, mask)[0]) == 6

    def test_no_voters(self):
        match = jnp.zeros((1, 4), jnp.int32)
        mask = jnp.zeros((1, 4), bool)
        assert int(quorum_match_index(match, mask)[0]) == NEG_INF_I32

    def test_single_voter(self):
        match = jnp.array([[9, 99, 99, 99]], jnp.int32)
        mask = jnp.array([[True, False, False, False]])
        assert int(quorum_match_index(match, mask)[0]) == 9

    @pytest.mark.parametrize("seed", range(5))
    def test_random_vs_oracle(self, seed):
        rng = np.random.default_rng(seed)
        G, P = 64, 8
        match = rng.integers(0, 1000, (G, P)).astype(np.int32)
        mask = rng.random((G, P)) < 0.7
        got = np.asarray(quorum_match_index(jnp.asarray(match), jnp.asarray(mask)))
        for g in range(G):
            voters = {p for p in range(P) if mask[g, p]}
            want = _oracle_quorum_match(match[g], voters)
            if want is None:
                assert got[g] == NEG_INF_I32
            else:
                assert got[g] == want, f"group {g}"

    @pytest.mark.parametrize("seed", range(5))
    def test_equivalent_to_per_index_ballots(self, seed):
        """The core equivalence claim: order statistic == walking per-index
        Ballots from pending_index (reference BallotBox#commitAt)."""
        rng = np.random.default_rng(100 + seed)
        P = 5
        for _ in range(50):
            voters = set(rng.choice(P, rng.integers(1, P + 1), replace=False).tolist())
            match = {p: int(rng.integers(0, 30)) for p in range(P)}
            pending = int(rng.integers(1, 15))
            last_log = pending + int(rng.integers(0, 20))
            cur = pending - 1
            want = oracle_commit_index(match, voters, None, pending, last_log, cur)
            row = np.array([[match[p] for p in range(P)]], np.int32)
            m = np.array([[p in voters for p in range(P)]])
            qi = int(quorum_match_index(jnp.asarray(row), jnp.asarray(m))[0])
            # kernel-side gating: commit = qi if qi >= pending else unchanged,
            # clamped to last_log (host guarantees match <= last_log; clamp anyway)
            got = max(cur, min(qi, last_log)) if qi >= pending else cur
            assert got == want


class TestJointQuorum:
    def test_joint_takes_min(self):
        match = jnp.array([[10, 10, 10, 2, 2]], jnp.int32)
        new = jnp.array([[True, True, True, False, False]])
        old = jnp.array([[False, False, True, True, True]])
        # new quorum idx = 10, old quorum idx = 2 -> joint = 2
        assert int(joint_quorum_match_index(match, new, old)[0]) == 2

    def test_stable_ignores_old(self):
        match = jnp.array([[10, 9, 8]], jnp.int32)
        new = jnp.ones((1, 3), bool)
        old = jnp.zeros((1, 3), bool)
        assert int(joint_quorum_match_index(match, new, old)[0]) == 9

    @pytest.mark.parametrize("seed", range(3))
    def test_random_joint_vs_oracle(self, seed):
        rng = np.random.default_rng(200 + seed)
        P = 6
        for _ in range(30):
            voters = set(rng.choice(P, rng.integers(1, P + 1), replace=False).tolist())
            old_voters = set(rng.choice(P, rng.integers(1, P + 1), replace=False).tolist())
            match = {p: int(rng.integers(0, 20)) for p in range(P)}
            pending = int(rng.integers(1, 10))
            last_log = pending + 15
            cur = pending - 1
            want = oracle_commit_index(match, voters, old_voters, pending, last_log, cur)
            row = np.array([[match[p] for p in range(P)]], np.int32)
            nm = np.array([[p in voters for p in range(P)]])
            om = np.array([[p in old_voters for p in range(P)]])
            qi = int(joint_quorum_match_index(jnp.asarray(row), jnp.asarray(nm), jnp.asarray(om))[0])
            got = max(cur, min(qi, last_log)) if qi >= pending else cur
            assert got == want


class TestVoteQuorum:
    def test_majority(self):
        granted = jnp.array([[True, True, False]])
        mask = jnp.ones((1, 3), bool)
        assert bool(vote_quorum(granted, mask)[0])

    def test_no_majority(self):
        granted = jnp.array([[True, False, False]])
        mask = jnp.ones((1, 3), bool)
        assert not bool(vote_quorum(granted, mask)[0])

    def test_non_voter_grants_ignored(self):
        granted = jnp.array([[True, False, False, True, True]])
        mask = jnp.array([[True, True, True, False, False]])
        assert not bool(vote_quorum(granted, mask)[0])

    def test_joint_needs_both(self):
        granted = jnp.array([[True, True, False, False]])
        new = jnp.array([[True, True, False, False]])
        old = jnp.array([[False, False, True, True]])
        assert not bool(joint_vote_quorum(granted, new, old)[0])
        granted2 = jnp.array([[True, True, True, True]])
        assert bool(joint_vote_quorum(granted2, new, old)[0])

    @pytest.mark.parametrize("seed", range(3))
    def test_random_vs_oracle_ballot(self, seed):
        rng = np.random.default_rng(300 + seed)
        P = 7
        for _ in range(50):
            voters = set(rng.choice(P, rng.integers(1, P + 1), replace=False).tolist())
            grants = set(rng.choice(P, rng.integers(0, P + 1), replace=False).tolist())
            b = OracleBallot(voters)
            for p in grants:
                b.grant(p)
            g = np.array([[p in grants for p in range(P)]])
            m = np.array([[p in voters for p in range(P)]])
            assert bool(vote_quorum(jnp.asarray(g), jnp.asarray(m))[0]) == b.is_granted()
