"""graftcheck fixture: seeded future-completion violations.  Parsed by
tests/test_analysis.py, never imported."""

import asyncio


def risky_step():
    raise RuntimeError("boom")


async def bad_straight_line_completion():
    fut = asyncio.get_running_loop().create_future()
    value = risky_step()        # raises -> set_result never runs
    fut.set_result(value)       # VIOLATION: no except/finally completion
    return None                 # (fut deliberately not returned)


async def bad_never_completed():
    fut = asyncio.get_running_loop().create_future()
    risky_step()                # VIOLATION: never completed, never escapes
    return None


async def ok_try_except_completion():
    fut = asyncio.get_running_loop().create_future()
    try:
        fut.set_result(risky_step())
    except Exception as e:          # noqa: BLE001 — fixture
        fut.set_exception(e)        # clean: failure path completes it
    return None


async def ok_finally_cancel():
    fut = asyncio.get_running_loop().create_future()
    try:
        fut.set_result(risky_step())
    finally:
        fut.cancel()                # clean: finally always completes
    return None


async def bad_annotated_straight_line():
    fut: asyncio.Future = asyncio.get_running_loop().create_future()
    value = risky_step()        # raises -> set_result never runs
    fut.set_result(value)       # VIOLATION: AnnAssign form, same rule
    return None


async def ok_escaping_future(registry):
    fut = asyncio.get_running_loop().create_future()
    registry.append(fut)        # ownership transferred: out of scope
    risky_step()
    return None


async def ok_immediate_completion():
    fut = asyncio.get_running_loop().create_future()
    fut.set_result(1)           # clean: nothing risky in between
    return None
