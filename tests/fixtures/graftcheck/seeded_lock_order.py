"""graftcheck fixture: a lock-order cycle (A->B in one path, B->A in
another) plus an edge only visible through one level of intra-module
call resolution.  Parsed by tests/test_analysis.py, never imported."""

import threading

_reg_lock = threading.Lock()
_registry = {}


class Engine:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:       # edge A -> B
                pass

    def backward(self):
        with self._block:
            with self._alock:       # edge B -> A: CYCLE
                pass

    def close(self):
        with self._alock:
            pass


def release(eng):
    with _reg_lock:
        eng.close()                 # resolved edge _reg_lock -> Engine._alock
