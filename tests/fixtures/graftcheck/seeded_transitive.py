"""graftcheck fixture: seeded transitive-blocking violations.

NOT imported by anything — parsed by tests/test_analysis.py.  Every
helper below blocks only CONTEXT-FREE (the intra-procedural lint stays
silent); the violations are the call sites that reach them from a
forbidden context through one or two resolution hops.
"""

import threading
import time

from tests.fixtures.graftcheck.seeded_transitive_dep import remote_pause


def sleeper():
    time.sleep(0.01)            # context-free: direct lint stays quiet


def hop():
    sleeper()                   # one more hop


def untimed_wait(fut):
    return fut.result()         # context-free untimed wait


async def bad_coro_transitive():
    hop()           # VIOLATION: coroutine -> hop -> sleeper -> time.sleep


async def bad_coro_cross_module():
    remote_pause()  # VIOLATION: the sink lives in seeded_transitive_dep


async def ok_result_via_helper(fut):
    # the soft coroutine contract carries over transitively: an untimed
    # .result() reached from a coroutine is the done-task idiom, not a
    # finding (sleep/socket only) — mirrors the direct lint
    return untimed_wait(fut)


# graftcheck: allow(transitive-blocking) — fixture: waiver honored
async def waived_coro_transitive():
    hop()


class Locky:
    def __init__(self, lock):
        self._lock = lock

    def bad_under_lock(self):
        with self._lock:
            hop()               # VIOLATION: transitively sleeps under lock

    def ok_outside_lock(self):
        hop()                   # clean: plain sync context is free to block


class SeededStateMachine:
    def on_apply(self, fut):
        return untimed_wait(fut)   # VIOLATION: FSM path -> untimed result


async def bad_await_under_sync_lock(box, other):
    with box.state_lock:
        await other.flush()     # VIOLATION: await while holding sync lock
