"""graftcheck fixture: the cross-module blocking sink for
seeded_transitive.py — proves summary propagation follows an absolute
import whose target module is in the analyzed set."""

import time


def remote_pause():
    time.sleep(0.05)        # the sink, one module away
