"""graftcheck fixture: donated-buffer reads after a donating jit call.

NOT imported by anything — parsed by tests/test_analysis.py.  Mirrors
the ``raft_tick_jit = jax.jit(raft_tick, donate_argnums=(0,))`` shape:
the state buffer handed to the jitted callable is invalidated by
donation, so only the returned arrays are legal afterwards.
"""

import jax
import jax.numpy as jnp


def step(state: jnp.ndarray, now: jnp.ndarray):
    return state + now


step_donating = jax.jit(step, donate_argnums=(0,))


def bad_read_after_donate(state, now):
    out = step_donating(state, now)
    return out, state.sum()         # VIOLATION: donated buffer read


def ok_rebind(state, now):
    state = step_donating(state, now)
    return state.sum()              # clean: rebound to the fresh output


def ok_no_later_read(state, now):
    return step_donating(state, now)
