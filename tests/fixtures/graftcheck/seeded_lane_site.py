"""graftcheck fixture: [G] lane lifecycle-site coverage violations.

NOT imported by anything — parsed by tests/test_analysis.py.  A
miniature MultiRaftEngine whose lanes exercise every lane-coverage
shape: fully covered, missing one site, reasoned waiver, reasonless
waiver, unknown waiver token, and a [P]-shaped row that is NOT a lane.
"""

import numpy as np

NEG = -(2 ** 30)


class MultiRaftEngine:
    def __init__(self, opts):
        g, p = opts.max_groups, opts.max_peers
        self.G, self.P = g, p
        self.ok_lane = np.zeros(g, np.int64)
        self.bad_free_lane = np.zeros((g, p), np.int64)  # VIOLATION: release
        self.bad_conf_lane = np.full(g, NEG, np.int64)   # VIOLATION: set_conf
        # lane: no-conf no-shift — fixture: registration-owned duration row
        self.waived_lane = np.full(g, 7, np.int64)
        # lane: no-shift
        self.bad_waiver_lane = np.zeros(g, np.int64)  # VIOLATION: no reason
        # lane: no-grift — fixture: typo'd site token
        self.bad_token_lane = np.zeros(g, np.int64)   # VIOLATION: bad site
        self.not_a_lane = np.zeros(p, np.int64)       # [P] row: not a lane
        self._free = list(range(g))

    def _grow(self):
        old_g = self.G

        def pad(a, fill=0):
            extra = np.full((old_g,) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, extra])

        self.ok_lane = pad(self.ok_lane)
        self.bad_free_lane = pad(self.bad_free_lane)
        self.bad_conf_lane = pad(self.bad_conf_lane, NEG)
        self.waived_lane = pad(self.waived_lane, 7)
        self.bad_waiver_lane = pad(self.bad_waiver_lane)
        self.bad_token_lane = pad(self.bad_token_lane)
        self.G = old_g * 2

    def release(self, slot):
        self.ok_lane[slot] = 0
        self.bad_conf_lane[slot] = NEG
        self.waived_lane[slot] = 7
        self.bad_token_lane[slot] = 0
        self._reset_extra(slot)

    def _reset_extra(self, slot):
        # one level of intra-class call resolution covers this write
        self.bad_waiver_lane[slot] = 0

    def set_conf(self, slot, conf):
        self.ok_lane[slot] = 0
        self.bad_free_lane[slot, :] = 0
        self.bad_waiver_lane[slot] = 0
        self.bad_token_lane[slot] = 0

    def _maybe_time_rebase(self, now):
        shift = now
        self.ok_lane -= shift
        self.bad_free_lane -= shift
        self.bad_conf_lane -= shift
        np.maximum(self.bad_waiver_lane - shift, NEG,
                   out=self.bad_waiver_lane)
        self.bad_token_lane -= shift
