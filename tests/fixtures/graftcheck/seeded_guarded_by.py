"""graftcheck fixture: seeded guarded-by / loop-confined violations.

NOT imported by anything — parsed by tests/test_analysis.py to prove
each rule fires (and that waivers suppress).  Line markers below are
matched by substring, not line number, so edits stay cheap.
"""

import threading
import time


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []        # guarded-by: _lock
        self.version = 0        # guarded-by: _lock (writes)

    def ok_locked_access(self):
        with self._lock:
            self._items.append(1)       # clean: under the lock
            self.version += 1

    def bad_unlocked_read(self):
        return len(self._items)         # VIOLATION: read without lock

    def bad_unlocked_write(self):
        self.version = 7                # VIOLATION: write without lock

    def ok_writes_mode_read(self):
        return self.version             # clean: (writes) mode, read ok

    def waived_access(self):
        # the escape hatch, with a written justification
        return self._items[:]  # graftcheck: allow(guarded-by) — fixture: snapshot copy is benign here

    def bad_closure_in_with(self):
        with self._lock:
            def later():
                return self._items.pop()    # VIOLATION: closure runs later
            return later

    def _helper_locked(self):
        self._items.clear()             # clean: _locked suffix = held

    def bad_call_without_lock(self):
        self._helper_locked()           # VIOLATION: holds-call site

    def ok_call_with_lock(self):
        with self._lock:
            self._helper_locked()       # clean


_mod_guard = threading.Lock()
_mod_registry = {}      # guarded-by: _mod_guard


def bad_module_closure():
    with _mod_guard:
        def later():
            return _mod_registry.popitem()  # VIOLATION: closure runs later
        return later


def ok_module_locked():
    with _mod_guard:
        _mod_registry.clear()               # clean


class TrailingCommentScope:
    """A trailing annotation must not leak onto the NEXT statement."""

    def __init__(self):
        self._lock = threading.Lock()
        self.a = 1          # guarded-by: _lock
        self.b = 2

    def bad_touch_a(self):
        return self.a               # VIOLATION: a is annotated

    def ok_touch_b(self):
        self.b = 9                  # clean: b inherited NOTHING from a


# graftcheck: loop-confined
class Confined:
    def __init__(self):
        time.sleep(0.01)                # VIOLATION: ctor is confined too

    def bad_thread_primitive(self):
        return threading.Lock()         # VIOLATION: loop-confined

    def bad_sleep(self):
        time.sleep(0.1)                 # VIOLATION: loop-confined


# graftcheck: loop-confined — the marker sits on the FIRST line of a
# multi-line annotation comment (the common in-tree shape); the checker
# must scan the whole contiguous block, not just the line above
class ConfinedMultiLineAnnotation:
    def bad_sleep_multiline(self):
        time.sleep(0.1)                 # VIOLATION: loop-confined


def _decor(cls):
    return cls


# graftcheck: loop-confined — the annotation above a DECORATED class
# must anchor at the decorator line (review catch: the block-above walk
# from the class line stops at @_decor and killed the marker — the
# in-tree @dataclass RegionHeat annotation was dead on arrival)
@_decor
class ConfinedDecorated:
    def bad_sleep_decorated(self):
        time.sleep(0.1)                 # VIOLATION: loop-confined
