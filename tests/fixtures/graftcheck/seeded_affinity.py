"""graftcheck fixture: loop-confined state touched from an inferred
executor context (+ transitive thread spawns).

NOT imported by anything — parsed by tests/test_analysis.py.  The
violations mirror the PR 11/12 in-thread flush-timing hazard: code
handed to run_in_executor / Thread(target=) / executor.submit writing
a loop-confined class's unguarded attributes.
"""

import threading


def noop():
    pass


def spawn_worker():
    t = threading.Thread(target=noop)
    t.start()
    return t


# graftcheck: loop-confined — fixture: caches and counters live on the
# owning loop; only the locked probe counter crosses threads
class ConfinedCache:
    def __init__(self, loop, lock):
        self._loop = loop
        self._entries = {}
        self._stale = False
        self._via_submit = 0
        self._probe_lock = lock
        self._flush_count = 0   # guarded-by: _probe_lock

    def kick(self):
        self._loop.run_in_executor(None, self._bad_refresh)
        self._loop.run_in_executor(None, self._ok_probe)

    def _bad_refresh(self):
        self._entries = {}          # VIOLATION: off-loop unguarded write

    def _ok_probe(self):
        with self._probe_lock:
            self._flush_count += 1  # clean: locked state is the channel

    def kick_indirect(self):
        self._loop.run_in_executor(None, self._outer)

    def _outer(self):
        self._inner()               # off-loop propagates to callees

    def _inner(self):
        self._stale = True          # VIOLATION: transitive off-loop write

    def kick_submit(self, executor):
        executor.submit(self._bad_submit_write)

    def _bad_submit_write(self):
        self._via_submit = 1        # VIOLATION: submit() target write

    def bad_spawns_via_helper(self):
        spawn_worker()  # VIOLATION: transitive thread spawn from confined

    def on_loop_write(self):
        self._entries = {"k": 1}    # clean: written on the loop itself


class UnconfinedWorkerOwner:
    """No loop-confined marker: off-loop writes are its own business."""

    def go(self, loop):
        loop.run_in_executor(None, self._work)

    def _work(self):
        self.done = True            # clean: class is not loop-confined
