"""graftcheck fixture: seeded blocking-call violations (and the shapes
that must NOT fire).  Parsed by tests/test_analysis.py, never imported."""

import asyncio
import socket
import threading
import time

_lock = threading.Lock()
_alock = asyncio.Lock()


def bad_sleep_under_lock():
    with _lock:
        time.sleep(0.5)                         # VIOLATION: lock held


def bad_untimed_result_under_lock(fut):
    with _lock:
        return fut.result()                     # VIOLATION: wedged-waiter


def ok_timed_result_under_lock(fut):
    with _lock:
        return fut.result(timeout=5.0)          # clean: bounded wait


def ok_sleep_no_context():
    time.sleep(0.1)                             # clean: plain sync helper


async def bad_sleep_in_coroutine():
    time.sleep(0.2)                             # VIOLATION: blocks the loop


async def ok_result_of_done_task(task):
    await task
    return task.result()                        # clean: done task, no block


async def ok_executor_reference():
    loop = asyncio.get_running_loop()
    # passing the callable is fine; only CALLS are flagged
    await loop.run_in_executor(None, time.sleep, 0.1)


async def bad_untimed_result_under_async_lock(fut):
    async with _alock:
        return fut.result()                 # VIOLATION: async lock held


async def ok_lambda_off_loop():
    loop = asyncio.get_running_loop()
    with _lock:
        # the sanctioned off-loop pattern: the lambda body runs on an
        # executor thread, NOT under the lock — must stay clean.  The
        # await itself sits OUTSIDE the with: holding a sync lock
        # across a suspension point is its own (transitive-blocking)
        # finding — graftcheck v2 flagged the original shape of this
        # very fixture for exactly that convoy hazard
        fut = loop.run_in_executor(None, lambda: time.sleep(0.1))
    await fut


def bad_socket_under_lock(server_sock):
    with _lock:
        return server_sock.accept()             # VIOLATION: blocking IO


class ReplayStateMachine:
    """Name matches *StateMachine: every method is an FSM apply path."""

    def on_apply(self, it):
        time.sleep(0.01)                        # VIOLATION: FSM path

    def bad_wait(self, fut):
        return fut.result()                     # VIOLATION: FSM path
