"""graftcheck fixture living under an ops/ directory: the whole module
is tick-plane context.  Parsed by tests/test_analysis.py, never
imported."""

import time


def bad_tick_sleep():
    time.sleep(0.001)       # VIOLATION: tick plane


def bad_tick_wait(fut):
    return fut.result()     # VIOLATION: tick plane (untimed wait)


def ok_compute(x):
    return x + 1
