"""graftcheck fixture: host-sync violations inside jitted bodies.

NOT imported by anything — parsed by tests/test_analysis.py.  Both jit
root shapes appear (module-level ``jax.jit(fn, ...)`` assignment and a
``functools.partial(jax.jit)`` decorator) plus a helper reached only
THROUGH a jit root, proving the jit-body set closes over the call
graph.  ``ok_host_probe`` uses every banned construct but is never
reachable from a root — host-side probe code stays legal.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _masked(x: jnp.ndarray, mask: jnp.ndarray):
    return jnp.where(mask, x, 0)


def bad_kernel(state: jnp.ndarray, mask: jnp.ndarray, flavor: str = "x"):
    total = _masked(state, mask).sum()
    peak = total.item()             # VIOLATION: .item() host sync
    host = np.asarray(state)        # VIOLATION: np.asarray on traced
    n = int(state[0])               # VIOLATION: int() of traced value
    if state.sum() > 0:             # VIOLATION: data-dependent `if`
        total = total + 1
    if flavor == "x":               # clean: static str argument
        total = total * 2
    while mask.any():               # VIOLATION: data-dependent `while`
        break
    return total, peak, host, n


bad_kernel_jit = jax.jit(bad_kernel, static_argnames=("flavor",))


def helper_sync(v: jnp.ndarray):
    return float(v)                 # VIOLATION: reached through a root


@functools.partial(jax.jit)
def bad_via_helper(v: jnp.ndarray):
    return helper_sync(v)


def ok_host_probe(v):
    # not reachable from any jit root: .item()/np/int branching is the
    # NORMAL host idiom out here
    arr = np.asarray(v)
    if arr.sum() > 0:
        return int(arr[0]), arr.item() if arr.size == 1 else None
    return 0, None
