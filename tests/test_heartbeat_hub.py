"""HeartbeatHub: coalesced cross-group heartbeats (SURVEY.md §3.5
batched send-matrix plane — a TPU-native scaling feature with no
reference counterpart)."""

import asyncio

import pytest  # noqa: F401

from tests.cluster import TestCluster
from tests.test_engine import MultiRaftCluster
from tpuraft.core.node import State
from tpuraft.entity import Task


async def test_coalesced_cluster_stable_and_applies():
    """Leadership must stay stable on hub heartbeats alone (no per-group
    heartbeat loops), and replication/commit still works."""
    c = TestCluster(3, coalesce_heartbeats=True)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        term0 = leader.current_term
        st = await c.apply_ok(leader, b"hub-1")
        assert st.is_ok()
        # several election timeouts of quiet time: followers must keep
        # receiving (coalesced) heartbeats, so no re-election happens
        await asyncio.sleep(1.2)
        assert leader.state == State.LEADER
        assert leader.current_term == term0
        st = await c.apply_ok(leader, b"hub-2")
        assert st.is_ok()
        await c.wait_applied(2)
        hub = c.managers[leader.server_id].heartbeat_hub
        assert hub.rpcs_sent > 0
    finally:
        await c.stop_all()


async def test_coalesced_leader_detects_dead_quorum():
    """Hub silence must feed dead-node detection exactly like direct
    heartbeats: an isolated leader steps down."""
    c = TestCluster(3, election_timeout_ms=200, coalesce_heartbeats=True)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        c.net.isolate(leader.server_id.endpoint)
        deadline = asyncio.get_running_loop().time() + 5
        while asyncio.get_running_loop().time() < deadline:
            if leader.state != State.LEADER:
                break
            await asyncio.sleep(0.02)
        assert leader.state != State.LEADER
        c.net.heal()
    finally:
        await c.stop_all()


class CoalescedMultiRaftCluster(MultiRaftCluster):
    coalesce_heartbeats = True


async def test_multi_group_idle_rpc_reduction():
    """The point of the hub: G groups x P peers idle heartbeats collapse
    to one multi_heartbeat RPC per endpoint pair per interval."""
    c = CoalescedMultiRaftCluster(3, 16, election_timeout_ms=400)
    calls: list[str] = []
    orig_call = c.net.call

    async def counting_call(src, dst, method, request, timeout_ms=None):
        calls.append(method)
        return await orig_call(src, dst, method, request, timeout_ms)

    c.net.call = counting_call
    await c.start_all()
    try:
        for gid in c.groups:
            await c.wait_leader(gid, timeout_s=20.0)
        # one write per group so every group has a leader with followers
        async def put(gid):
            leader = await c.wait_leader(gid)
            fut = asyncio.get_running_loop().create_future()
            await leader.apply(Task(data=b"x", done=fut.set_result))
            assert (await asyncio.wait_for(fut, 10)).is_ok()
        await asyncio.gather(*[put(g) for g in c.groups])

        # quiet window: count idle-traffic RPCs.  Hub counters are
        # cumulative, so snapshot them and assert on window DELTAS —
        # the boot/apply phases legitimately produce small unaligned
        # pulses that would dilute a lifetime ratio (observed flake:
        # lifetime 3.98 vs the 4x bound under full-suite contention).
        hubs = [m.heartbeat_hub for m in
                (c.nodes[(c.groups[0], ep)].node_manager
                 for ep in c.endpoints)]
        rpcs0 = sum(h.rpcs_sent for h in hubs)
        beats0 = sum(h.beats_sent + h.fast_beats_sent for h in hubs)
        calls.clear()
        await asyncio.sleep(1.0)
        n_multi = calls.count("multi_heartbeat") + calls.count(
            "multi_beat_fast")
        n_append = calls.count("append_entries")
        assert n_multi > 0
        # without coalescing, idle heartbeats would be ~16 groups x 2
        # followers per interval per endpoint; with the hub, per-group
        # append_entries RPCs in a quiet window stay far below that
        assert n_append < n_multi * 4, (n_append, n_multi)
        # and the hub batched many beats per RPC while idle (deadlines
        # phase-align to the hb grid, so due groups pulse together);
        # steady state rides the beat-plane fast path almost entirely
        d_rpcs = sum(h.rpcs_sent for h in hubs) - rpcs0
        d_beats = sum(h.beats_sent + h.fast_beats_sent
                      for h in hubs) - beats0
        assert d_beats > d_rpcs * 4, (d_beats, d_rpcs)
        assert sum(h.fast_beats_sent for h in hubs) > 0
    finally:
        await c.stop_all()


async def test_coalesced_failover_and_recovery():
    """Leader crash with coalescing on: survivors elect, the new
    leader's beats flow through the hub, and the restarted node is
    re-suppressed (no dueling elections)."""
    c = TestCluster(3, coalesce_heartbeats=True)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        st = await c.apply_ok(leader, b"a")
        assert st.is_ok()
        dead = leader.server_id
        await c.stop(dead)
        leader2 = await c.wait_leader()
        assert leader2.server_id != dead
        st = await c.apply_ok(leader2, b"b")
        assert st.is_ok()
        # fresh recorder: the memory:// log restarts empty and full
        # re-replication would double-count into a reused one
        from tests.cluster import MockStateMachine
        await c.start(dead, fsm=MockStateMachine())
        await c.wait_applied(2)
        assert c.fsms[dead].logs == [b"a", b"b"]
        # stability after recovery: term holds for several timeouts
        term = leader2.current_term
        await asyncio.sleep(1.0)
        assert leader2.state == State.LEADER
        assert leader2.current_term == term
    finally:
        await c.stop_all()


# -- fast-beat failure-path unit tests (ADVICE r4) ---------------------------

from types import SimpleNamespace  # noqa: E402

from tpuraft.core.heartbeat_hub import HeartbeatHub  # noqa: E402


def _fake_beat_rep(transport, peer_ep="dst:1"):
    node = SimpleNamespace(
        group_id="g",
        server_id="srv:1",
        current_term=3,
        transport=transport,
        options=SimpleNamespace(
            election_timeout_ms=400,
            raft_options=SimpleNamespace(election_heartbeat_factor=10)),
        ballot_box=SimpleNamespace(last_committed_index=7),
        is_leader=lambda: True,
        on_peer_ack=lambda peer, when: None,
    )
    return SimpleNamespace(
        _node=node,
        _running=True,
        _matched=True,
        peer_multi_hb=True,
        peer=SimpleNamespace(endpoint=peer_ep),
        match_index=7,
        last_rpc_ack=0.0,
    )


async def test_fast_beat_short_ack_list_falls_back_classic():
    """A response with fewer acks than beats must NOT silently drop the
    trailing replicators (zip truncation): the whole chunk deviates and
    gets the classic-beat follow-up."""

    class ShortTransport:
        async def call(self, dst, method, request, timeout_ms=None):
            from tpuraft.rpc.messages import BatchResponse
            return BatchResponse(items=[SimpleNamespace(ok=True)])

    hub = HeartbeatHub()
    tr = ShortTransport()
    reps = [_fake_beat_rep(tr) for _ in range(3)]
    fell_back: list = []
    hub._pulse_classic = lambda rs: fell_back.extend(rs)
    hub.pulse(reps)
    await asyncio.sleep(0.05)
    assert len(fell_back) == 3
    assert hub.fast_fallbacks == 3


async def test_fast_beat_crash_is_reaped_and_falls_back_classic():
    """A non-RpcError escaping _beat_fast must be retrieved by the done
    callback (no 'exception was never retrieved' spam) AND fall back to
    classic beats — a persistent codec failure must not silently starve
    those groups of heartbeats until their followers elect."""

    class ExplodingTransport:
        async def call(self, dst, method, request, timeout_ms=None):
            raise ValueError("codec blew up")

    hub = HeartbeatHub()
    tr = ExplodingTransport()
    reps = [_fake_beat_rep(tr) for _ in range(2)]
    fell_back: list = []
    hub._pulse_classic = lambda rs: fell_back.extend(rs)
    hub.pulse(reps)
    await asyncio.sleep(0.05)
    assert len(fell_back) == 2
    assert hub.fast_fallbacks == 2
    assert not hub._inflight  # chunk slot released for the next pulse


def test_compact_beat_decodes_old_wire_format():
    """Mixed-version fleets: a CompactBeat encoded BEFORE the quiesce
    handshake fields existed is 9 bytes shorter (bool + i64).  The
    positional field-stream decode must fill the missing trailing
    defaulted fields from their defaults instead of raising — an
    upgraded receiver behind an old sender would otherwise fail every
    fast-beat batch, and the old sender (seeing a generic error, not
    ENOMETHOD) would never fall back to classic beats."""
    import pytest

    from tpuraft.rpc.messages import CompactBeat, decode_message, \
        encode_message

    beat = CompactBeat(group_id="g0", server_id="127.0.0.1:1",
                       peer_id="127.0.0.2:2", term=3, committed_index=17,
                       quiesce=True, lease_ms=4000)
    wire = encode_message(beat)
    assert decode_message(wire) == beat          # new <-> new round trip
    got = decode_message(wire[:-9])              # strip quiesce+lease_ms
    assert got == CompactBeat(group_id="g0", server_id="127.0.0.1:1",
                              peer_id="127.0.0.2:2", term=3,
                              committed_index=17)  # defaults: no handshake
    # a genuinely truncated REQUIRED field still fails loudly
    with pytest.raises(Exception):
        decode_message(wire[:-10])


async def test_fast_beat_enomethod_counts_fallbacks_and_pins_classic():
    """ENOMETHOD (receiver predates the beat plane) must count one
    fallback per affected replicator, pin the dst to classic beats, and
    re-pulse the chunk classically — and the counters must surface
    through the hub's MetricRegistry gauges (util/metrics.py)."""
    from tpuraft.errors import RaftError, Status
    from tpuraft.rpc.transport import RpcError

    class NoMethodTransport:
        async def call(self, dst, method, request, timeout_ms=None):
            raise RpcError(Status.error(RaftError.ENOMETHOD,
                                        f"no handler {method}"))

    hub = HeartbeatHub()
    tr = NoMethodTransport()
    reps = [_fake_beat_rep(tr) for _ in range(3)]
    fell_back: list = []
    hub._pulse_classic = lambda rs: fell_back.extend(rs)
    hub.pulse(reps)
    await asyncio.sleep(0.05)
    assert hub.fast_fallbacks == 3
    assert hub._fast_ok["dst:1"] is False
    assert len(fell_back) == 3        # the re-pulse went classic
    snap = hub.metrics.snapshot()["gauges"]
    assert snap["hub.fast_fallbacks"] == 3
    assert snap["hub.rpcs_sent"] == hub.rpcs_sent
    # counters() (the soak stats line's view) agrees with the gauges
    assert hub.counters()["fast_fallbacks"] == 3


class AutoMultiRaftCluster(MultiRaftCluster):
    coalesce_heartbeats = None  # the RaftOptions DEFAULT: auto


async def test_auto_coalescing_by_default():
    """VERDICT r2 #6 done-when: with DEFAULT options, an idle
    multi-group cluster's heartbeat RPC rate is O(endpoints) — peers
    advertise multi_heartbeat in AppendEntries responses (they all run
    NodeManagers) and the engine's beat fan-out auto-coalesces."""
    c = AutoMultiRaftCluster(3, 16, election_timeout_ms=400)
    calls: list[str] = []
    orig_call = c.net.call

    async def counting_call(src, dst, method, request, timeout_ms=None):
        calls.append(method)
        return await orig_call(src, dst, method, request, timeout_ms)

    c.net.call = counting_call
    await c.start_all()
    try:
        for gid in c.groups:
            await c.wait_leader(gid, timeout_s=20.0)

        async def put(gid):
            leader = await c.wait_leader(gid)
            fut = asyncio.get_running_loop().create_future()
            await leader.apply(Task(data=b"x", done=fut.set_result))
            assert (await asyncio.wait_for(fut, 10)).is_ok()
        await asyncio.gather(*[put(g) for g in c.groups])

        # every leader's replicators learned the capability from probes
        for (gid, ep), n in c.nodes.items():
            if n.is_leader():
                for r in n.replicators.all():
                    assert r.peer_multi_hb, (gid, str(r.peer))

        calls.clear()
        await asyncio.sleep(1.0)
        n_multi = calls.count("multi_heartbeat")
        n_append = calls.count("append_entries")
        assert n_multi > 0, "auto mode never coalesced"
        # idle per-group beats ride the hub BY DEFAULT: direct
        # append_entries stays far under the uncoalesced 16 groups x 2
        # followers per interval per endpoint
        assert n_append < n_multi * 4, (n_append, n_multi)
    finally:
        await c.stop_all()
