"""Storage tier tests (reference: BaseLogStorageTest, RocksDBLogStorageTest,
LocalRaftMetaStorageTest, LogManagerTest — SURVEY.md §5)."""

import asyncio

import pytest

from tpuraft.conf import Configuration, ConfigurationEntry
from tpuraft.entity import EntryType, LogEntry, LogId, PeerId
from tpuraft.storage.log_manager import LogManager
from tpuraft.storage.log_storage import FileLogStorage, MemoryLogStorage
from tpuraft.storage.meta_storage import RaftMetaStorage


def mk_entries(first, count, term=1, size=16):
    return [
        LogEntry(type=EntryType.DATA, id=LogId(first + i, term), data=bytes(size))
        for i in range(count)
    ]


class _BaseLogStorageSuite:
    def mk(self, tmp_path):
        raise NotImplementedError

    def test_empty(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        assert s.first_log_index() == 1
        assert s.last_log_index() == 0
        assert s.get_entry(1) is None
        s.shutdown()

    def test_append_get(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 10))
        assert s.last_log_index() == 10
        e = s.get_entry(7)
        assert e and e.id == LogId(7, 1)
        assert s.get_term(7) == 1
        s.shutdown()

    def test_truncate_suffix(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 10))
        s.truncate_suffix(6)
        assert s.last_log_index() == 6
        assert s.get_entry(7) is None
        s.append_entries(mk_entries(7, 2, term=2))
        assert s.get_term(8) == 2
        s.shutdown()

    def test_truncate_prefix(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 10))
        s.truncate_prefix(5)
        assert s.first_log_index() == 5
        assert s.last_log_index() == 10
        s.shutdown()

    def test_reset(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5))
        s.reset(100)
        assert s.first_log_index() == 100
        assert s.last_log_index() == 99
        s.append_entries(mk_entries(100, 3, term=9))
        assert s.get_term(101) == 9
        s.shutdown()


class TestMemoryLogStorage(_BaseLogStorageSuite):
    def mk(self, tmp_path):
        return MemoryLogStorage()


class TestFileLogStorage(_BaseLogStorageSuite):
    def mk(self, tmp_path):
        return FileLogStorage(str(tmp_path / "log"), segment_max_bytes=512)

    def test_restart_recovery(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 20, size=40))  # spans segments
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 20
        assert s2.get_entry(15).id == LogId(15, 1)
        s2.shutdown()

    def test_restart_after_prefix_truncate(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 20, size=40))
        s.truncate_prefix(12)
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.first_log_index() == 12
        assert s2.last_log_index() == 20
        s2.shutdown()

    def test_torn_write_recovery(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        # corrupt: chop bytes off the tail of the (only) segment.  A torn
        # write only happens on a CRASH — clean shutdown advances the
        # durability watermark over the whole file, which would (rightly)
        # make this loud corruption instead; drop it to simulate the crash.
        (tmp_path / "log" / "synced").unlink()
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = seg.read_bytes()
        seg.write_bytes(data[:-10])
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 2  # last entry dropped, first two intact
        assert s2.get_entry(2) is not None
        s2.shutdown()

    def test_non_contiguous_append_rejected(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3))
        with pytest.raises(ValueError):
            s.append_entries(mk_entries(7, 1))
        s.shutdown()

    def test_tail_corruption_after_clean_shutdown_is_loud(self, tmp_path):
        """Clean shutdown leaves no torn-write window: the watermark
        covers the file, so even LAST-entry corruption fails loudly."""
        from tpuraft.storage.log_storage import CorruptLogError

        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = bytearray(seg.read_bytes())
        data[-5] ^= 0xFF
        seg.write_bytes(bytes(data))
        s2 = self.mk(tmp_path)
        with pytest.raises(CorruptLogError):
            s2.init()

    def test_crash_window_failures_stay_truncatable(self, tmp_path):
        """Length-prefix corruption BEYOND the watermark (the unsynced
        crash window) must stay a truncatable torn tail even when
        valid-looking frames follow — unordered page writeback can
        legitimately persist later blocks while losing earlier ones."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        (tmp_path / "log" / "synced").unlink()  # simulate crash
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = bytearray(seg.read_bytes())
        frame = 4 + 32 + 40
        data[frame] ^= 0xFF  # second entry's length prefix
        seg.write_bytes(bytes(data))
        s2 = self.mk(tmp_path)
        s2.init()  # no exception: entries 2-3 were never provably durable
        assert s2.last_log_index() == 1
        s2.shutdown()

    def test_truncate_suffix_crash_window_not_bricked(self, tmp_path, monkeypatch):
        """Crash between the suffix shrink and the final watermark save
        must NOT brick startup: the floored watermark (written fsynced
        BEFORE the shrink) makes the stale value LOW, never HIGH."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5, size=40))
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # watermark now covers all 5 entries
        orig = FileLogStorage._save_watermark

        def drop_final_save(self_, sync=False):
            if sync:
                orig(self_, sync)  # the pre-shrink floor still lands

        monkeypatch.setattr(FileLogStorage, "_save_watermark", drop_final_save)
        s2.truncate_suffix(3)
        monkeypatch.setattr(FileLogStorage, "_save_watermark", orig)
        # simulate crash: no shutdown; reopen from disk state
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.last_log_index() == 3
        s3.shutdown()

    def test_missing_durable_segment_fails_loudly(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 20, size=40))  # spans segments
        s.shutdown()
        from tpuraft.storage.log_storage import CorruptLogError

        segs = sorted((tmp_path / "log").glob("seg_*.log"),
                      key=lambda p: int(p.name[4:-4]))
        assert len(segs) >= 3
        segs[1].unlink()  # a fully-durable mid-chain segment vanishes
        s2 = self.mk(tmp_path)
        with pytest.raises(CorruptLogError):
            s2.init()

    def test_missing_watermark_segment_fails_loudly(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 20, size=40))
        s.shutdown()
        from tpuraft.storage.log_storage import CorruptLogError

        segs = sorted((tmp_path / "log").glob("seg_*.log"),
                      key=lambda p: int(p.name[4:-4]))
        segs[-1].unlink()  # the watermark segment itself vanishes
        s2 = self.mk(tmp_path)
        with pytest.raises(CorruptLogError):
            s2.init()

    def test_truncate_prefix_past_stale_watermark_then_crash(self, tmp_path):
        """Compaction deleting the persisted-watermark segment must move
        the watermark BEFORE deleting: a crash right after used to brick
        the next init() with a false 'watermark segment missing'."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5, size=40))
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # persisted watermark now names the (only) segment
        s2.append_entries(mk_entries(6, 20, size=40))  # rolls segments
        s2.truncate_prefix(15)  # compacts the watermark segment away
        # simulate crash: no shutdown; reopen from disk state
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.first_log_index() == 15
        assert s3.last_log_index() == 25
        assert s3.get_entry(20) is not None
        s3.shutdown()

    def test_unsynced_compaction_crash_does_not_brick(self, tmp_path,
                                                      monkeypatch):
        """sync=False run: the frontier never advances past boot, so a
        compaction past it must CLEAR the watermark, not name a
        survivor — else a crash mid-delete leaves a never-fsynced
        below-survivor segment to be scanned as fully-durable, and its
        legitimately torn tail bricks boot (r5 review finding)."""
        from tpuraft.storage.log_storage import _Segment

        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5, size=40))
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # frontier + persisted watermark at seg_1
        s2.append_entries(mk_entries(6, 20, size=40), sync=False)  # rolls
        deleted = []
        orig_delete = _Segment.delete

        def delete_once(seg):
            if deleted:
                raise RuntimeError("crash mid-delete")
            deleted.append(seg)
            orig_delete(seg)

        monkeypatch.setattr(_Segment, "delete", delete_once)
        with pytest.raises(RuntimeError):
            s2.truncate_prefix(15)
        monkeypatch.setattr(_Segment, "delete", orig_delete)
        # deterministic crash image: page cache flushed (no fsync), so
        # every byte except the chopped tail "survived" the crash
        for seg in s2._segments:
            seg._f.flush()
        # the surviving doomed segment was never fsynced: its tail may
        # legitimately vanish with the crash
        seg8 = min((tmp_path / "log").glob("seg_*.log"),
                   key=lambda p: int(p.name[4:-4]))
        seg8.write_bytes(seg8.read_bytes()[:-10])
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.first_log_index() == 15
        assert s3.last_log_index() == 25
        s3.shutdown()

    def test_rotted_garbage_below_first_does_not_brick(self, tmp_path,
                                                       monkeypatch):
        """A below-first segment NAMED BY the watermark whose range is
        provably compacted (a successor starts at first_log_index) must
        scan tolerantly even with a damaged tail: it is garbage awaiting
        deletion, not acked data (r5 review finding)."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 14, size=40))  # seg_1 + seg_8
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # persisted watermark names seg_8 (the last segment)
        s2.append_entries(mk_entries(15, 11, size=40))  # seg_15, seg_22
        orig = FileLogStorage._save_watermark

        def boom(self_, sync=False):
            raise RuntimeError("crash mid-truncate")

        monkeypatch.setattr(FileLogStorage, "_save_watermark", boom)
        with pytest.raises(RuntimeError):
            s2.truncate_prefix(15)  # meta saved; nothing deleted yet
        monkeypatch.setattr(FileLogStorage, "_save_watermark", orig)
        # the doomed watermark segment's tail rots before the next boot
        seg8 = tmp_path / "log" / "seg_8.log"
        seg8.write_bytes(seg8.read_bytes()[:-10])
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.first_log_index() == 15
        assert s3.last_log_index() == 25
        s3.shutdown()

    def test_truncate_prefix_whole_log_then_reopen(self, tmp_path):
        """Compacting the ENTIRE log (snapshot covers every entry, no
        surviving segment, no appends after) must reopen cleanly — the
        watermark is cleared before the deletes."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5, size=40))
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # persisted watermark names the (only) segment
        s2.truncate_prefix(6)  # whole log compacted
        # crash: no shutdown
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.first_log_index() == 6
        assert s3.last_log_index() == 5
        s3.append_entries(mk_entries(6, 3, term=2))
        assert s3.get_term(7) == 2
        s3.shutdown()

    def test_truncate_prefix_crash_before_watermark_save(self, tmp_path,
                                                         monkeypatch):
        """Crash inside truncate_prefix after _save_meta but before the
        watermark save + deletes: init's stale cleanup removes the
        watermark segment itself — that provable-compaction case must be
        forgiven, not reported as acked-entry loss."""
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 5, size=40))
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()  # persisted watermark names seg_1
        s2.append_entries(mk_entries(6, 20, size=40))  # rolls segments
        orig = FileLogStorage._save_watermark

        def boom(self_, sync=False):
            raise RuntimeError("crash mid-truncate")

        monkeypatch.setattr(FileLogStorage, "_save_watermark", boom)
        with pytest.raises(RuntimeError):
            s2.truncate_prefix(15)  # meta saved, nothing deleted yet
        monkeypatch.setattr(FileLogStorage, "_save_watermark", orig)
        s3 = self.mk(tmp_path)
        s3.init()  # must not raise CorruptLogError
        assert s3.first_log_index() == 15
        assert s3.last_log_index() == 25
        s3.shutdown()

    def test_midlog_corruption_fails_loudly(self, tmp_path):
        """CRC failure with valid entries AFTER it is corruption, not a
        torn tail: truncating there would silently drop acked suffix
        entries, so startup must refuse instead."""
        from tpuraft.storage.log_storage import CorruptLogError

        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = bytearray(seg.read_bytes())
        # flip one payload byte in the MIDDLE entry (frames are
        # 4B len + 32B header + 40B data each)
        frame = 4 + 32 + 40
        data[frame + frame - 5] ^= 0xFF
        seg.write_bytes(bytes(data))
        s2 = self.mk(tmp_path)
        with pytest.raises(CorruptLogError):
            s2.init()


def _native_available():
    try:
        from tpuraft.storage.native_log import ensure_built
        ensure_built()
        return True
    except Exception:
        return False


@pytest.mark.skipif(not _native_available(), reason="C++ engine not buildable")
class TestNativeLogStorage(_BaseLogStorageSuite):
    """The C++ engine must pass the same suite as the Python impl, plus
    recovery and cross-engine interop (same on-disk format)."""

    def mk(self, tmp_path):
        from tpuraft.storage.native_log import NativeLogStorage
        return NativeLogStorage(str(tmp_path / "log"), segment_max_bytes=512)

    def test_restart_recovery(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 20, size=40))  # spans segments
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 20
        assert s2.get_entry(15).id == LogId(15, 1)
        s2.shutdown()

    def test_torn_write_recovery(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = seg.read_bytes()
        seg.write_bytes(data[:-10])
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 2
        assert s2.get_entry(2) is not None
        s2.shutdown()

    def test_corrupt_entry_detected(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3, size=40))
        s.shutdown()
        seg = sorted((tmp_path / "log").glob("seg_*.log"))[0]
        data = bytearray(seg.read_bytes())
        data[-5] ^= 0xFF  # flip a byte in the last entry's payload
        seg.write_bytes(bytes(data))
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 2  # CRC scan drops the bad tail entry
        s2.shutdown()

    def test_non_contiguous_append_rejected(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 3))
        with pytest.raises(ValueError):
            s.append_entries(mk_entries(7, 1))
        s.shutdown()

    def test_conf_sidecar(self, tmp_path):
        s = self.mk(tmp_path)
        s.init()
        ents = mk_entries(1, 6)
        ents[2] = LogEntry(type=EntryType.CONFIGURATION, id=LogId(3, 1),
                           peers=[PeerId.parse("127.0.0.1:8001")])
        s.append_entries(ents)
        assert s.configuration_indexes() == [3]
        s.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.configuration_indexes() == [3]
        e = s2.get_entry(3)
        assert e.is_configuration() and e.peers == [PeerId.parse("127.0.0.1:8001")]
        s2.shutdown()

    def test_interop_with_python_engine(self, tmp_path):
        """Write with C++, read+extend with Python, read back with C++."""
        from tpuraft.storage.log_storage import FileLogStorage
        s = self.mk(tmp_path)
        s.init()
        s.append_entries(mk_entries(1, 10, size=40))
        s.shutdown()
        p = FileLogStorage(str(tmp_path / "log"), segment_max_bytes=512)
        p.init()
        assert p.last_log_index() == 10
        p.append_entries(mk_entries(11, 5, term=2, size=40))
        p.shutdown()
        s2 = self.mk(tmp_path)
        s2.init()
        assert s2.last_log_index() == 15
        assert s2.get_term(12) == 2
        s2.shutdown()

    def test_uri_factory(self, tmp_path):
        from tpuraft.storage.log_storage import create_log_storage
        s = create_log_storage(f"native://{tmp_path}/log")
        s.init()
        s.append_entries(mk_entries(1, 3))
        assert s.last_log_index() == 3
        s.shutdown()


class TestRaftMetaStorage:
    def test_roundtrip(self, tmp_path):
        m = RaftMetaStorage(str(tmp_path))
        m.init()
        assert m.term == 0 and m.voted_for.is_empty()
        m.set_term_and_voted_for(7, PeerId.parse("1.2.3.4:80"))
        m2 = RaftMetaStorage(str(tmp_path))
        m2.init()
        assert m2.term == 7
        assert m2.voted_for == PeerId.parse("1.2.3.4:80")

    def test_corruption_detected(self, tmp_path):
        m = RaftMetaStorage(str(tmp_path))
        m.init()
        m.set_term_and_voted_for(3, PeerId.parse("1.2.3.4:80"))
        p = tmp_path / "raft_meta"
        raw = bytearray(p.read_bytes())
        raw[0] ^= 0xFF
        p.write_bytes(bytes(raw))
        with pytest.raises(IOError):
            m2 = RaftMetaStorage(str(tmp_path))
            m2.init()

    def test_stale_instance_cannot_regress_term_or_vote(self, tmp_path):
        """A store restart creates a new storage over the same dir while
        the old node's last save may still be in flight on an executor
        thread: a late stale save must neither regress the durable term
        nor switch/forget a vote within a term (double-vote after the
        next crash)."""
        a, b = PeerId.parse("1.1.1.1:1"), PeerId.parse("1.1.1.1:2")
        stale = RaftMetaStorage(str(tmp_path))
        stale.init()
        fresh = RaftMetaStorage(str(tmp_path))  # the restarted node
        fresh.init()
        fresh.set_term_and_voted_for(5, a)
        stale.set_term_and_voted_for(3, b)     # late lower-term save
        m = RaftMetaStorage(str(tmp_path))
        m.init()
        assert (m.term, m.voted_for) == (5, a), "stale save regressed term"
        stale.set_term_and_voted_for(5, b)     # same-term vote SWITCH
        m = RaftMetaStorage(str(tmp_path))
        m.init()
        assert (m.term, m.voted_for) == (5, a), "same-term vote switched"
        fresh.set_term_and_voted_for(6, b)     # higher term always wins
        m = RaftMetaStorage(str(tmp_path))
        m.init()
        assert (m.term, m.voted_for) == (6, b)


class TestMultiMetaStorage:
    """Shared {term, votedFor} journal with group-commit fsync
    (storage/meta_multilog.py; reference: LocalRaftMetaStorage semantics
    at multi-raft density — SURVEY.md §3.1 'synced on change')."""

    def test_roundtrip_many_groups(self, tmp_path):
        from tpuraft.storage.meta_multilog import MultiRaftMetaStorage

        stores = [MultiRaftMetaStorage(str(tmp_path), f"g{i}")
                  for i in range(8)]
        for s in stores:
            s.init()
        for i, s in enumerate(stores):
            s.set_term_and_voted_for(i + 1, PeerId.parse(f"1.2.3.4:{80 + i}"))
        for s in stores:
            s.shutdown()
        back = [MultiRaftMetaStorage(str(tmp_path), f"g{i}")
                for i in range(8)]
        for i, s in enumerate(back):
            s.init()
            assert s.term == i + 1
            assert s.voted_for == PeerId.parse(f"1.2.3.4:{80 + i}")
        for s in back:
            s.shutdown()

    async def test_group_commit_coalesces_fsyncs(self, tmp_path):
        """N groups persisting concurrently must share fsync rounds —
        the whole point of the journal (r4 weak #5: durable-meta
        election herds)."""
        from tpuraft.storage.meta_multilog import MultiRaftMetaStorage

        G = 64
        stores = [MultiRaftMetaStorage(str(tmp_path), f"g{i}")
                  for i in range(G)]
        for s in stores:
            s.init()
        jnl = stores[0]._jnl
        sync0 = jnl.sync_count
        await asyncio.gather(*(
            s.save_async(5, PeerId.parse("1.2.3.4:80")) for s in stores))
        rounds = jnl.sync_count - sync0
        assert rounds < G / 4, rounds  # far fewer fsyncs than groups
        for s in stores:
            s.shutdown()

    def test_torn_tail_truncated_beyond_watermark(self, tmp_path):
        from tpuraft.storage.meta_multilog import MetaJournal

        j = MetaJournal(str(tmp_path))
        j.stage("g1", 3, PeerId.parse("1.2.3.4:80"))
        j.sync()
        # watermark still covers only the fsynced prefix recorded at
        # open; simulate crash AFTER an unsynced stage: chop bytes
        j.stage("g1", 4, PeerId.parse("1.2.3.4:81"))
        path = tmp_path / "meta.jnl"
        j._f.flush()
        j._f = None  # simulate crash (skip close's sync+watermark)
        data = path.read_bytes()
        path.write_bytes(data[:-3])
        j2 = MetaJournal(str(tmp_path))
        term, voted = j2.get("g1")
        assert term == 3  # torn record dropped, synced one survives
        j2.close()

    def test_corruption_below_watermark_is_loud(self, tmp_path):
        from tpuraft.storage.log_storage import CorruptLogError
        from tpuraft.storage.meta_multilog import MetaJournal

        j = MetaJournal(str(tmp_path))
        j.stage("g1", 3, PeerId.parse("1.2.3.4:80"))
        j.sync()
        j.close()  # clean close advances the watermark
        path = tmp_path / "meta.jnl"
        data = bytearray(path.read_bytes())
        data[5] ^= 0xFF
        path.write_bytes(bytes(data))
        with pytest.raises(CorruptLogError):
            MetaJournal(str(tmp_path))

    def test_compaction_keeps_latest_values(self, tmp_path):
        from tpuraft.storage.meta_multilog import MetaJournal

        j = MetaJournal(str(tmp_path))
        j.COMPACT_MIN_BYTES = 512  # force compaction early
        for term in range(1, 200):
            j.stage("g1", term, PeerId.parse("1.2.3.4:80"))
            j.stage("g2", term, PeerId.parse("1.2.3.4:81"))
            j.sync()
        assert (tmp_path / "meta.jnl").stat().st_size < 4096  # compacted
        j.close()
        j2 = MetaJournal(str(tmp_path))
        assert j2.get("g1") == (199, PeerId.parse("1.2.3.4:80"))
        assert j2.get("g2") == (199, PeerId.parse("1.2.3.4:81"))
        j2.close()


@pytest.mark.asyncio
class TestLogManager:
    async def mk(self):
        lm = LogManager(MemoryLogStorage())
        await lm.init()
        return lm

    async def test_leader_append_assigns_ids(self):
        lm = await self.mk()
        entries = [LogEntry(type=EntryType.DATA, data=b"a"),
                   LogEntry(type=EntryType.DATA, data=b"b")]
        last = await lm.append_entries_leader(entries, term=3)
        assert last == LogId(2, 3)
        assert lm.last_log_index() == 2
        assert lm.get_term(1) == 3
        await lm.shutdown()

    async def test_follower_append_and_conflict(self):
        lm = await self.mk()
        ok = await lm.append_entries_follower(0, 0, mk_entries(1, 5, term=1))
        assert ok and lm.last_log_index() == 5
        # conflicting suffix at index 4 with higher term
        newer = mk_entries(4, 3, term=2)
        ok = await lm.append_entries_follower(3, 1, newer)
        assert ok
        assert lm.last_log_index() == 6
        assert lm.get_term(4) == 2
        # gap rejected
        assert not await lm.append_entries_follower(99, 1, mk_entries(100, 1))
        # mismatched prev term rejected
        assert not await lm.append_entries_follower(4, 1, mk_entries(5, 1, term=2))
        await lm.shutdown()

    async def test_follower_rejects_wire_corrupted_entry(self):
        """A blob corrupted past TCP's checksum must NOT reach storage:
        the append is refused (leader backs off + retransmits), instead
        of staging bytes whose embedded CRC mismatches — which a later
        recovery scan would mistake for a torn tail."""
        lm = await self.mk()
        raw = bytearray(mk_entries(1, 1, term=1, size=64)[0].encode())
        raw[-3] ^= 0xFF
        bad = LogEntry.decode(bytes(raw), verify=False)  # wire path
        ok = await lm.append_entries_follower(0, 0, [bad])
        assert not ok
        assert lm.last_log_index() == 0  # nothing staged
        # a clean retransmission then succeeds
        ok = await lm.append_entries_follower(0, 0, mk_entries(1, 1, term=1))
        assert ok and lm.last_log_index() == 1
        await lm.shutdown()

    async def test_duplicate_append_idempotent(self):
        lm = await self.mk()
        await lm.append_entries_follower(0, 0, mk_entries(1, 5, term=1))
        ok = await lm.append_entries_follower(0, 0, mk_entries(1, 5, term=1))
        assert ok and lm.last_log_index() == 5
        await lm.shutdown()

    async def test_waiters(self):
        lm = await self.mk()
        fut = lm.wait_for(3)
        assert not fut.done()
        await lm.append_entries_leader(
            [LogEntry(type=EntryType.DATA, data=b"x") for _ in range(3)], term=1)
        assert await fut is True
        # already satisfied -> immediate
        assert (await lm.wait_for(1)) is True
        await lm.shutdown()

    async def test_conf_tracking(self):
        lm = await self.mk()
        conf_entry = LogEntry(
            type=EntryType.CONFIGURATION,
            peers=[PeerId.parse("1.1.1.1:1"), PeerId.parse("1.1.1.1:2")],
        )
        await lm.append_entries_leader([conf_entry], term=1)
        ce = lm.conf_manager.last()
        assert ce.id.index == 1
        assert len(ce.conf.peers) == 2
        await lm.shutdown()

    async def test_set_snapshot_compacts(self):
        lm = await self.mk()
        await lm.append_entries_leader(
            [LogEntry(type=EntryType.DATA, data=b"x") for _ in range(10)], term=1)
        conf = ConfigurationEntry(LogId(5, 1), Configuration.parse("1.1.1.1:1"))
        await lm.set_snapshot(LogId(5, 1), conf)
        assert lm.first_log_index() == 6
        assert lm.last_log_index() == 10
        assert lm.get_term(5) == 1  # via snapshot id
        assert lm.check_consistency().is_ok()
        await lm.shutdown()

    async def test_set_snapshot_divergent_resets(self):
        lm = await self.mk()
        await lm.append_entries_follower(0, 0, mk_entries(1, 5, term=1))
        # snapshot at index 8 term 3 — beyond our log: full reset
        conf = ConfigurationEntry(LogId(8, 3), Configuration.parse("1.1.1.1:1"))
        await lm.set_snapshot(LogId(8, 3), conf)
        assert lm.first_log_index() == 9
        assert lm.last_log_index() == 8
        assert lm.get_term(8) == 3
        await lm.shutdown()

    async def test_concurrent_appends_batched(self):
        lm = await self.mk()
        async def one(i):
            await lm.append_entries_leader(
                [LogEntry(type=EntryType.DATA, data=f"{i}".encode())], term=1)
        await asyncio.gather(*[one(i) for i in range(50)])
        assert lm.last_log_index() == 50
        await lm.shutdown()

    async def test_in_memory_window_retention_and_caps(self):
        """The recent-entry window (reference: logsInMemory) keeps
        stable+applied entries in RAM up to count AND bytes caps, so
        steady-state replication reads avoid storage."""
        lm = LogManager(MemoryLogStorage(), max_logs_in_memory=8,
                        max_logs_in_memory_bytes=64)
        await lm.init()
        entries = [LogEntry(type=EntryType.DATA, data=b"x" * 10)
                   for _ in range(20)]
        await lm.append_entries_leader(entries, term=1)
        lm.set_applied_index(20)
        # count cap 8, but bytes cap 64 allows only 6 entries of 10B
        kept = sorted(lm._mem)
        assert len(kept) <= 8
        assert sum(len(lm._mem[i].data) for i in kept) <= 64 + 10
        assert kept[-1] == 20  # most recent retained
        # entries are still readable (from storage) below the window
        assert lm.get_entry(1).data == b"x" * 10
        await lm.shutdown()

    async def test_conflict_hint_walks_term_run_in_memory(self):
        lm = LogManager(MemoryLogStorage(), max_logs_in_memory=64)
        await lm.init()
        await lm.append_entries_leader(
            [LogEntry(type=EntryType.DATA, data=b"a") for _ in range(5)],
            term=2)
        await lm.append_entries_leader(
            [LogEntry(type=EntryType.DATA, data=b"b") for _ in range(5)],
            term=4)
        # term-4 run starts at index 6
        assert lm.conflict_hint(10) == 6
        assert lm.conflict_hint(10, 4) == 6
        assert lm.conflict_hint(5) == 1  # term-2 run starts at 1
        assert lm.conflict_hint(0) == 0  # no term -> no hint
        await lm.shutdown()


def test_file_log_concurrent_reads_and_appends(tmp_path):
    """Regression: the event loop reads get_entry while the LogManager
    flusher appends in executor threads on the SAME segment file
    objects. Unlocked interleaved seeks corrupted reads — and a
    misaligned frame could silently return the WRONG entry to a
    replicator (observed as duplicated payloads in replicated logs
    under crash/fault soaks)."""
    import threading

    s = FileLogStorage(str(tmp_path / "clog"), segment_max_bytes=16 * 1024)
    s.init()
    N = 3000
    errors = []

    def writer():
        try:
            for i in range(1, N + 1):
                e = LogEntry(type=EntryType.DATA, data=b"payload-%06d" % i)
                e.id = LogId(i, 1)
                s.append_entries([e], sync=False)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    import time as _time

    t = threading.Thread(target=writer)
    t.start()
    reads = 0
    deadline = _time.monotonic() + 60
    while (t.is_alive() or reads == 0) and not errors \
            and _time.monotonic() < deadline:
        last = s.last_log_index()
        for idx in range(max(1, last - 20), last + 1):
            e = s.get_entry(idx)
            if e is not None:
                assert e.data == b"payload-%06d" % idx, (idx, e.data)
                reads += 1
    t.join()
    assert not errors, errors
    assert reads > 100
    # every entry still reads back correctly
    for i in (1, N // 2, N):
        assert s.get_entry(i).data == b"payload-%06d" % i
    s.shutdown()
