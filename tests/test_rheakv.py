"""RheaKV tests: raw store units + multi-store raft-backed integration.

Reference tiers mirrored (SURVEY.md §5): MemoryKVStoreTest-style unit
tests; StoreEngine/DefaultRheaKVStore-style in-process cluster tests with
leader kill and region split.
"""

import asyncio
import contextlib
import struct

import pytest

from tests.kv_cluster import KVTestCluster
from tpuraft.rheakv.kv_operation import KVOp, KVOperation
from tpuraft.rheakv.kv_service import (
    ERR_INVALID_EPOCH,
    KVCommandRequest,
    decode_result,
    encode_result,
    scan_op,
)
from tpuraft.rheakv.metadata import Region, RegionEpoch
from tpuraft.rheakv.raw_store import MemoryRawKVStore


# ---- unit: MemoryRawKVStore ------------------------------------------------


def test_memory_store_basic_ops():
    s = MemoryRawKVStore()
    assert s.get(b"a") is None
    s.put(b"a", b"1")
    s.put(b"c", b"3")
    s.put(b"b", b"2")
    assert s.get(b"b") == b"2"
    assert s.contains_key(b"c") and not s.contains_key(b"x")
    assert s.scan(b"", b"") == [(b"a", b"1"), (b"b", b"2"), (b"c", b"3")]
    assert s.scan(b"b", b"") == [(b"b", b"2"), (b"c", b"3")]
    assert s.scan(b"", b"b") == [(b"a", b"1")]
    assert s.scan(b"", b"", limit=2) == [(b"a", b"1"), (b"b", b"2")]
    assert s.reverse_scan(b"", b"")[0] == (b"c", b"3")
    s.delete(b"b")
    assert s.get(b"b") is None
    s.delete_range(b"a", b"c")
    assert s.scan(b"", b"") == [(b"c", b"3")]


def test_memory_store_cas_merge_putlist():
    s = MemoryRawKVStore()
    assert s.put_if_absent(b"k", b"v") is None
    assert s.put_if_absent(b"k", b"w") == b"v"
    assert not s.compare_and_put(b"k", b"wrong", b"x")
    assert s.compare_and_put(b"k", b"v", b"x")
    assert s.get(b"k") == b"x"
    assert s.get_and_put(b"k", b"y") == b"x"
    s.merge(b"m", b"a")
    s.merge(b"m", b"b")
    assert s.get(b"m") == b"a,b"
    s.put_list([(b"p1", b"1"), (b"p2", b"2")])
    assert s.get(b"p1") == b"1" and s.get(b"p2") == b"2"


def test_memory_store_sequence_and_locks():
    s = MemoryRawKVStore()
    seq = s.get_sequence(b"s", 10)
    assert (seq.start, seq.end) == (0, 10)
    seq = s.get_sequence(b"s", 5)
    assert (seq.start, seq.end) == (10, 15)
    assert s.get_sequence(b"s", 0).start == 15  # pure read
    s.reset_sequence(b"s")
    assert s.get_sequence(b"s", 1).start == 0

    ok, token, owner = s.try_lock_with(b"L", b"me", 60_000, False)
    assert ok and owner == b"me"
    ok2, token2, owner2 = s.try_lock_with(b"L", b"you", 60_000, False)
    assert not ok2 and owner2 == b"me" and token2 == token
    # reentrant acquire (keep_lease=False) bumps the hold count
    ok3, token3, _ = s.try_lock_with(b"L", b"me", 60_000, False)
    assert ok3 and token3 == token
    # watchdog renewal (keep_lease=True) does NOT add a hold
    okr, tokenr, _ = s.try_lock_with(b"L", b"me", 60_000, True)
    assert okr and tokenr == token
    assert not s.release_lock(b"L", b"you")
    assert s.release_lock(b"L", b"me")      # acquires 2 -> 1
    assert s.release_lock(b"L", b"me")      # released
    ok4, token4, _ = s.try_lock_with(b"L", b"you", 1000, False)
    assert ok4 and token4 > token  # fencing token monotonic


def test_memory_store_snapshot_roundtrip():
    s = MemoryRawKVStore()
    for i in range(20):
        s.put(b"k%02d" % i, b"v%d" % i)
    s.get_sequence(b"k05seq", 7)
    s.try_lock_with(b"k07", b"me", 60_000, False)
    blob = s.serialize_range(b"k00", b"k10")
    t = MemoryRawKVStore()
    t.load_serialized(blob)
    assert t.get(b"k05") == b"v5" and t.get(b"k15") is None
    assert t.get_sequence(b"k05seq", 0).start == 7
    ok, _, owner = t.try_lock_with(b"k07", b"you", 1000, False)
    assert not ok and owner == b"me"


def test_kv_operation_codec():
    for op in [
        KVOperation(KVOp.PUT, b"k", b"v"),
        KVOperation.cas(b"k", b"e", b"u"),
        KVOperation.get_sequence(b"s", 42),
        KVOperation.key_lock(b"L", b"id", 5000, True),
        KVOperation.range_split(9, b"m"),
        KVOperation.put_list([(b"a", b"1"), (b"b", b"2")]),
    ]:
        got = KVOperation.decode(op.encode())
        assert got == op
    kvs = KVOperation.unpack_kv_list(
        KVOperation.put_list([(b"a", b"1"), (b"b", b"2")]).value)
    assert kvs == [(b"a", b"1"), (b"b", b"2")]


def test_result_codec():
    for r in [None, True, False, b"bytes", (3, 9),
              (True, 7, b"owner"),
              [(b"k1", b"v1"), (b"k2", None)]]:
        assert decode_result(encode_result(r)) == r


# ---- integration: multi-store cluster --------------------------------------


@contextlib.asynccontextmanager
async def kv_cluster(tmp_path=None, **kw):
    c = KVTestCluster(3, tmp_path=tmp_path, **kw)
    await c.start_all()
    try:
        yield c
    finally:
        await c.stop_all()


async def test_region_replicated_put_get_scan():
    async with kv_cluster() as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        assert await rs.put(b"hello", b"world")
        assert await rs.get(b"hello") == b"world"
        await rs.put_list([(b"a", b"1"), (b"b", b"2"), (b"c", b"3")])
        assert [k for k, _ in await rs.scan(b"a", b"c")] == [b"a", b"b"]
        assert await rs.compare_and_put(b"a", b"1", b"1'")
        assert not await rs.compare_and_put(b"a", b"1", b"nope")
        got = await rs.multi_get([b"a", b"zz"])
        assert got[b"a"] == b"1'" and got[b"zz"] is None
        # replicas converge: every store's raw store sees the data
        await asyncio.sleep(0.2)
        for s in c.stores.values():
            assert s.raw_store.get(b"hello") == b"world"


async def test_sequence_and_lock_through_raft():
    async with kv_cluster() as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        s1 = await rs.get_sequence(b"ids", 100)
        s2 = await rs.get_sequence(b"ids", 100)
        assert (s1.start, s1.end, s2.start, s2.end) == (0, 100, 100, 200)
        ok, token, owner = await rs.try_lock_with(b"lock", b"client-A", 30_000)
        assert ok
        ok2, _, owner2 = await rs.try_lock_with(b"lock", b"client-B", 30_000)
        assert not ok2 and owner2 == b"client-A"
        assert await rs.release_lock(b"lock", b"client-A")
        ok3, token3, _ = await rs.try_lock_with(b"lock", b"client-B", 30_000)
        assert ok3 and token3 > token


async def test_kv_survives_leader_kill(tmp_path):
    async with kv_cluster(tmp_path) as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        for i in range(5):
            await rs.put(b"k%d" % i, b"v%d" % i)
        dead_ep = leader.store_engine.server_id.endpoint
        await c.stop_store(dead_ep)
        new_leader = await c.wait_region_leader(1)
        assert new_leader.store_engine.server_id.endpoint != dead_ep
        rs2 = new_leader.raft_store
        assert await rs2.get(b"k3") == b"v3"  # durable across failover
        assert await rs2.put(b"after", b"crash")
        assert await rs2.get(b"after") == b"crash"


async def test_kv_command_processor_epoch_check():
    async with kv_cluster() as c:
        leader = await c.wait_region_leader(1)
        t = c.client_transport()
        ep = leader.store_engine.server_id.endpoint
        put = KVOperation(KVOp.PUT, b"wire", b"ok").encode()
        # stale epoch rejected with current region meta attached
        resp = await t.call(ep, "kv_command", KVCommandRequest(
            region_id=1, conf_ver=99, version=99, op_blob=put), 2000)
        assert resp.code == ERR_INVALID_EPOCH
        cur = Region.decode(resp.region_meta)
        assert cur.id == 1
        # correct epoch accepted
        resp = await t.call(ep, "kv_command", KVCommandRequest(
            region_id=1, conf_ver=cur.epoch.conf_ver,
            version=cur.epoch.version, op_blob=put), 2000)
        assert resp.code == 0 and decode_result(resp.result) is True
        get = KVOperation(KVOp.GET, b"wire").encode()
        resp = await t.call(ep, "kv_command", KVCommandRequest(
            region_id=1, conf_ver=cur.epoch.conf_ver,
            version=cur.epoch.version, op_blob=get), 2000)
        assert decode_result(resp.result) == b"ok"
        # scan over the wire
        resp = await t.call(ep, "kv_command", KVCommandRequest(
            region_id=1, conf_ver=cur.epoch.conf_ver,
            version=cur.epoch.version,
            op_blob=scan_op(b"", b"").encode()), 2000)
        assert (b"wire", b"ok") in decode_result(resp.result)


async def test_kv_command_rejects_out_of_range_keys():
    """Epoch can match while a batched key escapes the range (split raced
    the client's grouping) — the server must bounce, never mis-commit."""
    from tpuraft.rheakv.kv_service import ERR_KEY_OUT_OF_RANGE

    regions = [Region(id=1, start_key=b"", end_key=b"m"),
               Region(id=2, start_key=b"m", end_key=b"")]
    c = KVTestCluster(3, regions=regions)
    await c.start_all()
    try:
        leader = await c.wait_region_leader(1)
        t = c.client_transport()
        ep = leader.store_engine.server_id.endpoint
        r1 = leader.region
        bad = KVOperation.put_list([(b"a", b"1"), (b"zzz", b"2")]).encode()
        resp = await t.call(ep, "kv_command", KVCommandRequest(
            region_id=1, conf_ver=r1.epoch.conf_ver,
            version=r1.epoch.version, op_blob=bad), 2000)
        assert resp.code == ERR_KEY_OUT_OF_RANGE
        # nothing leaked into the store
        assert leader.store_engine.raw_store.get(b"zzz") is None
    finally:
        await c.stop_all()


async def test_region_split():
    async with kv_cluster() as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        for i in range(32):
            await rs.put(b"key%02d" % i, b"v%d" % i)
        se = leader.store_engine
        st = await se.apply_split(1, 2)
        assert st.is_ok(), str(st)
        # new region appears on every store (applied via raft on each)
        await c.wait_region_on_all(2)
        for s in c.stores.values():
            r1 = s.get_region_engine(1).region
            r2 = s.get_region_engine(2).region
            assert r1.end_key == r2.start_key != b""
            assert r1.epoch.version == 2 and r2.epoch.version == 2
        # both regions elect leaders and serve their halves
        l1 = await c.wait_region_leader(1)
        l2 = await c.wait_region_leader(2)
        split_key = l1.region.end_key
        assert await l1.raft_store.get(b"key00") == b"v0"
        assert await l2.raft_store.get(b"key31") == b"v31"
        # writes routed to the proper region engines still work
        assert await l1.raft_store.put(split_key[:-1] + b"!", b"left")
        assert await l2.raft_store.put(split_key + b"z", b"right")


async def test_kv_over_device_commit_plane():
    """Regions' quorum bookkeeping on the MultiRaftEngine's [G,P] tick
    (numpy backend for test speed; same code path as the jax backend)."""
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions

    def factory():
        return MultiRaftEngine(TickOptions(
            max_groups=8, max_peers=4, tick_interval_ms=2, backend="numpy"))

    async with kv_cluster(multi_raft_engine_factory=factory) as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        for i in range(10):
            assert await rs.put(b"e%d" % i, b"v%d" % i)
        assert await rs.get(b"e7") == b"v7"
        await asyncio.sleep(0.2)
        for s in c.stores.values():
            assert s.raw_store.get(b"e9") == b"v9"


async def test_split_too_small_rejected():
    async with kv_cluster() as c:
        leader = await c.wait_region_leader(1)
        await leader.raft_store.put(b"only", b"one")
        st = await leader.store_engine.apply_split(1, 2)
        assert not st.is_ok()


def test_metrics_raw_kv_store_forwards_everything():
    """The latency decorator (reference: MetricsRawKVStore) must forward
    every op — including reset_range, which snapshot install calls, and
    the batch ops whose base-class defaults would shadow an inner store's
    specialized implementations — while recording timings."""
    from tpuraft.rheakv.raw_store import MetricsRawKVStore
    from tpuraft.util.metrics import MetricRegistry

    reg = MetricRegistry()
    inner = MemoryRawKVStore()
    s = MetricsRawKVStore(inner, reg)

    s.put(b"a", b"1")
    s.put_list([(b"b", b"2"), (b"c", b"3")])
    assert s.get(b"a") == b"1"
    assert s.multi_get([b"a", b"zz"]) == {b"a": b"1", b"zz": None}
    assert s.contains_key(b"b")
    assert s.compare_and_put(b"a", b"1", b"9")
    s.merge(b"m", b"x")
    assert [k for k, _ in s.scan(b"", b"")] == [b"a", b"b", b"c", b"m"]
    blob = s.serialize_range(b"", b"")

    # snapshot install path: reset_range + load_serialized through the
    # decorator must hit the inner store, not the abstract base
    s.delete_range(b"a", b"c")
    s.reset_range(b"", b"")
    assert s.scan(b"", b"") == []
    s.load_serialized(blob)
    assert s.get(b"a") == b"9"
    assert inner.get(b"m") == b"x"  # merged once before serialize

    snap = reg.snapshot()
    for op in ("kv_put", "kv_get", "kv_multi_get", "kv_reset_range",
               "kv_serialize_range", "kv_load_serialized"):
        assert op in snap["histograms"], op


def test_store_engine_kv_metrics_option():
    from tpuraft.rheakv.raw_store import MetricsRawKVStore
    from tpuraft.rheakv.store_engine import StoreEngineOptions

    opts = StoreEngineOptions(server_id="127.0.0.1:9001",
                              enable_kv_metrics=True)
    # constructing the engine wraps the raw store in the decorator
    from tpuraft.core.node_manager import NodeManager  # noqa: F401
    from tpuraft.rheakv.store_engine import StoreEngine
    from tpuraft.rpc.transport import InProcNetwork, RpcServer

    net = InProcNetwork()
    server = RpcServer("127.0.0.1:9001")
    net.bind(server)
    se = StoreEngine(opts, server, net)
    assert isinstance(se.raw_store, MetricsRawKVStore)
    se.raw_store.put(b"k", b"v")
    assert "kv_put" in se.metrics.snapshot()["histograms"]


async def test_64_region_store_with_engine_plane():
    """The BASELINE.md 'RheaKV 64-region' configuration at test scale:
    64 regions x 3 stores, every store batching all its regions' quorum
    math through one MultiRaftEngine plane, batched client ops spread
    across every region."""
    from tests.test_kv_client import kv_client_cluster
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions
    from tpuraft.rheakv.client import BatchingOptions

    # 64 key-range regions over 1-byte prefixes
    bounds = [bytes([i * 4]) for i in range(64)] + [b""]
    regions = [Region(id=i + 1, start_key=bounds[i] if i else b"",
                      end_key=bounds[i + 1]) for i in range(64)]

    def factory():
        return MultiRaftEngine(TickOptions(
            max_groups=72, max_peers=4, tick_interval_ms=2,
            backend="numpy"))

    async with kv_client_cluster(
            regions=regions, election_timeout_ms=1000,
            multi_raft_engine_factory=factory,
            batching=BatchingOptions(enabled=True)) as (c, kv):
        for rid in range(1, 65):
            await c.wait_region_leader(rid, timeout_s=30)
        # one key per region, written concurrently through batching
        keys = [bytes([i * 4]) + b"-k" for i in range(64)]
        oks = await asyncio.gather(*[kv.put(k, b"v-" + k) for k in keys])
        assert all(oks)
        got = await kv.multi_get(keys)
        assert all(got[k] == b"v-" + k for k in keys)
        # a full scan crosses all 64 regions in order
        rows = await kv.scan(b"", b"")
        assert [k for k, _ in rows] == sorted(keys)
        # commits flowed through the engine planes (eager ack-path
        # advances + tick-discovered ones are both engine-plane paths)
        advances = sum(s.multi_raft_engine.commit_advances
                       + s.multi_raft_engine.eager_commits
                       for s in c.stores.values())
        assert advances >= 64, advances


async def test_split_on_full_engine_grows_plane():
    """A region split on a store whose engine plane is at capacity must
    grow the [G, P] plane, not crash the new RegionEngine (splits mint
    raft groups at runtime)."""
    from tpuraft.core.engine import MultiRaftEngine
    from tpuraft.options import TickOptions

    engines = []

    def factory():
        e = MultiRaftEngine(TickOptions(
            max_groups=1, max_peers=4, tick_interval_ms=2,
            backend="numpy"))
        engines.append(e)
        return e

    async with kv_cluster(multi_raft_engine_factory=factory) as c:
        leader = await c.wait_region_leader(1)
        rs = leader.raft_store
        for i in range(32):
            assert await rs.put(b"gk%02d" % i, b"v%d" % i)
        assert all(e.G == 1 for e in engines)
        st = await leader.store_engine.apply_split(1, 2)
        assert st.is_ok(), str(st)
        await c.wait_region_on_all(2)
        l2 = await c.wait_region_leader(2)
        # every store's engine doubled to fit the new group
        assert all(e.G == 2 for e in engines), [e.G for e in engines]
        # both halves serve through the (grown) batched plane
        assert await leader.raft_store.get(b"gk00") == b"v0"
        assert await l2.raft_store.put(b"zz-new", b"after-grow")
        assert await l2.raft_store.get(b"zz-new") == b"after-grow"


def test_legacy_region_meta_migrates_to_shared_journal(tmp_path):
    """Upgrade path for multilog-scheme stores (r5): per-region file://
    {term, votedFor} seeds the shared multimeta:// journal ONCE, so a
    restarted store can never fall back to term 0 and double-vote; a
    replayed migration with an older legacy term is a no-op."""
    from tpuraft.entity import PeerId
    from tpuraft.rheakv.store_engine import StoreEngine
    from tpuraft.storage.meta_multilog import MultiRaftMetaStorage
    from tpuraft.storage.meta_storage import RaftMetaStorage

    store_base = f"{tmp_path}/s1"
    base = f"{store_base}/r7"
    old = RaftMetaStorage(f"{base}/meta")
    old.init()
    old.set_term_and_voted_for(9, PeerId.parse("1.2.3.4:80"))
    StoreEngine._migrate_legacy_meta(store_base, base, 7)
    m = MultiRaftMetaStorage(f"{store_base}/meta", "r7")
    m.init()
    assert m.term == 9
    assert m.voted_for == PeerId.parse("1.2.3.4:80")
    m.shutdown()
    assert not (tmp_path / "s1/r7/meta/raft_meta").exists()  # renamed
    # a resurrected legacy file with an OLDER term must not regress
    old2 = RaftMetaStorage(f"{base}/meta")
    old2.init()
    old2.set_term_and_voted_for(3, PeerId.parse("1.2.3.4:80"))
    StoreEngine._migrate_legacy_meta(store_base, base, 7)
    m2 = MultiRaftMetaStorage(f"{store_base}/meta", "r7")
    m2.init()
    assert m2.term == 9
    m2.shutdown()
