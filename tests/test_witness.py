"""Witness replicas: quorum math by enumeration, metadata-only
journal, election safety, snapshot-install skip, and the
witness-majority-must-not-commit case.

A witness votes and acks appends but stores no log payload — the geo
topology's cheap vote (2 data + 1 witness commits at quorum 2 without
a third full data copy).  Safety rests on three independent layers,
each tested here: config validation (witnesses a strict minority, so
every majority contains a data replica — enumerated), witnesses never
campaign (a witness-only partition side can never elect, hence never
commit), and the ballot box clamping the commit point to the best DATA
replica's match (defense in depth).
"""

import asyncio
import time

import pytest

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.core.ballot_box import commit_point
from tpuraft.entity import EntryType, LogEntry, LogId, PeerId
from tpuraft.util.quorum import (
    every_majority_has_data_peer,
    joint_quorums_intersect,
    majorities,
    witness_minority,
    witness_only_majorities,
)


def _p(i: int) -> PeerId:
    return PeerId("127.0.0.1", 5000 + i)


# ---------------------------------------------------------------------------
# quorum math by enumeration
# ---------------------------------------------------------------------------


def test_witness_minority_rule_by_enumeration():
    """For every voter-set size up to 7 and every witness count: the
    config rule (witnesses < quorum, >=1 data voter) holds exactly when
    every enumerated majority contains a data replica."""
    for n in range(1, 8):
        voters = [_p(i) for i in range(n)]
        for w in range(0, n + 1):
            witnesses = voters[:w]
            rule = witness_minority(voters, witnesses)
            enumerated = every_majority_has_data_peer(voters, witnesses)
            if w == 0:
                assert rule and enumerated
                continue
            if rule:
                assert enumerated, (n, w)
                assert witness_only_majorities(voters, witnesses) == []
            # the interesting direction: every rejected config has a
            # witness-only majority OR no data voter at all
            if not rule and w < n:
                assert not enumerated or w >= n // 2 + 1, (n, w)


def test_witness_geo_shapes_are_valid():
    """The ISSUE's two target shapes pass validation: 2+1 (3-zone) and
    4+1 (5-zone '2.5-replica')."""
    for n_data, n_wit in [(2, 1), (4, 1), (3, 2), (4, 3)]:
        voters = [_p(i) for i in range(n_data + n_wit)]
        witnesses = voters[n_data:]
        conf = Configuration(list(voters), witnesses=list(witnesses))
        expect = witness_minority(voters, witnesses)
        assert conf.is_valid() == expect, (n_data, n_wit)
        if expect:
            assert every_majority_has_data_peer(voters, witnesses)
    # all-witness and witness-majority confs are rejected
    assert not Configuration([_p(0)], witnesses=[_p(0)]).is_valid()
    assert not Configuration([_p(0), _p(1), _p(2)],
                             witnesses=[_p(1), _p(2)]).is_valid()


def test_witness_joint_consensus_intersection():
    """Joint consensus with witnesses on either side keeps quorum
    intersection (witnesses are ordinary voters in the math), verified
    by enumeration of every dual quorum."""
    old = [_p(0), _p(1), _p(2)]            # 2 data + 1 witness
    new = [_p(0), _p(1), _p(3), _p(4), _p(5)]  # 4 data + 1 witness
    assert joint_quorums_intersect(old, new)
    # and every dual quorum still contains a data peer when the
    # witness sets respect the minority rule on both sides
    wits = {_p(2), _p(5)}
    for qo in majorities(old):
        for qn in majorities(new):
            assert (qo | qn) - wits, "dual quorum with no data replica"


def test_ballot_clamps_commit_to_best_data_match():
    """Defense in depth: witness acks alone must never advance the
    commit point past what a data replica stored — even if a buggy
    path fed the ballot witness rows without the leader's own."""
    a, b, w1, w2 = _p(0), _p(1), _p(2), _p(3)
    conf = Configuration([a, b, w1, w2, _p(4)], witnesses=[w1, w2])
    # witness acks race ahead of every data replica
    match = {w1: 9, w2: 9, a: 2, b: 1}
    pt = commit_point(match, conf, Configuration())
    assert pt == 2, f"commit point {pt} ran past the best data match"
    # once a data replica catches up, the majority stat rules again
    match[a] = 9
    assert commit_point(match, conf, Configuration()) == 9
    # joint mode: the clamp covers both sides' data peers
    old = Configuration([a, b, w1], witnesses=[w1])
    assert commit_point({w1: 5, a: 3, b: 5}, conf, old) <= 5


# ---------------------------------------------------------------------------
# live clusters
# ---------------------------------------------------------------------------


async def _wait(cond, timeout_s=8.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.03)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.asyncio
async def test_two_plus_one_commits_at_majority_cost():
    """2 data + 1 witness: with the data FOLLOWER partitioned away, the
    leader + witness quorum keeps committing — the witness's ack buys
    availability without a third data copy.  The witness's journal
    holds payload-free entries throughout."""
    c = TestCluster(3, witness_idx=(2,), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        assert not leader.options.witness, "witness must never lead"
        witness_peer = c.peers[2]
        follower = next(p for p in c.peers[:2]
                        if p != leader.server_id)
        st = await c.apply_ok(leader, b"before")
        assert st.is_ok()
        # partition the data follower: quorum = {leader, witness}
        c.net.partition({follower.endpoint},
                        {leader.server_id.endpoint, witness_peer.endpoint})
        st = await asyncio.wait_for(c.apply_ok(leader, b"during"), 5.0)
        assert st.is_ok(), "leader+witness majority must commit"
        # the witness journaled METADATA only
        wnode = c.nodes[witness_peer]
        await _wait(lambda: wnode.ballot_box.last_committed_index
                    >= leader.ballot_box.last_committed_index - 1,
                    msg="witness commit catch-up")
        for i in range(1, wnode.log_manager.last_log_index() + 1):
            e = wnode.log_manager.get_entry(i)
            if e is not None and e.type == EntryType.DATA:
                assert e.data == b"", \
                    f"witness stored a payload at index {i}"
        c.net.heal()
        st = await c.apply_ok(leader, b"after")
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_votes_but_never_campaigns():
    """Kill the leader of a 2+1 group: the surviving DATA node must win
    (the witness grants its vote) and the witness itself must never
    become leader or candidate."""
    from tpuraft.core.node import State

    c = TestCluster(3, witness_idx=(2,), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"v")
        wnode = c.nodes[c.peers[2]]
        await c.stop(leader.server_id)
        new_leader = await c.wait_leader(timeout_s=8.0)
        assert not new_leader.options.witness
        assert new_leader.server_id != c.peers[2]
        assert wnode.state not in (State.LEADER, State.CANDIDATE,
                                   State.TRANSFERRING)
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_metadata_vote_protects_committed_entries():
    """Commit at {leader, witness} while the data follower lags, then
    kill the leader: the witness's metadata log is newer than the
    lagging follower's, so its vote REFUSES the follower — the group
    stalls (unavailable) instead of electing a leader that would lose
    the acked entry.  Restarting the old leader recovers both
    availability and the entry: witness safety through quorum
    intersection with a metadata-only journal."""
    c = TestCluster(3, witness_idx=(2,), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        lagger = next(p for p in c.peers[:2] if p != leader.server_id)
        witness_peer = c.peers[2]
        st = await c.apply_ok(leader, b"shared")
        assert st.is_ok()
        # lagger partitioned: the next commit lands on {leader, witness}
        c.net.partition({lagger.endpoint},
                        {leader.server_id.endpoint, witness_peer.endpoint})
        st = await asyncio.wait_for(c.apply_ok(leader, b"acked"), 5.0)
        assert st.is_ok()
        committed = leader.ballot_box.last_committed_index
        # leader dies; partition heals: survivors = lagging data + witness
        await c.stop(leader.server_id)
        c.net.heal()
        lag_node = c.nodes[lagger]
        wnode = c.nodes[witness_peer]
        # the lagger keeps campaigning but the witness must refuse — no
        # leader may emerge for several election timeouts
        await asyncio.sleep(2.0)
        assert not lag_node.is_leader(), (
            "a lagging data node was elected over the witness's newer "
            "metadata log — acked entry lost")
        assert not wnode.is_leader()
        # old leader returns: group recovers WITH the entry
        await c.start(leader.server_id)
        recovered = await c.wait_leader(timeout_s=10.0)
        await _wait(lambda: recovered.ballot_box.last_committed_index
                    >= committed, msg="committed entry recovery")
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_majority_partition_never_commits():
    """The ISSUE's safety case: a partition isolating the data replicas
    leaves a witness-majority side — it must NOT commit (witnesses
    never campaign, so that side can never even elect).  The config
    (1 data + 2 witnesses) is deliberately INVALID by the minority rule
    — the runtime layers must hold even when the config gate was
    bypassed."""
    c = TestCluster(3, witness_idx=(1, 2), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()   # the only data node
        assert leader.server_id == c.peers[0]
        st = await c.apply_ok(leader, b"v")
        assert st.is_ok()
        committed = leader.ballot_box.last_committed_index
        # isolate the data replica: the witness side holds 2/3 votes
        c.net.isolate(leader.server_id.endpoint)
        await asyncio.sleep(2.0)   # many election timeouts
        w1, w2 = c.nodes[c.peers[1]], c.nodes[c.peers[2]]
        assert not w1.is_leader() and not w2.is_leader(), \
            "witness-majority side elected a leader"
        assert w1.ballot_box.last_committed_index <= committed
        assert w2.ballot_box.last_committed_index <= committed
        # the cut-off data leader steps down on dead quorum: no side
        # commits (unavailable, never unsafe)
        await _wait(lambda: not leader.is_leader(), timeout_s=5.0,
                    msg="isolated leader step-down")
        c.net.heal()
        recovered = await c.wait_leader(timeout_s=10.0)
        assert recovered.server_id == c.peers[0]
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_crash_restart_metadata_journal(tmp_path):
    """Durable witness restart: the witness comes back from its
    metadata-only journal (no payload bytes on disk), rejoins, and
    resumes acking — the leader's replicator re-matches it at the
    tail."""
    c = TestCluster(3, witness_idx=(2,), tmp_path=tmp_path,
                    election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        for i in range(5):
            st = await c.apply_ok(leader, b"w%d" % i)
            assert st.is_ok()
        wp = c.peers[2]
        await c.stop(wp)
        for i in range(5, 8):
            st = await c.apply_ok(leader, b"w%d" % i)
            assert st.is_ok()
        await c.start(wp)
        wnode = c.nodes[wp]
        leader = await c.wait_leader()
        tail = leader.log_manager.last_log_index()
        await _wait(lambda: wnode.log_manager.last_log_index() >= tail,
                    msg="witness re-catch-up")
        for i in range(1, wnode.log_manager.last_log_index() + 1):
            e = wnode.log_manager.get_entry(i)
            if e is not None and e.type == EntryType.DATA:
                assert e.data == b"", f"payload survived restart at {i}"
        # and the restarted witness keeps the quorum liveness: kill the
        # data follower, the leader + restarted witness still commit
        follower = next(p for p in c.peers[:2] if p != leader.server_id)
        await c.stop(follower)
        st = await asyncio.wait_for(c.apply_ok(leader, b"post"), 5.0)
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_snapshot_install_skip(tmp_path):
    """A witness that fell behind the leader's compacted log catches up
    via a META-ONLY install: no state files cross the wire (the
    install-snapshot-witness-skips counter ticks, get_file is never
    called), and replication resumes from the snapshot point."""
    c = TestCluster(3, witness_idx=(2,), tmp_path=tmp_path,
                    snapshot=True, election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        for i in range(6):
            st = await c.apply_ok(leader, b"a%d" % i)
            assert st.is_ok()
        wp = c.peers[2]
        await c.stop(wp)
        for i in range(6, 12):
            st = await c.apply_ok(leader, b"b%d" % i)
            assert st.is_ok()
        st = await leader.snapshot()
        assert st.is_ok(), str(st)
        assert leader.log_manager.first_log_index() > 1, "no compaction"
        await c.drain_sends_to(leader, wp.endpoint)
        # count get_file RPCs at the leader's endpoint from now on
        get_files = []
        leader_server = c.managers[leader.server_id].server
        orig = leader_server._handlers.get("get_file")

        async def counting_get_file(req):
            get_files.append(req)
            return await orig(req)

        leader_server.register("get_file", counting_get_file)
        await c.start(wp)
        wnode = c.nodes[wp]
        await _wait(lambda: wnode.log_manager.last_snapshot_id().index
                    >= leader.log_manager.last_snapshot_id().index,
                    timeout_s=10.0, msg="witness meta-only install")
        assert wnode.metrics.counters.get(
            "install-snapshot-witness-skips", 0) >= 1
        assert not get_files, \
            "witness install downloaded state files over the wire"
        # replication resumes past the snapshot point
        st = await c.apply_ok(leader, b"tail")
        assert st.is_ok()
        tail = leader.log_manager.last_log_index()
        await _wait(lambda: wnode.log_manager.last_log_index() >= tail,
                    msg="witness post-install replication")
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_add_and_remove_witness_through_membership_change():
    """Joint-consensus add of a witness: catch-up ships payload-
    stripped entries, the committed conf carries the witness flag on
    every node, and removal prunes it cleanly."""
    c = TestCluster(4, election_timeout_ms=200)
    # only the first three are initial voters; the fourth joins as a
    # witness via change_peers
    c.conf = Configuration(list(c.peers[:3]))
    await c.start_all()
    try:
        # the 4th node must run in witness mode from boot
        d = c.peers[3]
        assert not c.nodes[d].is_leader()
        c.nodes[d].options.witness = True
        leader = await c.wait_leader()
        for i in range(4):
            st = await c.apply_ok(leader, b"x%d" % i)
            assert st.is_ok()
        st = await asyncio.wait_for(leader.add_peer(d, witness=True), 10.0)
        assert st.is_ok(), str(st)
        for n in c.nodes.values():
            if n.conf_entry.conf.contains(d):
                assert n.conf_entry.conf.is_witness(d), \
                    f"{n}: witness flag lost through the conf change"
        # catch-up + steady-state replication stayed payload-free
        dnode = c.nodes[d]
        await _wait(lambda: dnode.log_manager.last_log_index()
                    >= leader.log_manager.last_log_index(),
                    msg="witness catch-up")
        for i in range(1, dnode.log_manager.last_log_index() + 1):
            e = dnode.log_manager.get_entry(i)
            if e is not None and e.type == EntryType.DATA:
                assert e.data == b"", f"witness got a payload at {i}"
        assert leader.metrics.counters.get("witness-stripped-bytes", 0) > 0
        # conf entries survive the wire with the flag (decode check)
        tail_conf = leader.log_manager.conf_manager.last()
        assert tail_conf.conf.is_witness(d)
        # remove again
        st = await asyncio.wait_for(leader.remove_peer(d), 10.0)
        assert st.is_ok(), str(st)
        assert not leader.conf_entry.conf.contains(d)
        assert d not in leader.conf_entry.conf.witnesses
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_refuses_reads_and_transfers():
    from tpuraft.core.read_only import ReadIndexError
    from tpuraft.errors import RaftError

    c = TestCluster(3, witness_idx=(2,), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        st = await c.apply_ok(leader, b"v")
        assert st.is_ok()
        wnode = c.nodes[c.peers[2]]
        with pytest.raises(ReadIndexError):
            await wnode.read_index()
        st = await leader.transfer_leadership_to(c.peers[2])
        assert st.raft_error == RaftError.EINVAL, \
            "transfer to a witness must be refused"
        # leader-side reads still confirm through the witness's acks
        idx = await leader.read_index()
        assert idx >= 1
    finally:
        await c.stop_all()


# ---------------------------------------------------------------------------
# wire format: trailing-defaulted extensions, both directions
# ---------------------------------------------------------------------------


def test_conf_entry_wire_roundtrip_and_backcompat():
    """LogEntry CONFIGURATION codec: witness lists ride as a TRAILING
    extension of the peers blob — witness-free entries are
    byte-identical to the pre-witness format, and a pre-witness blob
    decodes with witnesses=None."""
    peers = [_p(0), _p(1), _p(2)]
    e = LogEntry(type=EntryType.CONFIGURATION, id=LogId(5, 2),
                 peers=list(peers), witnesses=[peers[2]])
    got = LogEntry.decode(e.encode())
    assert got.peers == peers and got.witnesses == [peers[2]]
    assert got.old_witnesses is None

    plain = LogEntry(type=EntryType.CONFIGURATION, id=LogId(5, 2),
                     peers=list(peers))
    # the no-witness encoding carries exactly 4 lists (old format):
    # strip header, check the blob parses as the OLD 4-list algorithm
    # with nothing left over
    import struct

    from tpuraft.entity import _HDR

    blob = plain.encode()
    (_m, _t, _r, _term, _idx, plen, _n2, _dl, _crc) = _HDR.unpack_from(blob)
    peers_blob = blob[_HDR.size:_HDR.size + plen]
    off = 0
    for _ in range(4):   # the OLD decoder's fixed 4-list loop
        (n,) = struct.unpack_from("<h", peers_blob, off)
        off += 2
        for _ in range(max(0, n)):
            (slen,) = struct.unpack_from("<H", peers_blob, off)
            off += 2 + slen
    assert off == len(peers_blob), \
        "witness-free entry grew bytes an old decoder would miss"
    # and an old decoder reading a WITNESS entry stops after 4 lists
    # with only the trailing witness lists left — by construction the
    # new lists are appended after the old 4, so the old parse above
    # would land exactly at the witness tail (ignored)
    assert LogEntry.decode(plain.encode()).witnesses is None


def test_cli_and_pd_messages_decode_old_frames():
    """Old-format (pre-witness / pre-zone) frames must decode on a new
    receiver with the trailing fields at their defaults — and a NEW
    frame decoded by an OLD receiver (simulated by a field-trimmed
    clone) must yield the old fields intact."""
    from dataclasses import dataclass, field, fields

    from tpuraft.rheakv.pd_messages import (
        StoreHeartbeatBatchRequest,
        StoreHeartbeatRequest,
    )
    from tpuraft.rpc.cli_messages import (
        AddPeerRequest,
        ChangePeersRequest,
        GetPeersResponse,
    )
    from tpuraft.rpc import messages as M

    cases = [
        # (new message, names of the trailing new fields)
        (ChangePeersRequest(group_id="g", peer_id="p",
                            new_peers=["a:1", "b:1"],
                            new_witnesses=["b:1"]), ["new_witnesses"]),
        (GetPeersResponse(peers=["a:1", "b:1"], witnesses=["b:1"]),
         ["witnesses"]),
        (AddPeerRequest(group_id="g", peer_id="p", adding="c:1",
                        witness=True), ["witness"]),
        (StoreHeartbeatRequest(store_id=7, endpoint="a:1", zone="z1",
                               health="sick"), ["zone", "health"]),
        # the batch request's trailing extensions span three PR
        # generations (zone, health, then the fleet-observability
        # heat/occupancy fields) — the oldest sender predates them all
        (StoreHeartbeatBatchRequest(store_id=7, endpoint="a:1",
                                    zone="z2", health="degraded",
                                    heat=b"\x01\x02\x03", replicas=4,
                                    replicas_quiescent=2),
         ["zone", "health", "heat", "replicas", "replicas_quiescent"]),
    ]
    for msg, new_fields in cases:
        cls = type(msg)
        tid = M._TYPE_ID[cls]
        wire = M.encode_message(msg)
        # direction 1: OLD sender -> NEW receiver.  An old sender's
        # frame is the new frame minus the trailing fields' bytes;
        # build it by encoding a default-field copy of the message.
        old_style = cls(**{f.name: getattr(msg, f.name)
                           for f in fields(cls)
                           if f.name not in new_fields})
        old_wire_len = len(M.encode_message(old_style)) - sum(
            _encoded_len(getattr(old_style, nf)) for nf in new_fields)
        got = M.decode_message(wire[:old_wire_len])
        for f in fields(cls):
            if f.name in new_fields:
                assert getattr(got, f.name) == getattr(old_style, f.name)
            else:
                assert getattr(got, f.name) == getattr(msg, f.name)
        # direction 2: NEW sender -> OLD receiver.  Simulate the old
        # receiver by swapping in a clone class without the new fields;
        # its decode must stop cleanly, ignoring the trailing bytes.
        clone = dataclass(type("Old" + cls.__name__, (), {
            "__annotations__": {
                f.name: f.type for f in fields(cls)
                if f.name not in new_fields},
            **{f.name: (f.default if f.default is not M._MISSING
                        else (field(default_factory=f.default_factory)
                              if f.default_factory is not M._MISSING
                              else M._MISSING))
               for f in fields(cls) if f.name not in new_fields
               and (f.default is not M._MISSING
                    or f.default_factory is not M._MISSING)},
        }))
        try:
            M._MSG_TYPES[tid] = clone
            old_got = M.decode_message(wire)
            for f in fields(clone):
                assert getattr(old_got, f.name) == getattr(msg, f.name), \
                    f"{cls.__name__}.{f.name} corrupted on old receiver"
        finally:
            M._MSG_TYPES[tid] = cls


def _encoded_len(v) -> int:
    """Wire length of one trailing field's default-valued encoding."""
    import struct as _s

    if isinstance(v, bool):
        return 1
    if isinstance(v, int):
        return 8
    if isinstance(v, str):
        return 2 + len(v.encode())
    if isinstance(v, bytes):
        return 4 + len(v)
    if isinstance(v, list):
        return 4 + sum(2 + len(x.encode()) for x in v)
    raise TypeError(type(v))


def test_snapshot_meta_witness_lists_backcompat():
    from tpuraft.rpc.messages import SnapshotMeta

    meta = SnapshotMeta(last_included_index=9, last_included_term=2,
                        peers=["a:1", "b:1", "c:1"], witnesses=["c:1"])
    got = SnapshotMeta.decode(meta.encode())
    assert got == meta
    plain = SnapshotMeta(last_included_index=9, last_included_term=2,
                         peers=["a:1", "b:1"])
    blob = plain.encode()
    # zoneless/witness-free meta keeps the old 4-list byte format
    assert SnapshotMeta.decode(blob) == plain
    # pre-witness decoder compatibility: the blob ends exactly after
    # the 4 old lists (no trailing bytes an old reader would choke on)
    import struct

    off = 16
    for _ in range(4):
        (n,) = struct.unpack_from("<H", blob, off)
        off += 2
        for _ in range(n):
            (sl,) = struct.unpack_from("<H", blob, off)
            off += 2 + sl
    assert off == len(blob)


def test_store_meta_zone_blob_backcompat():
    from tpuraft.rheakv.pd_messages import decode_store_meta, \
        encode_store_meta

    new = encode_store_meta(5, "1.2.3.4:80", "zone-a")
    assert decode_store_meta(new) == (5, "1.2.3.4:80", "zone-a")
    old = encode_store_meta(5, "1.2.3.4:80")           # zoneless: old bytes
    assert decode_store_meta(old) == (5, "1.2.3.4:80", "")
    # old reader (fixed-offset parse) on a NEW blob still reads id+ep
    import struct

    (sid,) = struct.unpack_from("<q", new, 0)
    (n,) = struct.unpack_from("<H", new, 8)
    assert (sid, new[10:10 + n].decode()) == (5, "1.2.3.4:80")


@pytest.mark.asyncio
async def test_runtime_added_witness_adopts_witness_mode():
    """Review finding: a PLAIN-booted node added via add-witness used
    to keep its real FSM (applying payload-stripped entries = silent
    divergence) and could still campaign.  The committed conf is now
    the truth: on applying a conf entry that flags it, the node adopts
    witness mode — null FSM, campaign/read/transfer gates closed."""
    from tpuraft.core.state_machine import WitnessStateMachine

    c = TestCluster(4, election_timeout_ms=200)
    c.conf = Configuration(list(c.peers[:3]))
    await c.start_all()
    try:
        d = c.peers[3]
        dnode = c.nodes[d]
        assert not dnode.options.witness, "sanity: plain boot"
        leader = await c.wait_leader()
        for i in range(3):
            st = await c.apply_ok(leader, b"r%d" % i)
            assert st.is_ok()
        st = await asyncio.wait_for(leader.add_peer(d, witness=True), 10.0)
        assert st.is_ok(), str(st)
        await _wait(lambda: dnode.options.witness, timeout_s=5.0,
                    msg="witness adoption from the committed conf")
        assert isinstance(dnode.options.fsm, WitnessStateMachine)
        assert isinstance(dnode.fsm_caller._fsm, WitnessStateMachine)
        # and its journal holds no payloads from here on
        st = await c.apply_ok(leader, b"post-adopt")
        assert st.is_ok()
        tail = leader.log_manager.last_log_index()
        await _wait(lambda: dnode.log_manager.last_log_index() >= tail,
                    msg="post-adoption replication")
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_in_place_witness_role_conversion_rejected():
    """Promoting a witness to data voter in place would serve from a
    payload-less journal; demoting a data voter to witness leaves it a
    stale full journal — both are EINVAL (remove, wipe, re-add)."""
    from tpuraft.errors import RaftError

    c = TestCluster(3, witness_idx=(2,), election_timeout_ms=200)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        # witness -> data (drop the flag, keep the peer)
        promote = Configuration(list(c.peers))
        st = await leader.change_peers(promote)
        assert st.raft_error == RaftError.EINVAL, str(st)
        assert "conversion" in st.error_msg
        # data -> witness (flag an existing data follower)
        follower = next(p for p in c.peers[:2] if p != leader.server_id)
        demote = Configuration(list(c.peers),
                               witnesses=[c.peers[2], follower])
        st = await leader.change_peers(demote)
        assert st.raft_error == RaftError.EINVAL, str(st)
        # the legal path still works: remove then re-add in the new role
        st = await asyncio.wait_for(leader.remove_peer(c.peers[2]), 10.0)
        assert st.is_ok(), str(st)
        assert not leader.conf_entry.conf.witnesses
    finally:
        await c.stop_all()
