"""Multi-node integration tests: the reference's NodeTest tier
(SURVEY.md §5) — elections, replication, fail-over, restart recovery,
partitions, leadership transfer, membership change, linearizable reads.
"""

import asyncio

import pytest

from tests.cluster import MockStateMachine, TestCluster
from tpuraft.core.node import State
from tpuraft.core.read_only import ReadIndexError
from tpuraft.entity import PeerId, Task
from tpuraft.errors import RaftError, Status


async def test_single_node_becomes_leader_and_applies():
    c = TestCluster(1)
    await c.start_all()
    leader = await c.wait_leader()
    st = await c.apply_ok(leader, b"hello")
    assert st.is_ok()
    await c.wait_applied(1)
    assert c.fsms[leader.server_id].logs == [b"hello"]
    await c.stop_all()


async def test_triple_node_elect_and_replicate():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(10):
        st = await c.apply_ok(leader, b"op%d" % i)
        assert st.is_ok(), str(st)
    await c.wait_applied(10)
    for p in c.peers:
        assert c.fsms[p].logs == [b"op%d" % i for i in range(10)]
    # exactly one leader, others followers
    assert sum(1 for n in c.nodes.values() if n.state == State.LEADER) == 1
    await c.stop_all()


async def test_apply_on_follower_rejected():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    follower = next(n for n in c.nodes.values() if n is not leader)
    st = await c.apply_ok(follower, b"nope", retry=False)
    assert not st.is_ok()
    assert st.raft_error == RaftError.EPERM
    await c.stop_all()


async def test_leader_failover():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"before")
    await c.wait_applied(1)
    dead = leader.server_id
    await c.stop(dead)
    leader2 = await c.wait_leader()
    assert leader2.server_id != dead
    st = await c.apply_ok(leader2, b"after")
    assert st.is_ok()
    await c.wait_applied(2)
    for p, n in c.nodes.items():
        assert c.fsms[p].logs == [b"before", b"after"]
    await c.stop_all()


async def test_restart_recovery_from_log(tmp_path):
    c = TestCluster(3, tmp_path=tmp_path)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(5):
        await c.apply_ok(leader, b"v%d" % i)
    await c.wait_applied(5)
    await c.stop_all()
    # full restart: state must replay from durable log
    c2 = TestCluster(3, tmp_path=tmp_path)
    c2.net = c.net
    await c2.start_all()
    leader2 = await c2.wait_leader()
    await c2.apply_ok(leader2, b"v5")
    await c2.wait_applied(6)
    for p in c2.peers:
        assert c2.fsms[p].logs == [b"v%d" % i for i in range(6)]
    await c2.stop_all()


async def test_partitioned_leader_steps_down_and_rejoins():
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"a")
    await c.wait_applied(1)
    # isolate the leader: remaining majority elects a new one
    c.net.isolate(leader.server_id.endpoint)
    others = [n for n in c.nodes.values() if n is not leader]
    deadline = asyncio.get_running_loop().time() + 5
    new_leader = None
    while asyncio.get_running_loop().time() < deadline:
        cands = [n for n in others if n.state == State.LEADER]
        if cands:
            new_leader = cands[0]
            break
        await asyncio.sleep(0.02)
    assert new_leader is not None, "majority side failed to elect"
    st = await c.apply_ok(new_leader, b"b")
    assert st.is_ok()
    # old leader must have stepped down (lost quorum)
    deadline = asyncio.get_running_loop().time() + 3
    while asyncio.get_running_loop().time() < deadline:
        if leader.state != State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert leader.state != State.LEADER, "isolated leader still thinks it leads"
    # heal: old leader rejoins as follower and catches up
    c.net.heal()
    await c.wait_applied(2)
    assert c.fsms[leader.server_id].logs == [b"a", b"b"]
    # pre-vote means terms didn't explode while partitioned
    assert new_leader.current_term <= leader.current_term + 2
    await c.stop_all()


async def test_symmetric_partition_no_term_explosion():
    """Pre-vote: an isolated node must NOT bump its term while cut off."""
    c = TestCluster(3, election_timeout_ms=150)
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(n for n in c.nodes.values() if n is not leader)
    term_before = victim.current_term
    c.net.isolate(victim.server_id.endpoint)
    await asyncio.sleep(1.0)  # several election timeouts worth
    assert victim.current_term == term_before, (
        f"term exploded: {term_before} -> {victim.current_term}")
    c.net.heal()
    await c.stop_all()


async def test_transfer_leadership():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"x")
    # re-resolve + retry: under suite load the leader can step down between
    # the apply ack and the transfer call (EPERM "not leader")
    st = Status.error(RaftError.EPERM)
    for _ in range(3):
        leader = await c.wait_leader()
        target = next(p for p in c.peers if p != leader.server_id)
        st = await leader.transfer_leadership_to(target)
        if st.is_ok():
            break
        await asyncio.sleep(0.1)
    assert st.is_ok(), str(st)
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        t_node = c.nodes[target]
        if t_node.state == State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert c.nodes[target].state == State.LEADER
    st = await c.apply_ok(c.nodes[target], b"y")
    assert st.is_ok()
    await c.wait_applied(2)
    await c.stop_all()


async def test_read_index_leader_and_follower():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"r1")
    await c.wait_applied(1)
    idx = await leader.read_index()
    assert idx >= 1
    follower = next(n for n in c.nodes.values() if n is not leader)
    idx_f = await follower.read_index()
    assert idx_f >= 1
    # follower FSM has applied through idx_f: linearizable local read
    assert len(c.fsms[follower.server_id].logs) >= 1
    await c.stop_all()


async def test_read_index_burst_no_orphans():
    """Regression: readers arriving WHILE a confirmation round is in
    flight must be served by a follow-up round, not orphaned until the
    next unrelated request (observed as client-timeout p99 tails)."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"r1")
    # staggered burst: waves land mid-round repeatedly
    async def one(delay):
        await asyncio.sleep(delay)
        return await leader.read_index()
    results = await asyncio.wait_for(
        asyncio.gather(*(one((i % 7) * 0.001) for i in range(40))), 5.0)
    assert all(r >= 1 for r in results)
    await c.stop_all()


async def test_read_index_fails_without_quorum():
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader()
    c.net.isolate(leader.server_id.endpoint)
    with pytest.raises(ReadIndexError):
        await asyncio.wait_for(leader.read_index(), 3)
    c.net.heal()
    await c.stop_all()


async def test_add_peer():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(5):
        await c.apply_ok(leader, b"d%d" % i)
    await c.wait_applied(5)
    # boot a 4th node with empty conf: it learns via replication
    new_peer = PeerId.parse("127.0.0.1:5003")
    c.peers.append(new_peer)
    from tpuraft.conf import Configuration
    save_conf = c.conf
    c.conf = Configuration()  # joiner starts with empty conf
    await c.start(new_peer)
    c.conf = save_conf
    st = await asyncio.wait_for(leader.add_peer(new_peer), 10)
    assert st.is_ok(), str(st)
    assert new_peer in leader.list_peers()
    st = await c.apply_ok(leader, b"d5")
    assert st.is_ok()
    await c.wait_applied(6)
    assert c.fsms[new_peer].logs == [b"d%d" % i for i in range(6)]
    await c.stop_all()


async def test_remove_peer():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"a")
    victim = next(p for p in c.peers if p != leader.server_id)
    st = await asyncio.wait_for(leader.remove_peer(victim), 10)
    assert st.is_ok(), str(st)
    assert victim not in leader.list_peers()
    assert len(leader.list_peers()) == 2
    # still works with 2 voters
    st = await c.apply_ok(leader, b"b")
    assert st.is_ok()
    await c.wait_applied(2, nodes=[leader])
    await c.stop_all()


async def test_remove_leader_steps_down():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    st = await asyncio.wait_for(leader.remove_peer(leader.server_id), 10)
    assert st.is_ok(), str(st)
    # leader must step down; remaining two elect a new leader
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        if leader.state != State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert leader.state != State.LEADER
    others = {p: n for p, n in c.nodes.items() if n is not leader}
    new_leader = None
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        cands = [n for n in others.values() if n.state == State.LEADER]
        if cands:
            new_leader = cands[0]
            break
        await asyncio.sleep(0.02)
    assert new_leader is not None
    assert len(new_leader.list_peers()) == 2
    await c.stop_all()


async def test_learner_replicates_but_does_not_vote():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    learner = PeerId.parse("127.0.0.1:5003")
    c.peers.append(learner)
    from tpuraft.conf import Configuration
    save = c.conf
    c.conf = Configuration()
    await c.start(learner)
    c.conf = save
    st = await asyncio.wait_for(leader.add_learners([learner]), 10)
    assert st.is_ok(), str(st)
    assert learner in leader.list_learners()
    assert learner not in leader.list_peers()
    await c.apply_ok(leader, b"l1")
    await c.wait_applied(1)
    assert c.fsms[learner].logs == [b"l1"]
    await c.stop_all()


async def test_expected_term_guard():
    c = TestCluster(1)
    await c.start_all()
    leader = await c.wait_leader()
    fut = asyncio.get_running_loop().create_future()
    await leader.apply(Task(data=b"x", done=fut.set_result,
                            expected_term=leader.current_term + 5))
    st = await fut
    assert not st.is_ok()
    await c.stop_all()
