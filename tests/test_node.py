"""Multi-node integration tests: the reference's NodeTest tier
(SURVEY.md §5) — elections, replication, fail-over, restart recovery,
partitions, leadership transfer, membership change, linearizable reads.
"""

import asyncio
import time

import pytest

from tests.cluster import MockStateMachine, TestCluster
from tpuraft.core.node import State
from tpuraft.core.read_only import ReadIndexError
from tpuraft.entity import PeerId, Task
from tpuraft.errors import RaftError, Status


async def test_single_node_becomes_leader_and_applies():
    c = TestCluster(1)
    await c.start_all()
    leader = await c.wait_leader()
    st = await c.apply_ok(leader, b"hello")
    assert st.is_ok()
    await c.wait_applied(1)
    assert c.fsms[leader.server_id].logs == [b"hello"]
    await c.stop_all()


async def test_triple_node_elect_and_replicate():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(10):
        st = await c.apply_ok(leader, b"op%d" % i)
        assert st.is_ok(), str(st)
    await c.wait_applied(10)
    for p in c.peers:
        assert c.fsms[p].logs == [b"op%d" % i for i in range(10)]
    # exactly one leader, others followers
    assert sum(1 for n in c.nodes.values() if n.state == State.LEADER) == 1
    await c.stop_all()


async def test_apply_on_follower_rejected():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    follower = next(n for n in c.nodes.values() if n is not leader)
    st = await c.apply_ok(follower, b"nope", retry=False)
    assert not st.is_ok()
    assert st.raft_error == RaftError.EPERM
    await c.stop_all()


async def test_leader_failover():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"before")
    await c.wait_applied(1)
    dead = leader.server_id
    await c.stop(dead)
    leader2 = await c.wait_leader()
    assert leader2.server_id != dead
    st = await c.apply_ok(leader2, b"after")
    assert st.is_ok()
    await c.wait_applied(2)
    for p, n in c.nodes.items():
        assert c.fsms[p].logs == [b"before", b"after"]
    await c.stop_all()


async def test_restart_recovery_from_log(tmp_path):
    c = TestCluster(3, tmp_path=tmp_path)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(5):
        await c.apply_ok(leader, b"v%d" % i)
    await c.wait_applied(5)
    await c.stop_all()
    # full restart: state must replay from durable log
    c2 = TestCluster(3, tmp_path=tmp_path)
    c2.net = c.net
    await c2.start_all()
    leader2 = await c2.wait_leader()
    await c2.apply_ok(leader2, b"v5")
    await c2.wait_applied(6)
    for p in c2.peers:
        assert c2.fsms[p].logs == [b"v%d" % i for i in range(6)]
    await c2.stop_all()


async def test_restart_recovery_with_multimeta(tmp_path):
    """multimeta:// {term, votedFor} journal end-to-end: terms persist
    across a full restart (a node must never vote twice in a term it
    already voted in) and the cluster keeps working."""
    c = TestCluster(3, tmp_path=tmp_path, meta_scheme="multimeta")
    await c.start_all()
    leader = await c.wait_leader()
    term1 = leader.current_term
    await c.apply_ok(leader, b"m0")
    await c.wait_applied(1)
    # force a term bump so there's a non-trivial value to persist
    await c.stop(leader.server_id)
    leader2 = await c.wait_leader()
    assert leader2.current_term > term1
    await c.apply_ok(leader2, b"m1")
    terms = {str(p): n._meta.term for p, n in c.nodes.items()}
    await c.stop_all()
    c2 = TestCluster(3, tmp_path=tmp_path, meta_scheme="multimeta")
    c2.net = c.net
    await c2.start_all()
    # recovered terms must be >= what was durably recorded pre-restart
    for p, n in c2.nodes.items():
        if str(p) in terms:
            assert n._meta.term >= terms[str(p)], (str(p), n._meta.term)
    leader3 = await c2.wait_leader()
    await c2.apply_ok(leader3, b"m2")
    await c2.wait_applied(3)
    await c2.stop_all()


async def test_partitioned_leader_steps_down_and_rejoins():
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"a")
    await c.wait_applied(1)
    # isolate the leader: remaining majority elects a new one
    c.net.isolate(leader.server_id.endpoint)
    others = [n for n in c.nodes.values() if n is not leader]
    deadline = asyncio.get_running_loop().time() + 5
    new_leader = None
    while asyncio.get_running_loop().time() < deadline:
        cands = [n for n in others if n.state == State.LEADER]
        if cands:
            new_leader = cands[0]
            break
        await asyncio.sleep(0.02)
    assert new_leader is not None, "majority side failed to elect"
    st = await c.apply_ok(new_leader, b"b")
    assert st.is_ok()
    # old leader must have stepped down (lost quorum)
    deadline = asyncio.get_running_loop().time() + 3
    while asyncio.get_running_loop().time() < deadline:
        if leader.state != State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert leader.state != State.LEADER, "isolated leader still thinks it leads"
    # heal: old leader rejoins as follower and catches up
    c.net.heal()
    await c.wait_applied(2)
    assert c.fsms[leader.server_id].logs == [b"a", b"b"]
    # pre-vote means terms didn't explode while partitioned
    assert new_leader.current_term <= leader.current_term + 2
    await c.stop_all()


async def test_symmetric_partition_no_term_explosion():
    """Pre-vote: an isolated node must NOT bump its term while cut off."""
    c = TestCluster(3, election_timeout_ms=150)
    await c.start_all()
    leader = await c.wait_leader()
    victim = next(n for n in c.nodes.values() if n is not leader)
    term_before = victim.current_term
    c.net.isolate(victim.server_id.endpoint)
    await asyncio.sleep(1.0)  # several election timeouts worth
    assert victim.current_term == term_before, (
        f"term exploded: {term_before} -> {victim.current_term}")
    c.net.heal()
    await c.stop_all()


async def test_transfer_leadership():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"x")
    # re-resolve + retry: under suite load the leader can step down between
    # the apply ack and the transfer call (EPERM "not leader")
    st = Status.error(RaftError.EPERM)
    for _ in range(3):
        leader = await c.wait_leader()
        target = next(p for p in c.peers if p != leader.server_id)
        st = await leader.transfer_leadership_to(target)
        if st.is_ok():
            break
        await asyncio.sleep(0.1)
    assert st.is_ok(), str(st)
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        t_node = c.nodes[target]
        if t_node.state == State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert c.nodes[target].state == State.LEADER
    st = await c.apply_ok(c.nodes[target], b"y")
    assert st.is_ok()
    await c.wait_applied(2)
    await c.stop_all()


async def test_read_index_leader_and_follower():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"r1")
    await c.wait_applied(1)
    idx = await leader.read_index()
    assert idx >= 1
    follower = next(n for n in c.nodes.values() if n is not leader)
    idx_f = await follower.read_index()
    assert idx_f >= 1
    # follower FSM has applied through idx_f: linearizable local read
    assert len(c.fsms[follower.server_id].logs) >= 1
    await c.stop_all()


async def test_read_index_burst_no_orphans():
    """Regression: readers arriving WHILE a confirmation round is in
    flight must be served by a follow-up round, not orphaned until the
    next unrelated request (observed as client-timeout p99 tails)."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"r1")
    # staggered burst: waves land mid-round repeatedly
    async def one(delay):
        await asyncio.sleep(delay)
        return await leader.read_index()
    results = await asyncio.wait_for(
        asyncio.gather(*(one((i % 7) * 0.001) for i in range(40))), 5.0)
    assert all(r >= 1 for r in results)
    await c.stop_all()


async def test_read_index_fails_without_quorum():
    c = TestCluster(3, election_timeout_ms=200)
    await c.start_all()
    leader = await c.wait_leader()
    c.net.isolate(leader.server_id.endpoint)
    with pytest.raises(ReadIndexError):
        await asyncio.wait_for(leader.read_index(), 3)
    c.net.heal()
    await c.stop_all()


async def test_add_peer():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(5):
        await c.apply_ok(leader, b"d%d" % i)
    await c.wait_applied(5)
    # boot a 4th node with empty conf: it learns via replication
    new_peer = PeerId.parse("127.0.0.1:5003")
    c.peers.append(new_peer)
    from tpuraft.conf import Configuration
    save_conf = c.conf
    c.conf = Configuration()  # joiner starts with empty conf
    await c.start(new_peer)
    c.conf = save_conf
    st = await asyncio.wait_for(leader.add_peer(new_peer), 10)
    assert st.is_ok(), str(st)
    assert new_peer in leader.list_peers()
    st = await c.apply_ok(leader, b"d5")
    assert st.is_ok()
    await c.wait_applied(6)
    assert c.fsms[new_peer].logs == [b"d%d" % i for i in range(6)]
    await c.stop_all()


async def test_remove_peer():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"a")
    victim = next(p for p in c.peers if p != leader.server_id)
    st = await asyncio.wait_for(leader.remove_peer(victim), 10)
    assert st.is_ok(), str(st)
    assert victim not in leader.list_peers()
    assert len(leader.list_peers()) == 2
    # still works with 2 voters
    st = await c.apply_ok(leader, b"b")
    assert st.is_ok()
    await c.wait_applied(2, nodes=[leader])
    await c.stop_all()


async def test_remove_leader_steps_down():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    st = await asyncio.wait_for(leader.remove_peer(leader.server_id), 10)
    assert st.is_ok(), str(st)
    # leader must step down; remaining two elect a new leader
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        if leader.state != State.LEADER:
            break
        await asyncio.sleep(0.02)
    assert leader.state != State.LEADER
    others = {p: n for p, n in c.nodes.items() if n is not leader}
    new_leader = None
    deadline = asyncio.get_running_loop().time() + 5
    while asyncio.get_running_loop().time() < deadline:
        cands = [n for n in others.values() if n.state == State.LEADER]
        if cands:
            new_leader = cands[0]
            break
        await asyncio.sleep(0.02)
    assert new_leader is not None
    assert len(new_leader.list_peers()) == 2
    await c.stop_all()


async def test_learner_replicates_but_does_not_vote():
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    learner = PeerId.parse("127.0.0.1:5003")
    c.peers.append(learner)
    from tpuraft.conf import Configuration
    save = c.conf
    c.conf = Configuration()
    await c.start(learner)
    c.conf = save
    st = await asyncio.wait_for(leader.add_learners([learner]), 10)
    assert st.is_ok(), str(st)
    assert learner in leader.list_learners()
    assert learner not in leader.list_peers()
    await c.apply_ok(leader, b"l1")
    await c.wait_applied(1)
    assert c.fsms[learner].logs == [b"l1"]
    await c.stop_all()


async def test_expected_term_guard():
    c = TestCluster(1)
    await c.start_all()
    leader = await c.wait_leader()
    fut = asyncio.get_running_loop().create_future()
    await leader.apply(Task(data=b"x", done=fut.set_result,
                            expected_term=leader.current_term + 5))
    st = await fut
    assert not st.is_ok()
    await c.stop_all()


async def test_change_peers_joint_consensus():
    """Arbitrary membership change (reference: NodeTest changePeers):
    {a,b,c} -> {a,d,e} goes through joint consensus; the new majority
    carries writes, the removed peers are gone from the conf."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(3):
        await c.apply_ok(leader, b"pre%d" % i)
    await c.wait_applied(3)

    from tpuraft.conf import Configuration

    d = PeerId.parse("127.0.0.1:5003")
    e = PeerId.parse("127.0.0.1:5004")
    save = c.conf
    c.conf = Configuration()  # joiners start empty, learn via replication
    c.peers.extend([d, e])
    await c.start(d)
    await c.start(e)
    c.conf = save

    new_conf = Configuration([leader.server_id, d, e])
    st = await asyncio.wait_for(leader.change_peers(new_conf), 15)
    assert st.is_ok(), str(st)
    assert set(leader.list_peers()) == {leader.server_id, d, e}

    st = await c.apply_ok(leader, b"post")
    assert st.is_ok(), str(st)
    await c.wait_applied(4, nodes=[c.nodes[d], c.nodes[e]])
    assert c.fsms[d].logs == [b"pre0", b"pre1", b"pre2", b"post"]
    # removed voters are no longer in the committed conf
    removed = [p for p in save.peers if p != leader.server_id]
    for p in removed:
        assert p not in leader.list_peers()
    await c.stop_all()


async def test_reset_peers_recovers_lost_quorum():
    """Unsafe manual reset when a majority is permanently dead
    (reference: NodeTest resetPeers): the survivor, told it is now a
    single-voter group, elects itself and serves writes again."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()
    await c.apply_ok(leader, b"before")
    await c.wait_applied(1)
    # kill both followers: quorum permanently lost
    followers = [p for p in c.peers if p != leader.server_id]
    for p in followers:
        await c.stop(p)
    # a write cannot commit now
    fut = asyncio.get_running_loop().create_future()
    await leader.apply(Task(data=b"stuck", done=lambda s: fut.set_result(s)))
    from tpuraft.conf import Configuration

    st = await asyncio.wait_for(
        leader.reset_peers(Configuration([leader.server_id])), 5)
    assert st.is_ok(), str(st)
    # it re-elects itself as the sole voter and accepts writes
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and leader.state != State.LEADER:
        await asyncio.sleep(0.02)
    assert leader.state == State.LEADER
    st = await c.apply_ok(leader, b"after-reset")
    assert st.is_ok(), str(st)
    await c.stop_all()


async def test_chaos_rolling_crashes_converge():
    """Chaos tier (reference: rheakv ChaosTest-style): sustained client
    load while nodes crash and restart one at a time; at the end all
    replicas converge to identical, gap-free, duplicate-free logs."""
    import random

    rng = random.Random(7)
    c = TestCluster(3, election_timeout_ms=150)
    await c.start_all()
    await c.wait_leader()

    applied: list[bytes] = []
    stop_writer = asyncio.Event()

    async def writer():
        # unique payload per ATTEMPT: an attempt whose ack timed out may
        # still have committed, so reusing its payload on retry would
        # legitimately commit the same bytes twice and break the
        # exactly-once assertion below
        attempt = 0
        while not stop_writer.is_set():
            data = b"chaos-%d" % attempt
            attempt += 1
            try:
                leader = await c.wait_leader(3.0)
                st = await c.apply_ok(leader, data, timeout_s=3.0)
                if st.is_ok():
                    applied.append(data)
            except (TimeoutError, asyncio.TimeoutError):
                pass
            await asyncio.sleep(0)

    wtask = asyncio.ensure_future(writer())
    try:
        for _round in range(4):
            await asyncio.sleep(0.3)
            victim = rng.choice(c.peers)
            if victim not in c.nodes:
                continue
            await c.stop(victim)
            await asyncio.sleep(0.3)
            # memory:// log: the node rejoins empty and is re-replicated
            # from scratch, so give it a fresh FSM recorder too
            await c.start(victim, fsm=MockStateMachine())
    finally:
        stop_writer.set()
        await wtask

    assert len(applied) > 10, f"only {len(applied)} writes survived chaos"
    # quiesce: every replica must contain every acked write (a raw count
    # would under-wait, since logs also hold timed-out-but-committed
    # attempts)
    acked_set = set(applied)
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if all(acked_set <= set(c.fsms[p].logs) for p in c.peers):
            break
        await asyncio.sleep(0.05)
    logs = {str(p): c.fsms[p].logs for p in c.peers}
    reference_log = None
    for p, log in logs.items():
        acked = [x for x in log if x in acked_set]
        # every acked write appears exactly once, in order
        assert acked == applied, (
            f"{p}: {len(acked)} acked in log vs {len(applied)} acked")
        if reference_log is None:
            reference_log = log
        else:
            assert log == reference_log, f"{p} diverged"
    await c.stop_all()


async def test_join_unblocks_on_shutdown():
    """Node#join / RaftGroupService#join parity: join() blocks until
    shutdown completes."""
    c = TestCluster(1)
    await c.start_all()
    leader = await c.wait_leader()
    joiner = asyncio.ensure_future(leader.join())
    await asyncio.sleep(0.05)
    assert not joiner.done()
    await c.stop_all()
    await asyncio.wait_for(joiner, 2.0)


async def test_lease_based_read_index():
    """LEASE_BASED linearizable reads skip the quorum heartbeat round
    while the leader lease holds (reference: ReadOnlyOption.LEASE_BASED),
    and still fail when the lease lapses under isolation."""
    from tpuraft.options import ReadOnlyOption

    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    leader = await c.wait_leader()
    for n in c.nodes.values():
        n.options.raft_options.read_only_option = ReadOnlyOption.LEASE_BASED
    await c.apply_ok(leader, b"lr")
    await c.wait_applied(1)
    # the lease path must answer WITHOUT invoking the quorum heartbeat
    # round at all (that's the whole point vs SAFE)
    rounds = []
    orig_round = leader.replicators.heartbeat_round

    async def counting_round():
        rounds.append(1)
        return await orig_round()

    leader.replicators.heartbeat_round = counting_round
    idx = await leader.read_index()
    assert idx >= 1
    assert rounds == [], "lease read fell back to the SAFE quorum round"
    leader.replicators.heartbeat_round = orig_round
    # isolated leader: the lease lapses and lease reads stop succeeding
    c.net.isolate(leader.server_id.endpoint)
    await asyncio.sleep(0.8)  # > lease window
    with pytest.raises(ReadIndexError):
        await asyncio.wait_for(leader.read_index(), 3)
    c.net.heal()
    await c.stop_all()


async def test_adversarial_network_invariants():
    """Short adversarial soak: 5% packet drop + 3ms delay + rolling
    one-way partitions under sustained writes, with an election-safety
    monitor (never two leaders in one term) and exactly-once + identical
    convergent logs asserted at the end."""
    import random

    rng = random.Random(42)
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    await c.wait_leader()
    c.net.set_delay_ms(3)
    c.net.set_drop_rate(0.05)

    violations: list[str] = []
    stop = False

    async def monitor():
        while not stop:
            by_term: dict[int, list[str]] = {}
            for p, n in c.nodes.items():
                if n.state == State.LEADER:
                    by_term.setdefault(n.current_term, []).append(str(p))
            for t, ls in by_term.items():
                if len(ls) > 1:
                    violations.append(f"two leaders in term {t}: {ls}")
            await asyncio.sleep(0.005)

    acked: list[bytes] = []

    async def writer(wid):
        i = 0
        while not stop:
            try:
                leader = await c.wait_leader(3.0)
                st = await c.apply_ok(leader, b"w%d-%05d" % (wid, i),
                                      timeout_s=3.0)
                if st.is_ok():
                    acked.append(b"w%d-%05d" % (wid, i))
            except Exception:
                pass
            i += 1
            await asyncio.sleep(0.002)

    mon = asyncio.ensure_future(monitor())
    writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
    t0 = time.monotonic()
    while time.monotonic() - t0 < 8:
        await asyncio.sleep(1.5)
        a, b = rng.choice(c.peers), rng.choice(c.peers)
        if a != b:
            c.net.partition_one_way({a.endpoint}, {b.endpoint})
            await asyncio.sleep(0.5)
            c.net.heal()  # note: heal() clears partitions only; the
            # delay/drop settings stay in effect throughout
    stop = True
    await asyncio.gather(*writers)
    mon.cancel()
    c.net.set_drop_rate(0)
    c.net.set_delay_ms(0)

    assert not violations, violations[:3]
    assert len(acked) > 50, len(acked)
    acked_set = set(acked)
    # converge on the condition actually asserted below: identical logs
    # containing every acked entry (a leader can briefly hold applied
    # tail entries its followers haven't applied yet)
    deadline = time.monotonic() + 15
    converged = False
    while time.monotonic() < deadline:
        logs = [c.fsms[p].logs for p in c.peers]
        if (logs[0] == logs[1] == logs[2]
                and acked_set <= set(logs[0])):
            converged = True
            break
        await asyncio.sleep(0.1)
    assert converged, "replicas failed to converge on identical logs"
    # exactly-once PER ENTRY: a compensating duplicate+loss pair must
    # not cancel out in an aggregate count
    from collections import Counter

    occurrences = Counter(logs[0])
    for entry in acked_set:
        assert occurrences[entry] == 1, (entry, occurrences[entry])
    await c.stop_all()


async def test_cluster_on_native_log_engine(tmp_path):
    """A raft cluster whose durable log is the C++ engine
    (native/logstore.cc via log_uri=native://): replicate, crash the
    leader, restart it, recover from the native log."""
    from tests.test_storage import _native_available

    if not _native_available():
        pytest.skip("C++ engine not buildable")
    c = TestCluster(3, tmp_path=tmp_path, log_scheme="native")
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(10):
        st = await c.apply_ok(leader, b"n%d" % i)
        assert st.is_ok(), st
    await c.wait_applied(10)
    dead = leader.server_id
    await c.stop(dead)
    leader2 = await c.wait_leader()
    st = await c.apply_ok(leader2, b"post")
    assert st.is_ok()
    await c.start(dead, fsm=MockStateMachine())
    await c.wait_applied(11)
    assert c.fsms[dead].logs == [b"n%d" % i for i in range(10)] + [b"post"]
    await c.stop_all()


async def test_five_node_quorum_survives_two_failures():
    """5 voters tolerate 2 crashes (reference NodeTest's larger-quorum
    coverage): writes keep committing with 3/5, and the crashed pair
    catches up on restart."""
    c = TestCluster(5)
    await c.start_all()
    leader = await c.wait_leader()
    for i in range(5):
        st = await c.apply_ok(leader, b"q%d" % i)
        assert st.is_ok()
    await c.wait_applied(5)
    victims = [p for p in c.peers if p != leader.server_id][:2]
    for v in victims:
        await c.stop(v)
    leader = await c.wait_leader()
    st = await c.apply_ok(leader, b"with-3-of-5")
    assert st.is_ok(), st
    # a third failure would break quorum: verify 3/5 still commits but
    # don't go below (that's covered by reset_peers tests)
    for v in victims:
        await c.start(v, fsm=MockStateMachine())
    await c.wait_applied(6)
    for v in victims:
        assert c.fsms[v].logs == [b"q%d" % i for i in range(5)] + \
            [b"with-3-of-5"]
    await c.stop_all()


async def test_change_peers_under_sustained_load():
    """Membership change under fire (reference: NodeTest changePeers
    with concurrent applies): grow 3 -> 5 while writers run, then
    shrink back to the new pair + leader, losing no acked write."""
    c = TestCluster(3)
    await c.start_all()
    leader = await c.wait_leader()

    acked: list[bytes] = []
    stop = False

    async def writer():
        i = 0
        while not stop:
            try:
                ld = await c.wait_leader(3.0)
                st = await c.apply_ok(ld, b"m%05d" % i, timeout_s=3.0)
                if st.is_ok():
                    acked.append(b"m%05d" % i)
            except Exception:
                pass
            i += 1
            await asyncio.sleep(0.002)

    w = asyncio.ensure_future(writer())
    try:
        from tpuraft.conf import Configuration

        d = PeerId.parse("127.0.0.1:5005")
        e = PeerId.parse("127.0.0.1:5006")
        c.peers.extend([d, e])
        save = c.conf
        c.conf = Configuration()
        await c.start(d)
        await c.start(e)
        c.conf = save
        leader = await c.wait_leader()
        st = await asyncio.wait_for(
            leader.change_peers(Configuration(
                list(save.peers) + [d, e])), 20)
        assert st.is_ok(), st
        assert len(leader.list_peers()) == 5
        await asyncio.sleep(0.3)  # writes through the 5-voter quorum
        leader = await c.wait_leader()
        st = await asyncio.wait_for(
            leader.change_peers(Configuration(
                [leader.server_id, d, e])), 20)
        assert st.is_ok(), st
        assert set(leader.list_peers()) == {leader.server_id, d, e}
        await asyncio.sleep(0.3)
    finally:
        stop = True
        await w
    assert len(acked) > 30, len(acked)
    # every acked write is exactly-once on the final membership
    acked_set = set(acked)
    final_nodes = [n for n in c.nodes.values()
                   if n.server_id in leader.list_peers()]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if all(acked_set <= set(c.fsms[n.server_id].logs)
               for n in final_nodes):
            break
        await asyncio.sleep(0.1)
    from collections import Counter
    for n in final_nodes:
        occ = Counter(c.fsms[n.server_id].logs)
        for entry in acked_set:
            assert occ[entry] == 1, (str(n.server_id), entry, occ[entry])
    await c.stop_all()


async def test_divergence_below_applied_fails_node_not_rpc_storm():
    """A replica whose applied state diverges from the leader's
    committed log (only reachable via storage loss / amnesiac restart)
    must fail FATALLY — enter ERROR state and answer EHOSTDOWN so
    leaders take the paced-retry path — instead of rejecting the same
    AppendEntries forever (reference: NodeImpl#onError semantics)."""
    from tpuraft.conf import Configuration
    from tpuraft.entity import EntryType, LogEntry, LogId
    from tpuraft.errors import RaftError
    from tpuraft.rpc.messages import AppendEntriesRequest
    from tpuraft.rpc.transport import RpcError

    c = TestCluster(3)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        for i in range(3):
            st = await c.apply_ok(leader, b"e%d" % i)
            assert st.is_ok(), str(st)
        follower_id = next(p for p in c.peers if p != leader.server_id)
        await c.wait_applied(3, nodes=[c.nodes[follower_id]])
        fnode = c.nodes[follower_id]
        lm = fnode.log_manager
        # fabricate a conflicting entry BELOW the applied index, as a
        # fake higher-term leader would present after divergence
        bad_term = fnode.current_term + 5
        idx = lm.last_log_index()          # an applied, committed index
        prev = idx - 1
        req = AppendEntriesRequest(
            group_id=c.group_id, server_id="127.0.0.1:9999",
            peer_id=str(follower_id), term=bad_term,
            prev_log_index=prev, prev_log_term=lm.get_term(prev),
            committed_index=0,
            entries=[LogEntry(type=EntryType.NO_OP,
                              id=LogId(index=idx, term=bad_term))])
        try:
            await fnode.handle_append_entries(req)
            raise AssertionError("conflicting append below applied "
                                 "index was accepted")
        except RpcError as e:
            assert e.status.code == int(RaftError.EHOSTDOWN), e.status
        assert fnode.state == State.ERROR
        # and it stays failed: the retry answers the same way
        try:
            await fnode.handle_append_entries(req)
            raise AssertionError("ERROR-state node served an RPC")
        except RpcError as e:
            assert e.status.code == int(RaftError.EHOSTDOWN), e.status
        # the application's StateMachine#onError hook hears about it
        for _ in range(100):
            if c.fsms[follower_id].errors:
                break
            await asyncio.sleep(0.02)
        assert c.fsms[follower_id].errors, "fsm.on_error never fired"
        # ERROR is sticky: a straggler higher-term response must not
        # resurrect the node into FOLLOWER with live timers
        await fnode.step_down_on_higher_term(bad_term + 1, "straggler")
        assert fnode.state == State.ERROR
        # the apply pipeline is poisoned (no further commits reach the
        # FSM) and InstallSnapshot is refused like AppendEntries
        assert fnode.fsm_caller._error is not None
        try:
            await fnode.handle_install_snapshot(object())
            raise AssertionError("ERROR-state node accepted a snapshot")
        except RpcError as e:
            assert e.status.code == int(RaftError.EHOSTDOWN), e.status
        # conf surgery can't revive it — and must say so
        st = await fnode.reset_peers(
            Configuration([follower_id]))
        assert st.code == int(RaftError.EHOSTDOWN), str(st)
    finally:
        await c.stop_all()


async def test_read_committed_user_log():
    """Node#readCommittedUserLog parity: first DATA entry at/after the
    index; EINVAL beyond commit; ENOENT once compacted."""
    from tpuraft.errors import RaftError, RaftException

    c = TestCluster(3)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        for i in range(5):
            st = await c.apply_ok(leader, b"u%d" % i)
            assert st.is_ok(), str(st)
        # index 1 is the leader's no-op CONFIGURATION entry: skipped
        # forward to the first DATA entry
        e = leader.read_committed_user_log(1)
        assert e.data == b"u0"
        assert leader.read_committed_user_log(e.id.index + 1).data == b"u1"
        try:
            leader.read_committed_user_log(
                leader.ballot_box.last_committed_index + 1)
            raise AssertionError("index beyond commit accepted")
        except RaftException as ex:
            assert ex.status.raft_error == RaftError.EINVAL
    finally:
        await c.stop_all()


async def test_read_committed_user_log_compacted(tmp_path):
    from tpuraft.errors import RaftError, RaftException

    c = TestCluster(3, tmp_path=tmp_path, snapshot=True)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        for i in range(8):
            await c.apply_ok(leader, b"c%d" % i)
        await c.wait_applied(8)
        st = await leader.snapshot()
        assert st.is_ok(), str(st)
        try:
            leader.read_committed_user_log(2)
            raise AssertionError("compacted index served")
        except RaftException as ex:
            assert ex.status.raft_error == RaftError.ENOENT
    finally:
        await c.stop_all()


async def test_transfer_timeout_reverts_to_leader():
    """Transferring to an unreachable target must not wedge the group:
    applies are rejected EBUSY during the handoff window, then the
    watchdog reverts to LEADER after an election timeout and service
    resumes (reference: NodeImpl transfer deadline handling)."""
    c = TestCluster(3, election_timeout_ms=300)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        st = await c.apply_ok(leader, b"pre")
        assert st.is_ok(), str(st)
        target = next(p for p in c.peers if p != leader.server_id)
        # cut the target off so TimeoutNow can never reach it
        c.net.isolate(target.endpoint)
        st = await leader.transfer_leadership_to(target)
        assert st.is_ok(), str(st)   # transfer is initiated
        assert leader.state == State.TRANSFERRING
        st = await c.apply_ok(leader, b"during", retry=False)
        assert not st.is_ok() and st.raft_error == RaftError.EBUSY, str(st)
        # the watchdog gives up after one election timeout
        deadline = asyncio.get_running_loop().time() + 3
        while asyncio.get_running_loop().time() < deadline:
            if leader.state == State.LEADER:
                break
            await asyncio.sleep(0.02)
        assert leader.state == State.LEADER, leader.state
        c.net.heal()
        st = await c.apply_ok(leader, b"post")
        assert st.is_ok(), str(st)
        await c.wait_applied(2)
    finally:
        await c.stop_all()


async def test_follower_read_index_forward_batches():
    """Concurrent forwarded readIndex calls on a follower share RPC
    rounds (reference: ReadOnlyServiceImpl batches on every node), and
    late arrivals get a FRESH round, never an already-in-flight one."""
    c = TestCluster(3)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"rr")
        follower = next(n for n in c.nodes.values() if n is not leader)

        calls = {"n": 0}
        real = follower.transport.read_index

        async def counting(dst, req, timeout_ms=None):
            calls["n"] += 1
            return await real(dst, req, timeout_ms)

        follower.transport.read_index = counting
        # 30 concurrent readers -> far fewer forward RPCs than readers
        results = await asyncio.gather(
            *(follower.read_index() for _ in range(30)))
        assert all(r >= 1 for r in results)
        assert calls["n"] < 10, calls["n"]
        # staggered waves keep landing mid-round without orphaning
        calls["n"] = 0
        async def one(delay):
            await asyncio.sleep(delay)
            return await follower.read_index()
        results = await asyncio.wait_for(
            asyncio.gather(*(one((i % 5) * 0.001) for i in range(25))), 5.0)
        assert all(r >= 1 for r in results)
    finally:
        await c.stop_all()


async def test_replication_pipelines_under_latency():
    """Pipelined replication (reference: maxReplicatorInflightMsgs):
    with 12ms one-way delay and small batches, a serial replicator
    moves ~1 batch per RTT; the window must keep multiple AppendEntries
    in flight and commit 60 entries far faster than the serial bound."""
    c = TestCluster(3, election_timeout_ms=1500)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"warm")
        await c.wait_applied(1)
        # tiny batches force many RPCs; the delay makes serial painful
        for n in c.nodes.values():
            n.options.raft_options.max_entries_size = 1
        c.net.set_delay_ms(12)
        N = 60
        t0 = time.monotonic()
        futs = []
        loop = asyncio.get_running_loop()
        for i in range(N):
            fut = loop.create_future()
            await leader.apply(Task(
                data=b"p%03d" % i,
                done=lambda st, fut=fut: fut.done() or fut.set_result(st)))
            futs.append(fut)
        sts = await asyncio.wait_for(asyncio.gather(*futs), 30)
        dt = time.monotonic() - t0
        c.net.set_delay_ms(0)
        assert all(st.is_ok() for st in sts)
        # serial bound: 60 batches x ~24ms RTT = ~1.44s per follower;
        # the margin is generous for full-suite CPU contention — the
        # inflight_peak assert below is the primary pipelining proof
        assert dt < 1.3, f"took {dt:.2f}s — pipeline not engaging?"
        peaks = [r.inflight_peak for r in
                 (leader.replicators.get(p) for p in c.peers
                  if p != leader.server_id) if r is not None]
        assert any(pk > 3 for pk in peaks), peaks
        await c.wait_applied(N + 1, timeout_s=10)
        logs = [c.fsms[p].logs for p in c.peers]
        assert logs[0] == logs[1] == logs[2]
    finally:
        await c.stop_all()


async def test_read_index_refused_until_term_first_commit():
    """A fresh leader must refuse readIndex until the first entry of
    ITS OWN term commits: the carried-over commit marker may lag
    entries the old leader committed and acked, and serving reads
    against it loses acked writes (found by the linearizability soak;
    reference: ReadOnlyServiceImpl rejects until current-term commit)."""
    c = TestCluster(3)
    await c.start_all()
    try:
        leader = await c.wait_leader()
        await c.apply_ok(leader, b"g1")
        idx = await leader.read_index()
        assert idx >= 1
        # simulate the fresh-leader window: first-term entry not yet
        # committed -> reads must fail closed, not serve the stale index
        leader._term_first_index = leader.log_manager.last_log_index() + 5
        with pytest.raises(ReadIndexError):
            await asyncio.wait_for(leader.read_index(), 5)
        # once the term's first entry is committed, reads resume
        leader._term_first_index = 0
        assert await leader.read_index() >= idx
    finally:
        await c.stop_all()


async def test_read_after_leader_kill_sees_acked_write():
    """Kill the leader immediately after an acked write (followers'
    commit markers typically lag it); a linearizable read through the
    new leader must include the acked write — the safety gate makes the
    read wait for the new term's no-op commit instead of serving the
    stale carried-over index."""
    for round_i in range(3):
        c = TestCluster(3, election_timeout_ms=200)
        await c.start_all()
        try:
            leader = await c.wait_leader()
            st = await c.apply_ok(leader, b"pre-%d" % round_i)
            assert st.is_ok()
            st = await c.apply_ok(leader, b"acked-%d" % round_i)
            assert st.is_ok(), str(st)
            # kill within the heartbeat gap: commit-marker propagation
            # to followers likely hasn't happened yet
            await c.stop(leader.server_id)
            new_leader = await c.wait_leader()
            idx = await asyncio.wait_for(new_leader.read_index(), 10)
            applied = c.fsms[new_leader.server_id].logs
            assert b"acked-%d" % round_i in applied, (
                f"round {round_i}: acked write missing after "
                f"read_index={idx}: {applied}")
        finally:
            await c.stop_all()
