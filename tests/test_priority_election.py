"""Priority election under geo failure: decay convergence when the
high-priority zone dies, and priority RE-election (leadership handed
back) after it heals.

Reference anchors: NodeImpl#allowLaunchElection / targetPriority decay
(PAPER.md §1 priority election as the SOFAJRaft locality lever);
the transfer-back is this repo's geo extension
(RaftOptions.priority_transfer_rounds) — a leader elected via decay
returns leadership to the preferred zone once it is healthy again.
"""

import asyncio
import time

import pytest

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.entity import PeerId


def _priority_cluster(prios, witness_idx=(), **kw):
    c = TestCluster(len(prios), tmp_path=None, **kw)
    c.peers = [PeerId("127.0.0.1", 5000 + i, 0, pr)
               for i, pr in enumerate(prios)]
    witnesses = [c.peers[i] for i in witness_idx]
    c.conf = Configuration(list(c.peers), witnesses=witnesses)
    return c


async def _wait_leader_priority(c, want_priority, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    leader = None
    while time.monotonic() < deadline:
        try:
            leader = await c.wait_leader(timeout_s=2.0)
        except TimeoutError:
            continue
        if leader.server_id.priority == want_priority:
            return leader
        await asyncio.sleep(0.1)
    raise AssertionError(
        f"no leader with priority {want_priority} in {timeout_s}s "
        f"(last leader: {leader and leader.server_id})")


@pytest.mark.asyncio
async def test_low_priority_wins_after_high_priority_node_dies():
    """The decay path end-to-end: the high-priority LEADER dies mid-run
    (not merely never started), survivors' target stays at the dead
    node's priority until the decay gap lets the 40-node through."""
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await _wait_leader_priority(c, 80)
        await c.apply_ok(leader, b"pre-kill")
        await c.stop(leader.server_id)
        # survivors: target 80 decays (gap = max(10, 80//5) = 16:
        # 80 -> 64 -> 48 -> 32 lets the 40-node campaign)
        new_leader = await _wait_leader_priority(c, 40)
        # commits still flow under the decayed leadership
        st = await c.apply_ok(new_leader, b"post-decay")
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_leadership_transfers_back_after_high_priority_heals():
    """Priority RE-election: once the priority-80 node restarts,
    catches up, and acks for priority_transfer_rounds stepdown rounds,
    the decayed (40) leader hands leadership back — leadership returns
    to the preferred zone instead of sticking where the decay left it."""
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await _wait_leader_priority(c, 80)
        high = leader.server_id
        await c.apply_ok(leader, b"v1")
        await c.stop(high)
        low_leader = await _wait_leader_priority(c, 40)
        st = await c.apply_ok(low_leader, b"v2")
        assert st.is_ok()
        # the high-priority zone heals
        await c.start(high)
        healed = await _wait_leader_priority(c, 80, timeout_s=20.0)
        assert healed.server_id == high
        assert low_leader.metrics.counters.get("priority-transfers", 0) >= 1
        st = await c.apply_ok(healed, b"v3")
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_priority_transfer_disabled_keeps_decayed_leader():
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        for n in c.nodes.values():
            n.options.raft_options.priority_transfer_rounds = 0
        leader = await _wait_leader_priority(c, 80)
        high = leader.server_id
        await c.stop(high)
        low_leader = await _wait_leader_priority(c, 40)
        await c.start(high)
        # restarted node must NOT depose: no transfer, and its own
        # campaign is barred by the live leader's lease.  Give it a few
        # election timeouts to (not) act.
        await asyncio.sleep(1.2)
        assert low_leader.is_leader(), \
            "priority_transfer_rounds=0 must leave the decayed leader"
        assert low_leader.metrics.counters.get("priority-transfers", 0) == 0
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_priority_never_raises_target():
    """A witness's priority must not gate data replicas' elections: the
    witness never campaigns, so a high witness priority raising the
    target would only delay every real candidate behind pointless decay
    rounds."""
    # witness has the HIGHEST priority on purpose
    c = _priority_cluster([90, 40, 20], witness_idx=(0,),
                          election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await c.wait_leader(timeout_s=10.0)
        # the 40-node must win immediately (target = max over DATA
        # voters = 40), without a single decay round against the 90
        assert leader.server_id.priority == 40
        for n in c.nodes.values():
            assert n.target_priority == 40, (
                f"{n}: witness priority leaked into target "
                f"({n.target_priority})")
    finally:
        await c.stop_all()
