"""Priority election under geo failure: decay convergence when the
high-priority zone dies, and priority RE-election (leadership handed
back) after it heals.

Reference anchors: NodeImpl#allowLaunchElection / targetPriority decay
(PAPER.md §1 priority election as the SOFAJRaft locality lever);
the transfer-back is this repo's geo extension
(RaftOptions.priority_transfer_rounds) — a leader elected via decay
returns leadership to the preferred zone once it is healthy again.
"""

import asyncio
import time

import pytest

from tests.cluster import TestCluster
from tpuraft.conf import Configuration
from tpuraft.entity import PeerId


def _priority_cluster(prios, witness_idx=(), **kw):
    c = TestCluster(len(prios), tmp_path=None, **kw)
    c.peers = [PeerId("127.0.0.1", 5000 + i, 0, pr)
               for i, pr in enumerate(prios)]
    witnesses = [c.peers[i] for i in witness_idx]
    c.conf = Configuration(list(c.peers), witnesses=witnesses)
    return c


async def _wait_leader_priority(c, want_priority, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    leader = None
    while time.monotonic() < deadline:
        try:
            leader = await c.wait_leader(timeout_s=2.0)
        except TimeoutError:
            continue
        if leader.server_id.priority == want_priority:
            return leader
        await asyncio.sleep(0.1)
    raise AssertionError(
        f"no leader with priority {want_priority} in {timeout_s}s "
        f"(last leader: {leader and leader.server_id})")


@pytest.mark.asyncio
async def test_low_priority_wins_after_high_priority_node_dies():
    """The decay path end-to-end: the high-priority LEADER dies mid-run
    (not merely never started), survivors' target stays at the dead
    node's priority until the decay gap lets the 40-node through."""
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await _wait_leader_priority(c, 80)
        await c.apply_ok(leader, b"pre-kill")
        await c.stop(leader.server_id)
        # survivors: target 80 decays (gap = max(10, 80//5) = 16:
        # 80 -> 64 -> 48 -> 32 lets the 40-node campaign)
        new_leader = await _wait_leader_priority(c, 40)
        # commits still flow under the decayed leadership
        st = await c.apply_ok(new_leader, b"post-decay")
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_leadership_transfers_back_after_high_priority_heals():
    """Priority RE-election: once the priority-80 node restarts,
    catches up, and acks for priority_transfer_rounds stepdown rounds,
    the decayed (40) leader hands leadership back — leadership returns
    to the preferred zone instead of sticking where the decay left it."""
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await _wait_leader_priority(c, 80)
        high = leader.server_id
        await c.apply_ok(leader, b"v1")
        await c.stop(high)
        low_leader = await _wait_leader_priority(c, 40)
        st = await c.apply_ok(low_leader, b"v2")
        assert st.is_ok()
        # the high-priority zone heals
        await c.start(high)
        healed = await _wait_leader_priority(c, 80, timeout_s=20.0)
        assert healed.server_id == high
        assert low_leader.metrics.counters.get("priority-transfers", 0) >= 1
        st = await c.apply_ok(healed, b"v3")
        assert st.is_ok()
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_priority_transfer_disabled_keeps_decayed_leader():
    c = _priority_cluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        for n in c.nodes.values():
            n.options.raft_options.priority_transfer_rounds = 0
        leader = await _wait_leader_priority(c, 80)
        high = leader.server_id
        await c.stop(high)
        low_leader = await _wait_leader_priority(c, 40)
        await c.start(high)
        # restarted node must NOT depose: no transfer, and its own
        # campaign is barred by the live leader's lease.  Give it a few
        # election timeouts to (not) act.
        await asyncio.sleep(1.2)
        assert low_leader.is_leader(), \
            "priority_transfer_rounds=0 must leave the decayed leader"
        assert low_leader.metrics.counters.get("priority-transfers", 0) == 0
    finally:
        await c.stop_all()


@pytest.mark.asyncio
async def test_witness_priority_never_raises_target():
    """A witness's priority must not gate data replicas' elections: the
    witness never campaigns, so a high witness priority raising the
    target would only delay every real candidate behind pointless decay
    rounds."""
    # witness has the HIGHEST priority on purpose
    c = _priority_cluster([90, 40, 20], witness_idx=(0,),
                          election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await c.wait_leader(timeout_s=10.0)
        # the 40-node must win immediately (target = max over DATA
        # voters = 40), without a single decay round against the 90
        assert leader.server_id.priority == 40
        for n in c.nodes.values():
            assert n.target_priority == 40, (
                f"{n}: witness priority leaked into target "
                f"({n.target_priority})")
    finally:
        await c.stop_all()


class _EnginePriorityCluster:
    """The engine-lane mirror of ``_priority_cluster``: 3 endpoints x 1
    group, each endpoint hosting ONE MultiRaftEngine whose tick plane
    schedules the periodic stepdown scan (Node._check_dead_nodes) for
    its leaders.  Before ISSUE 19 the engine only fired that handler on
    DEAD quorums, so engine-backed decay leaders never accrued
    priority_transfer_rounds and leadership stuck wherever the decay
    left it — the transfer-back test below pins the restored cadence."""

    def __init__(self, prios, election_timeout_ms=150, tick_ms=5):
        from tpuraft.rpc.transport import InProcNetwork

        self.net = InProcNetwork()
        self.peers = [PeerId("127.0.0.1", 6100 + i, 0, pr)
                      for i, pr in enumerate(prios)]
        self.conf = Configuration(list(self.peers))
        self.gid = "prio_engine_group"
        self.election_timeout_ms = election_timeout_ms
        self.tick_ms = tick_ms
        self.nodes = {}
        self.engines = {}
        self.fsms = {}

    async def start(self, peer):
        from tests.cluster import MockStateMachine
        from tpuraft.core.engine import MultiRaftEngine
        from tpuraft.core.node import Node
        from tpuraft.core.node_manager import NodeManager
        from tpuraft.options import NodeOptions, TickOptions
        from tpuraft.rpc.transport import InProcTransport, RpcServer

        server = RpcServer(peer.endpoint)
        manager = NodeManager(server)
        self.net.bind(server)
        self.net.start_endpoint(peer.endpoint)
        transport = InProcTransport(self.net, peer.endpoint)
        # backend pinned to jax (conftest's CPU default resolves "auto"
        # to numpy): the point is the DEVICE tick's stepdown lane
        engine = MultiRaftEngine(TickOptions(
            max_groups=4, max_peers=8, tick_interval_ms=self.tick_ms,
            backend="jax"))
        await engine.start()
        fsm = MockStateMachine()
        opts = NodeOptions(
            election_timeout_ms=self.election_timeout_ms,
            initial_conf=self.conf.copy(), fsm=fsm,
            log_uri="memory://", raft_meta_uri="memory://")
        node = Node(self.gid, peer, opts, transport,
                    ballot_box_factory=engine.ballot_box_factory())
        node.node_manager = manager
        manager.add(node)
        assert await node.init()
        self.engines[peer] = engine
        self.nodes[peer] = node
        self.fsms[peer] = fsm
        return node

    async def start_all(self):
        for p in self.peers:
            await self.start(p)

    async def stop(self, peer):
        """Crash-stop the whole endpoint: node, engine, network."""
        self.net.stop_endpoint(peer.endpoint)
        node = self.nodes.pop(peer, None)
        engine = self.engines.pop(peer, None)
        if node:
            self.net.unbind(peer.endpoint)
            await node.shutdown()
        if engine:
            await engine.shutdown()

    async def stop_all(self):
        for p in list(self.nodes):
            await self.stop(p)

    async def wait_leader(self, timeout_s=5.0):
        from tpuraft.core.node import State

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [n for n in self.nodes.values()
                       if n.state == State.LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"no leader in {timeout_s}s; states="
            f"{[(str(p), n.state.value) for p, n in self.nodes.items()]}")

    async def apply_ok(self, node, data, timeout_s=5.0):
        from tpuraft.entity import Task
        from tpuraft.errors import RaftError

        deadline = time.monotonic() + timeout_s
        while True:
            fut = asyncio.get_running_loop().create_future()
            await node.apply(Task(data=data,
                                  done=lambda st: fut.set_result(st)))
            st = await asyncio.wait_for(
                fut, max(0.1, deadline - time.monotonic()))
            if (st.is_ok() or st.raft_error != RaftError.EPERM
                    or time.monotonic() >= deadline):
                return st
            await asyncio.sleep(0.05)
            try:
                node = await self.wait_leader(
                    max(0.1, deadline - time.monotonic()))
            except TimeoutError:
                return st


@pytest.mark.asyncio
async def test_engine_leadership_transfers_back_after_high_priority_heals():
    """ISSUE 19 stepdown lane end-to-end: an ENGINE-backed decay leader
    (priority 40, elected while the 80 was dead) must hand leadership
    back once the 80-node heals — which requires the device tick's
    stepdown_due lane to keep delivering _check_dead_nodes rounds, the
    cadence that accrues priority_transfer_rounds.  Mirrors the
    timer-mode test_leadership_transfers_back_after_high_priority_heals
    above, with every node's ballot box on a MultiRaftEngine."""
    c = _EnginePriorityCluster([80, 40, 20], election_timeout_ms=150)
    await c.start_all()
    try:
        leader = await _wait_leader_priority(c, 80)
        high = leader.server_id
        st = await c.apply_ok(leader, b"v1")
        assert st.is_ok()
        await c.stop(high)
        low_leader = await _wait_leader_priority(c, 40)
        st = await c.apply_ok(low_leader, b"v2")
        assert st.is_ok()
        low_engine = c.engines[low_leader.server_id]
        # the high-priority zone heals (amnesiac restart, like the
        # timer-mode test: memory:// storage, caught up over the wire)
        await c.start(high)
        healed = await _wait_leader_priority(c, 80, timeout_s=20.0)
        assert healed.server_id == high
        assert low_leader.metrics.counters.get("priority-transfers", 0) >= 1
        # and the cadence really came from the engine's device lane
        assert low_engine.stepdown_ticks > 0, \
            "transfer happened without a single engine stepdown tick?"
        st = await c.apply_ok(healed, b"v3")
        assert st.is_ok()
    finally:
        await c.stop_all()
