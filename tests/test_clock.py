"""Time-chaos plane (ISSUE 18): ChaosClock algebra, the peer-skew
sentinel, timers under injected clocks, and the lease boundary / drift
bound regressions the plane exists to catch."""

import asyncio

import pytest

from tpuraft.util.clock import SYSTEM, ChaosClock, ClockSentinel, resolve


class FakeClock:
    """Hand-cranked base clock for deterministic algebra tests."""

    def __init__(self, t: float = 0.0):
        self.t = t
        self.w = 1_000_000.0 + t

    def monotonic(self) -> float:
        return self.t

    def wall(self) -> float:
        return self.w + self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- ChaosClock algebra -------------------------------------------------------


def test_resolve_defaults_to_system():
    assert resolve(None) is SYSTEM
    fake = FakeClock()
    assert resolve(fake) is fake


def test_chaos_clock_tracks_base_at_rate_one():
    base = FakeClock(10.0)
    c = ChaosClock(base=base)
    assert c.monotonic() == pytest.approx(10.0)
    base.advance(2.5)
    assert c.monotonic() == pytest.approx(12.5)


def test_chaos_clock_rate_drift_piecewise():
    base = FakeClock()
    c = ChaosClock(base=base)
    base.advance(10.0)            # 10 virtual s at rate 1
    c.set_rate(1.1)
    base.advance(10.0)            # 11 virtual s at rate 1.1
    assert c.monotonic() == pytest.approx(21.0)
    c.set_rate(0.5)
    base.advance(4.0)             # 2 virtual s at rate 0.5
    assert c.monotonic() == pytest.approx(23.0)
    assert c.faults["drift"] == 2


def test_chaos_clock_jump_is_forward_only():
    base = FakeClock()
    c = ChaosClock(base=base)
    base.advance(1.0)
    c.jump(5.0)
    assert c.monotonic() == pytest.approx(6.0)
    with pytest.raises(ValueError):
        c.jump(-0.1)
    with pytest.raises(ValueError):
        c.set_rate(-1.0)


def test_chaos_clock_freeze_unfreeze_restores_prior_rate():
    base = FakeClock()
    c = ChaosClock(base=base)
    c.set_rate(1.25)
    base.advance(4.0)             # 5 virtual s
    c.freeze()
    assert c.frozen
    base.advance(100.0)           # frozen: no virtual progress
    assert c.monotonic() == pytest.approx(5.0)
    c.unfreeze()
    assert c.rate == pytest.approx(1.25)   # freeze remembers the drift
    base.advance(4.0)
    assert c.monotonic() == pytest.approx(10.0)


def test_chaos_clock_heal_keeps_accumulated_offset():
    base = FakeClock()
    c = ChaosClock(base=base)
    c.jump(30.0)
    c.set_rate(2.0)
    base.advance(5.0)
    c.heal()
    assert c.rate == 1.0
    # healed forward-skewed clock NEVER steps backwards
    before = c.monotonic()
    base.advance(1.0)
    assert c.monotonic() == pytest.approx(before + 1.0)
    assert c.monotonic() > 40.0


def test_chaos_clock_never_runs_backwards_through_chaos_steps():
    base = FakeClock()
    c = ChaosClock(seed=7, base=base)
    last = c.monotonic()
    for _ in range(200):
        c.chaos_step()
        base.advance(0.05)
        now = c.monotonic()
        assert now >= last
        last = now


def test_chaos_step_is_seeded_deterministic():
    a = ChaosClock(seed=42, base=FakeClock())
    b = ChaosClock(seed=42, base=FakeClock())
    assert [a.chaos_step() for _ in range(20)] \
        == [b.chaos_step() for _ in range(20)]


def test_chaos_clock_wall_mirrors_monotonic_displacement():
    base = FakeClock()
    c = ChaosClock(base=base)
    w0 = c.wall()
    c.jump(10.0)
    assert c.wall() - w0 == pytest.approx(10.0)


# -- ClockSentinel ------------------------------------------------------------


def _feed(sent, peer, local_t, peer_t, rtt=0.002):
    """One beat-ack probe with a tiny symmetric RTT."""
    sent.observe(peer, peer_t, local_t - rtt / 2, local_t + rtt / 2)


def test_sentinel_estimates_peer_rate_and_skew():
    clk = FakeClock()
    s = ClockSentinel(drift_bound=0.05, clock=clk, label="s1")
    # peer clock runs exactly with ours, offset +3s
    for i in range(8):
        t = i * 1.0
        _feed(s, "p1", t, t + 3.0)
    assert s.rate_of("p1") == pytest.approx(1.0, abs=1e-6)
    assert s.skew_of("p1") == pytest.approx(3.0, abs=1e-3)
    assert not s.suspect()


def test_sentinel_minority_fast_peer_does_not_fence_local():
    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="s1")
    for i in range(10):
        t = i * 1.0
        _feed(s, "fast", t, t * 1.5)      # one broken peer, 50% fast
        _feed(s, "ok1", t, t)
        _feed(s, "ok2", t, t)
    # the MEDIAN peer ratio is ~1.0: the local clock is fine
    assert not s.suspect()
    assert s.lease_check()
    assert s.lease_fenced == 0


def test_sentinel_median_deviation_means_local_clock_suspect():
    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="s1")
    # EVERY peer appears ~0.8x slow == the LOCAL clock is ~25% fast
    for i in range(10):
        t = i * 1.0
        for p in ("a", "b", "c"):
            _feed(s, p, t, t * 0.8)
    assert s.suspect()
    assert not s.lease_check()
    assert s.lease_fenced == 1
    assert s.counters()["clock_anomalies"] == 1
    assert s.counters()["clock_suspect"] == 1


def test_sentinel_recovers_when_estimates_reconverge():
    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="s1")
    t = 0.0
    peer = 0.0
    for _ in range(10):                    # local 25% fast
        t += 1.0
        peer += 0.8
        for p in ("a", "b", "c"):
            _feed(s, p, t, peer)
    assert s.suspect()
    for _ in range(60):                    # healed: rates re-converge
        t += 1.0
        peer += 1.0
        for p in ("a", "b", "c"):
            _feed(s, p, t, peer)
    assert not s.suspect()
    assert s.lease_check()


def test_sentinel_detects_frozen_local_clock():
    """A frozen local clock yields near-zero local deltas while peers
    advance: rate math breaks down, but the signature must still read
    as an extreme ratio (the one fault division cannot see)."""
    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="s1")
    for i in range(6):                     # healthy warm-up
        t = i * 1.0
        for p in ("a", "b", "c"):
            _feed(s, p, t, t)
    # local clock freezes at t=5; peers keep advancing seconds apart
    for j in range(1, 8):
        for p in ("a", "b", "c"):
            _feed(s, p, 5.0 + j * 1e-4, 5.0 + j * 1.0)
    assert s.suspect()
    assert not s.lease_check()


def test_sentinel_detection_only_without_drift_bound():
    """drift_bound=0 deployments observe (gauges, skew estimates) but
    NEVER fence — exact legacy lease behavior."""
    s = ClockSentinel(drift_bound=0.0, clock=FakeClock(), label="s1")
    for i in range(10):
        t = i * 1.0
        for p in ("a", "b", "c"):
            _feed(s, p, t, t * 0.5)
    assert not s.suspect()
    assert s.lease_check()
    assert s.samples > 0


def test_sentinel_ignores_pre_clock_peers_and_forgets():
    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="s1")
    _feed(s, "old", 1.0, 0.0)      # clock_ms=0 decodes as 0.0 reading
    assert s.samples == 0
    _feed(s, "p", 1.0, 1.0)
    _feed(s, "p", 2.0, 2.0)
    assert s.rate_of("p") is not None
    s.forget("p")
    assert s.rate_of("p") is None
    assert s.skew_of("p") is None


def test_sentinel_gauges_and_describe():
    from tpuraft.util.metrics import MetricRegistry

    s = ClockSentinel(drift_bound=0.05, clock=FakeClock(), label="st")
    m = MetricRegistry()
    s.register_gauges(m)
    for i in range(6):
        t = i * 1.0
        _feed(s, "p", t, t + 2.0)
    g = m.snapshot()["gauges"]
    assert g["clock.suspect"] == 0.0
    assert g["clock.max_abs_skew_s"] == pytest.approx(2.0, abs=1e-2)
    assert "ClockSentinel<st" in s.describe()
    snap = s.snapshot()
    assert snap["peers"]["p"]["skew_s"] == pytest.approx(2.0, abs=1e-2)


# -- RepeatedTimer under injected clocks -------------------------------------


async def test_timer_fires_early_under_fast_clock():
    from tpuraft.util.timer import RepeatedTimer

    base = SYSTEM
    chaos = ChaosClock(base=base)
    chaos.set_rate(10.0)            # 10x fast: 1.5s timeout ~ 0.15s real
    fired = asyncio.Event()

    async def trig():
        fired.set()

    t = RepeatedTimer("t", 1500, trig, clock=chaos)
    t.start()
    try:
        await asyncio.wait_for(fired.wait(), timeout=1.0)
    finally:
        await t.destroy()


async def test_timer_parks_under_frozen_clock():
    from tpuraft.util.timer import RepeatedTimer

    chaos = ChaosClock()
    chaos.freeze()
    fired = asyncio.Event()

    async def trig():
        fired.set()

    t = RepeatedTimer("t", 50, trig, clock=chaos)
    t.start()
    await asyncio.sleep(0.3)        # frozen: 50ms deadline never arrives
    assert not fired.is_set()
    chaos.unfreeze()
    try:
        await asyncio.wait_for(fired.wait(), timeout=1.0)
    finally:
        await t.destroy()


async def test_timer_jump_fires_immediately():
    from tpuraft.util.timer import RepeatedTimer

    chaos = ChaosClock()
    fired = asyncio.Event()

    async def trig():
        fired.set()

    t = RepeatedTimer("t", 3_000, trig, clock=chaos)
    t.start()
    await asyncio.sleep(0.1)
    assert not fired.is_set()
    chaos.jump(10.0)                # deadline is long past now
    try:
        await asyncio.wait_for(fired.wait(), timeout=1.0)
    finally:
        await t.destroy()


# -- lease boundaries / drift-bound hardening --------------------------------


def _fake_timer_node(eto_ms=1000, ratio=0.9, rho=0.0, sentinel=None,
                     clock=None):
    """Minimal node double for TimerControl lease math."""
    from types import SimpleNamespace

    from tpuraft.conf import Configuration
    from tpuraft.core.node import TimerControl
    from tpuraft.entity import PeerId
    from tpuraft.options import NodeOptions

    opts = NodeOptions(election_timeout_ms=eto_ms)
    opts.raft_options.leader_lease_time_ratio = ratio
    opts.raft_options.clock_drift_bound = rho
    opts.clock = clock
    opts.clock_sentinel = sentinel
    conf = Configuration.parse("127.0.0.1:1,127.0.0.2:2,127.0.0.3:3")
    node = SimpleNamespace(
        options=opts,
        server_id=PeerId.parse("127.0.0.1:1"),
        conf_entry=SimpleNamespace(conf=conf,
                                   old_conf=Configuration()),
        list_peers=lambda: list(conf.peers),
        _handle_election_timeout=None,
        _handle_vote_timeout=None,
        _check_dead_nodes=None,
    )
    return node, TimerControl(node)


def test_lease_expires_exactly_at_deadline():
    """Boundary: quorum ack age == lease window must read INVALID (the
    comparison is strict <) — at the edge there is zero margin left, so
    serving there is serving on margin that does not exist."""
    clk = FakeClock(100.0)
    node, ctrl = _fake_timer_node(eto_ms=1000, ratio=0.9, clock=clk)
    peers = node.list_peers()
    # quorum (2 of 3, self included): one peer acked at t=100
    ctrl.record_ack(peers[1], 100.0)
    clk.advance(0.8999)
    assert ctrl.lease_valid()
    clk.t = 100.9               # age == 0.9 == eto * ratio exactly
    assert not ctrl.lease_valid()


def test_drift_bound_shrinks_leader_lease_window():
    clk = FakeClock(0.0)
    node, ctrl = _fake_timer_node(eto_ms=1000, ratio=0.9, rho=0.1,
                                  clock=clk)
    peers = node.list_peers()
    ctrl.record_ack(peers[1], 0.0)
    clk.t = 0.85                # inside 0.9 but OUTSIDE 0.9 * (1-0.1)
    assert not ctrl.lease_valid()
    clk.t = 0.80
    assert ctrl.lease_valid()


def test_frozen_clock_leader_serves_forever_without_drift_bound():
    """REGRESSION (the bug the chaos plane flushed out): a leader whose
    clock freezes right after a quorum ack sees quorum_ack_age_s pinned
    at ~0 forever — without the sentinel it would serve lease reads
    past any real expiry.  With the drift bound + sentinel the fence
    closes the hole."""
    base = FakeClock(0.0)
    chaos = ChaosClock(base=base)
    node, ctrl = _fake_timer_node(eto_ms=1000, ratio=0.9, clock=chaos)
    peers = node.list_peers()
    ctrl.record_ack(peers[1], chaos.monotonic())
    chaos.freeze()
    base.advance(3600.0)        # an hour of real time
    # unfenced: the frozen clock says the ack is still fresh — this IS
    # the unsafe serve the regression pins down
    assert ctrl.lease_valid()
    # the hardened config routes the same check through the sentinel
    sent = ClockSentinel(drift_bound=0.05, clock=chaos, label="s")
    sent._suspect = True        # the frozen-local signature flipped it
    node2, ctrl2 = _fake_timer_node(eto_ms=1000, ratio=0.9, rho=0.05,
                                    sentinel=sent, clock=chaos)
    ctrl2.record_ack(node2.list_peers()[1], chaos.monotonic())
    assert not ctrl2.lease_valid()
    assert sent.lease_fenced == 1


def test_jump_forward_expires_lease_instead_of_stale_serve():
    """A forward clock jump makes every ack look ancient: the lease
    must read EXPIRED (forcing the SAFE fallback), never stale-valid."""
    base = FakeClock(0.0)
    chaos = ChaosClock(base=base)
    node, ctrl = _fake_timer_node(eto_ms=1000, ratio=0.9, clock=chaos)
    ctrl.record_ack(node.list_peers()[1], chaos.monotonic())
    assert ctrl.lease_valid()
    chaos.jump(5.0)
    assert not ctrl.lease_valid()


def test_hub_receiver_pads_store_lease_by_drift_bound():
    """The satellite fix: the receiver times out a duration GRANTED on
    the sender's clock — it must honor only (1 - rho) of it."""
    from tpuraft.core.heartbeat_hub import HeartbeatHub

    clk = FakeClock(50.0)
    hub = HeartbeatHub(clock=clk)
    hub.clock_drift_bound = 0.1
    hub.note_lease_from("s1", 1000)          # 1s grant -> 0.9s held
    assert hub.lease_fresh("s1")
    clk.advance(0.95)
    assert not hub.lease_fresh("s1")         # unpadded would still hold
    hub.note_lease_from("s2", 1000)
    clk.advance(0.85)
    assert hub.lease_fresh("s2")


def test_hub_sender_lease_ack_window_shrinks_by_drift_bound():
    from tpuraft.core.heartbeat_hub import HeartbeatHub

    clk = FakeClock(10.0)
    hub = HeartbeatHub(clock=clk)
    hub.clock_drift_bound = 0.1
    hub._lease_ack_at["dst"] = clk.monotonic()
    clk.advance(0.95)
    assert not hub.lease_ack_fresh("dst", 1000)  # 0.95 >= 1.0 * (1-0.1)
    hub._lease_ack_at["dst"] = clk.monotonic()
    clk.advance(0.85)
    assert hub.lease_ack_fresh("dst", 1000)      # inside the 0.9s pad


def test_hub_zero_bound_keeps_legacy_windows():
    from tpuraft.core.heartbeat_hub import HeartbeatHub

    clk = FakeClock(0.0)
    hub = HeartbeatHub(clock=clk)
    hub.note_lease_from("s1", 1000)
    clk.advance(0.99)
    assert hub.lease_fresh("s1")
    clk.advance(0.02)
    assert not hub.lease_fresh("s1")


# -- wire compatibility -------------------------------------------------------


def test_beat_ack_clock_ms_decodes_old_wire_format():
    """BeatAck/StoreLeaseAck encoded BEFORE clock_ms existed must decode
    with clock_ms=0 ('no reading'), and the sentinel must ignore it."""
    from tpuraft.rpc.messages import (BeatAck, StoreLeaseAck,
                                      decode_message, encode_message)

    ack = BeatAck(ok=True, term=3, clock_ms=123456)
    wire = encode_message(ack)
    assert decode_message(wire) == ack
    old = decode_message(wire[:-8])          # strip the trailing i64
    assert old == BeatAck(ok=True, term=3, clock_ms=0)

    lack = StoreLeaseAck(ok=True, dependents=2, clock_ms=99_000)
    lwire = encode_message(lack)
    assert decode_message(lwire) == lack
    lold = decode_message(lwire[:-8])
    assert lold == StoreLeaseAck(ok=True, dependents=2, clock_ms=0)


def test_engine_control_lease_shrinks_and_fences(monkeypatch):
    """EngineControl mirrors TimerControl: drift bound shrinks _lease_ms
    at registration; a suspect sentinel fails lease_valid closed."""
    from tpuraft.core.engine import EngineControl

    class _Sent:
        def __init__(self):
            self.fenced = 0

        def lease_check(self):
            self.fenced += 1
            return False

    sent = _Sent()
    ctrl = EngineControl.__new__(EngineControl)
    ctrl.node = type("N", (), {})()
    ctrl.node.options = type("O", (), {})()
    ctrl.node.options.clock_sentinel = sent
    assert ctrl.lease_valid() is False
    assert sent.fenced == 1
