"""TestCluster: N full Nodes in one process over the loopback transport.

The reference's signature integration pattern (SURVEY.md §5): real
protocol, real storage, fault injection by stopping/partitioning
endpoints.  MockStateMachine records applied entries and exposes events.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from tpuraft.conf import Configuration
from tpuraft.core.cli_service import CliProcessors
from tpuraft.core.node import Node, State
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.entity import PeerId, Task
from tpuraft.errors import Status
from tpuraft.options import NodeOptions, RaftOptions
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


class MockStateMachine(StateMachine):
    def __init__(self):
        self.logs: list[bytes] = []
        self.applied_event = asyncio.Event()
        self.leader_terms: list[int] = []
        self.snapshots_saved = 0
        self.snapshots_loaded = 0
        self.errors: list[Status] = []

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():
            self.logs.append(it.data())
            it.next()
        self.applied_event.set()

    async def on_leader_start(self, term: int) -> None:
        self.leader_terms.append(term)

    async def on_error(self, status: Status) -> None:
        self.errors.append(status)

    async def on_snapshot_save(self, writer, done) -> None:
        import struct

        blob = struct.pack("<I", len(self.logs)) + b"".join(
            struct.pack("<I", len(x)) + x for x in self.logs)
        writer.write_file("data", blob)
        self.snapshots_saved += 1
        done(Status.OK())

    async def on_snapshot_load(self, reader) -> bool:
        import struct

        blob = reader.read_file("data")
        if blob is None:
            return False
        (n,) = struct.unpack_from("<I", blob, 0)
        off = 4
        self.logs = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            self.logs.append(bytes(blob[off:off + ln]))
            off += ln
        self.snapshots_loaded += 1
        return True


class TestCluster:
    __test__ = False  # not a pytest class

    def __init__(self, n: int, tmp_path=None, election_timeout_ms: int = 300,
                 snapshot: bool = False, group_id: str = "test_group",
                 snapshot_interval_secs: int = 0,
                 coalesce_heartbeats: bool = False,
                 log_scheme: str = "file",
                 meta_scheme: str = "file",
                 witness_idx: tuple = (),
                 append_batching: bool = False):
        self.net = InProcNetwork()
        self.group_id = group_id
        self.peers = [PeerId.parse(f"127.0.0.1:{5000 + i}") for i in range(n)]
        # witness_idx: indices of peers that are WITNESS voters (vote +
        # ack metadata appends, store no payload, never campaign)
        witnesses = [self.peers[i] for i in witness_idx]
        self.conf = Configuration(list(self.peers), witnesses=witnesses)
        self.tmp_path = tmp_path
        self.election_timeout_ms = election_timeout_ms
        self.snapshot = snapshot
        if snapshot_interval_secs > 0 and not (snapshot and
                                               tmp_path is not None):
            raise ValueError(
                "snapshot_interval_secs needs snapshot=True AND a "
                "tmp_path (no snapshot storage -> no executor -> the "
                "timer never fires)")
        self.snapshot_interval_secs = snapshot_interval_secs
        self.coalesce_heartbeats = coalesce_heartbeats
        if log_scheme != "file" and tmp_path is None:
            raise ValueError(f"log_scheme={log_scheme!r} needs a tmp_path "
                             "(memory:// would silently be used instead)")
        self.log_scheme = log_scheme  # "file" | "native" | "multilog" (needs tmp_path)
        if meta_scheme != "file" and tmp_path is None:
            raise ValueError(f"meta_scheme={meta_scheme!r} needs a tmp_path")
        self.meta_scheme = meta_scheme  # "file" | "multimeta"
        # store-wide write plane: each endpoint gets an AppendBatcher
        # and its node submits windows through it (the StoreEngine
        # wiring, reproduced for bare protocol nodes)
        self.append_batching = append_batching
        self.nodes: dict[PeerId, Node] = {}
        self.fsms: dict[PeerId, MockStateMachine] = {}
        self.managers: dict[PeerId, NodeManager] = {}
        self.batchers: dict[PeerId, object] = {}

    def _options(self, peer: PeerId) -> NodeOptions:
        opts = NodeOptions(
            election_timeout_ms=self.election_timeout_ms,
            initial_conf=self.conf.copy(),
            fsm=self.fsms[peer],
        )
        if self.tmp_path is not None:
            base = f"{self.tmp_path}/{peer.ip}_{peer.port}"
            if self.log_scheme == "multilog":
                # shared journal engine (one per endpoint dir here; the
                # scheme needs a group fragment)
                opts.log_uri = f"multilog://{base}/mlog#{self.group_id}"
            else:
                opts.log_uri = f"{self.log_scheme}://{base}/log"
            if self.meta_scheme == "multimeta":
                # shared fsynced {term, votedFor} journal (group-commit)
                opts.raft_meta_uri = f"multimeta://{base}/meta#{self.group_id}"
            else:
                opts.raft_meta_uri = f"file://{base}/meta"
            if self.snapshot:
                opts.snapshot_uri = f"file://{base}/snapshot"
        else:
            opts.log_uri = "memory://"
            opts.raft_meta_uri = "memory://"
        # 0 = only on-demand snapshots (the default for tests)
        opts.snapshot.interval_secs = self.snapshot_interval_secs
        opts.raft_options.coalesce_heartbeats = self.coalesce_heartbeats
        opts.witness = self.conf.is_witness(peer)
        return opts

    async def start_all(self) -> None:
        for p in self.peers:
            await self.start(p)

    async def start(self, peer: PeerId, fsm: Optional[MockStateMachine] = None
                    ) -> Node:
        if fsm is not None or peer not in self.fsms:
            self.fsms[peer] = fsm or MockStateMachine()
        server = RpcServer(peer.endpoint)
        manager = NodeManager(server)
        CliProcessors(manager)
        self.net.bind(server)
        self.net.start_endpoint(peer.endpoint)
        transport = InProcTransport(self.net, peer.endpoint)
        node = Node(self.group_id, peer, self._options(peer), transport)
        node.node_manager = manager
        if self.append_batching:
            from tpuraft.core.append_batcher import AppendBatcher

            self.batchers[peer] = node.append_batcher = AppendBatcher()
        manager.add(node)
        ok = await node.init()
        assert ok, f"init failed for {peer}"
        self.nodes[peer] = node
        self.managers[peer] = manager
        return node

    async def stop(self, peer: PeerId) -> None:
        """Crash-stop: unbind from the network, shut the node down."""
        self.net.stop_endpoint(peer.endpoint)
        node = self.nodes.pop(peer, None)
        batcher = self.batchers.pop(peer, None)
        if batcher is not None:
            await batcher.shutdown()
        if node:
            self.net.unbind(peer.endpoint)
            await node.shutdown()

    async def stop_all(self) -> None:
        for p in list(self.nodes):
            await self.stop(p)

    def client_transport(self, endpoint: str = "client:0") -> InProcTransport:
        """A transport for out-of-cluster clients (CliService, RouteTable)."""
        return InProcTransport(self.net, endpoint)

    async def wait_leader(self, timeout_s: float = 5.0) -> Node:
        """Poll until exactly one live node is leader (reference:
        TestCluster#waitLeader)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            leaders = [n for n in self.nodes.values() if n.state == State.LEADER]
            if len(leaders) == 1:
                # require a majority following it
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"no leader in {timeout_s}s; states="
            f"{[(str(p), n.state.value) for p, n in self.nodes.items()]}")

    async def apply_ok(self, node: Node, data: bytes, timeout_s: float = 5.0,
                       retry: bool = True) -> Status:
        """Apply `data` and wait for the commit ack. With retry=True (the
        default), a not-leader/stepped-down rejection is retried through
        the current leader (what a real client does via RouteTable
        refresh) — tests asserting the rejection itself pass retry=False."""
        from tpuraft.errors import RaftError

        deadline = time.monotonic() + timeout_s
        while True:
            fut = asyncio.get_running_loop().create_future()
            await node.apply(Task(data=data, done=lambda st: fut.set_result(st)))
            st = await asyncio.wait_for(
                fut, max(0.1, deadline - time.monotonic()))
            # Only EPERM (rejected at propose time, never appended) is safe
            # to resubmit; ENEWLEADER means the entry was already appended
            # and may yet commit — retrying would duplicate it.
            if (st.is_ok() or not retry or st.raft_error != RaftError.EPERM
                    or time.monotonic() >= deadline):
                return st
            await asyncio.sleep(0.05)
            try:
                node = await self.wait_leader(
                    max(0.1, deadline - time.monotonic()))
            except TimeoutError:
                return st

    @staticmethod
    async def drain_sends_to(leader, endpoint: str,
                             timeout_s: float = 5.0) -> None:
        """Wait until the leader's send plane has no queued or in-flight
        traffic to `endpoint`.  Used by install-snapshot tests before
        restarting a crashed follower: a retry pump may legally build an
        entry-bearing AppendEntries from the not-yet-compacted log
        DURING the snapshot, and if that frame is still in flight when
        the follower's new server comes up, the delayed delivery catches
        the follower up via the log path — valid raft, but it bypasses
        the InstallSnapshot the test wants to assert on (the r4
        snapshots_loaded=0 flake)."""
        sender = leader.node_manager.send_plane.sender(endpoint)
        deadline = time.monotonic() + timeout_s
        while (sender.queued() or (sender._task is not None
                                   and not sender._task.done())):
            if time.monotonic() >= deadline:
                raise TimeoutError(f"send plane to {endpoint} never drained")
            await asyncio.sleep(0.02)

    async def wait_applied(self, count: int, timeout_s: float = 5.0,
                           nodes=None) -> None:
        """Wait until every (given) node's FSM has `count` log entries."""
        deadline = time.monotonic() + timeout_s
        targets = nodes if nodes is not None else list(self.nodes.values())
        while time.monotonic() < deadline:
            if all(len(self.fsms[n.server_id].logs) >= count for n in targets
                   if n.server_id in self.fsms):
                return
            await asyncio.sleep(0.02)
        states = {str(n.server_id): len(self.fsms[n.server_id].logs)
                  for n in targets}
        raise TimeoutError(f"applied counts after {timeout_s}s: {states}")
