"""Crash-consistency harness: simulated power-loss crashes over the
storage plane (tpuraft/storage/fault.py).

Three generational harnesses — FileLogStorage + MetaJournal under live
``ChaosDir`` interposition, the native multilog under
``NativeJournalTracker`` tail imaging — each runs dozens of seeded
power-loss crashes (>= 220 in total across the module) and checks the
recovery invariants after EVERY one:

  - recovery never raises (a torn/bit-flipped unsynced tail is
    truncated at the last CRC-valid record, not crashed on);
  - log prefix property: recovered entries byte-match what was staged;
  - acked floor: nothing proven durable by a completed fsync is lost
    (last_recovered >= last_acked, {term, votedFor} never regresses
    below an acked save);
  - staged ceiling: recovery never invents entries beyond what was
    staged;
  - no orphaned gids: an acked registration keeps its gid across
    crashes; journal records whose registration was lost are truncated,
    never adopted or shadowed.

Bit rot of the DURABLE region is the opposite contract — fail loudly,
never truncate silently — and is covered by the explicit tests at the
bottom.
"""

from __future__ import annotations

import os
import random
import struct

from tpuraft.entity import EMPTY_PEER, EntryType, LogEntry, LogId, PeerId
from tpuraft.storage.fault import (
    ChaosDir,
    NativeJournalTracker,
)
from tpuraft.storage.log_storage import CorruptLogError, FileLogStorage
from tpuraft.storage.meta_multilog import MetaJournal
from tpuraft.storage.multilog import MultiLogStorage


def _entry(index: int, gen: int, term: int = 1) -> LogEntry:
    return LogEntry(type=EntryType.DATA, id=LogId(index, term),
                    data=b"g%03d-i%06d" % (gen, index))


# ---------------------------------------------------------------------------
# FileLogStorage under ChaosDir
# ---------------------------------------------------------------------------


def _filelog_lifetime(root: str, rng: random.Random, gens: int) -> int:
    """One directory, ``gens`` crash generations; returns crash count."""
    first, entries, acked_last = 1, {}, 0

    def staged_last():
        return max(entries) if entries else first - 1

    with ChaosDir(root) as chaos:
        for gen in range(gens):
            st = FileLogStorage(os.path.join(root, "log"),
                                segment_max_bytes=200)
            st.init()  # must tolerate whatever the crash left
            rf, rl = st.first_log_index(), st.last_log_index()
            assert rf == first, f"gen {gen}: first {rf} != {first}"
            assert acked_last <= rl <= staged_last(), \
                f"gen {gen}: last {rl} not in [{acked_last}, {staged_last()}]"
            for i in range(rf, rl + 1):
                e = st.get_entry(i)
                assert e is not None and e.data == entries[i], \
                    f"gen {gen}: entry {i} mismatch"
            # recovered state is durable (init re-fsyncs + watermarks)
            for i in list(entries):
                if i > rl:
                    del entries[i]
            acked_last = rl

            for _ in range(rng.randrange(1, 5)):
                op = rng.random()
                if op < 0.70 or not entries:
                    n = rng.randrange(1, 6)
                    batch = [_entry(staged_last() + 1 + k, gen)
                             for k in range(n)]
                    st.append_entries(batch, sync=True)  # fsynced => acked
                    for e in batch:
                        entries[e.id.index] = e.data
                    acked_last = staged_last()
                elif op < 0.85 and acked_last >= first:
                    keep = rng.randrange(first - 1, staged_last() + 1)
                    st.truncate_suffix(keep)  # fsynced by contract
                    for i in list(entries):
                        if i > keep:
                            del entries[i]
                    acked_last = min(acked_last, keep)
                elif op < 0.95 and staged_last() > first:
                    cut = rng.randrange(first, staged_last() + 1)
                    st.truncate_prefix(cut)  # meta fsynced by contract
                    first = max(first, cut)
                    for i in list(entries):
                        if i < first:
                            del entries[i]
                    acked_last = max(acked_last, first - 1)
                else:
                    nxt = staged_last() + rng.randrange(1, 10)
                    st.reset(nxt)
                    first, entries, acked_last = nxt, {}, nxt - 1

            if rng.random() < 0.7:
                # the in-flight append the power interrupts: staged
                # bytes on disk, fsync never completed — on-disk
                # identical to a crash mid sync=True append
                n = rng.randrange(1, 5)
                batch = [_entry(staged_last() + 1 + k, gen, term=2)
                         for k in range(n)]
                st.append_entries(batch, sync=False)
                for e in batch:
                    entries[e.id.index] = e.data

            plan = chaos.capture_crash(rng)   # power dies here
            st.shutdown()                     # in-proc cleanup only...
            chaos.apply_crash(plan)           # ...discarded by the image
        return chaos.crash_count


def test_filelog_power_loss_recovery():
    import tempfile

    crashes = 0
    for seed in range(3):
        with tempfile.TemporaryDirectory() as tmp:
            crashes += _filelog_lifetime(
                os.path.join(tmp, f"flog{seed}"),
                random.Random(1000 + seed), gens=20)
    assert crashes >= 60


# ---------------------------------------------------------------------------
# MetaJournal under ChaosDir
# ---------------------------------------------------------------------------


def _meta_lifetime(root: str, rng: random.Random, gens: int) -> int:
    groups = [f"r{i}" for i in range(4)]
    history = {g: [(0, "")] for g in groups}   # staged (term, voted) per group
    acked = {g: 0 for g in groups}             # index into history[g]
    term = {g: 0 for g in groups}

    with ChaosDir(root) as chaos:
        for gen in range(gens):
            j = MetaJournal(root)
            j.COMPACT_MIN_BYTES = 512  # force compaction under chaos
            for g in groups:
                t, voted = j.get(g)
                v = "" if voted.is_empty() else str(voted)
                hist = history[g]
                assert (t, v) in hist, f"gen {gen}: {g} has unknown {t}/{v}"
                pos = hist.index((t, v))
                assert pos >= acked[g], \
                    f"gen {gen}: {g} regressed below acked " \
                    f"({t} < {hist[acked[g]][0]})"
                # recovered value is durable now (reopen fsync + wm)
                history[g] = [(t, v)]
                acked[g] = 0
                term[g] = max(term[g], t)

            for _ in range(rng.randrange(2, 8)):
                g = rng.choice(groups)
                term[g] += rng.randrange(1, 3)
                voted = PeerId.parse(f"10.0.0.{rng.randrange(1, 5)}:80") \
                    if rng.random() < 0.8 else EMPTY_PEER
                j.stage(g, term[g], voted)
                history[g].append(
                    (term[g], "" if voted.is_empty() else str(voted)))
                if rng.random() < 0.4:
                    j.sync()  # group-commit round: everything staged acks
                    for gg in groups:
                        acked[gg] = len(history[gg]) - 1

            plan = chaos.capture_crash(rng)
            j.close()
            chaos.apply_crash(plan)
        return chaos.crash_count


def test_meta_journal_power_loss_recovery():
    import tempfile

    crashes = 0
    for seed in range(4):
        with tempfile.TemporaryDirectory() as tmp:
            crashes += _meta_lifetime(
                os.path.join(tmp, f"meta{seed}"),
                random.Random(2000 + seed), gens=20)
    assert crashes >= 80


# ---------------------------------------------------------------------------
# native multilog under tail imaging
# ---------------------------------------------------------------------------


class _GroupModel:
    def __init__(self) -> None:
        self.first = 1
        self.acked_first = 1
        self.entries: dict[int, bytes] = {}
        self.acked_last = 0

    def staged_last(self) -> int:
        return max(self.entries) if self.entries else self.first - 1


def _native_lifetime(base: str, rng: random.Random, gens: int) -> int:
    names = [f"g{i}" for i in range(3)]
    model = {n: _GroupModel() for n in names}
    gids: dict[str, int] = {}
    live = os.path.join(base, "gen0")
    crashes = 0

    for gen in range(gens):
        stores = {n: MultiLogStorage(live, n) for n in names}
        for n in names:
            stores[n].init()  # shared engine; recovery scan runs once
        eng = stores[names[0]].engine
        eng.sync()  # registrations of any new names ack immediately
        for n in names:
            if n in gids:
                assert stores[n]._gid == gids[n], \
                    f"gen {gen}: acked group {n} changed gid " \
                    f"{gids[n]} -> {stores[n]._gid} (orphan/shadow)"
            else:
                gids[n] = stores[n]._gid

        tracker = NativeJournalTracker(live)
        tracker.note_sync()  # the recovered image IS the durable state

        for n in names:
            m, s = model[n], stores[n]
            rf, rl = s.first_log_index(), s.last_log_index()
            assert m.acked_first <= rf, \
                f"gen {gen}: {n} first {rf} below acked {m.acked_first}"
            assert rf <= max(m.first, m.acked_first), \
                f"gen {gen}: {n} first {rf} beyond staged {m.first}"
            assert m.acked_last <= rl, \
                f"gen {gen}: {n} last {rl} below acked {m.acked_last}"
            assert rl <= m.staged_last() or not m.entries, \
                f"gen {gen}: {n} last {rl} beyond staged {m.staged_last()}"
            for i in range(rf, rl + 1):
                e = s.get_entry(i)
                assert e is not None and e.data == m.entries[i], \
                    f"gen {gen}: {n} entry {i} mismatch"
            m.first = rf
            m.acked_first = rf
            for i in list(m.entries):
                if i < rf or i > rl:
                    del m.entries[i]
            m.acked_last = rl

        synced = False
        for _ in range(rng.randrange(2, 6)):
            n = rng.choice(names)
            m, s = model[n], stores[n]
            op = rng.random()
            if op < 0.60 or not m.entries:
                cnt = rng.randrange(1, 5)
                batch = [_entry(m.staged_last() + 1 + k, gen)
                         for k in range(cnt)]
                s.append_entries(batch, sync=False)  # staged, not acked
                for e in batch:
                    m.entries[e.id.index] = e.data
            elif op < 0.75:
                eng.sync()
                tracker.note_sync()
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
                    mm.acked_first = mm.first
                synced = True
            elif op < 0.85 and m.acked_last >= m.first:
                keep = rng.randrange(m.first - 1, m.staged_last() + 1)
                s.truncate_suffix(keep)  # fsyncs everything staged
                tracker.note_sync()
                for i in list(m.entries):
                    if i > keep:
                        del m.entries[i]
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
                    mm.acked_first = mm.first
            elif op < 0.95 and m.staged_last() > m.first:
                cut = rng.randrange(m.first, m.staged_last() + 1)
                s.truncate_prefix(cut)  # lazily durable control record
                m.first = max(m.first, cut)
                # keep entries down to acked_first: a crash can lose the
                # staged trunc record and legitimately revive them
                for i in list(m.entries):
                    if i < m.acked_first:
                        del m.entries[i]
            else:
                nxt = m.staged_last() + rng.randrange(1, 8)
                s.reset(nxt)  # fsyncs everything staged
                tracker.note_sync()
                m.first = m.acked_first = nxt
                m.entries = {}
                m.acked_last = nxt - 1
                for mm in model.values():
                    mm.acked_last = mm.staged_last()
        del synced

        nxt_dir = os.path.join(base, f"gen{gen + 1}")
        tracker.crash_image(nxt_dir, rng)  # power dies here
        for s in stores.values():
            s.shutdown()  # releases/closes the live engine afterwards
        live = nxt_dir
        crashes += 1
    return crashes


def test_native_multilog_power_loss_recovery(tmp_path):
    crashes = 0
    for seed in range(4):
        crashes += _native_lifetime(
            str(tmp_path / f"nat{seed}"), random.Random(3000 + seed),
            gens=30)
    assert crashes >= 120


# ---------------------------------------------------------------------------
# explicit contract tests
# ---------------------------------------------------------------------------


def test_torn_tail_truncated_at_last_crc_valid_record(tmp_path):
    """A torn unsynced tail recovers by CRC truncation — acked prefix
    intact, no exception, no garbage read."""
    root = str(tmp_path / "torn")
    rng = random.Random(7)
    with ChaosDir(root, modes=(("torn-write", 1.0),)) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 6)], sync=True)
        st.append_entries([_entry(i, 0) for i in range(6, 9)], sync=False)
        plan = chaos.capture_crash(rng)
        st.shutdown()
        chaos.apply_crash(plan)
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()
        assert 5 <= st2.last_log_index() <= 8
        for i in range(1, st2.last_log_index() + 1):
            assert st2.get_entry(i).data == _entry(i, 0).data
        st2.shutdown()


def test_bit_flip_in_unsynced_tail_is_truncated(tmp_path):
    root = str(tmp_path / "flip")
    rng = random.Random(11)
    with ChaosDir(root, modes=(("bit-flip", 1.0),)) as chaos:
        st = FileLogStorage(os.path.join(root, "log"))
        st.init()
        st.append_entries([_entry(i, 0) for i in range(1, 4)], sync=True)
        st.append_entries([_entry(i, 0) for i in range(4, 9)], sync=False)
        plan = chaos.capture_crash(rng)
        st.shutdown()
        chaos.apply_crash(plan)
        st2 = FileLogStorage(os.path.join(root, "log"))
        st2.init()  # must not raise: flip is in the unsynced region
        assert st2.last_log_index() >= 3
        for i in range(1, st2.last_log_index() + 1):
            assert st2.get_entry(i).data == _entry(i, 0).data
        st2.shutdown()


def test_durable_bit_rot_fails_loudly_filelog(tmp_path):
    """Corruption BELOW the durability watermark is not a torn tail:
    startup must refuse to truncate acked entries."""
    d = str(tmp_path / "rot")
    st = FileLogStorage(d)
    st.init()
    st.append_entries([_entry(i, 0) for i in range(1, 6)], sync=True)
    st.shutdown()  # advances the watermark over everything
    seg = next(n for n in os.listdir(d) if n.startswith("seg_"))
    p = os.path.join(d, seg)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    open(p, "wb").write(bytes(blob))
    st2 = FileLogStorage(d)
    try:
        st2.init()
        raise AssertionError("durable-region rot went undetected")
    except CorruptLogError:
        pass


def test_multilog_get_crc_guards_read_path(tmp_path):
    """Bit rot in a live, indexed record: tlm_get must fail loudly
    (CorruptLogError), not hand garbage (or a silent hole) upward."""
    d = str(tmp_path / "mrot")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(i, 0) for i in range(1, 4)], sync=True)
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    p = os.path.join(d, jnl)
    blob = bytearray(open(p, "rb").read())
    blob[30] ^= 0x10  # inside the first record's payload
    open(p, "wb").write(bytes(blob))
    try:
        s.get_entry(1)
        raise AssertionError("rotted record served without complaint")
    except CorruptLogError:
        pass
    finally:
        s.shutdown()


def test_multilog_len_rot_on_live_record_fails_loudly(tmp_path):
    """A len field rotted HIGH on a live, indexed record must surface
    as corruption (CorruptLogError), not read as a missing-entry hole
    via a short payload read."""
    d = str(tmp_path / "lenrot")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(i, 0) for i in range(1, 3)], sync=True)
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    p = os.path.join(d, jnl)
    blob = bytearray(open(p, "rb").read())
    blob[3] |= 0x40  # inflate the first record's len field past the file
    open(p, "wb").write(bytes(blob))
    try:
        s.get_entry(1)
        raise AssertionError("len-rotted record read as a hole")
    except CorruptLogError:
        pass
    finally:
        s.shutdown()


def test_multilog_unreadable_registry_fails_open_not_truncates(tmp_path):
    """A registry that cannot be READ must fail the engine open loudly
    (retryable) — scanning journals against a partial registry would
    read every acked record as orphan garbage and truncate them."""
    d = str(tmp_path / "regdead")
    s = MultiLogStorage(d, "g")
    s.init()
    s.append_entries([_entry(1, 0)], sync=True)
    s.shutdown()
    jsize = os.path.getsize(os.path.join(d, next(
        n for n in sorted(os.listdir(d)) if n.startswith("journal_"))))
    reg = os.path.join(d, "groups")
    os.remove(reg)
    os.mkdir(reg)  # open(O_RDWR) now fails EISDIR: unreadable registry
    s2 = MultiLogStorage(d, "g")
    try:
        s2.init()
        raise AssertionError("open succeeded against unreadable registry")
    except IOError:
        pass
    # the acked journal bytes must be untouched by the failed open
    jnl = next(n for n in sorted(os.listdir(d))
               if n.startswith("journal_"))
    assert os.path.getsize(os.path.join(d, jnl)) == jsize
    os.rmdir(reg)


def test_multilog_registry_gid_alias_is_truncated(tmp_path):
    """A flipped gid in the registry's unsynced tail must not alias an
    acked gid (shadowing another group's log): the sequential-gid scan
    truncates the tail at the deviation."""
    d = str(tmp_path / "reg")
    sa, sb = MultiLogStorage(d, "a"), MultiLogStorage(d, "b")
    sa.init(), sb.init()
    sa.engine.sync()  # both registrations acked
    gid_a, gid_b = sa._gid, sb._gid
    sa.shutdown(), sb.shutdown()
    # forge a tail record claiming gid_a for a different name (what a
    # partial-page writeback bit flip can leave behind)
    with open(os.path.join(d, "groups"), "ab") as f:
        f.write(struct.pack("<II", gid_a, 1) + b"z")
    sa2, sz = MultiLogStorage(d, "a"), MultiLogStorage(d, "z")
    sa2.init(), sz.init()
    try:
        assert sa2._gid == gid_a
        assert sz._gid not in (gid_a, gid_b), "alias adopted: shadowing"
    finally:
        sa2.shutdown(), sz.shutdown()


def test_multilog_registry_tolerates_legacy_gid_gaps(tmp_path):
    """Registries written before register_group rolled next_gid back on
    a failed append can hold gid GAPS in their durable region; the
    alias guard must accept those (strictly increasing), not truncate
    acked registrations on upgrade."""
    d = str(tmp_path / "gap")
    sa, sb = MultiLogStorage(d, "a"), MultiLogStorage(d, "b")
    sa.init(), sb.init()
    gid_a, gid_b = sa._gid, sb._gid
    sa.engine.sync()
    sa.shutdown(), sb.shutdown()
    # legacy gap: a registration that consumed gid_b+1 without a record,
    # then a later group registered at gid_b+2
    with open(os.path.join(d, "groups"), "ab") as f:
        f.write(struct.pack("<II", gid_b + 2, 1) + b"c")
    sa2 = MultiLogStorage(d, "a")
    sb2 = MultiLogStorage(d, "b")
    sc2 = MultiLogStorage(d, "c")
    sd2 = MultiLogStorage(d, "dnew")
    for s in (sa2, sb2, sc2, sd2):
        s.init()
    try:
        assert sa2._gid == gid_a and sb2._gid == gid_b
        assert sc2._gid == gid_b + 2, "gap-following record truncated"
        assert sd2._gid == gid_b + 3  # next_gid resumed past the gap
    finally:
        for s in (sa2, sb2, sc2, sd2):
            s.shutdown()


def test_multilog_orphan_journal_records_are_torn(tmp_path):
    """Journal records whose registration never became durable are an
    unsynced tail by construction: recovery truncates them instead of
    adopting records for an unregistered gid."""
    import shutil

    d = str(tmp_path / "orph")
    sa = MultiLogStorage(d, "a")
    sa.init()
    sa.append_entries([_entry(1, 0)], sync=True)   # a: acked
    reg_durable = os.path.getsize(os.path.join(d, "groups"))
    sb = MultiLogStorage(d, "b")
    sb.init()                                       # b: registration staged
    sb.append_entries([_entry(1, 0), _entry(2, 0)], sync=False)
    # power loss: journal pages survived writeback, registry tail didn't
    img = str(tmp_path / "orph_img")
    shutil.copytree(d, img)
    with open(os.path.join(img, "groups"), "r+b") as f:
        f.truncate(reg_durable)
    sa.shutdown(), sb.shutdown()
    ra, rb = MultiLogStorage(img, "a"), MultiLogStorage(img, "b")
    ra.init(), rb.init()
    try:
        assert ra.last_log_index() == 1
        assert ra.get_entry(1).data == _entry(1, 0).data
        # b's staged-only records were truncated with its registration;
        # the re-registered b starts empty (no adopted orphan records)
        assert rb.last_log_index() == 0
        assert rb.get_entry(1) is None
    finally:
        ra.shutdown(), rb.shutdown()


async def test_reboot_after_compaction_keeps_acked_suffix(tmp_path):
    """Regression for the amnesiac-reboot bug the power-loss soak found:
    after snapshot compaction prunes the entry AT the snapshot index
    (margin 0, first == S+1), the next boot's set_snapshot saw term 0
    there, called it divergence, and RESET the log — silently dropping
    the whole acked suffix.  Two stores rebooting in one fault window
    then break quorum intersection and un-commit acked writes."""
    from tpuraft.conf import Configuration, ConfigurationEntry
    from tpuraft.storage.log_manager import LogManager

    d = str(tmp_path / "lm")
    conf = ConfigurationEntry(
        LogId(0, 0), Configuration.parse("1.1.1.1:1,1.1.1.2:1,1.1.1.3:1"))

    st = FileLogStorage(d)
    lm = LogManager(st)
    await lm.init()
    await lm.append_entries_follower(
        0, 0, [_entry(i, 0, term=3) for i in range(1, 11)])
    # snapshot at 5 (margin 0): prunes entries <= 5, first becomes 6
    await lm.set_snapshot(LogId(5, 3), conf)
    assert lm.first_log_index() == 6 and lm.last_log_index() == 10
    await lm.shutdown()

    # reboot: snapshot load replays set_snapshot on the compacted log
    st2 = FileLogStorage(d)
    lm2 = LogManager(st2)
    await lm2.init()
    await lm2.set_snapshot(LogId(5, 3), conf)
    assert lm2.last_log_index() == 10, \
        "acked suffix dropped on reboot after compaction"
    for i in range(6, 11):
        assert lm2.get_term(i) == 3
    assert lm2.check_consistency().is_ok()
    await lm2.shutdown()

    # the true-divergence case still resets: entry AT the snapshot index
    # present with a DIFFERENT term (install-snapshot over a stale log)
    st3 = FileLogStorage(str(tmp_path / "lm3"))
    lm3 = LogManager(st3)
    await lm3.init()
    await lm3.append_entries_follower(
        0, 0, [_entry(i, 0, term=2) for i in range(1, 11)])
    await lm3.set_snapshot(LogId(7, 5), conf)   # term 5 != stored term 2
    assert lm3.last_log_index() == 7            # stale tail dropped
    assert lm3.first_log_index() == 8
    await lm3.shutdown()


def test_chaosdir_lost_fsync_and_survival(tmp_path):
    """Sanity of the model itself: unsynced bytes vanish under
    lost-fsync; fsynced bytes always survive."""
    root = str(tmp_path / "model")
    rng = random.Random(5)
    with ChaosDir(root, modes=(("lost-fsync", 1.0),)) as chaos:
        p = os.path.join(root, "f.bin")
        f = open(p, "wb")
        f.write(b"durable")
        f.flush()
        os.fsync(f.fileno())
        f.write(b"+volatile")
        f.flush()
        f.close()
        assert open(p, "rb").read() == b"durable+volatile"
        chaos.crash(rng)
        assert open(p, "rb").read() == b"durable"
